"""Topology chooser: enumerate candidate tree shapes, cost each, pick argmin.

The rebuild of ``cost_model/ChooseWidth.h`` + ``CostModel.h:82-119``'s
driver loop: enumerate ordered factorizations, evaluate the cost model,
return the cheapest shape (the reference prints it; we return a structured
plan whose ``widths`` drop straight into ``flextree_tpu.allreduce(topo=...)``
or the ``FT_TOPO`` env var).

Prime/odd device counts: the reference's planner proposes shapes for N±1
(``ChooseWidth.h:16-21`` — the disabled "lonely node" idea), but its runtime
aborts unless the width product equals N (``mpi_mod.hpp:914-918``).  Ours
goes further: lonely shapes are EXECUTABLE (``"3,2+1"`` runs through
``parallel.allreduce.lonely_allreduce``), so for prime N every
factorization of N-1 plus one lonely rank joins the candidate table as a
real choice, alongside the flat tree and the ring; the N±1 *resize*
suggestions remain as advisory strings, matching the reference's printed
``+1``/``-1`` notation.

Torus-aware mode: given a mesh shape (e.g. ``(16, 16)``), only
factorizations whose widths tile the torus axes in order are physical —
each stage's groups then ride a single ICI axis.  ``choose_topology``
prefers those when a mesh shape is provided.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass

from ..schedule.ir import IRFamilySpec
from ..schedule.stages import LonelyTopology, Topology
from .cost_model import (
    CostBreakdown,
    TpuCostParams,
    all_gather_cost,
    allreduce_cost,
    generalized_cost,
    lonely_allreduce_cost,
    reduce_scatter_cost,
    sharded_sync_cost,
    swing_cost,
)
from .factorize import is_prime, ordered_factorizations

__all__ = [
    "Candidate",
    "Plan",
    "choose_topology",
    "candidate_topologies",
    "choose_bucket_bytes",
    "choose_overlap_boundaries",
    "predict_overlap_schedule",
    "overlap_comm_us",
    "WIRE_PESSIMISM_BAND",
    "replan_for_survivors",
]


@dataclass(frozen=True)
class Candidate:
    widths: tuple[int, ...]
    cost: CostBreakdown
    torus_aligned: bool = False
    lonely: int = 0  # ranks outside the tree (executable "+k" shapes)
    # IR families (ISSUE 8): "tree" covers every legacy shape (the ring
    # rides widths=(1,)); "swing"/"generalized" are schedule-IR families
    # executed through schedule.ir.compile_ir.  ``ports`` is the
    # generalized construction's per-round port count.
    family: str = "tree"
    ports: int = 0

    @property
    def total_us(self) -> float:
        return self.cost.total_us

    def shape_label(self) -> str:
        if self.family == "swing":
            return "swing"
        if self.family == "generalized":
            return f"gen:{','.join(map(str, self.widths))}@{self.ports}"
        label = "ring" if self.widths == (1,) else "*".join(map(str, self.widths))
        if self.lonely:
            label += f"+{self.lonely}"
        return label


@dataclass(frozen=True)
class Plan:
    """Chooser output: the winning topology plus the full ranked table."""

    num_nodes: int
    nbytes: int
    topology: Topology
    candidates: tuple[Candidate, ...]  # ranked, cheapest first
    advisory: tuple[str, ...] = ()  # e.g. prime-N resize suggestions

    @property
    def widths(self) -> tuple[int, ...]:
        return self.topology.widths

    def to_ft_topo(self) -> str:
        """The ``FT_TOPO`` env value selecting this plan (IR families
        return their own spec grammar: ``"swing"`` / ``"gen:4,2@2"``)."""
        if isinstance(self.topology, IRFamilySpec):
            return self.topology.spec
        spec = ",".join(map(str, self.topology.widths))
        if isinstance(self.topology, LonelyTopology):
            spec += f"+{self.topology.lonely}"
        return spec

    def summary(self) -> str:
        lines = [
            f"plan for N={self.num_nodes}, {self.nbytes} bytes: "
            f"topo {self.topology} ({self.candidates[0].total_us:.1f} µs predicted)"
        ]
        for c in self.candidates[:8]:
            mark = " torus" if c.torus_aligned else ""
            shape = c.shape_label()
            lines.append(
                f"  {shape:>12}: {c.total_us:9.1f} µs "
                f"(lat {c.cost.latency_us:.1f} + bw {c.cost.bandwidth_us:.1f} "
                f"+ red {c.cost.reduce_us:.1f} + ctl {c.cost.control_us:.1f}){mark}"
            )
        for a in self.advisory:
            lines.append(f"  advisory: {a}")
        return "\n".join(lines)


def _stage_axes(
    widths: tuple[int, ...], mesh_shape: tuple[int, ...]
) -> tuple[int, ...] | None:
    """Map each stage to the mesh axis its groups ride, or None if the
    widths don't tile ``mesh_shape`` axis by axis in order.

    Aligned means: each mesh axis is covered by a contiguous run of widths
    whose product equals the axis size (so every stage's groups span exactly
    one physical axis).  The per-stage axis indices are returned so DCN
    stages can be identified by the same traversal that decides alignment.
    """
    ai = 0
    acc = 1
    axes: list[int] = []
    for w in widths:
        if ai >= len(mesh_shape):
            return None
        axes.append(ai)
        acc *= w
        if acc == mesh_shape[ai]:
            ai += 1
            acc = 1
        elif mesh_shape[ai] % acc != 0:
            return None
    if ai == len(mesh_shape) and acc == 1:
        return tuple(axes)
    return None


def candidate_topologies(n: int) -> list[tuple[int, ...]]:
    """All usable stage-width vectors for ``n`` devices: every ordered
    factorization plus the ring sentinel ``(1,)`` (the reference appends
    flat/ring sentinels in ``GetWidth.h:214-219``)."""
    shapes: list[tuple[int, ...]] = list(ordered_factorizations(n))
    shapes.append((1,))
    return shapes


def choose_topology(
    n: int,
    nbytes: int,
    params: TpuCostParams | None = None,
    mesh_shape: tuple[int, ...] | None = None,
    dcn_axes: tuple[int, ...] = (),
    codec=None,
    collective: str = "allreduce",
    ir_families: tuple[str, ...] = (),
) -> Plan:
    """Pick the cheapest topology for ``n`` devices and ``nbytes``/chip.

    ``mesh_shape``: physical torus shape, e.g. ``(16, 16)`` for a v5e-256
    slice; when given, torus-aligned shapes get exact per-axis costing and
    non-aligned shapes are penalized implicitly (their stages still cost as
    single-axis rings, which is optimistic — alignment is reported so the
    caller can filter).  ``dcn_axes``: indices of mesh axes that are DCN
    (multi-slice outer axes).

    ``codec``: wire codec for the collective (``ops/quantize.py``); the
    argmin then trades shape against the codec's wire ratio and per-hop
    encode/decode cost.  ``None``/``"f32"`` reproduces the uncompressed
    costing exactly.  The codec x shape product is searched by
    ``planner.autotune.autotune_plan``, which measures the analytic
    shortlist instead of trusting it.

    ``collective`` selects what is being planned: ``"allreduce"`` (the
    default, historical behavior), ``"reduce_scatter"`` / ``"all_gather"``
    (one phase alone, per-phase bandwidth scales applied), or
    ``"sharded"`` — one ZeRO-1 sync round (quantized grad reduce-scatter
    + quantized param all-gather, ``cost_model.sharded_sync_cost``).
    Split collectives have no lonely candidates (lonely ranks own no
    block — the runtime falls back to the flat tree there too).

    ``ir_families``: opt-in schedule-IR families for the candidate table
    (``("swing", "generalized")`` — ISSUE 8).  Only meaningful for the
    fused ``"allreduce"`` collective (the IR families have no split-phase
    or compressed lowering yet); the default keeps the historical
    candidate set byte-for-byte, and ``planner.autotune.autotune_plan``
    passes the full set so measurement, not the model, gets the final
    word on the wider space.  IR candidates never win a cost TIE against
    a legacy shape (the sort prefers proven grouped-collective lowerings
    at equal predicted time).
    """
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")
    if collective not in ("allreduce", "reduce_scatter", "all_gather", "sharded"):
        raise ValueError(f"unknown collective {collective!r}")

    def cost_fn(topo, dcn_stages=()):
        if collective == "allreduce":
            return allreduce_cost(topo, nbytes, params, dcn_stages=dcn_stages, codec=codec)
        if collective == "reduce_scatter":
            return reduce_scatter_cost(topo, nbytes, params, dcn_stages=dcn_stages, codec=codec)
        if collective == "all_gather":
            return all_gather_cost(topo, nbytes, params, dcn_stages=dcn_stages, codec=codec)
        return sharded_sync_cost(topo, nbytes, params, dcn_stages=dcn_stages, codec=codec)
    if params is None:
        # measured constants from $FLEXTREE_CALIBRATION when present
        # (per-backend CALIBRATION.json, see planner/calibrate.py), else
        # the documented v5e-flavored defaults
        from .calibrate import default_params

        params = default_params()
    if dcn_axes and not mesh_shape:
        raise ValueError("dcn_axes requires mesh_shape (which axes are DCN?)")
    if mesh_shape:
        if math.prod(mesh_shape) != n:
            raise ValueError(
                f"mesh_shape {mesh_shape} has {math.prod(mesh_shape)} devices, "
                f"but n is {n}"
            )
        # drop degenerate size-1 axes, remapping dcn_axes indices to match
        keep = [i for i, s in enumerate(mesh_shape) if s > 1]
        dcn_axes = tuple(keep.index(a) for a in dcn_axes if a in keep)
        mesh_shape = tuple(mesh_shape[i] for i in keep) or None
    if n == 1:
        t = Topology.flat(1)
        return Plan(
            1, nbytes, t,
            (Candidate((1,), allreduce_cost(t, nbytes, params, codec=codec)),),
        )

    cands: list[Candidate] = []
    for widths in candidate_topologies(n):
        if widths == (1,):
            if collective == "allreduce":
                from .cost_model import ring_cost

                cost = ring_cost(
                    n, nbytes, params, crosses_dcn=bool(dcn_axes), codec=codec
                )
            else:
                cost = cost_fn(
                    Topology.ring(n), dcn_stages=(0,) if dcn_axes else ()
                )
            cands.append(Candidate((1,), cost, False))
            continue
        topo = Topology(n, widths)
        stage_axes = _stage_axes(widths, mesh_shape) if mesh_shape else None
        aligned = stage_axes is not None
        dcn_stages: tuple[int, ...] = ()
        if dcn_axes:
            if aligned:
                # stages whose mesh axis is DCN pay DCN constants
                dcn_stages = tuple(
                    i for i, a in enumerate(stage_axes) if a in set(dcn_axes)
                )
            else:
                # a shape that doesn't tile the torus axes has groups
                # straddling the DCN boundary: price every stage at DCN
                # (pessimistic) so misaligned shapes can't win on an
                # optimistic ICI-only estimate
                dcn_stages = tuple(range(len(widths)))
        cost = cost_fn(topo, dcn_stages=dcn_stages)
        cands.append(Candidate(widths, cost, aligned))

    if ir_families and collective == "allreduce" and n >= 2:
        if "swing" in ir_families:
            core = 1 << (n.bit_length() - 1)
            cands.append(
                Candidate(
                    (2,) * (core.bit_length() - 1),
                    swing_cost(
                        n, nbytes, params, crosses_dcn=bool(dcn_axes),
                        codec=codec,
                    ),
                    False,
                    family="swing",
                )
            )
        if "generalized" in ir_families:
            for widths in ordered_factorizations(n):
                # the construction's interesting ports corners: fully
                # serial rounds and fully parallel (tree-pattern) rounds
                for p in sorted({1, max(widths) - 1}):
                    if p < 1:
                        continue
                    dcn_gen = (
                        tuple(range(len(widths))) if dcn_axes else ()
                    )
                    cands.append(
                        Candidate(
                            widths,
                            generalized_cost(
                                widths, p, nbytes, params,
                                dcn_stages=dcn_gen, codec=codec,
                            ),
                            False,
                            family="generalized",
                            ports=p,
                        )
                    )

    advisory: tuple[str, ...] = ()
    if is_prime(n) and n > 3 and collective == "allreduce":
        # Prime N: the reference could only *advise* resizing to N±1
        # (ChooseWidth.h:16-21; its runtime aborts on product != N).  Our
        # runtime executes lonely shapes (schedule.stages.LonelyTopology),
        # so every factorization of N-1 plus one lonely rank enters the
        # candidate table for real.  Lonely candidates are priced
        # fabric-uniform (a +1 world doesn't tile a torus; the tree part's
        # stages still ride ICI, the buddy hop is rank-adjacent).
        for widths in ordered_factorizations(n - 1):
            tree = Topology(n - 1, widths)
            # like misaligned shapes: when a DCN boundary exists, a +1
            # world can't tile the torus, so price every tree stage at DCN
            # (pessimistic) rather than let an optimistic ICI-only estimate
            # win
            dcn_lonely = tuple(range(len(widths))) if dcn_axes else ()
            cost = lonely_allreduce_cost(
                tree, 1, nbytes, params, dcn_stages=dcn_lonely,
                buddy_crosses_dcn=bool(dcn_axes), codec=codec,
            )
            cands.append(Candidate(widths, cost, False, lonely=1))
        near = []
        from .shapes import format_shape

        for m, delta in ((n - 1, +1), (n + 1, -1)):
            alt = choose_topology(m, nbytes, params)
            near.append(
                f"N={n} is prime; resizing to {m} would allow "
                f"topo {format_shape(alt.widths, delta)}"
            )
        advisory = tuple(near)

    # prefer torus-aligned shapes at equal cost, then legacy grouped
    # lowerings over IR families, then in-tree over lonely, then fewer
    # stages
    cands.sort(
        key=lambda c: (
            c.total_us,
            not c.torus_aligned,
            c.family != "tree",
            c.lonely,
            len(c.widths),
        )
    )
    best = cands[0]
    if best.family == "swing":
        topo = IRFamilySpec("swing", n)
    elif best.family == "generalized":
        topo = IRFamilySpec("generalized", n, best.widths, best.ports)
    elif best.lonely:
        topo = LonelyTopology(n, Topology(n - best.lonely, best.widths), best.lonely)
    elif best.widths == (1,):
        topo = Topology.ring(n)
    else:
        topo = Topology(n, best.widths)

    return Plan(n, nbytes, topo, tuple(cands), advisory)


def choose_bucket_bytes(
    nbytes: int,
    topos,
    *,
    n_leaves: int | None = None,
    params: TpuCostParams | None = None,
    max_buckets: int = 64,
    codec=None,
    sharded: bool = False,
) -> int:
    """Cost-model-driven gradient-bucket size: the fused-sync bucket cap
    that minimizes predicted sync time for ``nbytes`` of gradients.

    With ``k`` buckets the sync pays the per-collective fixed overhead
    (launch + per-hop latency + control — every byte-independent term of
    :func:`allreduce_cost`) ``k`` times, while consecutive buckets give the
    compiler pipelining slack: bucket ``i``'s phase-2 allgather can overlap
    bucket ``i+1``'s phase-1 reduce-scatter, which at the model level turns
    the byte-proportional terms from ``B`` into ``B * (k+1) / (2k)`` (the
    classic α-β chunking tradeoff — arXiv:2409.04202's latency-vs-bandwidth
    decomposition; perfect overlap halves the exposed byte time as k grows).
    So

        T(k) = k * fixed + byte_terms(nbytes) * (k + 1) / (2 * k)

    is evaluated for ``k`` in 1..min(max_buckets, n_leaves) and the argmin's
    ``ceil(nbytes / k)`` is returned.  ``topos`` is one resolved
    ``Topology`` (or a sequence of them, one per replication axis the sync
    loops over — the fixed and byte terms then sum across axes).  ``params``
    defaults to the calibrated constants (``FLEXTREE_CALIBRATION``) like
    every other chooser entry point; on hosts where calibration measured a
    large launch overhead the argmin lands on few, large buckets, and on
    fabrics where bandwidth dominates it shrinks them toward the pipelined
    regime.  Interior optimum: ``dT/dk = 0`` at ``k* = sqrt(byte/(2*fixed))``.
    """
    if nbytes < 0:
        raise ValueError(f"nbytes must be >= 0, got {nbytes}")
    if params is None:
        from .calibrate import default_params

        params = default_params()
    topo_list = (
        [topos] if isinstance(topos, (Topology, LonelyTopology)) else list(topos)
    )
    if not topo_list:
        raise ValueError("choose_bucket_bytes needs at least one topology")
    if nbytes == 0:
        return 1

    def cost(t, nb):
        if isinstance(t, LonelyTopology):
            return lonely_allreduce_cost(t.tree, t.lonely, nb, params, codec=codec)
        return allreduce_cost(t, nb, params, codec=codec)

    def sharded_cost(nb):
        # the ZeRO split schedule per bucket: grad reduce-scatter + param
        # all-gather on the FIRST (shard) topology, shard-sized allreduce
        # on the rest — cost_model.sharded_sync_cost prices exactly the
        # collectives zero_sync_and_update issues
        first = topo_list[0]
        shard_topo = (
            Topology.flat(first.num_nodes)
            if isinstance(first, LonelyTopology)
            else first
        )
        return sharded_sync_cost(
            shard_topo, nb, params, codec=codec,
            secondary_topos=tuple(
                Topology.flat(t.num_nodes) if isinstance(t, LonelyTopology) else t
                for t in topo_list[1:]
            ),
        )

    fixed = byte_us = 0.0
    if sharded:
        fixed = sharded_cost(0).total_us
        full = sharded_cost(nbytes)
        byte_us = full.bandwidth_us + full.reduce_us + full.codec_us
    else:
        for t in topo_list:
            fixed += cost(t, 0).total_us
            full = cost(t, nbytes)
            # codec_us is byte-proportional (encode/decode passes), so a
            # compressed sync amortizes it across buckets exactly like
            # bandwidth — the argmin shifts toward fewer, larger buckets as
            # the wire gets cheaper relative to the fixed launch cost
            byte_us += full.bandwidth_us + full.reduce_us + full.codec_us
    k_max = max(1, min(max_buckets, n_leaves or max_buckets))
    best_k, best_t = 1, float("inf")
    for k in range(1, k_max + 1):
        t_k = k * fixed + byte_us * (k + 1) / (2 * k)
        if t_k < best_t:
            best_k, best_t = k, t_k
    return -(-nbytes // best_k)  # ceil


#: Wire pessimism band for the overlap boundary argmin: candidate
#: partitions are scored by the sum of predicted makespans with comm
#: scaled by each factor.  1x is the calibrated capability estimate; the
#: inflated points model in-step contention (collectives share memory
#: bandwidth and cores with the backward), which hurts late-firing plans
#: far more than early-firing ones.
WIRE_PESSIMISM_BAND = (1.0, 2.0, 4.0)


def overlap_comm_us(
    nbytes: int,
    topos,
    params: TpuCostParams | None = None,
    codec=None,
) -> float:
    """Predicted wall time (µs) of ONE fired overlap bucket of ``nbytes``:
    one allreduce sequence per replication-axis topology in ``topos``
    (launch + wire + reduce + codec terms, summed across axes) — the unit
    the boundary chooser's wire-serial schedule model is built from."""
    if params is None:
        from .calibrate import default_params

        params = default_params()
    topo_list = (
        [topos] if isinstance(topos, (Topology, LonelyTopology)) else list(topos)
    )
    total = 0.0
    for t in topo_list:
        if isinstance(t, LonelyTopology):
            total += lonely_allreduce_cost(
                t.tree, t.lonely, nbytes, params, codec=codec
            ).total_us
        else:
            total += allreduce_cost(t, nbytes, params, codec=codec).total_us
    return total


def predict_overlap_schedule(
    boundaries,
    seg_bytes,
    seg_compute_us,
    topos,
    params: TpuCostParams | None = None,
    codec=None,
) -> tuple[float, float]:
    """(total_us, exposed_us) of a readiness-ordered overlap schedule.

    Model: backward segments run in readiness order (segment ``i`` of
    ``seg_compute_us`` finishes at ``cum[i]``); a bucket — a group of
    consecutive segment indices in ``boundaries`` — is *issued* when its
    last segment's grads exist, and the wire is serial: a bucket's
    collective starts at ``max(issue_time, wire_free)`` and holds the wire
    for its :func:`overlap_comm_us`.  ``total`` is when the last collective
    drains; ``exposed = total - total_backward_compute`` is the sync time
    NOT hidden behind remaining backward compute — the quantity the
    train-step bench measures as the step-time delta over a sync-free
    step.  The last bucket always issues at backward end, so its comm is
    always exposed: overlap shrinks exposure, never to zero.
    """
    if params is None:
        from .calibrate import default_params

        params = default_params()
    cum = [0.0]
    for c in seg_compute_us:
        cum.append(cum[-1] + float(c))
    wire_free = 0.0
    for bucket in boundaries:
        nbytes = sum(seg_bytes[i] for i in bucket)
        issue = cum[bucket[-1] + 1]
        start = max(issue, wire_free)
        wire_free = start + overlap_comm_us(nbytes, topos, params, codec)
    total = max(cum[-1], wire_free)
    return total, total - cum[-1]


def choose_overlap_boundaries(
    seg_bytes,
    seg_compute_us,
    topos,
    *,
    params: TpuCostParams | None = None,
    codec=None,
    max_enum_segments: int = 12,
) -> tuple[tuple[int, ...], ...]:
    """Compute-equalized bucket boundaries for readiness-ordered overlap.

    ``seg_bytes[i]`` / ``seg_compute_us[i]`` describe backward segment
    ``i`` in READINESS order (loss head first, then layers last-to-first,
    then the embedding, whose grad completes only at backward end).  The
    returned boundaries partition ``range(len(seg_bytes))`` into
    consecutive groups; each group syncs as one fired bucket (one
    allreduce sequence per replication axis).

    This is NOT ``choose_bucket_bytes``'s sync-time argmin: a bucket here
    trades the launch amortization of growing against the *hiding budget*
    of closing early — a bucket that closes after segment ``j`` can hide
    its wire time under the backward compute of segments ``j+1..``, so the
    chooser equalizes each bucket's predicted comm against the remaining
    compute below it by minimizing the :func:`predict_overlap_schedule`
    makespan.  Robustness to wire-model error: the calibrated wire
    constants are a capability estimate, and IN-STEP comm is slower
    (collectives contend with the backward for memory bandwidth and
    cores) — an error that punishes asymmetrically, because an
    underestimated wire makes a late-firing plan queue its whole tail
    past backward end while an early-firing plan just hides less.  The
    argmin therefore scores each candidate partition by the SUM of its
    predicted makespans under a pessimism band (comm scaled by
    :data:`WIRE_PESSIMISM_BAND`), which biases near-ties toward earlier
    firing; ties break toward fewer buckets (launch amortization).  Up
    to ``max_enum_segments`` segments every contiguous partition is
    enumerated exactly (span comm costs memoized, so this is a few
    thousand table lookups); beyond that a greedy pass closes a bucket as
    soon as extending it would push its comm past the remaining-compute
    hiding budget.
    """
    if params is None:
        from .calibrate import default_params

        params = default_params()
    s = len(seg_bytes)
    if s == 0:
        return ()
    if len(seg_compute_us) != s:
        raise ValueError(
            f"seg_bytes has {s} segments, seg_compute_us {len(seg_compute_us)}"
        )
    if s == 1:
        return ((0,),)

    # memoize comm cost per contiguous span [i, j]
    span_us: dict[tuple[int, int], float] = {}
    for i in range(s):
        nbytes = 0
        for j in range(i, s):
            nbytes += seg_bytes[j]
            span_us[(i, j)] = overlap_comm_us(nbytes, topos, params, codec)

    cum = [0.0]
    for c in seg_compute_us:
        cum.append(cum[-1] + float(c))

    def simulate(bounds, scale: float = 1.0) -> tuple[float, float]:
        wire_free = 0.0
        for i, j in bounds:
            start = max(cum[j + 1], wire_free)
            wire_free = start + scale * span_us[(i, j)]
        total = max(cum[-1], wire_free)
        return total, total - cum[-1]

    if s <= max_enum_segments:
        best = None
        # a partition of s segments = a subset of the s-1 interior cuts
        for mask in range(1 << (s - 1)):
            bounds = []
            start = 0
            for cut in range(s - 1):
                if mask >> cut & 1:
                    bounds.append((start, cut))
                    start = cut + 1
            bounds.append((start, s - 1))
            score = sum(
                simulate(bounds, scale)[0] for scale in WIRE_PESSIMISM_BAND
            )
            key = (score, len(bounds))
            if best is None or key < best[0]:
                best = (key, bounds)
        bounds = best[1]
    else:
        # greedy fallback (> max_enum_segments): close a bucket as soon
        # as it has amortized its fixed launch cost — early firing is the
        # robust default (see the pessimism rationale above) and a bucket
        # only grows while launches still dominate its wire time.  Two
        # boundary conditions mirror the exhaustive path's limits: while
        # hiding budget remains (compute left below the close), fire
        # amortized buckets eagerly; once none remains (the unhideable
        # tail) stop splitting entirely — every further cut would add a
        # fully-exposed launch for nothing.
        fixed_us = overlap_comm_us(0, topos, params, codec)
        bounds = []
        start = 0
        for j in range(s - 1):
            remaining_after_next = cum[-1] - cum[j + 2]
            if (
                remaining_after_next > 0
                and span_us[(start, j)] >= 4.0 * fixed_us
            ):
                bounds.append((start, j))
                start = j + 1
        bounds.append((start, s - 1))
    return tuple(tuple(range(i, j + 1)) for i, j in bounds)


def replan_for_survivors(
    n_alive: int,
    nbytes: int,
    params: TpuCostParams | None = None,
    configured: int | None = None,
) -> Plan:
    """Degrade-to-survivors replanning: the cheapest *executable* topology
    for the ranks that actually joined (docs/FAILURE_MODEL.md §replanning).

    When a configured world never assembles (a host never joins before the
    bring-up deadline, ``parallel.launch.init_distributed_or_degrade``),
    the job can run on the survivors instead of aborting — but the planned
    topology no longer fits: widths must factor ``n_alive``, not the
    configured count.  This re-runs the chooser for ``n_alive``; awkward
    survivor counts get real shapes because the candidate table already
    includes the ring and, for prime counts, executable lonely ``+1``
    topologies (7 of 8 alive runs ``3,2+1`` rather than idling a rank).

    Survivor worlds are priced fabric-uniform (no ``mesh_shape``): losing
    arbitrary ranks breaks torus alignment, so axis-exact costing would be
    optimistic about shapes that no longer tile anything.

    ``configured``: the originally requested world size — recorded in the
    plan's advisory so artifacts show the degradation.
    """
    if n_alive < 1:
        raise ValueError(f"n_alive must be >= 1, got {n_alive}")
    if configured is not None and n_alive > configured:
        raise ValueError(
            f"n_alive {n_alive} exceeds the configured world {configured}"
        )
    plan = choose_topology(n_alive, nbytes, params=params)
    if configured is not None and n_alive < configured:
        note = (
            f"DEGRADED WORLD: {n_alive}/{configured} ranks alive; "
            f"replanned to topo {plan.to_ft_topo()}"
        )
        plan = dataclasses.replace(plan, advisory=(note,) + plan.advisory)
    return plan
