"""Data pipeline: deterministic token batching with device prefetch.

The reference is a collectives library with no input pipeline; a training
framework needs one, so this module provides the minimal TPU-correct
version: a sliding-window language-modeling dataset over a flat token
array (memory-mappable), deterministic per-epoch shuffling (seeded,
resumable from any step), and a background-thread prefetcher that keeps
the next batches in flight so the host never stalls the device step loop.

Determinism contract: ``batch_at(step)`` is a pure function of
``(tokens, batch, seq_len, seed, step)`` — resuming a run at step k
produces exactly the batches a straight-through run would see, which is
what makes checkpoint/resume training bitwise-reproducible end to end
(pinned with the trainer tests).
"""

from __future__ import annotations

import queue
import threading

import numpy as np

__all__ = ["LMDataset", "synthetic_tokens", "prefetch"]


def synthetic_tokens(n: int, vocab_size: int, seed: int = 0) -> np.ndarray:
    """A deterministic pseudo-corpus with local structure (not iid noise):
    a random walk over the vocabulary, so a model can actually learn."""
    rng = np.random.default_rng(seed)
    steps = rng.integers(-3, 4, size=n)
    walk = np.cumsum(steps) + vocab_size // 2
    return np.mod(walk, vocab_size).astype(np.int32)


class LMDataset:
    """Sliding-window next-token-prediction batches over a token array.

    Windows of length ``seq_len + 1`` start every ``seq_len`` tokens
    (non-overlapping targets); each epoch visits every window once in a
    seeded shuffled order.  ``batch_at(step)`` indexes the infinite
    epoch-concatenated stream, so any step is addressable directly.
    """

    def __init__(self, tokens: np.ndarray, batch: int, seq_len: int,
                 seed: int = 0):
        tokens = np.asarray(tokens)
        if tokens.ndim != 1:
            raise ValueError(f"tokens must be 1-D, got {tokens.shape}")
        self.tokens = tokens
        self.batch = batch
        self.seq_len = seq_len
        self.seed = seed
        self.num_windows = (tokens.size - 1) // seq_len
        if self.num_windows < batch:
            raise ValueError(
                f"{tokens.size} tokens give {self.num_windows} windows of "
                f"seq_len={seq_len}; need at least batch={batch}"
            )
        self.batches_per_epoch = self.num_windows // batch
        self._order_cache: tuple[int, np.ndarray] | None = None

    def _epoch_order(self, epoch: int) -> np.ndarray:
        # memoized: the permutation is O(num_windows) to build and batch_at
        # is called once per training step within the same epoch
        if self._order_cache is None or self._order_cache[0] != epoch:
            rng = np.random.default_rng((self.seed, epoch))
            self._order_cache = (epoch, rng.permutation(self.num_windows))
        return self._order_cache[1]

    def batch_at(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        """(tokens, targets), each (batch, seq_len) int32, for ``step``."""
        epoch, within = divmod(step, self.batches_per_epoch)
        order = self._epoch_order(epoch)
        idx = order[within * self.batch : (within + 1) * self.batch]
        starts = idx * self.seq_len
        windows = np.stack(
            [self.tokens[s : s + self.seq_len + 1] for s in starts]
        ).astype(np.int32)
        return windows[:, :-1], windows[:, 1:]

    def iter_from(self, step: int = 0):
        """Infinite iterator of batches starting at ``step``."""
        while True:
            yield self.batch_at(step)
            step += 1


def prefetch(iterator, size: int = 2):
    """Pull ``size`` items ahead on a daemon thread.

    The consumer's next item is already materialized (and, for device
    arrays, already transferring) while the current step runs — the
    host-side analog of the double-buffered DMAs the Pallas kernels use
    on-chip.  Exceptions from the source re-raise at the consumer.
    """
    q: queue.Queue = queue.Queue(maxsize=size)
    stop = threading.Event()
    _END = object()

    def put(item) -> bool:
        """Blocking put that aborts when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for item in iterator:
                if not put(item):
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised at consumer
            put((_END, e))
            return
        put((_END, None))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, tuple) and len(item) == 2 and item[0] is _END:
                if item[1] is not None:
                    raise item[1]
                return
            yield item
    finally:
        # consumer abandoned us (break / close / error): release the worker
        stop.set()
