"""Chip-lease protocol: arbiter-published grants on the heartbeat dir.

The heartbeat directory already carries the runtime's only cross-process
truths — per-rank liveness beats, atomically replaced, wall-stamped.
Leases ride the same transport: the **arbiter** (``flextree_tpu.arbiter``)
is the single writer of one ``lease_ledger.json`` naming, per holder
(``"train"`` / ``"serve"``), exactly which chips that holder may use at
which **epoch**; every holder polls the ledger and, when the epoch moved,
applies the new grant and writes an ``lease_ack_{holder}.json`` naming
the epoch it now runs under.  The handshake is the whole protocol:

1. the arbiter revokes chips from a holder by publishing epoch ``E`` with
   a smaller grant (the revoked chips are parked on the ``"arbiter"``
   holder — granted to nobody while in flight);
2. the holder sees ``E``, stops using the revoked chips (training:
   checkpoint-now + shrink-to-survivors rebuild — the SIGTERM-preemption
   path, arbiter-triggered), and **acks** ``E``;
3. only after the ack does the arbiter publish ``E+1`` granting those
   chips to the other holder — a chip is never promised to two holders,
   because the grant that takes it away is acknowledged before the grant
   that hands it on exists.

Every write is atomic (tmp + ``os.replace``, the beat-file discipline),
so a reader never sees a torn ledger; a mid-rewrite crash leaves the
previous epoch, which is always a consistent assignment.  The files are
human-readable JSON — ``cat $FT_HB_DIR/lease_ledger.json`` IS the
debugging story.

:class:`TrainLeaseClient` is training's side: the handle
``parallel.loop.fit(arbiter=...)`` polls every loop iteration (throttled
to ``poll_interval_s`` — a file read per step would be rude) and turns an
epoch move into a :class:`ResizeDirective` the loop applies through the
same checkpoint → rebuild → restore machinery the shrink path proved.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable

from ..utils.logging import get_logger
from .ctrlfile import read_control_json, write_control_json

__all__ = [
    "LEASE_FILE",
    "TRAIN",
    "SERVE",
    "ARBITER",
    "LeaseGrant",
    "LeaseLedger",
    "ResizeDirective",
    "ServeDirective",
    "ServeLeaseClient",
    "TrainLeaseClient",
]

log = get_logger("flextree.runtime")

LEASE_FILE = "lease_ledger.json"
_ACK_FMT = "lease_ack_{holder}.json"

# holder names: the two tenants plus the arbiter's own parking slot for
# chips mid-handoff (revoked from one holder, not yet granted to the other)
TRAIN, SERVE, ARBITER = "train", "serve", "arbiter"

# injection point for tests (patch this, not time.time): lease files are
# read across processes, so stamps are wall time like heartbeat beats
_wall = time.time


@dataclasses.dataclass(frozen=True)
class LeaseGrant:
    """One published ledger state: who holds which chips, at which epoch.

    ``grants`` maps holder → a sorted tuple of chip ids.  ``reason`` is
    forensic (what SLO reading drove the change); ``wall`` stamps when it
    was published."""

    epoch: int
    grants: dict
    wall: float
    reason: str = ""

    def chips(self, holder: str) -> tuple:
        return tuple(self.grants.get(holder, ()))

    def to_payload(self) -> dict:
        return {
            "epoch": self.epoch,
            "grants": {h: list(c) for h, c in sorted(self.grants.items())},
            "wall": self.wall,
            "reason": self.reason,
        }


class LeaseLedger:
    """The lease file pair on a heartbeat dir: single-writer publish
    (the arbiter), any-reader poll, per-holder acks.

    The ledger itself enforces only the mechanics (atomicity, epoch
    monotonicity, ack bookkeeping); *policy* — who loses chips when —
    lives in :class:`flextree_tpu.arbiter.PoolArbiter`."""

    def __init__(self, dir: str):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.dir, LEASE_FILE)

    def _ack_path(self, holder: str) -> str:
        return os.path.join(self.dir, _ACK_FMT.format(holder=holder))

    # ---- writer side (the arbiter) ----------------------------------------

    def publish(self, epoch: int, grants: dict, reason: str = "") -> LeaseGrant:
        """Atomically publish a new ledger state.  Epochs must strictly
        increase — a replayed or reordered publish is a protocol bug, not
        a race to smooth over."""
        cur = self.read()
        if cur is not None and epoch <= cur.epoch:
            raise ValueError(
                f"lease epoch must increase: {epoch} <= published {cur.epoch}"
            )
        seen: dict = {}
        for holder, chips in grants.items():
            for c in chips:
                if c in seen:
                    raise ValueError(
                        f"chip {c!r} granted to both {seen[c]!r} and "
                        f"{holder!r} at epoch {epoch}"
                    )
                seen[c] = holder
        grant = LeaseGrant(
            epoch=int(epoch),
            grants={h: tuple(sorted(c)) for h, c in grants.items()},
            wall=_wall(),
            reason=reason,
        )
        # CRC-trailered write (runtime.ctrlfile): a torn ledger must
        # parse-refuse on every reader, never half-parse as a grant
        write_control_json(self.dir, self.path, grant.to_payload())
        return grant

    # ---- reader side (every holder) ---------------------------------------

    def read(self) -> LeaseGrant | None:
        """The current ledger state (None before the first publish; a
        torn/garbage file parse-refuses to None too — the CRC trailer
        makes truncation at any byte offset detectable, and the replace
        discipline makes a mismatch transient)."""
        doc = read_control_json(self.path)
        if doc is None:
            return None
        try:
            return LeaseGrant(
                epoch=int(doc["epoch"]),
                grants={h: tuple(c) for h, c in doc["grants"].items()},
                wall=float(doc.get("wall", 0.0)),
                reason=str(doc.get("reason", "")),
            )
        except (ValueError, KeyError, TypeError, AttributeError):
            return None

    def ack(self, holder: str, epoch: int, control_epoch: int | None = None) -> None:
        """Record that ``holder`` now runs under ``epoch``'s grant.

        ``control_epoch`` (optional) names the coordination-protocol epoch
        the holder applied the grant under (``runtime.coordination``) —
        the fencing breadcrumb proving a multi-process tenant never acks
        a lease it did not group-apply."""
        payload = {"holder": holder, "epoch": int(epoch), "wall": _wall()}
        if control_epoch is not None:
            payload["control_epoch"] = int(control_epoch)
        write_control_json(self.dir, self._ack_path(holder), payload)

    def read_ack(self, holder: str) -> dict | None:
        """``holder``'s newest ack document, or None (never acked / torn
        — parse-refuses instead of raising on the arbiter thread).  One
        read serves both the epoch and the control-epoch stamp, so a
        caller never pairs fields from two different ack versions."""
        return read_control_json(self._ack_path(holder))

    def acked_epoch(self, holder: str) -> int:
        """The newest epoch ``holder`` acknowledged (-1: never acked)."""
        doc = self.read_ack(holder)
        try:
            return int(doc["epoch"]) if doc is not None else -1
        except (ValueError, KeyError, TypeError):
            return -1

    def acked_control_epoch(self, holder: str) -> int | None:
        """The coordination epoch stamped on ``holder``'s newest ack, when
        the tenant runs under the coordination protocol (None otherwise)."""
        doc = self.read_ack(holder)
        if doc is None:
            return None
        ce = doc.get("control_epoch")
        return int(ce) if ce is not None else None


@dataclasses.dataclass(frozen=True)
class ResizeDirective:
    """A grant change training has not applied yet: the new chip set and
    the ledger epoch to acknowledge once the rebuild lands.

    ``control_epoch`` names the coordination-protocol epoch that committed
    this resize (``runtime.coordination``) — set only when the tenant is a
    multi-process group, in which case the directive can ONLY come from a
    committed group decision and the lease ack is fenced on it.  ``topo``
    is the coordinator's replanned FT_TOPO spec for the new chip count,
    broadcast so every rank applies THE SAME plan (the same override the
    shrink commit carries)."""

    epoch: int
    chips: tuple
    reason: str = ""
    control_epoch: int | None = None
    topo: str | None = None

    @property
    def n(self) -> int:
        return len(self.chips)


class TrainLeaseClient:
    """Training's lease handle — what ``fit(arbiter=...)`` polls.

    ``on_resize(chips, plan)`` is the rebuild hook, the resize twin of
    ``Supervision.on_shrink``: return ``None`` to keep the current step
    (world-size-agnostic steps), a ``(step_fn, mesh, state_specs)``
    3-tuple, or the re-shard path's 5-tuple with checkpoint-layout
    converters for the new world.  ``configured`` is the full-inventory
    grant size (prices the replan; defaults to the largest grant seen).

    The client is deliberately dumb: it reports grant CHANGES and acks
    what the loop applied.  All sequencing safety lives in the ledger
    handshake — the arbiter cannot hand our revoked chips to serving
    until our ack exists, so a slow rebuild stretches the handoff instead
    of racing it.

    ``coordination`` (optional): a
    :class:`~flextree_tpu.runtime.CoordinationHandle` when this tenant is
    a multi-process group.  A grant change then never becomes a directive
    directly — the group's coordinator PROPOSES a ``"resize"`` decision
    and every rank applies it through the committed control epoch
    (``fit``'s coordination gate), so no rank can resize alone.  The
    lease ack is fenced: :meth:`ack` refuses a directive that does not
    carry the committed control epoch, which is exactly "a cross-process
    tenant can never ack an epoch it didn't apply".
    """

    def __init__(
        self,
        ledger: LeaseLedger,
        *,
        holder: str = TRAIN,
        on_resize: Callable | None = None,
        initial_chips=None,
        configured: int | None = None,
        nbytes_hint: int = 4 << 20,
        poll_interval_s: float = 0.2,
        coordination=None,
        _mono=time.monotonic,
    ):
        self.ledger = ledger
        self.holder = holder
        self.on_resize = on_resize
        self.configured = configured
        self.nbytes_hint = nbytes_hint
        self.poll_interval_s = float(poll_interval_s)
        self.coordination = coordination
        self._proposed_lease_epoch = -1
        self._mono = _mono
        self._next_poll = 0.0
        self._applied_epoch = -1
        # the grant the step was BUILT for.  Pass it whenever you know it
        # (the builders do): with it, a first poll that reads a smaller
        # grant — an early revocation, or a restart mid-handoff against
        # the persistent heartbeat dir — is a resize directive like any
        # other.  Without it, the first observation is trusted as the
        # build world (convenience for tests and single-epoch runs).
        self._chips: tuple | None = (
            tuple(sorted(initial_chips)) if initial_chips is not None
            else None
        )

    def poll(self, step: int) -> ResizeDirective | None:
        """A pending grant change, or None.  Throttled file read; an
        epoch whose chip set matches what we already run is acked in
        place (e.g. the publish that granted OUR former chips to serving
        — our slice did not change again)."""
        now = self._mono()
        if now < self._next_poll:
            return None
        self._next_poll = now + self.poll_interval_s
        grant = self.ledger.read()
        if grant is None or grant.epoch <= self._applied_epoch:
            return None
        chips = grant.chips(self.holder)
        if self._chips is None:
            # first observation: adopt the current grant as the world we
            # were built for (the builder sized the mesh from it)
            self._adopt(grant.epoch, chips)
            return None
        if chips == self._chips:
            self._adopt(grant.epoch, chips)  # epoch moved, our slice didn't
            return None
        if self.configured is not None:
            self.configured = max(self.configured, len(chips))
        if self.coordination is not None:
            # group tenant: the observation is not authority.  The
            # coordinator turns it into a propose→ack→commit decision;
            # every rank (this one included) receives the directive from
            # the committed control epoch via fit's coordination gate.
            # Followers return straight away — building the payload
            # costs a planner solve, and only the coordinator's
            # proposal can land.
            if (
                self._proposed_lease_epoch < grant.epoch
                and self.coordination.is_coordinator
            ):
                payload = {
                    "lease_epoch": grant.epoch,
                    "chips": list(chips),
                    "reason": grant.reason,
                }
                if chips:
                    # broadcast the coordinator's replanned topology so
                    # every rank applies THE SAME plan (a skewed local
                    # calibration must not split the group — the same
                    # override the shrink commit carries)
                    from ..planner.choose import replan_for_survivors

                    configured = max(self.configured or len(chips), len(chips))
                    payload["topo"] = replan_for_survivors(
                        len(chips), self.nbytes_hint, configured=configured
                    ).to_ft_topo()
                proposed = self.coordination.propose(
                    "resize",
                    payload,
                    # one agreed boundary: on a shared-wire tenant a rank
                    # rebuilding to the new chip plan while a peer still
                    # steps the old one is a collective mismatch — the
                    # same reason coordinated replans name a boundary
                    apply_step=self.coordination.suggest_apply_step(),
                )
                if proposed is not None:
                    self._proposed_lease_epoch = grant.epoch
            return None
        return ResizeDirective(
            epoch=grant.epoch, chips=chips, reason=grant.reason
        )

    def _adopt(
        self, epoch: int, chips: tuple, control_epoch: int | None = None
    ) -> None:
        self._applied_epoch = epoch
        self._chips = chips
        if self.configured is None or len(chips) > self.configured:
            self.configured = len(chips)
        self.ledger.ack(self.holder, epoch, control_epoch=control_epoch)

    def ack(self, directive: ResizeDirective) -> None:
        """The loop applied ``directive`` (checkpointed, rebuilt,
        restored): acknowledge the epoch so the arbiter may hand the
        revoked chips on.

        Fenced under coordination: a group tenant's directive must carry
        the control epoch that committed it — an ack for a lease epoch
        this rank did not group-apply is refused loudly, never written."""
        if self.coordination is not None and directive.control_epoch is None:
            from .coordination import ProtocolViolation

            raise ProtocolViolation(
                f"lease epoch {directive.epoch} acked without a committed "
                "control epoch — a coordinated tenant may only ack resizes "
                "it applied through the group protocol"
            )
        self._adopt(
            directive.epoch, directive.chips,
            control_epoch=directive.control_epoch,
        )

    @property
    def chips(self) -> tuple:
        return self._chips or ()


@dataclasses.dataclass(frozen=True)
class ServeDirective:
    """A serving-grant change the fleet has not applied yet: the new chip
    set, split into what was gained and what was revoked relative to the
    fleet the manager currently runs.

    ``revoked`` chips carry the hard sequencing rule of the whole
    protocol: the manager must DRAIN the replicas on them (SIGTERM →
    drain-refusals → exit) before the epoch may be acked, because the ack
    is what releases those chips onward to training.  ``control_epoch``
    mirrors training's fencing: a coordinated (multi-process) serving
    tenant may only ack epochs it group-applied."""

    epoch: int
    chips: tuple
    added: tuple = ()
    revoked: tuple = ()
    reason: str = ""
    control_epoch: int | None = None

    @property
    def n(self) -> int:
        return len(self.chips)


class ServeLeaseClient:
    """Serving's lease handle — the :class:`TrainLeaseClient` twin.

    Where training's "apply" is a checkpoint → mesh rebuild → restore,
    serving's is a real-process fleet change: ``on_grant(chips)`` spawns
    a warmed ``replica_main.py`` process per gained chip (its endpoint
    file registers it with the front door), ``on_revoke(chips)``
    SIGTERM-drains the replicas on the revoked chips and returns only
    once the drain completed (every queued/in-flight request answered
    with a drain refusal the front door re-routes exactly-once).

    The ack is double-fenced:

    - **drain fence** — ``inflight`` (optional callable → the number of
      requests still in flight on the revoked replicas) is consulted at
      :meth:`ack`; a revocation acked while requests are in flight is a
      :class:`~flextree_tpu.runtime.coordination.ProtocolViolation`,
      never a written ack.  This is the real-code twin of the lease
      model's ``serve-ack-before-drain`` mutation — the ledger handshake
      only protects chips if "acked" implies "no longer using them".
    - **control-epoch fence** — exactly like training's: a coordinated
      tenant's directive must carry the committed control epoch, or the
      ack is refused loudly.

    The client never spawns or signals anything itself — sequencing
    lives here, process mechanics live in the hooks — so tests can bind
    it to the protocol model with toy hooks and the chaos driver can
    bind the same object to real processes.
    """

    def __init__(
        self,
        ledger: LeaseLedger,
        *,
        holder: str = SERVE,
        on_grant: Callable | None = None,
        on_revoke: Callable | None = None,
        inflight: Callable | None = None,
        initial_chips=None,
        poll_interval_s: float = 0.2,
        coordination=None,
        _mono=time.monotonic,
    ):
        self.ledger = ledger
        self.holder = holder
        self.on_grant = on_grant
        self.on_revoke = on_revoke
        self.inflight = inflight
        self.poll_interval_s = float(poll_interval_s)
        self.coordination = coordination
        self._mono = _mono
        self._next_poll = 0.0
        self._applied_epoch = -1
        # the fleet the manager actually runs.  Pass it whenever you know
        # it (a restarted manager reconciling against live replica
        # processes does): with it, a first poll that reads a different
        # grant — a revoke published while we were down, a restart
        # mid-handoff — is a directive like any other.  Without it, the
        # first observation is trusted as the running fleet.
        self._chips: tuple | None = (
            tuple(sorted(initial_chips)) if initial_chips is not None
            else None
        )

    def poll(self) -> ServeDirective | None:
        """A pending grant change, or None.  Throttled file read; an
        epoch whose chip set matches the running fleet is acked in place
        (e.g. the publish that returned OUR former chips to training —
        our slice did not change again)."""
        now = self._mono()
        if now < self._next_poll:
            return None
        self._next_poll = now + self.poll_interval_s
        grant = self.ledger.read()
        if grant is None or grant.epoch <= self._applied_epoch:
            return None
        chips = grant.chips(self.holder)
        if self._chips is None:
            self._adopt(grant.epoch, chips)
            return None
        if chips == self._chips:
            self._adopt(grant.epoch, chips)  # epoch moved, our slice didn't
            return None
        cur = set(self._chips)
        new = set(chips)
        return ServeDirective(
            epoch=grant.epoch,
            chips=chips,
            added=tuple(sorted(new - cur)),
            revoked=tuple(sorted(cur - new)),
            reason=grant.reason,
        )

    def apply(self, directive: ServeDirective) -> None:
        """Drive one directive end to end in protocol order: drain the
        revoked replicas FIRST (the ack below is what releases their
        chips onward), then spawn onto the gained chips, then ack."""
        if directive.revoked and self.on_revoke is not None:
            self.on_revoke(directive.revoked)
        if directive.added and self.on_grant is not None:
            self.on_grant(directive.added)
        self.ack(directive)

    def _adopt(
        self, epoch: int, chips: tuple, control_epoch: int | None = None
    ) -> None:
        self._applied_epoch = epoch
        self._chips = chips
        self.ledger.ack(self.holder, epoch, control_epoch=control_epoch)

    def ack(self, directive: ServeDirective) -> None:
        """The fleet now matches ``directive``: acknowledge the epoch so
        the arbiter may hand the revoked chips on.  Refuses loudly — no
        ack is written — if requests are still in flight on a revocation
        (the drain fence) or, under coordination, if the directive does
        not carry the committed control epoch."""
        from .coordination import ProtocolViolation

        if self.coordination is not None and directive.control_epoch is None:
            raise ProtocolViolation(
                f"lease epoch {directive.epoch} acked without a committed "
                "control epoch — a coordinated tenant may only ack resizes "
                "it applied through the group protocol"
            )
        if directive.revoked and self.inflight is not None:
            n = int(self.inflight())
            if n > 0:
                raise ProtocolViolation(
                    f"lease epoch {directive.epoch} revokes chips "
                    f"{list(directive.revoked)} but {n} request(s) are "
                    "still in flight — acking now would release the chips "
                    "while replicas are mid-request (serve-ack-before-"
                    "drain); drain first"
                )
        self._adopt(
            directive.epoch, directive.chips,
            control_epoch=directive.control_epoch,
        )

    @property
    def chips(self) -> tuple:
        return self._chips or ()
