"""Chip-lease protocol: arbiter-published grants on the heartbeat dir.

The heartbeat directory already carries the runtime's only cross-process
truths — per-rank liveness beats, atomically replaced, wall-stamped.
Leases ride the same transport: the **arbiter** (``flextree_tpu.arbiter``)
is the single writer of one ``lease_ledger.json`` naming, per holder
(``"train"`` / ``"serve"``), exactly which chips that holder may use at
which **epoch**; every holder polls the ledger and, when the epoch moved,
applies the new grant and writes an ``lease_ack_{holder}.json`` naming
the epoch it now runs under.  The handshake is the whole protocol:

1. the arbiter revokes chips from a holder by publishing epoch ``E`` with
   a smaller grant (the revoked chips are parked on the ``"arbiter"``
   holder — granted to nobody while in flight);
2. the holder sees ``E``, stops using the revoked chips (training:
   checkpoint-now + shrink-to-survivors rebuild — the SIGTERM-preemption
   path, arbiter-triggered), and **acks** ``E``;
3. only after the ack does the arbiter publish ``E+1`` granting those
   chips to the other holder — a chip is never promised to two holders,
   because the grant that takes it away is acknowledged before the grant
   that hands it on exists.

Every write is atomic (tmp + ``os.replace``, the beat-file discipline),
so a reader never sees a torn ledger; a mid-rewrite crash leaves the
previous epoch, which is always a consistent assignment.  The files are
human-readable JSON — ``cat $FT_HB_DIR/lease_ledger.json`` IS the
debugging story.

:class:`TrainLeaseClient` is training's side: the handle
``parallel.loop.fit(arbiter=...)`` polls every loop iteration (throttled
to ``poll_interval_s`` — a file read per step would be rude) and turns an
epoch move into a :class:`ResizeDirective` the loop applies through the
same checkpoint → rebuild → restore machinery the shrink path proved.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
from typing import Callable

from ..utils.logging import get_logger

__all__ = [
    "LEASE_FILE",
    "TRAIN",
    "SERVE",
    "ARBITER",
    "LeaseGrant",
    "LeaseLedger",
    "ResizeDirective",
    "TrainLeaseClient",
]

log = get_logger("flextree.runtime")

LEASE_FILE = "lease_ledger.json"
_ACK_FMT = "lease_ack_{holder}.json"

# holder names: the two tenants plus the arbiter's own parking slot for
# chips mid-handoff (revoked from one holder, not yet granted to the other)
TRAIN, SERVE, ARBITER = "train", "serve", "arbiter"

# injection point for tests (patch this, not time.time): lease files are
# read across processes, so stamps are wall time like heartbeat beats
_wall = time.time


def _atomic_write_json(dir: str, path: str, payload: dict) -> None:
    fd, tmp = tempfile.mkstemp(dir=dir, suffix=".lease.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


@dataclasses.dataclass(frozen=True)
class LeaseGrant:
    """One published ledger state: who holds which chips, at which epoch.

    ``grants`` maps holder → a sorted tuple of chip ids.  ``reason`` is
    forensic (what SLO reading drove the change); ``wall`` stamps when it
    was published."""

    epoch: int
    grants: dict
    wall: float
    reason: str = ""

    def chips(self, holder: str) -> tuple:
        return tuple(self.grants.get(holder, ()))

    def to_payload(self) -> dict:
        return {
            "epoch": self.epoch,
            "grants": {h: list(c) for h, c in sorted(self.grants.items())},
            "wall": self.wall,
            "reason": self.reason,
        }


class LeaseLedger:
    """The lease file pair on a heartbeat dir: single-writer publish
    (the arbiter), any-reader poll, per-holder acks.

    The ledger itself enforces only the mechanics (atomicity, epoch
    monotonicity, ack bookkeeping); *policy* — who loses chips when —
    lives in :class:`flextree_tpu.arbiter.PoolArbiter`."""

    def __init__(self, dir: str):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.dir, LEASE_FILE)

    def _ack_path(self, holder: str) -> str:
        return os.path.join(self.dir, _ACK_FMT.format(holder=holder))

    # ---- writer side (the arbiter) ----------------------------------------

    def publish(self, epoch: int, grants: dict, reason: str = "") -> LeaseGrant:
        """Atomically publish a new ledger state.  Epochs must strictly
        increase — a replayed or reordered publish is a protocol bug, not
        a race to smooth over."""
        cur = self.read()
        if cur is not None and epoch <= cur.epoch:
            raise ValueError(
                f"lease epoch must increase: {epoch} <= published {cur.epoch}"
            )
        seen: dict = {}
        for holder, chips in grants.items():
            for c in chips:
                if c in seen:
                    raise ValueError(
                        f"chip {c!r} granted to both {seen[c]!r} and "
                        f"{holder!r} at epoch {epoch}"
                    )
                seen[c] = holder
        grant = LeaseGrant(
            epoch=int(epoch),
            grants={h: tuple(sorted(c)) for h, c in grants.items()},
            wall=_wall(),
            reason=reason,
        )
        _atomic_write_json(self.dir, self.path, grant.to_payload())
        return grant

    # ---- reader side (every holder) ---------------------------------------

    def read(self) -> LeaseGrant | None:
        """The current ledger state (None before the first publish; a
        torn/garbage file reads as None too — the replace discipline makes
        that transient)."""
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            return LeaseGrant(
                epoch=int(doc["epoch"]),
                grants={h: tuple(c) for h, c in doc["grants"].items()},
                wall=float(doc.get("wall", 0.0)),
                reason=str(doc.get("reason", "")),
            )
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def ack(self, holder: str, epoch: int) -> None:
        """Record that ``holder`` now runs under ``epoch``'s grant."""
        _atomic_write_json(
            self.dir,
            self._ack_path(holder),
            {"holder": holder, "epoch": int(epoch), "wall": _wall()},
        )

    def acked_epoch(self, holder: str) -> int:
        """The newest epoch ``holder`` acknowledged (-1: never acked)."""
        try:
            with open(self._ack_path(holder), encoding="utf-8") as f:
                return int(json.load(f)["epoch"])
        except (OSError, ValueError, KeyError, TypeError):
            return -1


@dataclasses.dataclass(frozen=True)
class ResizeDirective:
    """A grant change training has not applied yet: the new chip set and
    the ledger epoch to acknowledge once the rebuild lands."""

    epoch: int
    chips: tuple
    reason: str = ""

    @property
    def n(self) -> int:
        return len(self.chips)


class TrainLeaseClient:
    """Training's lease handle — what ``fit(arbiter=...)`` polls.

    ``on_resize(chips, plan)`` is the rebuild hook, the resize twin of
    ``Supervision.on_shrink``: return ``None`` to keep the current step
    (world-size-agnostic steps), a ``(step_fn, mesh, state_specs)``
    3-tuple, or the re-shard path's 5-tuple with checkpoint-layout
    converters for the new world.  ``configured`` is the full-inventory
    grant size (prices the replan; defaults to the largest grant seen).

    The client is deliberately dumb: it reports grant CHANGES and acks
    what the loop applied.  All sequencing safety lives in the ledger
    handshake — the arbiter cannot hand our revoked chips to serving
    until our ack exists, so a slow rebuild stretches the handoff instead
    of racing it.
    """

    def __init__(
        self,
        ledger: LeaseLedger,
        *,
        holder: str = TRAIN,
        on_resize: Callable | None = None,
        initial_chips=None,
        configured: int | None = None,
        nbytes_hint: int = 4 << 20,
        poll_interval_s: float = 0.2,
        _mono=time.monotonic,
    ):
        self.ledger = ledger
        self.holder = holder
        self.on_resize = on_resize
        self.configured = configured
        self.nbytes_hint = nbytes_hint
        self.poll_interval_s = float(poll_interval_s)
        self._mono = _mono
        self._next_poll = 0.0
        self._applied_epoch = -1
        # the grant the step was BUILT for.  Pass it whenever you know it
        # (the builders do): with it, a first poll that reads a smaller
        # grant — an early revocation, or a restart mid-handoff against
        # the persistent heartbeat dir — is a resize directive like any
        # other.  Without it, the first observation is trusted as the
        # build world (convenience for tests and single-epoch runs).
        self._chips: tuple | None = (
            tuple(sorted(initial_chips)) if initial_chips is not None
            else None
        )

    def poll(self, step: int) -> ResizeDirective | None:
        """A pending grant change, or None.  Throttled file read; an
        epoch whose chip set matches what we already run is acked in
        place (e.g. the publish that granted OUR former chips to serving
        — our slice did not change again)."""
        now = self._mono()
        if now < self._next_poll:
            return None
        self._next_poll = now + self.poll_interval_s
        grant = self.ledger.read()
        if grant is None or grant.epoch <= self._applied_epoch:
            return None
        chips = grant.chips(self.holder)
        if self._chips is None:
            # first observation: adopt the current grant as the world we
            # were built for (the builder sized the mesh from it)
            self._adopt(grant.epoch, chips)
            return None
        if chips == self._chips:
            self._adopt(grant.epoch, chips)  # epoch moved, our slice didn't
            return None
        if self.configured is not None:
            self.configured = max(self.configured, len(chips))
        return ResizeDirective(
            epoch=grant.epoch, chips=chips, reason=grant.reason
        )

    def _adopt(self, epoch: int, chips: tuple) -> None:
        self._applied_epoch = epoch
        self._chips = chips
        if self.configured is None or len(chips) > self.configured:
            self.configured = len(chips)
        self.ledger.ack(self.holder, epoch)

    def ack(self, directive: ResizeDirective) -> None:
        """The loop applied ``directive`` (checkpointed, rebuilt,
        restored): acknowledge the epoch so the arbiter may hand the
        revoked chips on."""
        self._adopt(directive.epoch, directive.chips)

    @property
    def chips(self) -> tuple:
        return self._chips or ()
