"""Heartbeat/lease membership: who is alive, who is slow, who is gone.

The launcher owns liveness at bring-up (``parallel.launch``'s
``survivors=`` source); once the run is stepping, liveness has to be
observable *from inside* the job — a preempted or SIGSTOP'd worker does
not tell anybody it stopped.  The mechanism is deliberately boring and
fabric-free: every process runs a :class:`Supervisor` daemon thread that
writes a small lease-stamped beat file (rank, pid, step counter,
step-duration EWMA) into a shared directory every ``interval_s``; any
process (usually rank 0, or the launcher) reads the directory back
through a :class:`MembershipView` and classifies each peer:

- **healthy** — beat younger than ``straggler_s``;
- **straggler** — beat older than ``straggler_s`` but inside the
  ``lease_s`` budget (a SIGSTOP'd or badly stalled process: its
  heartbeat thread is frozen with it), or a healthy beat whose
  step-duration EWMA is ``ewma_factor``× the median of its peers (a
  slow-but-alive rank, the classic straggler);
- **dead** — lease expired: no beat for ``lease_s``.  A kill -9 leaves
  exactly this signature.

A file-per-rank directory works on one host (the chaos harness's real
processes) and on any shared filesystem; the store is append-free and
each write is atomic (tmp + ``os.replace``), so a reader never sees a
torn beat.  Classification is pure arithmetic over (now - beat wall
time), injectable for tests via the module's ``_wall`` hook — the same
pattern ``parallel.launch`` uses for ``_monotonic``.

Error-taxonomy continuity: the thresholds ride env knobs (``FT_LEASE``,
``FT_STRAGGLER``) like the bring-up layer's ``FT_INIT_*``, and the
classifications feed ``RunReport.membership_epochs`` /
``RunReport.stragglers`` the way ``BringupReport`` records attempts.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from ..utils.logging import get_logger
from .ctrlfile import read_control_json, write_control_json

__all__ = [
    "HEALTHY",
    "STRAGGLER",
    "DEAD",
    "FT_LEASE_ENV",
    "FT_STRAGGLER_ENV",
    "SupervisorConfig",
    "Supervisor",
    "PeerStatus",
    "MembershipView",
]

log = get_logger("flextree.runtime")

HEALTHY, STRAGGLER, DEAD = "healthy", "straggler", "dead"

# env knobs (documented in docs/FAILURE_MODEL.md §Runtime failures):
# lease budget in seconds (no beat for this long -> dead) and the
# straggler threshold (stale-but-leased, or EWMA-outlier)
FT_LEASE_ENV = "FT_LEASE"
FT_STRAGGLER_ENV = "FT_STRAGGLER"

# injection point for the tests (patch this, not time.time): beats are
# stamped with wall time because readers live in OTHER processes — a
# monotonic clock has no cross-process epoch
_wall = time.time

_BEAT_FMT = "hb_{rank:05d}.json"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return float(raw) if raw else default


@dataclass(frozen=True)
class SupervisorConfig:
    """One process's membership in a supervised group.

    ``rank``: this process's id in the group (any stable small int —
    process index at launch).  ``dir``: the shared heartbeat directory.
    ``interval_s`` must be comfortably under ``straggler_s`` (a healthy
    process misses classification windows otherwise); ``lease_s`` is the
    death budget — how long a silent peer is given before survivors
    replan around it.
    """

    rank: int
    dir: str
    interval_s: float = 0.25
    straggler_s: float = 1.0
    lease_s: float = 3.0
    ewma_factor: float = 3.0  # EWMA > factor x peer median -> straggler

    @classmethod
    def from_env(cls, rank: int, dir: str, **overrides) -> "SupervisorConfig":
        kw = dict(
            straggler_s=_env_float(FT_STRAGGLER_ENV, cls.straggler_s),
            lease_s=_env_float(FT_LEASE_ENV, cls.lease_s),
        )
        kw.update(overrides)
        return cls(rank=rank, dir=dir, **kw)


class Supervisor:
    """The per-process heartbeat emitter: a daemon thread owning one beat
    file.  The step loop feeds it progress via :meth:`record_step`; the
    thread publishes the latest (step, EWMA) every ``interval_s`` — so
    the step path's cost is two attribute stores, never an fsync.

    Context-manager friendly::

        with Supervisor(SupervisorConfig(rank=0, dir=hb)) as sup:
            for step in ...:
                ...
                sup.record_step(step, duration_s)
    """

    def __init__(self, cfg: SupervisorConfig):
        from ..utils.profiling import Ewma

        self.cfg = cfg
        self._step = 0
        self._ewma = Ewma()  # the shared EWMA definition, one alpha
        self._beats = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        os.makedirs(cfg.dir, exist_ok=True)

    # ---- producer side (the step loop) ------------------------------------

    def record_step(self, step: int, duration_s: float | None = None) -> None:
        """Publish step progress (and optionally its duration, folded into
        the straggler EWMA — ``profiling.Ewma``, the one definition both
        the beat payload and any host-side accounting share)."""
        self._step = int(step)
        if duration_s is not None:
            self._ewma.update(duration_s * 1e3)

    @property
    def _ewma_ms(self) -> float | None:
        return self._ewma.value

    # ---- the beat ---------------------------------------------------------

    @property
    def beat_path(self) -> str:
        return os.path.join(self.cfg.dir, _BEAT_FMT.format(rank=self.cfg.rank))

    def beat_now(self) -> None:
        """Write one beat immediately (atomic: tmp + replace)."""
        payload = {
            "rank": self.cfg.rank,
            "pid": os.getpid(),
            "step": self._step,
            "ewma_ms": self._ewma_ms,
            "wall": _wall(),
            "beats": self._beats,
        }
        from ..obs import record_event

        record_event(
            "heartbeat", hb_rank=self.cfg.rank, step=self._step,
            ewma_ms=self._ewma_ms, beats=self._beats,
        )
        # CRC-trailered write (runtime.ctrlfile): a truncated or torn beat
        # must parse-refuse on the reader, never half-parse as a fresh beat
        write_control_json(self.cfg.dir, self.beat_path, payload)
        self._beats += 1

    def _loop(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.beat_now()
            except OSError as e:  # beat dir yanked: degrade loudly, once/loop
                log.warning("heartbeat write failed: %s", e)

    def start(self) -> "Supervisor":
        if self._thread is None:
            self.beat_now()  # first beat before the interval elapses
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="ft-heartbeat"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self) -> "Supervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


@dataclass(frozen=True)
class PeerStatus:
    """One peer's classification at poll time."""

    rank: int
    state: str  # HEALTHY | STRAGGLER | DEAD
    age_s: float  # seconds since its last beat
    step: int
    ewma_ms: float | None
    pid: int | None = None


class MembershipView:
    """The coordinator read side: poll the beat directory, classify peers.

    Stateless between polls except for remembering ranks ever seen, so a
    peer that dies *and its beat file is deleted* still reads as dead
    rather than silently vanishing from the roster.  ``configured``
    (optional) seeds the roster with ranks ``0..configured-1`` so a peer
    that never wrote a single beat — crashed before its first — is dead,
    not invisible.
    """

    def __init__(
        self,
        dir: str,
        *,
        straggler_s: float = 1.0,
        lease_s: float = 3.0,
        ewma_factor: float = 3.0,
        configured: int | None = None,
    ):
        self.dir = dir
        self.straggler_s = straggler_s
        self.lease_s = lease_s
        self.ewma_factor = ewma_factor
        self._seen: dict[int, dict] = {}
        self._last_states: dict[int, str] = {}  # lease-event edge detector
        # monotonic-per-rank wall guard: the newest wall stamp ever read
        # from each rank.  A beat whose wall moves BACKWARDS (NTP step,
        # clock skew across hosts) must not resurrect a lease-expired rank
        # or extend a live one — ages are computed against this watermark,
        # and the regression is a loud `clock_regression` flight event.
        self._max_wall: dict[int, float] = {}
        self._regressed: set[int] = set()  # event edge: once per episode
        if configured:
            for r in range(configured):
                self._seen.setdefault(r, {})

    @classmethod
    def for_config(cls, cfg: SupervisorConfig, configured=None) -> "MembershipView":
        return cls(
            cfg.dir,
            straggler_s=cfg.straggler_s,
            lease_s=cfg.lease_s,
            ewma_factor=cfg.ewma_factor,
            configured=configured,
        )

    def _read_beats(self) -> None:
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        from ..obs import record_event

        for name in names:
            if not (name.startswith("hb_") and name.endswith(".json")):
                continue
            beat = read_control_json(os.path.join(self.dir, name))
            if beat is None:
                continue  # torn/removed mid-read: next poll sees the replace
            try:
                rank, wall = int(beat["rank"]), float(beat["wall"])
            except (ValueError, KeyError, TypeError):
                continue
            watermark = self._max_wall.get(rank)
            if watermark is not None and wall < watermark:
                # clock regression: keep the watermark as the effective
                # stamp (never extend a lease from a stepped-back clock;
                # never resurrect an expired one), surface the episode
                # loudly ONCE until the clock catches back up
                if rank not in self._regressed:
                    self._regressed.add(rank)
                    record_event(
                        "clock_regression", peer=rank,
                        wall=wall, watermark=watermark,
                        regression_s=round(watermark - wall, 3),
                    )
                    log.warning(
                        "rank %d beat wall moved backwards by %.3fs "
                        "(NTP step / cross-host skew); holding its lease "
                        "age to the prior watermark",
                        rank, watermark - wall,
                    )
                beat = dict(beat, wall=watermark)
            else:
                self._max_wall[rank] = wall
                self._regressed.discard(rank)
            self._seen[rank] = beat

    def poll(self) -> dict[int, PeerStatus]:
        """Classify every known rank; see the module docstring for the
        healthy/straggler/dead rules."""
        self._read_beats()
        now = _wall()
        out: dict[int, PeerStatus] = {}
        ewma_by_rank = {
            r: b["ewma_ms"]
            for r, b in self._seen.items()
            if b and b.get("ewma_ms") is not None
        }

        def _peer_median(rank):
            # median of the OTHER ranks' EWMAs: including the candidate's
            # own beat makes the outlier test inert in small groups (in a
            # 2-rank world the upper median IS the slow rank's own value,
            # so `slow > factor * slow` can never fire)
            others = sorted(v for r, v in ewma_by_rank.items() if r != rank)
            return others[len(others) // 2] if others else None

        for rank, beat in sorted(self._seen.items()):
            if not beat:  # roster-seeded, never beat once
                out[rank] = PeerStatus(rank, DEAD, float("inf"), -1, None)
                continue
            age = max(0.0, now - beat["wall"])
            ewma = beat.get("ewma_ms")
            median = _peer_median(rank)
            if age > self.lease_s:
                state = DEAD
            elif age > self.straggler_s:
                state = STRAGGLER  # leased but stalled (SIGSTOP signature)
            elif (
                ewma is not None
                and median is not None
                and ewma > self.ewma_factor * median
            ):
                state = STRAGGLER  # alive but slow (EWMA outlier)
            else:
                state = HEALTHY
            out[rank] = PeerStatus(
                rank, state, age, int(beat.get("step", -1)), ewma,
                beat.get("pid"),
            )
        from ..obs import record_event

        for rank, status in out.items():
            prev = self._last_states.get(rank)
            if status.state != prev:
                self._last_states[rank] = status.state
                # classification EDGES only: a healthy 100-step run logs
                # one lease event per peer, not one per poll
                age = status.age_s
                record_event(
                    "lease", peer=rank, state=status.state, prev=prev,
                    age_s=round(age, 3) if age != float("inf") else None,
                    peer_step=status.step,
                )
        return out

    # convenience filters over one poll -------------------------------------

    def dead(self) -> list[int]:
        return [r for r, s in self.poll().items() if s.state == DEAD]

    def stragglers(self) -> list[int]:
        return [r for r, s in self.poll().items() if s.state == STRAGGLER]

    def alive_count(self) -> int:
        return sum(1 for s in self.poll().values() if s.state != DEAD)
