"""Extracted transition model of the lease ledger's revoke→ack→grant.

The two-phase chip handoff of :mod:`.leases` + :mod:`..arbiter.core`
reduced to an explicit-state machine for `analysis/protocol_check.py`:
an arbiter revokes chips from a tenant (parking them on the
:data:`~.leases.ARBITER` holder), waits for the SOURCE tenant's ack,
then grants the parked chips to the destination — in BOTH directions:
train→serve (the SLO-breach preempt) and serve→train (the burst-drained
return, a real handshake since serving became a lease tenant with its
own ack file).  A tenant restart is injectable mid-handoff at every
transition.

Pinned to the implementation:

- the three holders ARE :data:`~.leases.TRAIN` / :data:`~.leases.SERVE`
  / :data:`~.leases.ARBITER` (imported, not restated);
- the publish-time rules mirror ``LeaseLedger.publish``: epochs
  strictly increase, a chip in two holders is refused at the write —
  the ``"double_grant"`` mutation skips exactly that validation;
- the grant gate mirrors ``PoolArbiter._maybe_complete_handoff``: ONE
  ack read of the handoff's SOURCE holder serves both the epoch and
  the control stamp.  The ``"torn_ack_read"`` mutation re-introduces
  the two-reads version PR 14's review fixed (``read_ack`` returning
  the whole doc): the epoch is read from the newest ack version and
  the control stamp from the previous one, and the checker flags any
  consumed pair that no single ack version ever contained;
- serving's ack is DOUBLE-FENCED like ``ServeLeaseClient.ack``: the
  revocation ack may only be written after the replicas on the revoked
  chips have drained their in-flight requests.  The
  ``"serve_ack_before_drain"`` mutation removes that fence — serving
  acks while requests are still decoding on the revoked chips, the
  arbiter grants them to training, and the effective-exclusion
  invariant (``dual-holder-use``) becomes reachable;
- ``tests/test_control_plane_analysis.py`` drives the REAL
  ``LeaseLedger`` (and ``ServeLeaseClient``) through model-derived
  traces (double-grant refused at the write, epoch floor enforced, the
  drain fence raising) to pin the shared rules.

Honest limits: control files are atomic state (CRC tears are proven at
the ctrlfile layer), the SLO reading that *triggers* a preempt is
abstracted into a budget (the protocol is what's being checked, not the
policy), and "in flight" is one bit per tenant, not a request count —
the drain fence's contract is zero-vs-nonzero, which one bit carries.

Mutations: ``"double_grant"`` (publish skips the one-holder-per-chip
validation), ``"grant_before_ack"`` (phase 2 fires without the source
tenant's ack — the revoked chips reach the destination while the source
still runs on them), ``"torn_ack_read"`` (see above),
``"serve_ack_before_drain"`` (serving's drain fence removed).
"""

from __future__ import annotations

from .leases import ARBITER, SERVE, TRAIN

__all__ = ["LeaseModel", "LEASE_MUTATIONS"]

LEASE_MUTATIONS = (
    "double_grant",
    "grant_before_ack",
    "torn_ack_read",
    "serve_ack_before_drain",
)

_CHIPS = ("c0", "c1")


class LeaseModel:
    """State = (epoch, grants, tenants, pending, acks, budgets).

    ``grants``: per-holder chip frozensets (the ledger document).
    ``tenants``: ``(t_use, t_seen)`` for TRAIN and ``(s_use, s_seen,
    s_busy)`` for SERVE — what each tenant actually runs on vs what it
    has observed, plus serving's in-flight bit (requests decoding on
    its chips).  ``pending``: in-flight handoff ``(chips, revoke_epoch,
    src_holder)`` or None — the destination is the other tenant.
    ``acks``: per-tenant ack-file version histories (newest last,
    bounded) of ``(epoch, control_stamp)`` pairs — history, because the
    torn-read class is precisely about pairing fields across versions.
    ``budgets``: ``(preempts, returns, restarts)`` remaining.
    """

    name_prefix = "lease"

    def __init__(self, *, preempts: int = 2, returns: int = 1,
                 restarts: int = 1, mutation: str | None = None):
        if mutation is not None and mutation not in LEASE_MUTATIONS:
            raise ValueError(f"unknown lease mutation: {mutation}")
        self.mutation = mutation
        self.budget0 = (preempts, returns, restarts)
        self.name = f"{self.name_prefix}@{len(_CHIPS)}chips"
        if mutation:
            self.name += f"+{mutation}"

    def initial(self):
        grants = ((TRAIN, frozenset(_CHIPS)), (SERVE, frozenset()),
                  (ARBITER, frozenset()))
        tenants = ((frozenset(_CHIPS), 0), (frozenset(), 0, False))
        acks = (((0, 0),), ((0, 0),))  # train history, serve history
        return (0, grants, tenants, None, acks, self.budget0)

    def is_fault_label(self, label: str) -> bool:
        return label.startswith("restart")

    # ---- transitions -------------------------------------------------------

    def transitions(self, state):
        epoch, grants, tenants, pending, acks, budgets = state
        preempts, returns, restarts = budgets
        g = dict(grants)
        (t_use, t_seen), (s_use, s_seen, s_busy) = tenants
        t_acks, s_acks = acks
        out = []

        # -- phase 1 forward: revoke (preempt) — park a nonempty subset
        #    of training's chips on the arbiter holder
        if pending is None and preempts > 0 and g[TRAIN]:
            for chips in _subsets(g[TRAIN]):
                ng = dict(g)
                ng[TRAIN] = g[TRAIN] - chips
                ng[ARBITER] = g[ARBITER] | chips
                t = self._publish(state, epoch + 1, ng,
                                  label=f"revoke({sorted(chips)},e{epoch+1})",
                                  pending=(chips, epoch + 1, TRAIN),
                                  budgets=(preempts - 1, returns, restarts))
                out.append(t)

        # -- phase 1 reverse: the burst drained — park ALL of serving's
        #    chips for the return handoff (``PoolArbiter._return`` in
        #    tenant mode); serving's replicas keep running until serving
        #    observes the revocation, drains, and acks
        if pending is None and returns > 0 and g[SERVE]:
            chips = g[SERVE]
            ng = dict(g)
            ng[SERVE] = frozenset()
            ng[ARBITER] = g[ARBITER] | chips
            t = self._publish(
                state, epoch + 1, ng,
                label=f"return({sorted(chips)},e{epoch+1})",
                pending=(chips, epoch + 1, SERVE),
                budgets=(preempts, returns - 1, restarts))
            out.append(t)

        # -- tenants observe a newer ledger — the lease clients' poll.
        #    Training adopts instantly (the step boundary is the only
        #    sync point it needs).  Serving with traffic in flight keeps
        #    USING its chips until the drain transition: observation is
        #    a read, drain is what actually stops the replicas.
        if t_seen < epoch:
            nt = ((g[TRAIN], epoch), (s_use, s_seen, s_busy))
            out.append((f"observe(train,e{epoch})",
                        (epoch, grants, nt, pending, acks, budgets), []))
        if s_seen < epoch:
            new_use = (s_use | g[SERVE]) if s_busy else g[SERVE]
            new_busy = s_busy or bool(g[SERVE])  # a grant brings traffic
            nt = ((t_use, t_seen), (new_use, epoch, new_busy))
            out.append((f"observe(serve,e{epoch})",
                        (epoch, grants, nt, pending, acks, budgets), []))

        # -- serving drains: every in-flight request on a revoked chip
        #    is answered/refused; replicas on revoked chips terminate,
        #    so use shrinks to the currently granted set
        if s_busy:
            nt = ((t_use, t_seen), (s_use & g[SERVE], s_seen, False))
            out.append(("drain(serve)",
                        (epoch, grants, nt, pending, acks, budgets), []))

        # -- training acks what it observed (the ack file carries the
        #    lease epoch + the control stamp of the group decision it
        #    applied the revocation under — ONE document)
        if t_seen > t_acks[-1][0]:
            stamp = t_seen  # the control stamp advances with each applied
            # revocation epoch; modelling it as the seen epoch keeps the
            # two fields distinct across versions without a second counter
            nacks = ((t_acks + ((t_seen, stamp),))[-3:], s_acks)
            out.append((f"ack(train,e{t_seen})",
                        (epoch, grants, tenants, pending, nacks, budgets),
                        []))

        # -- serving acks what it observed — DOUBLE-FENCED like
        #    ``ServeLeaseClient.ack``: the ack that releases revoked
        #    chips may only be written once no revoked chip is still in
        #    use (drain completed).  The mutation removes the fence.
        if s_seen > s_acks[-1][0]:
            drained = s_use <= g[SERVE]
            if drained or self.mutation == "serve_ack_before_drain":
                nacks = (t_acks, (s_acks + ((s_seen, s_seen),))[-3:])
                out.append((f"ack(serve,e{s_seen})",
                            (epoch, grants, tenants, pending, nacks,
                             budgets), []))

        # -- phase 2: grant — the arbiter hands parked chips to the
        #    destination once the SOURCE tenant's ack covers the revoke
        #    epoch (one gate, both directions)
        if pending is not None and g[ARBITER] >= pending[0]:
            chips, revoke_epoch, src = pending
            src_acks = t_acks if src == TRAIN else s_acks
            dst = SERVE if src == TRAIN else TRAIN
            viol = []
            if self.mutation == "torn_ack_read" and src == TRAIN and \
                    len(src_acks) >= 2:
                # the seeded two-reads bug: epoch from the newest ack
                # version, control stamp from the previous one
                consumed = (src_acks[-1][0], src_acks[-2][1])
                if consumed not in src_acks:
                    viol.append((
                        "torn-ack-read",
                        f"arbiter consumed ack pair {consumed} that no "
                        f"single ack version ever contained "
                        f"({list(src_acks)}) — epoch and control stamp "
                        "read from different versions",
                    ))
                acked = consumed[0]
            else:
                acked = src_acks[-1][0]
            if acked >= revoke_epoch or self.mutation == "grant_before_ack":
                ng = dict(g)
                ng[ARBITER] = g[ARBITER] - chips
                ng[dst] = g[dst] | chips
                t = self._publish(
                    state, epoch + 1, ng,
                    label=f"grant({sorted(chips)},e{epoch+1},to={dst})",
                    pending=None, budgets=budgets, extra_viol=viol)
                out.append(t)

        # -- fault injection: tenant restart at every transition — the
        #    restarted tenant re-reads the ledger (first observation
        #    adopts), its ack files survive on disk, and a restarted
        #    serving fleet comes up with NO in-flight requests (fresh
        #    processes) — which is why restart-mid-handoff is safe
        if restarts > 0:
            nb = (preempts, returns, restarts - 1)
            nt = ((g[TRAIN], epoch), (s_use, s_seen, s_busy))
            out.append(("restart(train)",
                        (epoch, grants, nt, pending, acks, nb), []))
            nt = ((t_use, t_seen), (g[SERVE], epoch, False))
            out.append(("restart(serve)",
                        (epoch, grants, nt, pending, acks, nb), []))
        return out

    def _publish(self, state, new_epoch, new_grants, *, label, pending,
                 budgets, tenants=None, extra_viol=None):
        """``LeaseLedger.publish``'s write-time rules: strictly
        increasing epoch, every chip in exactly one holder — skipped by
        the ``double_grant`` mutation, which is what makes the
        invariant's violation reachable."""
        epoch, grants, old_tenants, _, acks, _ = state
        viol = list(extra_viol or [])
        if new_epoch <= epoch:
            viol.append((
                "epoch-regression",
                f"lease epoch {new_epoch} published after {epoch}",
            ))
        if self.mutation == "double_grant" and pending is None and \
                new_grants[SERVE]:
            # the seeded corruption: the grant ALSO leaves the chips in
            # the training set (validation skipped)
            new_grants = dict(new_grants)
            new_grants[TRAIN] = new_grants[TRAIN] | new_grants[SERVE]
        seen: dict = {}
        for holder in (TRAIN, SERVE, ARBITER):
            for chip in new_grants[holder]:
                if chip in seen:
                    viol.append((
                        "double-grant",
                        f"chip {chip} granted to both {seen[chip]} and "
                        f"{holder} at epoch {new_epoch}",
                    ))
                seen[chip] = holder
        for chip in _CHIPS:
            if chip not in seen:
                viol.append((
                    "lost-chip",
                    f"chip {chip} granted to nobody at epoch {new_epoch}",
                ))
        ng = tuple((h, frozenset(new_grants[h]))
                   for h in (TRAIN, SERVE, ARBITER))
        return (label,
                (new_epoch, ng, tenants or old_tenants, pending, acks,
                 budgets),
                viol)

    # ---- reachable-state invariants ---------------------------------------

    def state_violations(self, state):
        """Checked at EVERY reachable state (not just writes): the
        effective-exclusion invariant — no chip in active use by two
        tenants — which the ack-before-grant handshake (and serving's
        drain-before-ack fence) exists to hold."""
        epoch, grants, tenants, pending, acks, budgets = state
        (t_use, _), (s_use, _, _) = tenants
        both = t_use & s_use
        if both:
            return [(
                "dual-holder-use",
                f"chips {sorted(both)} in active use by train AND serve "
                f"at lease epoch {epoch} — the grant outran the "
                "revocation ack (or the ack outran the drain)",
            )]
        return []

    def quiescent_violations(self, state):
        epoch, grants, tenants, pending, acks, budgets = state
        viols, truncated = [], False
        if pending is not None:
            # a handoff whose grant is enabled would not be quiescent;
            # pending at quiescence means the ack gate can never open
            viols.append((
                "wedged-handoff",
                f"handoff of {sorted(pending[0])} (revoke epoch "
                f"{pending[1]}, from {pending[2]}) never completed",
            ))
        return viols, truncated


def _subsets(chips):
    chips = sorted(chips)
    out = []
    for mask in range(1, 1 << len(chips)):
        out.append(frozenset(
            c for i, c in enumerate(chips) if mask & (1 << i)
        ))
    return out
