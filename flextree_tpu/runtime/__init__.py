"""Runtime supervision: keep a training run alive through mid-run failures.

PR 1 made *bring-up* fault-tolerant (``parallel.launch``: retry/backoff,
degrade-to-survivors); this package covers the run itself.  Once ``fit``
is stepping, a slow, hung, or preempted worker otherwise stalls every
collective forever — there is no in-run failure detection in XLA's
collectives on this pin, so the detection has to live at the host level:

- :mod:`.supervisor` — heartbeat/lease membership.  Every process runs a
  :class:`Supervisor` daemon thread writing lease-stamped beats (rank,
  step, step-duration EWMA) into a shared directory; a
  :class:`MembershipView` classifies peers as healthy / straggler / dead
  from lease age and per-step progress, the way the launcher's liveness
  probe classified processes at bring-up.
- :mod:`.watchdog` — the step deadline.  :class:`StepWatchdog` runs the
  step on a persistent worker thread and converts a hang into a typed
  :class:`StepTimeout` (``FT_STEP_TIMEOUT``) instead of an infinite
  block; the simulator's :class:`~flextree_tpu.backends.simulator.Mailbox`
  carries the same contract at message granularity
  (``FaultPlan.recv_timeout`` → ``StageTimeout``).
- :mod:`.leases` — the chip-lease protocol.  A
  :class:`LeaseLedger` on the same heartbeat directory carries the
  arbiter's epoch-numbered chip grants (atomic publish, per-holder acks);
  a :class:`TrainLeaseClient` is the handle ``fit(arbiter=...)`` polls to
  shrink/expand the training world when the arbiter moves chips between
  training and serving (``flextree_tpu.arbiter``, docs/ARBITER.md).
- :mod:`.coordination` — the coordinated elastic control plane.  Every
  elastic event (drift replan, shrink-to-survivors, lease resize)
  becomes an epoch-numbered propose → ack → commit group decision on
  the same directory (:class:`CoordinationHandle`), with coordinator
  failover to the lowest-rank healthy member and epoch fencing for
  ranks that miss the window; control files are torn-proof via
  :mod:`.ctrlfile`'s length+CRC32 trailers (docs/COORDINATION.md).
- :mod:`.preemption` — preemption-aware checkpointing.  A
  :class:`PreemptionGuard` turns SIGTERM into a "checkpoint now" fast
  path inside ``fit``; a :class:`BackgroundSaver` moves periodic saves
  off the step path so the rewind window stays small without stalling
  steps on serialization + fsync.

``parallel.loop.fit`` wires all three through its ``supervision=``
argument and records every recovery event (membership epoch transitions,
step timeouts, stragglers, preemption checkpoints) in the
:class:`~flextree_tpu.parallel.loop.RunReport` persisted as
``run_report.json``.  The executed proof is ``tools/chaos_runtime.py``
(mid-run SIGKILL / SIGSTOP / SIGTERM against real processes →
``CHAOS_RUNTIME.json``); see docs/FAILURE_MODEL.md §Runtime failures.
"""

from .coordination import (
    ControlDecision,
    CoordinationAbandoned,
    CoordinationConfig,
    CoordinationHandle,
    CoordLedger,
    EpochFenced,
    ProtocolViolation,
)
from .ctrlfile import read_control_json, write_control_json
from .leases import (
    ARBITER,
    SERVE,
    TRAIN,
    LeaseGrant,
    LeaseLedger,
    ResizeDirective,
    ServeDirective,
    ServeLeaseClient,
    TrainLeaseClient,
)
from .preemption import BackgroundSaver, PreemptionGuard
from .supervisor import (
    DEAD,
    FT_LEASE_ENV,
    HEALTHY,
    STRAGGLER,
    MembershipView,
    PeerStatus,
    Supervisor,
    SupervisorConfig,
)
from .watchdog import FT_STEP_TIMEOUT_ENV, StepTimeout, StepWatchdog

__all__ = [
    "Supervisor",
    "SupervisorConfig",
    "MembershipView",
    "PeerStatus",
    "HEALTHY",
    "STRAGGLER",
    "DEAD",
    "StepWatchdog",
    "StepTimeout",
    "PreemptionGuard",
    "BackgroundSaver",
    "FT_STEP_TIMEOUT_ENV",
    "FT_LEASE_ENV",
    "LeaseGrant",
    "LeaseLedger",
    "ResizeDirective",
    "ServeDirective",
    "ServeLeaseClient",
    "TrainLeaseClient",
    "TRAIN",
    "SERVE",
    "ARBITER",
    "ControlDecision",
    "CoordLedger",
    "CoordinationAbandoned",
    "CoordinationConfig",
    "CoordinationHandle",
    "EpochFenced",
    "ProtocolViolation",
    "read_control_json",
    "write_control_json",
]
