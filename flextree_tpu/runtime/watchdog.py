"""Step watchdog: convert a hang into a typed ``FT_STEP_TIMEOUT``.

On this JAX pin a collective whose peer died blocks forever — there is
no in-collective timeout to configure — so the deadline has to wrap the
*step* from the host side.  :class:`StepWatchdog` runs the step on a
persistent daemon worker thread and waits with a deadline: expiry raises
:class:`StepTimeout` (carrying the step index and budget, message tagged
``FT_STEP_TIMEOUT`` — the runtime twin of the bring-up layer's
``FT_INIT_TIMEOUT``) while the stuck call is *abandoned* on its thread
(a blocked C call cannot be interrupted from Python; the thread is
daemonized so it never blocks interpreter exit, and the next ``run``
gets a fresh worker).  ``fit`` then decides what a timeout means: poll
membership — a confirmed death goes to shrink-to-survivors, a mere stall
gets a bounded retry.

The simulator backend carries the same contract at message granularity:
``FaultPlan.recv_timeout`` turns a hung sender into a typed
``StageTimeout`` instead of a deadlock (``backends.simulator``).

Fault-free overhead is one queue round-trip per step (~tens of µs — the
worker thread is persistent, never spawned per step); measured ≤ 2% of
``run_train_step_bench``'s step time (WINS.md).
"""

from __future__ import annotations

import os
import queue
import threading

__all__ = ["FT_STEP_TIMEOUT_ENV", "StepTimeout", "StepWatchdog", "step_timeout_from_env"]

# env knob: per-step deadline in seconds for fit's watchdog (None = off)
FT_STEP_TIMEOUT_ENV = "FT_STEP_TIMEOUT"


def step_timeout_from_env() -> float | None:
    raw = os.environ.get(FT_STEP_TIMEOUT_ENV)
    return float(raw) if raw else None


class StepTimeout(RuntimeError):
    """A supervised step exceeded its deadline — the typed replacement for
    an infinite block.  Carries ``step`` and ``timeout_s``; ``code`` is
    the stable taxonomy tag harnesses match on."""

    code = "FT_STEP_TIMEOUT"

    def __init__(self, step: int | None, timeout_s: float, note: str = ""):
        self.step = step
        self.timeout_s = timeout_s
        at = f"step {step}" if step is not None else "step"
        super().__init__(
            f"{self.code}: {at} exceeded its {timeout_s:g}s deadline"
            + (f" ({note})" if note else "")
        )


class _Worker:
    """One daemon thread executing submitted calls in order."""

    def __init__(self):
        self.jobs: queue.Queue = queue.Queue()
        self.results: queue.Queue = queue.Queue()
        self.thread = threading.Thread(
            target=self._loop, daemon=True, name="ft-step-watchdog"
        )
        self.thread.start()

    def _loop(self):
        while True:
            job = self.jobs.get()
            if job is None:
                return
            fn, args, kwargs = job
            try:
                self.results.put(("ok", fn(*args, **kwargs)))
            except BaseException as e:  # delivered to the waiter, not lost
                self.results.put(("err", e))


class StepWatchdog:
    """Deadline-wrapped call execution on a persistent worker thread.

    ``run(fn, *args, timeout_s=...)`` returns ``fn``'s result or raises
    what it raised; on deadline expiry it raises :class:`StepTimeout` and
    abandons the stuck worker (counted in ``abandoned``) — the next call
    runs on a fresh thread, so one hang never poisons the watchdog.
    ``timeout_s=None`` calls ``fn`` inline (watchdog off, zero overhead).
    """

    def __init__(self):
        self._worker: _Worker | None = None
        self.abandoned = 0

    def run(self, fn, *args, timeout_s: float | None, step: int | None = None, **kwargs):
        if timeout_s is None:
            return fn(*args, **kwargs)
        if self._worker is None:
            self._worker = _Worker()
        w = self._worker
        w.jobs.put((fn, args, kwargs))
        try:
            status, value = w.results.get(timeout=timeout_s)
        except queue.Empty:
            # the worker is stuck inside fn: abandon it (daemon thread) and
            # let a future run() start clean
            self._worker = None
            self.abandoned += 1
            from ..obs import record_event

            record_event(
                "watchdog_timeout", step=step, timeout_s=timeout_s,
                abandoned=self.abandoned,
            )
            raise StepTimeout(step, timeout_s) from None
        if status == "err":
            raise value
        return value

    def close(self) -> None:
        if self._worker is not None:
            self._worker.jobs.put(None)
            self._worker = None

    def __enter__(self) -> "StepWatchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
