"""Extracted transition model of the coordination handshake.

This is the propose→ack→commit protocol of :mod:`.coordination` reduced
to an explicit-state machine the analysis layer can exhaustively
enumerate (`analysis/protocol_check.py`): small worlds of 2–4 ranks,
with a coordinator crash injectable at EVERY transition, stalled
followers, duplicate acks (absorbed by state identity), and lost
proposal/commit races (the ledger's epoch-floor and idempotent-commit
rules are encoded as the write-time checks below, verbatim from
``CoordLedger.publish_proposal`` / ``publish_commit``).

The model is pinned to the implementation, not a parallel truth:

- decision identity IS :func:`~.coordination.decision_fingerprint` over
  a kind from :data:`~.coordination.DECISION_KINDS` — the same sha256
  the chaos floors compare across survivors;
- the re-propose survivor rule is the production line verbatim
  (``acks.get(r, -1) >= epoch or r == self.rank``) — the mutated model
  that drops the ``or r == self.rank`` clause reproduces PR 14's
  self-ack-held coordinator interleaving as a reachable violation;
- the commit rules mirror ``publish_commit``: idempotent no-op on a
  byte-identical re-commit, a protocol violation on a divergent
  decision at the same epoch, back-off on a lost race;
- ``tests/test_control_plane_analysis.py`` drives the REAL
  ``CoordLedger`` through model-derived traces and asserts the same
  accept/refuse outcomes.

What the model abstracts (honest limits): the filesystem (control-file
writes are atomic state updates — tears are `ctrlfile`'s CRC layer's
problem, proven separately), wall-clock deadlines (the ack deadline is
the nondeterministic enabling of the re-propose transition, gated on
every missing rank being faulted), and membership lag (the driver's
health view is exact; the lost-race write rules cover the stale-view
overlap).

Mutations (`mutation=` kwarg) re-introduce historical bug classes so
the checker can prove it would have caught them:

- ``"commit_without_all_acks"``: the driver may seal with acks missing;
- ``"drop_survivor_self"``: re-propose survivors lose the
  ``or r == self.rank`` clause (the PR 14 interleaving);
- ``"diverge_commit"``: the commit writes a different fingerprint than
  the proposal (breaks byte-identical commit-vs-proposal);
- ``"fenced_apply"``: a fenced rank applies anyway.
"""

from __future__ import annotations

from .coordination import DECISION_KINDS, decision_fingerprint

__all__ = ["CoordModel", "COORD_MUTATIONS"]

COORD_MUTATIONS = (
    "commit_without_all_acks",
    "drop_survivor_self",
    "diverge_commit",
    "fenced_apply",
)

# rank status codes (status, acked, applied, ever_faulted) per rank
LIVE, STALLED, CRASHED, FENCED = 0, 1, 2, 3
_STATUS_NAMES = {LIVE: "live", STALLED: "stalled", CRASHED: "crashed",
                 FENCED: "fenced"}


class CoordModel:
    """State = (ranks, prop, commit, commits_log, props_log, budgets).

    ``ranks``: per-rank ``(status, acked_epoch, applied_epoch, faulted)``.
    ``prop``/``commit``: ``None`` or ``(epoch, fp, participants, owner)``
    — the two ledger slots.  ``commits_log``/``props_log``: every write
    ever made to each slot (the slots are overwritten; the invariants
    quantify over history).  ``budgets``: ``(decisions, reproposals,
    crashes, stalls)`` remaining — explicit bounds, reported as
    truncation rather than silently absorbed (see
    :meth:`quiescent_violations`).
    """

    name_prefix = "coordination"

    def __init__(self, n_ranks: int = 3, *, decisions: int = 1,
                 reproposals: int = 2, crashes: int | None = None,
                 stalls: int = 1, mutation: str | None = None):
        if mutation is not None and mutation not in COORD_MUTATIONS:
            raise ValueError(f"unknown coordination mutation: {mutation}")
        self.n = int(n_ranks)
        self.mutation = mutation
        if crashes is None:
            crashes = min(2, self.n - 1)
        self.budget0 = (decisions, reproposals, min(crashes, self.n - 1),
                        stalls)
        # decision identity comes from the production fingerprint over a
        # production kind — one fresh decision per budget slot
        self.kind = DECISION_KINDS[0]
        self.fps = tuple(
            decision_fingerprint(self.kind, {"seq": i})
            for i in range(decisions)
        )
        self.name = f"{self.name_prefix}@{self.n}ranks"
        if mutation:
            self.name += f"+{mutation}"

    # ---- state helpers -----------------------------------------------------

    def initial(self):
        ranks = tuple((LIVE, -1, -1, False) for _ in range(self.n))
        return (ranks, None, None, (), frozenset(), self.budget0)

    @staticmethod
    def _coordinator(ranks):
        """Lowest live non-stalled rank — ``is_coordinator``'s
        lowest-healthy rule (a stalled rank's beat is stale, so it is a
        straggler, not healthy).  None when nobody can drive."""
        for r, (st, _, _, _) in enumerate(ranks):
            if st == LIVE:
                return r
        return None

    @staticmethod
    def _slot_floor(prop, commit):
        return max(prop[0] if prop else -1, commit[0] if commit else -1)

    def is_fault_label(self, label: str) -> bool:
        return label.startswith(("crash", "stall", "resume"))

    # ---- transitions -------------------------------------------------------

    def transitions(self, state):
        """All enabled ``(label, next_state, violations)`` triples.
        Violations are write-time invariant breaches (only reachable in
        mutated models); the explorer attaches the witness path."""
        ranks, prop, commit, clog, plog, budgets = state
        decisions, reproposals, crashes, stalls = budgets
        out = []
        coord = self._coordinator(ranks)
        ce = commit[0] if commit else -1

        # -- propose: coordinator only, one decision at a time, applied
        #    floor respected (CoordinationHandle.propose verbatim)
        if (coord is not None and decisions > 0
                and not (prop is not None and prop[0] > ce)
                and ce <= ranks[coord][2]):
            epoch = 1 + self._slot_floor(prop, commit)
            fp = self.fps[len(self.fps) - decisions]
            participants = tuple(
                r for r, (st, _, _, _) in enumerate(ranks) if st != CRASHED
            )  # _alive_ranks: everything not dead, stragglers included
            newp = (epoch, fp, participants, coord)
            out.append((
                f"propose(r{coord},e{epoch})",
                (ranks, newp, commit, clog, plog | {newp},
                 (decisions - 1, reproposals, crashes, stalls)),
                [],
            ))

        # -- ack: any live participant with a newer proposal (the
        #    proposer's own immediate self-ack is this same transition —
        #    modelling it separately is what lets a crash land between
        #    publish and self-ack).  A duplicate ack rewrites the same
        #    file: the successor state is identical, so the explorer's
        #    memoization absorbs it — replayed acks cannot change the
        #    reachable set.
        if prop is not None and prop[0] > ce:
            epoch, fp, participants, owner = prop
            for r in participants:
                st, acked, applied, faulted = ranks[r]
                if st == LIVE and epoch > max(acked, applied):
                    nr = _set(ranks, r, (st, epoch, applied, faulted))
                    out.append((f"ack(r{r},e{epoch})",
                                (nr, prop, commit, clog, plog, budgets), []))

        # -- commit: the driver seals when every participant promised
        if prop is not None and prop[0] > ce and coord is not None:
            epoch, fp, participants, owner = prop
            acks_in = [r for r in participants if ranks[r][1] >= epoch]
            missing = [r for r in participants if ranks[r][1] < epoch]
            can_seal = not missing
            if (self.mutation == "commit_without_all_acks" and missing
                    and acks_in):
                can_seal = True  # the seeded corruption: seal on a quorum<all
            if can_seal:
                wfp = fp + "-x" if self.mutation == "diverge_commit" else fp
                t = self._commit_write(
                    state, coord, (epoch, wfp, participants, owner))
                if t is not None:  # None = lost race / idempotent no-op
                    out.append(t)

        # -- re-propose: deadline passed (abstracted: every missing rank
        #    is faulted — a live rank's ack is still in flight) →
        #    exclude the silent ranks, keep the decision content
        if (prop is not None and prop[0] > ce and coord is not None
                and reproposals > 0):
            epoch, fp, participants, owner = prop
            missing = [r for r in participants if ranks[r][1] < epoch]
            # deadline abstraction: the window closes once every missing
            # rank OTHER than the driver is faulted — the driver's own
            # ack may be absent at its own deadline (it inherited the
            # proposal, or crashed between publish and self-ack), which
            # is exactly the case the production survivor rule's
            # `or r == self.rank` clause exists for
            if missing and all(
                    ranks[r][0] != LIVE for r in missing if r != coord):
                # production survivor rule (coordination._drive):
                #   acks.get(r, -1) >= epoch or r == self.rank
                survivors = tuple(
                    r for r in participants
                    if ranks[r][1] >= epoch
                    or (r == coord and self.mutation != "drop_survivor_self")
                )
                viol = []
                if coord in participants and coord not in survivors:
                    viol.append((
                        "coordinator-self-excluded",
                        f"rank {coord} re-proposed epoch excluding ITSELF "
                        f"(its own ack for epoch {epoch} was still in "
                        "flight) — the driver's commit will fence the "
                        "driver (PR 14's self-ack-held interleaving)",
                    ))
                ne = 1 + self._slot_floor(prop, commit)
                newp = (ne, fp, survivors, coord)
                out.append((
                    f"repropose(r{coord},e{ne},excl={missing})",
                    (ranks, newp, commit, clog, plog | {newp},
                     (decisions, reproposals - 1, crashes, stalls)),
                    viol,
                ))

        # -- observe commit: deliver (apply) or fence
        if commit is not None:
            epoch, fp, participants, owner = commit
            for r in range(self.n):
                st, acked, applied, faulted = ranks[r]
                mutant = (st == FENCED and self.mutation == "fenced_apply"
                          and epoch > applied)
                if not mutant and (st != LIVE or epoch <= applied):
                    continue  # crashed/stalled/fenced ranks observe nothing
                if r not in participants and st != FENCED:
                    nr = _set(ranks, r, (FENCED, acked, applied, faulted))
                    viol = []
                    if not faulted:
                        viol.append((
                            "clean-rank-fenced",
                            f"rank {r} is live and never faulted yet the "
                            f"commit at epoch {epoch} excludes it — the "
                            "re-propose survivor rule dropped a healthy "
                            "driver (PR 14's self-ack-held interleaving)",
                        ))
                    out.append((f"fence(r{r},e{epoch})",
                                (nr, prop, commit, clog, plog, budgets),
                                viol))
                    continue
                viol = []
                if st == FENCED:
                    viol.append((
                        "fenced-apply",
                        f"fenced rank {r} applied epoch {epoch} — a fenced "
                        "rank must exit, never apply",
                    ))
                nr = _set(ranks, r, (st, acked, epoch, faulted))
                out.append((f"apply(r{r},e{epoch})",
                            (nr, prop, commit, clog, plog, budgets), viol))

        # -- fault injection: crash / stall / resume at every state —
        #    which is to say, between (before/after) every protocol
        #    transition above
        if crashes > 0:
            alive = [r for r, (st, _, _, _) in enumerate(ranks)
                     if st in (LIVE, STALLED)]
            if len(alive) >= 2:
                for r in alive:
                    st, acked, applied, _ = ranks[r]
                    nr = _set(ranks, r, (CRASHED, acked, applied, True))
                    out.append((f"crash(r{r})",
                                (nr, prop, commit, clog, plog,
                                 (decisions, reproposals, crashes - 1,
                                  stalls)), []))
        for r, (st, acked, applied, faulted) in enumerate(ranks):
            if st == LIVE and stalls > 0:
                nr = _set(ranks, r, (STALLED, acked, applied, True))
                out.append((f"stall(r{r})",
                            (nr, prop, commit, clog, plog,
                             (decisions, reproposals, crashes, stalls - 1)),
                            []))
            elif st == STALLED:
                nr = _set(ranks, r, (LIVE, acked, applied, True))
                out.append((f"resume(r{r})",
                            (nr, prop, commit, clog, plog, budgets), []))
        return out

    def _commit_write(self, state, driver, decision):
        """``CoordLedger.publish_commit``'s rules as one transition:
        idempotent no-op on identical re-commit, violation on divergence
        at the same epoch or a backwards epoch, plus the quorum and
        byte-identity invariants the checker exists to quantify."""
        ranks, prop, commit, clog, plog, budgets = state
        epoch, fp, participants, owner = decision
        viol = []
        if commit is not None:
            cepoch, cfp = commit[0], commit[1]
            if cepoch > epoch:
                # production backs off (coord_commit_race) — lost race,
                # no write, no state change: not a transition
                return None
            if cepoch == epoch:
                if cfp != fp:
                    viol.append((
                        "epoch-double-commit",
                        f"two decisions at epoch {epoch}: committed {cfp}, "
                        f"now {fp} — >1 commit per control epoch",
                    ))
                else:
                    return None  # idempotent failover no-op
        # invariant: the sealed decision must be byte-identical to a
        # published proposal at that epoch (fingerprint + participants)
        if (epoch, fp, participants, owner) not in plog:
            viol.append((
                "commit-proposal-divergence",
                f"commit at epoch {epoch} (fp {fp}) matches no published "
                "proposal — commit must be byte-identical to its proposal",
            ))
        # invariant: a seal requires every participant's promise
        missing = [r for r in participants if ranks[r][1] < epoch]
        if missing:
            viol.append((
                "commit-quorum",
                f"commit at epoch {epoch} sealed with no ack from ranks "
                f"{missing} — a participant can apply a plan it never "
                "promised a boundary for",
            ))
        if clog and epoch <= clog[-1][0] and not any(
                v[0] == "epoch-double-commit" for v in viol):
            viol.append((
                "epoch-regression",
                f"commit epoch {epoch} after {clog[-1][0]} — control epochs "
                "must strictly increase",
            ))
        ns = (ranks, prop, decision, clog + ((epoch, fp),), plog, budgets)
        return (f"commit(r{driver},e{epoch})", ns, viol)

    # ---- quiescence --------------------------------------------------------

    def quiescent_violations(self, state):
        """Checks on states with no outgoing transitions.  A quiescent
        state with an unresolved proposal and a live driver is a wedged
        handshake — unless only a budget bound stops progress, which is
        truncation (counted, not a violation): the bound is explicit."""
        ranks, prop, commit, clog, plog, budgets = state
        ce = commit[0] if commit else -1
        viols, truncated = [], False
        if prop is not None and prop[0] > ce:
            coord = self._coordinator(ranks)
            if coord is not None:
                epoch, fp, participants, owner = prop
                missing = [r for r in participants if ranks[r][1] < epoch]
                if missing and all(ranks[r][0] != LIVE for r in missing):
                    if budgets[1] == 0:
                        truncated = True  # re-propose only blocked by budget
                    else:
                        viols.append((
                            "wedged-handshake",
                            f"proposal epoch {epoch} unresolved at "
                            f"quiescence: missing acks {missing}, driver "
                            f"r{coord} live",
                        ))
                elif missing:
                    viols.append((
                        "wedged-handshake",
                        f"proposal epoch {epoch} unresolved at quiescence "
                        f"with live non-acking ranks {missing}",
                    ))
        # a sealed decision must reach every live participant
        if commit is not None:
            epoch, fp, participants, owner = commit
            lagging = [
                r for r in participants
                if ranks[r][0] == LIVE and ranks[r][2] < epoch
            ]
            if lagging:
                viols.append((
                    "unapplied-commit",
                    f"quiescent with live participants {lagging} never "
                    f"applying committed epoch {epoch}",
                ))
        return viols, truncated


def _set(ranks, r, row):
    return ranks[:r] + (row,) + ranks[r + 1:]
