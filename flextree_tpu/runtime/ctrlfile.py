"""Torn-proof control files: length+CRC32-trailered JSON on the heartbeat dir.

Every cross-process truth in the runtime — heartbeat beats, the lease
ledger and its acks, and the coordination protocol's proposal/commit/ack
files (``runtime.coordination``) — is a small JSON file in a shared
directory, written with the atomic tmp + ``os.replace`` discipline.  The
replace makes a *well-behaved* writer invisible mid-write; it does not
protect against a truncated flush on a dying filesystem, a half-copied
directory, or an adversarial scribbler (the chaos harness's torn-ledger
injection).  Before this module, a torn ``lease_ack_{holder}.json`` was
whatever the caller's ``except ValueError`` happened to do with a
half-parsed document — and a truncation that still parses as valid JSON
(a cut that lands exactly on a line boundary) was silently *accepted*.

The fix is an end-of-file integrity trailer:

- :func:`write_control_json` writes the payload as ONE compact JSON line
  followed by a trailer line ``{"len": N, "crc32": "xxxxxxxx"}`` naming
  the byte length and CRC32 of the payload line (newline included) —
  then atomically replaces the target.  ``head -1 file`` is still the
  human-readable payload.
- :func:`read_control_json` refuses any file whose trailer is missing,
  malformed, or disagrees with the payload bytes — truncation at EVERY
  byte offset is detected, pinned by the truncate-at-every-offset test —
  and **rereads** before giving up: with atomic writers a mismatch is
  transient (a non-atomic scribbler mid-line), so the reader retries a
  bounded number of times and only then reports the file torn (a
  ``torn_control_file`` flight event + ``None``, never an exception on
  the polling thread).

Writers and readers must pair: a trailer-less file (hand-written, or
from a pre-trailer checkout) is REFUSED, because accepting it would
re-open the exact hole the trailer closes — a truncation that cuts the
trailer off cleanly would read as a valid legacy file.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import zlib

__all__ = [
    "write_control_json",
    "read_control_json",
    "control_trailer",
]


def control_trailer(body: bytes) -> dict:
    """The integrity trailer for a payload line (newline included)."""
    return {"len": len(body), "crc32": f"{zlib.crc32(body) & 0xFFFFFFFF:08x}"}


def write_control_json(dir: str, path: str, payload: dict) -> None:
    """Atomically write ``payload`` to ``path`` with an integrity trailer.

    The tmp file lives in ``dir`` (same filesystem as ``path``, so the
    ``os.replace`` stays atomic); a failed write never leaves the tmp
    behind."""
    body = (json.dumps(payload, sort_keys=True) + "\n").encode("utf-8")
    trailer = (json.dumps(control_trailer(body), sort_keys=True) + "\n").encode(
        "utf-8"
    )
    fd, tmp = tempfile.mkstemp(dir=dir, suffix=".ctrl.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(body + trailer)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _parse(raw: bytes) -> dict | None:
    """Payload dict iff ``raw`` is a trailered control file whose trailer
    verifies; None otherwise (missing/malformed/mismatched trailer, or a
    payload that is not a JSON object)."""
    # the trailer is the LAST newline-terminated line; everything before
    # it is the payload bytes the trailer certifies.  The terminator is
    # part of the format: a file missing its final newline lost at least
    # one byte, so truncation at EVERY offset — including the last — is
    # refused.
    if not raw.endswith(b"\n"):
        return None
    stripped = raw.rstrip(b"\n")
    nl = stripped.rfind(b"\n")
    if nl < 0:
        return None  # one line: no trailer at all
    body, trailer_line = raw[: nl + 1], stripped[nl + 1 :]
    try:
        trailer = json.loads(trailer_line)
    except ValueError:
        return None
    if not isinstance(trailer, dict):
        return None
    expect = control_trailer(body)
    if (
        trailer.get("len") != expect["len"]
        or trailer.get("crc32") != expect["crc32"]
    ):
        return None
    try:
        payload = json.loads(body)
    except ValueError:
        return None  # CRC of garbage that collided is not worth modeling
    return payload if isinstance(payload, dict) else None


#: paths whose torn state was already reported (edge detection: a
#: persistently unparseable file — a stuck legacy artifact in a reused
#: dir — must not spam one flight event per poll; cleared the moment the
#: path reads clean again)
_torn_reported: set = set()


def read_control_json(
    path: str,
    *,
    rereads: int = 2,
    reread_delay_s: float = 0.005,
    _sleep=time.sleep,
) -> dict | None:
    """Read a trailered control file; ``None`` when absent or torn.

    A trailer mismatch triggers up to ``rereads`` re-reads (with atomic
    writers a mismatch is a transient race with a non-atomic scribbler;
    re-reads stop early when the bytes are not changing — a static bad
    file cannot heal by waiting); a mismatch that SURVIVES the rereads is
    recorded as a ``torn_control_file`` flight event ONCE per torn
    episode — parse-refuse, never a ``JSONDecodeError`` on the polling
    thread — and reads as absent, so the caller's next poll sees the
    eventual replace."""
    saw_bytes = False
    prev_raw = None
    for attempt in range(max(0, rereads) + 1):
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None  # absent: the common pre-first-publish case
        saw_bytes = saw_bytes or bool(raw)
        payload = _parse(raw)
        if payload is not None:
            _torn_reported.discard(path)
            return payload
        if raw == prev_raw:
            break  # static content: nobody is mid-write, stop waiting
        prev_raw = raw
        if attempt < rereads:
            _sleep(reread_delay_s)
    if saw_bytes and path not in _torn_reported:
        _torn_reported.add(path)
        from ..obs import record_event

        record_event(
            "torn_control_file",
            path=os.path.basename(path),
            bytes=len(raw),
            rereads=rereads,
        )
    return None
