"""Coordinated elastic control plane: epoch consensus for group decisions.

Every elastic decision this stack makes — a drift-triggered replan
(``planner.feedback``), a shrink-to-survivors (``parallel.loop.fit``), an
arbiter lease resize (``runtime.leases``) — was decided and applied by
each rank independently.  That is sound on an in-process mesh, where
"each rank" is one process; on a real multi-process group it is a split
brain waiting to happen: two ranks observing slightly different residuals
replan to different topologies and the next collective deadlocks, or one
rank misses a death and keeps waiting on a world the others already
shrank away from.

This module turns every elastic event into a **two-phase group decision**
over the heartbeat/lease directory (the same atomic tmp+replace,
single-writer-per-file idiom ``runtime.leases`` uses, hardened by
``runtime.ctrlfile``'s CRC trailers):

1. **propose** — the *coordinator* (rank 0, or the failover successor:
   the lowest-rank healthy member) observes drift / death / SLO pressure
   and publishes ``coord_proposal.json`` carrying a strictly-increasing
   **control epoch**, the decision kind + payload, the participant set,
   an ack deadline bounded by the lease budget, and the step boundary the
   group will apply at;
2. **ack** — every participant that reads the proposal writes
   ``coord_ack_{rank}.json`` naming the epoch.  An ack is a promise: the
   rank will pause at the apply boundary until the decision resolves;
3. **commit** — only once every participant's ack is in does the
   coordinator publish ``coord_commit.json`` (same epoch, same payload —
   the commit IS the proposal, sealed), and all ranks apply at the
   agreed step boundary.  A participant that misses the ack deadline is
   excluded: the coordinator **re-proposes** the decision at the next
   epoch for the ranks that did ack, and the excluded rank — resumed
   from its SIGSTOP, say — finds the epoch moved past it and is
   **fenced** (:class:`EpochFenced`): it exits loudly rather than
   training on a stale plan.

Failure cases the protocol survives (executed by ``tools/coord_chaos.py``
→ ``COORD_CHAOS.json``):

- **coordinator death at any phase**: the successor (lowest-rank healthy
  member) re-reads the directory and either *completes* the in-flight
  commit (every ack present → publish the commit at the SAME epoch:
  idempotent, because a commit for epoch E is uniquely the proposal for
  epoch E — two writers racing write byte-identical decisions) or
  *re-proposes* at the next epoch for the survivors.  No rank can
  double-apply: applied epochs strictly increase per rank, and an epoch
  commits at most one decision;
- **stalled/partitioned ranks**: SIGSTOP past the ack deadline → excluded
  and fenced on resume (above);
- **torn/duplicate control files**: every file carries a CRC trailer;
  a torn file parse-refuses and re-reads (``runtime.ctrlfile``), and a
  duplicate/replayed proposal or commit is rejected by epoch
  monotonicity.

The protocol is deliberately tick-driven and thread-free: ``fit`` calls
:meth:`CoordinationHandle.gate` once per loop iteration, the same way it
polls membership and the lease client.  All clocks are injectable for the
property suite (``tests/test_coordination.py``), which drives randomized
interleavings of propose/ack/commit/failover against the invariants:
epochs strictly increase, at most one commit per epoch, no rank applies
uncommitted state.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Callable

from ..utils.logging import get_logger
from .ctrlfile import read_control_json, write_control_json

__all__ = [
    "PROPOSAL_FILE",
    "COMMIT_FILE",
    "EpochFenced",
    "CoordinationAbandoned",
    "ProtocolViolation",
    "ControlDecision",
    "decision_fingerprint",
    "CoordLedger",
    "CoordinationConfig",
    "CoordinationHandle",
    "committed_shrink_plan",
]

log = get_logger("flextree.runtime")

PROPOSAL_FILE = "coord_proposal.json"
COMMIT_FILE = "coord_commit.json"
_ACK_FMT = "coord_ack_{rank:05d}.json"

# injection point for tests (patch this, not time.time): control files are
# read across processes, so stamps are wall time like heartbeat beats
_wall = time.time


class EpochFenced(RuntimeError):
    """This rank was excluded from a committed control epoch (it missed
    the ack window — stalled, partitioned, or resumed from a SIGSTOP
    after the group moved on).  Training on the stale plan would wedge or
    silently diverge the group's next collective: exit loudly instead."""


class CoordinationAbandoned(RuntimeError):
    """An acked proposal never resolved (no commit, no re-proposal, no
    successor) within the resolve budget — every healthy peer is gone.
    The rank refuses to guess and exits loudly."""


class ProtocolViolation(RuntimeError):
    """The control directory contradicts the protocol invariants (two
    different decisions at one epoch, an epoch moving backwards) — a bug
    or an adversarial writer, never smoothed over."""


@dataclasses.dataclass(frozen=True)
class ControlDecision:
    """One group decision: what to apply, who applies it, at which epoch
    and step boundary.

    ``kind``: ``"replan"`` (drift-triggered refit+replan, payload carries
    the refitted constants + topo spec), ``"shrink"`` (dead peers,
    payload carries the survivor set + replanned topo) or ``"resize"``
    (arbiter lease change, payload carries the lease epoch + chip set).
    ``participants`` is the rank set whose acks gate the commit and which
    the commit fences everyone else out of.  ``apply_step`` is the step
    boundary every participant applies at (``None``: apply at the next
    boundary after the commit is observed)."""

    epoch: int
    kind: str
    payload: dict
    participants: tuple
    coordinator: int
    apply_step: int | None = None
    wall: float = 0.0

    def to_payload(self) -> dict:
        return {
            "epoch": self.epoch,
            "kind": self.kind,
            "payload": self.payload,
            "participants": sorted(self.participants),
            "coordinator": self.coordinator,
            "apply_step": self.apply_step,
            "wall": self.wall,
            "fingerprint": self.fingerprint,
        }

    @classmethod
    def from_payload(cls, doc: dict) -> "ControlDecision":
        return cls(
            epoch=int(doc["epoch"]),
            kind=str(doc["kind"]),
            payload=dict(doc["payload"]),
            participants=tuple(int(r) for r in doc["participants"]),
            coordinator=int(doc["coordinator"]),
            apply_step=(
                int(doc["apply_step"]) if doc.get("apply_step") is not None
                else None
            ),
            wall=float(doc.get("wall", 0.0)),
        )

    @property
    def fingerprint(self) -> str:
        return decision_fingerprint(self.kind, self.payload)


# The closed set of decision kinds the control plane carries.  Shared
# with the extracted transition model (`runtime/coord_model.py`) so the
# protocol checker and the implementation cannot silently diverge on
# what a decision IS; `tests/test_control_plane_analysis.py` pins both
# sides to this tuple.
DECISION_KINDS = ("replan", "shrink", "resize")


def decision_fingerprint(kind: str, payload: dict) -> str:
    """Stable content hash of a decision — the quantity the chaos floors
    compare across survivors ("same plan fingerprint") and the idempotency
    token for commit-at-same-epoch writes."""
    blob = json.dumps({"kind": kind, "payload": payload}, sort_keys=True)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


class CoordLedger:
    """The control-file layer: one proposal slot, one commit slot, one
    ack file per rank — all CRC-trailered, all atomically replaced.

    Mechanics only; the state machine lives in
    :class:`CoordinationHandle`.  Epoch rules enforced here:

    - a proposal's epoch must exceed both the published proposal's and
      the published commit's (strictly-increasing control epochs);
    - a commit must match an epoch's proposal content exactly
      (fingerprint); publishing the SAME commit twice is a no-op (the
      failover successor completing an in-flight commit races the dying
      coordinator's own write — both write byte-identical decisions);
      publishing a DIFFERENT decision at a committed epoch is a
      :class:`ProtocolViolation`.
    """

    def __init__(self, dir: str):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        # stat-guarded read cache for the two slot files: the gate runs
        # every training step and the slots are idle >99% of the time —
        # an unchanged (mtime_ns, size, inode) answers from memory, so
        # the idle path costs two stat calls, not two read+CRC passes
        # (tmp+replace always changes the inode, so the key can't alias)
        self._slot_cache: dict = {}

    def _cached_slot(self, path: str):
        try:
            st = os.stat(path)
        except OSError:
            self._slot_cache.pop(path, None)
            return None
        key = (st.st_mtime_ns, st.st_size, st.st_ino)
        hit = self._slot_cache.get(path)
        if hit is not None and hit[0] == key:
            return hit[1]
        doc = read_control_json(path)
        if doc is None:
            # torn/absent: never cache a refusal — the replace that heals
            # it must be seen immediately
            self._slot_cache.pop(path, None)
            return None
        self._slot_cache[path] = (key, doc)
        return doc

    @property
    def proposal_path(self) -> str:
        return os.path.join(self.dir, PROPOSAL_FILE)

    @property
    def commit_path(self) -> str:
        return os.path.join(self.dir, COMMIT_FILE)

    def _ack_path(self, rank: int) -> str:
        return os.path.join(self.dir, _ACK_FMT.format(rank=rank))

    # ---- proposal slot ----------------------------------------------------

    def publish_proposal(
        self, decision: ControlDecision, ack_deadline_wall: float
    ) -> None:
        cur = self.read_proposal()
        committed = self.read_commit()
        floor = max(
            cur[0].epoch if cur is not None else -1,
            committed.epoch if committed is not None else -1,
        )
        if decision.epoch <= floor:
            raise ProtocolViolation(
                f"control epoch must increase: proposed {decision.epoch} <= "
                f"published {floor}"
            )
        write_control_json(
            self.dir,
            self.proposal_path,
            {**decision.to_payload(), "ack_deadline_wall": ack_deadline_wall},
        )

    def read_proposal(self) -> tuple[ControlDecision, float] | None:
        doc = self._cached_slot(self.proposal_path)
        if doc is None:
            return None
        try:
            return (
                ControlDecision.from_payload(doc),
                float(doc.get("ack_deadline_wall", 0.0)),
            )
        except (ValueError, KeyError, TypeError):
            return None

    def next_epoch(self) -> int:
        cur = self.read_proposal()
        committed = self.read_commit()
        return 1 + max(
            cur[0].epoch if cur is not None else -1,
            committed.epoch if committed is not None else -1,
        )

    # ---- acks -------------------------------------------------------------

    def ack(self, rank: int, epoch: int, extra: dict | None = None) -> None:
        """Write ``rank``'s ack for ``epoch``.  ``extra`` rides along in
        the same file — the follower-drift channel: a rank's local
        drift-window summary (``planner.feedback.DriftDetector.summary``)
        ships under ``extra["drift"]`` so the coordinator's next propose
        decision sees pooled cross-rank skew, not just its own wire."""
        write_control_json(
            self.dir,
            self._ack_path(rank),
            {
                **(extra or {}),
                "rank": int(rank),
                "epoch": int(epoch),
                "wall": _wall(),
            },
        )

    def read_ack_docs(self) -> dict[int, dict]:
        """{rank: full ack payload} over every ack file in the dir."""
        out: dict[int, dict] = {}
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("coord_ack_") and name.endswith(".json")):
                continue
            doc = read_control_json(os.path.join(self.dir, name))
            if doc is None:
                continue
            try:
                out[int(doc["rank"])] = doc
            except (ValueError, KeyError, TypeError):
                continue
        return out

    def read_acks(self) -> dict[int, int]:
        """{rank: newest acked epoch} over every ack file in the dir."""
        out: dict[int, int] = {}
        for rank, doc in self.read_ack_docs().items():
            try:
                out[rank] = int(doc["epoch"])
            except (ValueError, KeyError, TypeError):
                continue
        return out

    # ---- commit slot ------------------------------------------------------

    def publish_commit(self, decision: ControlDecision) -> bool:
        """Seal ``decision``.  True when this call wrote the commit; False
        when an identical commit already existed (the idempotent failover
        race).  A different decision at the same-or-newer epoch raises."""
        cur = self.read_commit()
        if cur is not None:
            if cur.epoch > decision.epoch:
                raise ProtocolViolation(
                    f"commit epoch moving backwards: {decision.epoch} after "
                    f"{cur.epoch}"
                )
            if cur.epoch == decision.epoch:
                if cur.fingerprint != decision.fingerprint:
                    raise ProtocolViolation(
                        f"two decisions at epoch {decision.epoch}: committed "
                        f"{cur.fingerprint}, proposed {decision.fingerprint}"
                    )
                return False  # already sealed: the idempotent no-op
        write_control_json(self.dir, self.commit_path, decision.to_payload())
        return True

    def read_commit(self) -> ControlDecision | None:
        doc = self._cached_slot(self.commit_path)
        if doc is None:
            return None
        try:
            return ControlDecision.from_payload(doc)
        except (ValueError, KeyError, TypeError):
            return None


@dataclasses.dataclass(frozen=True)
class CoordinationConfig:
    """Budgets, all lease-bounded so one protocol round can never outlive
    the membership machinery that supervises it.

    ``ack_timeout_s``: how long a proposal waits for acks before the
    coordinator excludes the missing ranks and re-proposes (default: one
    lease window — a rank that cannot ack within a lease would be
    classified dead anyway).  ``resolve_timeout_s``: how long a follower
    blocked at an apply boundary waits for the decision to resolve before
    raising :class:`CoordinationAbandoned` (default: 4 lease windows —
    enough for a coordinator death + successor takeover + re-propose).
    ``apply_margin_steps``: how far past the newest observed peer step the
    coordinator schedules the apply boundary.  ``poll_interval_s``: the
    sleep between polls while blocked at a boundary."""

    ack_timeout_s: float = 3.0
    resolve_timeout_s: float = 12.0
    apply_margin_steps: int = 2
    poll_interval_s: float = 0.05

    @classmethod
    def for_lease(cls, lease_s: float, **overrides) -> "CoordinationConfig":
        kw = dict(
            ack_timeout_s=lease_s,
            resolve_timeout_s=4.0 * lease_s,
        )
        kw.update(overrides)
        return cls(**kw)


class CoordinationHandle:
    """One rank's view of the control plane: follower duties always
    (ack proposals, surface commits, fence itself), coordinator duties
    whenever this rank is the lowest-rank healthy member.

    ``membership``: a :class:`~flextree_tpu.runtime.MembershipView` (or
    any callable returning ``{rank: state_str}``) — the same source
    ``fit`` polls; ``None`` pins this rank as the sole coordinator (the
    single-process degenerate case, where the protocol reduces to a
    journal).  The handle never spawns threads: drive it with
    :meth:`gate` (one call per step) and, for event-driven proposals,
    :meth:`propose`.

    The flight record carries every transition: ``coord_propose``,
    ``coord_ack``, ``coord_commit``, ``coord_repropose``,
    ``coord_failover``, ``coord_fence``, ``coord_apply`` — rendered as
    the dedicated coordination lane of the merged timeline
    (``obs/timeline.py``).
    """

    def __init__(
        self,
        dir_or_ledger,
        rank: int,
        *,
        membership: Any = None,
        cfg: CoordinationConfig | None = None,
        on_fence: Callable | None = None,
        _sleep=time.sleep,
    ):
        self.ledger = (
            dir_or_ledger
            if isinstance(dir_or_ledger, CoordLedger)
            else CoordLedger(dir_or_ledger)
        )
        self.rank = int(rank)
        self.membership = membership
        self.cfg = cfg or CoordinationConfig()
        self.on_fence = on_fence
        self._sleep = _sleep
        # follower-drift channel: when set (FeedbackController wires its
        # detector's summary here), every ack this rank writes carries the
        # current drift-window summary under "drift" — the coordinator
        # reads the pooled view via peer_drift() before proposing
        self.drift_provider: Callable[[], dict] | None = None
        self._applied_epoch = -1
        self._acked_epoch = -1
        # follower-side boundary promise: (epoch, apply_step) of the
        # newest proposal this rank acked that has not resolved yet
        # (+ the wall stamp of the ack, for the no-boundary abandon check)
        self._pending: tuple[int, int | None] | None = None
        self._pending_wall = 0.0
        # commit observed but held back until its apply boundary
        self._held: ControlDecision | None = None
        self._was_coordinator: bool | None = None
        self.applied: list[int] = []  # epochs applied, in order (audit)

    # ---- membership --------------------------------------------------------

    def _statuses(self) -> dict[int, str] | None:
        m = self.membership
        if m is None:
            return None
        if hasattr(m, "poll"):
            return {r: s.state for r, s in m.poll().items()}
        return dict(m())

    def _alive_ranks(self) -> tuple[int, ...]:
        """Non-dead ranks (self always counts: our own beat may be stale
        to our own reader thread, but we are demonstrably running)."""
        statuses = self._statuses()
        if statuses is None:
            return (self.rank,)
        alive = {r for r, st in statuses.items() if st != "dead"}
        alive.add(self.rank)
        return tuple(sorted(alive))

    def _healthy_ranks(self) -> tuple[int, ...]:
        statuses = self._statuses()
        if statuses is None:
            return (self.rank,)
        healthy = {r for r, st in statuses.items() if st == "healthy"}
        healthy.add(self.rank)
        return tuple(sorted(healthy))

    @property
    def is_coordinator(self) -> bool:
        """Coordinator = the lowest-rank healthy member.  Rank 0 while it
        lives; the failover successor after."""
        return self.rank == min(self._healthy_ranks())

    def suggest_apply_step(self) -> int | None:
        """A step boundary comfortably ahead of every peer: the newest
        step any beat reports plus ``apply_margin_steps`` — far enough
        that the commit lands before anyone reaches it, so the whole
        group flips plans at ONE boundary.  ``None`` when the membership
        source carries no step info (apply at first observation)."""
        m = self.membership
        if m is None or not hasattr(m, "poll"):
            return None
        steps = [
            s.step for s in m.poll().values()
            if getattr(s, "step", None) is not None and s.step >= 0
        ]
        if not steps:
            return None
        return max(steps) + max(1, self.cfg.apply_margin_steps)

    # ---- proposing (coordinator side) --------------------------------------

    def propose(
        self, kind: str, payload: dict, *, apply_step: int | None = None
    ) -> int | None:
        """Publish a proposal (coordinator only; followers get ``None`` —
        their observation is not authority).  Returns the control epoch.
        A proposal already in flight wins: one decision at a time, the
        new observation re-fires on a later tick once the slot clears."""
        if not self.is_coordinator:
            return None
        inflight = self.ledger.read_proposal()
        committed = self.ledger.read_commit()
        committed_epoch = committed.epoch if committed is not None else -1
        if inflight is not None and inflight[0].epoch > committed_epoch:
            return None  # a decision is mid-handshake: never interleave two
        if committed_epoch > self._applied_epoch:
            # a sealed decision this rank has not applied yet: apply what
            # is committed before deciding anew (a proposal here would
            # race its own gate and duplicate the in-flight decision)
            return None
        epoch = self.ledger.next_epoch()
        decision = ControlDecision(
            epoch=epoch,
            kind=kind,
            payload=payload,
            participants=self._alive_ranks(),
            coordinator=self.rank,
            apply_step=apply_step,
            wall=_wall(),
        )
        deadline = _wall() + self.cfg.ack_timeout_s
        try:
            self.ledger.publish_proposal(decision, deadline)
        except ProtocolViolation:
            # lost a propose race (divergent membership views made two
            # ranks coordinator for a beat, or a publish landed between
            # our epoch read and our write): back off — the caller
            # retries on a later tick against the slot's winner.  A
            # crash here would turn a benign split-second overlap into
            # a dead healthy rank.
            return None
        self._record(
            "coord_propose", epoch=epoch, decision=kind,
            participants=sorted(decision.participants),
            apply_step=apply_step, fingerprint=decision.fingerprint,
        )
        log.warning(
            "coord: rank %d proposed epoch %d (%s) to %s, apply_step=%s",
            self.rank, epoch, kind, sorted(decision.participants), apply_step,
        )
        # the proposer's own ack, immediately — it is a participant too
        self._ack(decision)
        return epoch

    # ---- the per-step gate -------------------------------------------------

    def gate(self, step: int) -> ControlDecision | None:
        """One protocol tick.  Returns a committed decision this rank must
        apply NOW (at this step boundary), else ``None``.  Blocks —
        bounded by ``resolve_timeout_s`` — when this rank promised (acked)
        a boundary at-or-before ``step`` and the decision has not resolved:
        proceeding would run the boundary step on the old plan while acked
        peers run the new one."""
        decision = self._poll(step)
        if decision is not None:
            return decision
        pending = self._pending
        if pending is None:
            return None
        p_epoch, p_apply = pending
        if p_apply is None:
            # no named boundary: the promise doesn't bind any step, so
            # keep stepping — but an acked decision that NOBODY resolves
            # (no commit, no re-proposal, no driver) within the resolve
            # budget still means the control plane is dead, and the
            # failure model promises a loud typed exit, not an
            # indefinitely wedged handshake
            if _wall() - self._pending_wall > self.cfg.resolve_timeout_s:
                raise CoordinationAbandoned(
                    f"rank {self.rank} acked control epoch {p_epoch} "
                    f"(no apply boundary) and nothing resolved it within "
                    f"{self.cfg.resolve_timeout_s:.1f}s — no healthy peer "
                    "left to drive the decision"
                )
            return None
        if step < p_apply:
            # not at the boundary yet: keep stepping, keep polling
            return None
        deadline = _wall() + self.cfg.resolve_timeout_s
        while _wall() < deadline:
            decision = self._poll(step)
            if decision is not None:
                return decision
            if self._pending is None or self._pending[0] != p_epoch:
                # resolved without an apply for us: superseded (we acked a
                # newer proposal — loop back to honor ITS boundary) or the
                # commit excluded us (fenced inside _poll)
                return self.gate(step)
            self._sleep(self.cfg.poll_interval_s)
        raise CoordinationAbandoned(
            f"rank {self.rank} acked control epoch {p_epoch} but no commit, "
            f"re-proposal or successor appeared within "
            f"{self.cfg.resolve_timeout_s:.1f}s — no healthy peer left to "
            "resolve the decision"
        )

    def mark_applied(self, decision: ControlDecision) -> None:
        """The caller applied ``decision`` — advance the fence.  Applied
        epochs strictly increase per rank, so a replayed commit can never
        double-apply (the chaos floors count ``coord_apply`` events per
        (rank, epoch))."""
        if decision.epoch <= self._applied_epoch:
            raise ProtocolViolation(
                f"rank {self.rank} double-apply: epoch {decision.epoch} "
                f"after {self._applied_epoch}"
            )
        self._applied_epoch = decision.epoch
        self.applied.append(decision.epoch)
        if self._pending is not None and self._pending[0] <= decision.epoch:
            self._pending = None
        self._record(
            "coord_apply", epoch=decision.epoch, decision=decision.kind,
            fingerprint=decision.fingerprint,
        )

    @property
    def applied_epoch(self) -> int:
        return self._applied_epoch

    @property
    def phase(self) -> str:
        """Where the in-flight handshake stands from this rank's view —
        the field every guaranteed failure dump attaches so a postmortem
        can say WHICH phase the fault interrupted: ``"commit"`` (sealed
        but unapplied here), ``"ack_wait"`` (we acked, unresolved),
        ``"propose"`` (proposal observed, not acked), ``"idle"``."""
        if self._held is not None:
            return "commit"
        committed = self.ledger.read_commit()
        ce = committed.epoch if committed is not None else -1
        if ce > self._applied_epoch:
            return "commit"
        prop = self.ledger.read_proposal()
        if prop is not None and prop[0].epoch > ce:
            if max(self._acked_epoch, self._applied_epoch) >= prop[0].epoch:
                return "ack_wait"
            return "propose"
        return "idle"

    # ---- internals ---------------------------------------------------------

    def _record(self, kind: str, **fields) -> None:
        from ..obs import record_event

        record_event(kind, coord_rank=self.rank, **fields)

    def peer_drift(self, min_epoch: int | None = None) -> dict[int, dict]:
        """{rank: drift-window summary} from every OTHER rank's newest
        ack — the pooled cross-rank skew view the feedback controller's
        propose decision consumes.  Summaries are only as fresh as each
        rank's last ack (a group with no prior decision has none yet —
        the first proposal is decided from the coordinator's own view).

        ``min_epoch`` drops summaries attached to acks for OLDER epochs:
        an ack is written when a rank *observes* a proposal — before the
        apply resets its detector — so after a replan commits at epoch E,
        every surviving ack's drift describes the PRE-refit world.
        Pooling those would immediately re-trigger the drift that was
        just corrected; the controller passes ``applied_epoch + 1`` so
        only summaries written since the last applied decision count."""
        out: dict[int, dict] = {}
        for rank, doc in self.ledger.read_ack_docs().items():
            if rank == self.rank:
                continue
            if min_epoch is not None:
                try:
                    if int(doc.get("epoch", -1)) < min_epoch:
                        continue
                except (TypeError, ValueError):
                    continue
            drift = doc.get("drift")
            if isinstance(drift, dict) and drift:
                out[rank] = drift
        return out

    def _ack(self, decision: ControlDecision) -> None:
        extra = None
        if self.drift_provider is not None:
            try:
                summary = self.drift_provider()
            except Exception:  # noqa: BLE001 — telemetry never blocks an ack
                summary = None
            if summary:
                extra = {"drift": summary}
        self.ledger.ack(self.rank, decision.epoch, extra=extra)
        self._acked_epoch = decision.epoch
        self._pending = (decision.epoch, decision.apply_step)
        self._pending_wall = _wall()
        self._record(
            "coord_ack", epoch=decision.epoch, decision=decision.kind,
            apply_step=decision.apply_step,
        )

    def _fence(self, committed: ControlDecision) -> None:
        self._record(
            "coord_fence", epoch=committed.epoch, decision=committed.kind,
            participants=sorted(committed.participants),
        )
        log.error(
            "coord: rank %d FENCED — epoch %d (%s) committed to %s without "
            "us (we missed the ack window); exiting rather than training on "
            "a stale plan",
            self.rank, committed.epoch, committed.kind,
            sorted(committed.participants),
        )
        from ..obs import dump_current

        dump_current(
            "coord_fence", epoch=committed.epoch, kind=committed.kind,
            coord_phase="commit",
        )
        if self.on_fence is not None:
            self.on_fence(committed)
        raise EpochFenced(
            f"rank {self.rank} excluded from committed control epoch "
            f"{committed.epoch} ({committed.kind}); participants "
            f"{sorted(committed.participants)}"
        )

    def _poll(self, step: int) -> ControlDecision | None:
        """One non-blocking protocol scan: follower duties, then
        coordinator duties."""
        # -- commits first: the commit is the authority
        held = self._held
        if held is None:
            committed = self.ledger.read_commit()
            if committed is not None and committed.epoch > self._applied_epoch:
                if self.rank not in committed.participants:
                    self._fence(committed)  # raises
                self._held = held = committed
        if held is not None:
            if held.apply_step is None or step >= held.apply_step:
                self._held = None
                return held
        # -- proposals: ack anything newer than what we acked
        prop = self.ledger.read_proposal()
        if prop is not None:
            decision, deadline = prop
            if (
                decision.epoch > max(self._acked_epoch, self._applied_epoch)
                and self.rank in decision.participants
            ):
                self._ack(decision)
        # -- coordinator duties (incl. failover takeover)
        self._drive(prop)
        return None

    def _drive(self, prop) -> None:
        """Advance an in-flight proposal: commit it when every ack is in,
        exclude-and-re-propose past the deadline, take over from a dead
        coordinator."""
        if prop is None:
            # nothing in flight: skip the membership poll entirely (the
            # idle-path cost of gate() stays two control-file reads).
            # None = "leadership unknown"; the takeover edge below treats
            # it as not-previously-coordinator, which is exactly right —
            # inheriting a dead proposer's decision IS a failover.
            self._was_coordinator = None
            return
        decision, deadline = prop
        committed = self.ledger.read_commit()
        if committed is not None and committed.epoch >= decision.epoch:
            return  # nothing in flight
        # one membership scan per drive tick: statuses feed both the
        # who-is-coordinator question and the missing-rank classification
        statuses = self._statuses()
        if statuses is None:
            healthy = {self.rank}
            statuses = {}
        else:
            healthy = {
                r for r, st in statuses.items() if st == "healthy"
            } | {self.rank}
        if self.rank != min(healthy):
            self._was_coordinator = False
            return
        # the CURRENT coordinator drives ANY in-flight proposal — its
        # owner is either us, dead, or demoted (a healthy owner ranked
        # below us would make us not-coordinator; a recovered straggler
        # ranked above us stopped driving the moment we became lowest
        # healthy).  Deferring to a live-but-demoted owner deadlocks the
        # slot: it won't drive (not coordinator) and neither would we.
        if decision.coordinator != self.rank and not self._was_coordinator:
            # takeover edge — announce once, then drive like any other
            self._record(
                "coord_failover", epoch=decision.epoch,
                dead_coordinator=decision.coordinator, decision=decision.kind,
                owner_state=statuses.get(decision.coordinator),
            )
            log.warning(
                "coord: rank %d taking over epoch %d from coordinator "
                "rank %d (%s)", self.rank, decision.epoch,
                decision.coordinator,
                statuses.get(decision.coordinator, "unknown"),
            )
        self._was_coordinator = True
        acks = self.ledger.read_acks()
        missing = [
            r for r in decision.participants
            if acks.get(r, -1) < decision.epoch
        ]
        if not missing:
            try:
                wrote = self.ledger.publish_commit(decision)
            except ProtocolViolation as e:
                # two drivers raced the non-CAS epoch floor (a
                # straggler-classified old coordinator still driving
                # beside us) and the slot sealed a DIFFERENT decision
                # first.  The sealed commit is the authority: back off,
                # re-read it next tick (deliver or fence) — crashing a
                # healthy rank over a lost race would turn a benign
                # split-second overlap into an outage.
                self._record(
                    "coord_commit_race", epoch=decision.epoch,
                    reason=str(e)[:200],
                )
                log.warning(
                    "coord: rank %d lost a commit race at epoch %d: %s",
                    self.rank, decision.epoch, e,
                )
                return
            if wrote:
                self._record(
                    "coord_commit", epoch=decision.epoch, decision=decision.kind,
                    participants=sorted(decision.participants),
                    fingerprint=decision.fingerprint,
                )
                log.warning(
                    "coord: rank %d committed epoch %d (%s)",
                    self.rank, decision.epoch, decision.kind,
                )
            return
        now = _wall()
        if now < deadline and not all(
            statuses.get(r) == "dead" for r in missing
        ):
            return  # inside the window and somebody may still ack: wait
        # deadline passed (or every missing rank is confirmed dead):
        # exclude the silent ranks and re-propose for the ones that acked
        survivors = tuple(
            sorted(
                r for r in decision.participants
                if acks.get(r, -1) >= decision.epoch or r == self.rank
            )
        )
        epoch = self.ledger.next_epoch()
        redo = ControlDecision(
            epoch=epoch,
            kind=decision.kind,
            payload=decision.payload,
            participants=survivors,
            coordinator=self.rank,
            apply_step=decision.apply_step,
            wall=now,
        )
        try:
            self.ledger.publish_proposal(redo, now + self.cfg.ack_timeout_s)
        except ProtocolViolation:
            # lost a re-propose race (a demoted-but-running old
            # coordinator published first): the next tick re-reads the
            # winner from the slot and acks it like any follower
            return
        self._record(
            "coord_repropose", epoch=epoch, prev_epoch=decision.epoch,
            decision=decision.kind, excluded=sorted(missing),
            participants=sorted(survivors),
        )
        log.warning(
            "coord: rank %d re-proposed epoch %d (was %d): ranks %s missed "
            "the ack window and are excluded",
            self.rank, epoch, decision.epoch, sorted(missing),
        )
        self._ack(redo)


def apply_spec_override(plan, spec, n: int):
    """Override ``plan``'s topology with the broadcast FT_TOPO spec when
    they disagree — ONE definition shared by the shrink, replan and
    resize commit paths, so a rank whose local calibration skews must
    still run the group's plan (and a future spec-grammar change cannot
    make two apply sites read the same commit differently).  Ring specs
    normalize (``"ring"`` ≡ ``"1"``)."""
    if not spec:
        return plan
    spec = str(spec).strip()
    spec = "1" if spec == "ring" else spec
    if plan.to_ft_topo() == spec:
        return plan
    from ..schedule.stages import Topology

    log.warning(
        "coord: local replan picked %s but the committed plan is %s — "
        "following the group", plan.to_ft_topo(), spec,
    )
    return dataclasses.replace(plan, topology=Topology.resolve(n, spec))


def committed_shrink_plan(payload: dict, nbytes: int):
    """Reconstruct the group-wide survivor plan from a committed shrink
    payload: every rank replans locally for the broadcast survivor count,
    then the broadcast topo spec OVERRIDES the local winner."""
    from ..planner.choose import replan_for_survivors

    n_alive = int(payload["alive"])
    configured = payload.get("configured")
    plan = replan_for_survivors(
        n_alive, nbytes, configured=int(configured) if configured else None
    )
    return apply_spec_override(plan, payload.get("topo"), n_alive)
