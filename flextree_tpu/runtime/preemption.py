"""Preemption-aware checkpointing: SIGTERM → checkpoint now, saves off
the step path.

Preemptible capacity (spot VMs, TPU preemptions, kubernetes evictions)
delivers SIGTERM with a short grace window.  Losing ``ckpt_every`` steps
of work to every preemption makes cheap capacity expensive; the two
pieces here shrink the rewind window from both ends:

- :class:`PreemptionGuard` — an async-signal-safe SIGTERM trap.  The
  handler only sets an event (nothing else is safe in a signal handler);
  ``fit`` polls it every loop iteration and takes the "checkpoint now"
  fast path — a synchronous save of the *current* state — before exiting
  cleanly, so at most one step of work is lost (pinned by the SIGTERM
  scenario of ``tools/chaos_runtime.py``).
- :class:`BackgroundSaver` — periodic saves without stalling steps.
  Serialization + fsync of a snapshot can take longer than a step; the
  saver owns a daemon thread with a depth-1 latest-wins slot, so the
  step loop's cost is handing over a (immutable) state pytree reference.
  Device arrays are host-gathered on the saver thread — ``jax`` arrays
  are immutable, so the snapshot is consistent no matter how many steps
  run in the meantime.  A new submit while a save is in flight replaces
  the pending one (newest state wins — exactly the checkpoint you want).

Both report what they did (``triggered_at``/``saves``/``errors``) so
``RunReport`` can account for them; neither raises into the step loop.
"""

from __future__ import annotations

import os
import signal
import threading
import time

from ..utils.logging import get_logger

__all__ = ["PreemptionGuard", "BackgroundSaver"]

log = get_logger("flextree.runtime")


class PreemptionGuard:
    """Latch SIGTERM (by default) into a pollable "checkpoint now" flag.

    ``install()`` replaces the handler (main thread only — a Python
    constraint) and remembers the previous one; ``uninstall()`` restores
    it.  ``trigger()`` is the in-process injection point for tests and
    for other delivery mechanisms (e.g. a cloud metadata watcher thread).
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._previous: dict[int, object] = {}
        self.triggered_at: float | None = None

    # -- delivery -----------------------------------------------------------

    def _handler(self, signum, frame):
        # async-signal-safe: set the flag, nothing else
        self.trigger()

    def trigger(self) -> None:
        if not self._event.is_set():
            self.triggered_at = time.time()
        self._event.set()

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    # -- lifecycle ----------------------------------------------------------

    def install(self) -> "PreemptionGuard":
        for sig in self.signals:
            self._previous[sig] = signal.signal(sig, self._handler)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            signal.signal(sig, prev)
        self._previous.clear()

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class BackgroundSaver:
    """Off-step-path checkpoint writer: depth-1 latest-wins save slot.

    ``submit(state)`` never blocks on I/O; the daemon thread host-gathers
    and writes via ``save_train_state`` (same rotation/integrity path as
    synchronous saves, so restores cannot tell them apart).  ``drain()``
    waits for the slot to empty — call it before process exit or before
    a synchronous save of the same directory (two writers racing the
    rotation is the one thing the design forbids).
    """

    def __init__(self, ckpt_dir: str | os.PathLike, *, max_to_keep: int = 3):
        self.ckpt_dir = os.fspath(ckpt_dir)
        self.max_to_keep = max_to_keep
        self.saves = 0
        self.dropped = 0  # submits coalesced away by latest-wins
        self.errors: list[str] = []
        self._pending = None  # guarded-by: _lock (depth-1 latest-wins slot)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ft-bg-ckpt"
        )
        self._thread.start()

    def submit(self, state) -> None:
        with self._lock:
            if self._pending is not None:
                self.dropped += 1
            self._pending = state
            self._idle.clear()
        self._wake.set()

    def _loop(self) -> None:
        from ..utils.checkpoint import save_train_state

        while True:
            self._wake.wait()
            with self._lock:
                state, self._pending = self._pending, None
                self._wake.clear()
                if state is None and self._stop:
                    self._idle.set()
                    return
            if state is None:
                self._idle.set()
                continue
            try:
                save_train_state(
                    self.ckpt_dir, state, max_to_keep=self.max_to_keep
                )
                self.saves += 1
            except Exception as e:  # never raises into the step loop
                self.errors.append(f"{type(e).__name__}: {e}")
                log.warning("background checkpoint failed: %s", e)
            with self._lock:
                if self._pending is None:
                    self._idle.set()

    def drain(self, timeout: float | None = 30.0) -> bool:
        """Wait until no save is pending or in flight."""
        return self._idle.wait(timeout)

    def close(self, timeout: float | None = 30.0) -> None:
        self.drain(timeout)
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=timeout)

    def __enter__(self) -> "BackgroundSaver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
