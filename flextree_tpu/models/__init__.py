"""Model substrate the collectives serve: dense transformer LM + MoE LM."""

from .generate import (
    cached_attention,
    decode_step,
    generate,
    init_kv_cache,
    prefill,
    prefill_ragged,
    sample_token,
)
from .moe import (
    MoEConfig,
    init_moe_params,
    moe_forward,
    moe_layer,
    moe_param_specs,
)
from .transformer import (
    TransformerConfig,
    attention_block,
    cross_entropy_loss,
    forward,
    init_params,
    layer_forward,
    mlp_block,
    param_specs,
)

__all__ = [
    "TransformerConfig",
    "cross_entropy_loss",
    "forward",
    "layer_forward",
    "attention_block",
    "mlp_block",
    "init_params",
    "param_specs",
    "MoEConfig",
    "init_moe_params",
    "moe_forward",
    "moe_layer",
    "moe_param_specs",
    "generate",
    "prefill",
    "prefill_ragged",
    "decode_step",
    "init_kv_cache",
    "sample_token",
    "cached_attention",
]
