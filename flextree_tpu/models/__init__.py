"""Model substrate: the flagship transformer LM the collectives serve."""

from .transformer import (
    TransformerConfig,
    cross_entropy_loss,
    forward,
    init_params,
    param_specs,
)

__all__ = [
    "TransformerConfig",
    "cross_entropy_loss",
    "forward",
    "init_params",
    "param_specs",
]
