"""Flagship model: a decoder-only transformer LM, sharded TPU-first.

The reference repo is a collectives library with no model layer (SURVEY
§2.6); this model is the framework's demonstration workload — the thing the
hierarchical allreduce, ring attention, and planner exist to serve.  Design
is MXU-friendly and mesh-native:

- **Tensor parallelism** over the ``tp`` mesh axis: QKV and MLP-up are
  column-parallel (each shard owns a contiguous slice of heads / hidden
  units), attention-out and MLP-down are row-parallel; the row-parallel
  partial sums are combined with the framework's own topology-parameterized
  ``flextree_tpu.parallel.allreduce`` — our collective is the TP backend,
  the moral equivalent of the reference interposing its allreduce under a
  host framework (``mpi_mod.hpp:1167-1171``).
- **Sequence parallelism** over the ``sp`` mesh axis, strategy selected by
  ``sp_impl``: ``ring_attention`` (K/V blocks walk the ring, flash-style
  accumulation) or ``ulysses_attention`` (all-to-all head/sequence
  re-shard, full-sequence local attention).
- **RoPE** positions (global offsets derived from the ``sp`` axis index),
  RMSNorm, GELU MLP, tied input/output embeddings — no learned position
  table, so sequence length is bounded only by memory.
- Pure functional: params are a plain dict pytree; ``forward`` works both
  as an ordinary single-device function (no axes bound) and as a
  collective-context function inside ``shard_map``.

All matmuls keep a (tokens, features) trailing structure with static shapes
so XLA tiles them onto the MXU; compute dtype is configurable (bfloat16 for
TPU), accumulation and softmax stay float32.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.allreduce import allreduce
from ..parallel.ring_attention import local_attention, ring_attention
from ..parallel.ulysses import ulysses_attention
from ..parallel.zigzag import zigzag_ring_attention

__all__ = [
    "TransformerConfig",
    "init_params",
    "param_specs",
    "forward",
    "layer_forward",
    "attention_block",
    "mlp_block",
    "final_logits",
    "global_positions",
    "cross_entropy_loss",
]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 256
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    rope_theta: float = 10000.0
    dtype: Any = jnp.float32  # compute dtype; params stay float32
    # topology spec for the TP-combining allreduce (None -> FT_TOPO/flat)
    tp_topo: Any = None
    # sequence-parallel attention strategy: "ring" (K/V walk the ring,
    # heads unconstrained), "zigzag" (the ring with the load-balanced
    # chunk-pair layout — critical path 2-1/n of plain causal ring's,
    # see ZIGZAG_ACCOUNTING.json; even local length),
    # or "ulysses" (two all-to-alls, needs the local head count divisible
    # by the sp axis size)
    sp_impl: str = "ring"
    # local attention compute: "reference" (jnp full-matrix) or "flash"
    # (fused Pallas kernel, ops.pallas_attention) — applies wherever the
    # full sequence is local (no sp axis, or the Ulysses inner attention)
    attn_impl: str = "reference"
    # extra kwargs for the flash kernel on the full-sequence-local path
    # (block_q / block_k / variant), as a hashable tuple of (key, value)
    # pairs so the frozen config stays usable as a jit static — e.g.
    # (("block_q", 1024), ("variant", "kvgrid")) to run the autotuned
    # winner instead of library defaults
    attn_opts: tuple = ()

    @property
    def head_dim(self) -> int:
        if self.d_model % self.n_heads:
            raise ValueError("d_model must be divisible by n_heads")
        return self.d_model // self.n_heads


def _dense_init(key, shape, scale):
    return (jax.random.normal(key, shape, jnp.float32) * scale).astype(jnp.float32)


def init_params(key, cfg: TransformerConfig) -> dict:
    """Full (unsharded) parameter pytree; shard_map in_specs slice it."""
    d, ff = cfg.d_model, cfg.d_ff
    keys = jax.random.split(key, 2 + cfg.n_layers)
    params = {
        "embed": _dense_init(keys[0], (cfg.vocab_size, d), 1.0 / math.sqrt(d)),
        "ln_f": jnp.ones((d,), jnp.float32),
        "layers": [],
    }
    out_scale = 1.0 / math.sqrt(d * 2 * cfg.n_layers)
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 6)
        params["layers"].append(
            {
                "ln1": jnp.ones((d,), jnp.float32),
                "wq": _dense_init(k[0], (d, d), 1.0 / math.sqrt(d)),
                "wk": _dense_init(k[1], (d, d), 1.0 / math.sqrt(d)),
                "wv": _dense_init(k[2], (d, d), 1.0 / math.sqrt(d)),
                "wo": _dense_init(k[3], (d, d), out_scale),
                "ln2": jnp.ones((d,), jnp.float32),
                "w1": _dense_init(k[4], (d, ff), 1.0 / math.sqrt(d)),
                "w2": _dense_init(k[5], (ff, d), out_scale),
            }
        )
    return params


def param_specs(cfg: TransformerConfig, tp_axis: str | None = "tp") -> dict:
    """PartitionSpec pytree matching ``init_params`` structure.

    Column-parallel weights shard their output dim over ``tp_axis``,
    row-parallel weights their input dim; everything else is replicated.
    """
    t = tp_axis
    layer = {
        "ln1": P(None),
        "wq": P(None, t),
        "wk": P(None, t),
        "wv": P(None, t),
        "wo": P(t, None),
        "ln2": P(None),
        "w1": P(None, t),
        "w2": P(t, None),
    }
    return {
        "embed": P(None, None),
        "ln_f": P(None),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }


def rms_norm(x, scale, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return ((x32 / rms) * scale).astype(x.dtype)


def apply_rope(x, positions, theta: float):
    """Rotary embedding on (B, T, H, Dh) with global ``positions`` — (T,)
    shared across the batch, or (B, T) per-sequence (the ragged decode
    batches of the serving path)."""
    half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs  # (..., T, half)
    if ang.ndim == 2:
        ang = ang[None]  # shared positions broadcast over the batch
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    )
    return rotated.astype(x.dtype)


def _tp_combine(partial, tp_axis, cfg: TransformerConfig):
    """Sum row-parallel partials across TP shards with *our* allreduce."""
    if tp_axis is None:
        return partial
    return allreduce(partial, tp_axis, topo=cfg.tp_topo, op="sum")




def attention_block(
    layer,
    x,
    positions,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
):
    """Pre-norm attention residual half of a block (shared by the dense and
    MoE models): ``x + W_o attn(RoPE(QKV(norm(x))))`` with the row-parallel
    output combined through the FlexTree allreduce."""
    b, t_local, _ = x.shape
    head_dim = cfg.head_dim
    attn_opts = dict(cfg.attn_opts)
    if attn_opts and cfg.attn_impl != "flash":
        # a tuned config silently running with library defaults is exactly
        # the artifact-comparison hazard ADVICE r5 flagged — fail loudly
        raise ValueError(
            f"attn_opts {sorted(attn_opts)} require attn_impl='flash', "
            f"got {cfg.attn_impl!r}"
        )
    h = rms_norm(x, layer["ln1"])
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(b, t_local, -1, head_dim)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(b, t_local, -1, head_dim)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(b, t_local, -1, head_dim)
    q = apply_rope(q, positions, cfg.rope_theta)
    k = apply_rope(k, positions, cfg.rope_theta)
    if sp_axis is None:
        attn = local_attention(
            q, k, v, causal=True, impl=cfg.attn_impl, **attn_opts
        )
    elif cfg.sp_impl == "ulysses":
        # Ulysses' inner attention is also full-sequence-local flash —
        # the tuned opts apply there too (ADVICE r5)
        attn = ulysses_attention(
            q, k, v, sp_axis, causal=True, impl=cfg.attn_impl, **attn_opts
        )
    elif attn_opts:
        # ring/zigzag hop kernels run library defaults; a tuned config
        # that cannot be honored must fail, not silently degrade
        raise ValueError(
            f"attn_opts {sorted(attn_opts)} are not supported by "
            f"sp_impl={cfg.sp_impl!r} (only the full-sequence-local and "
            f"ulysses paths take flash kwargs)"
        )
    elif cfg.sp_impl == "ring":
        attn = ring_attention(q, k, v, sp_axis, causal=True, impl=cfg.attn_impl)
    elif cfg.sp_impl == "zigzag":
        # contiguous layout at the model boundary: RoPE positions above are
        # contiguous-shard positions, so convert around the attention only
        attn = zigzag_ring_attention(
            q, k, v, sp_axis, layout="contiguous", impl=cfg.attn_impl
        )
    else:
        raise ValueError(f"unknown sp_impl {cfg.sp_impl!r}")
    o = attn.reshape(b, t_local, -1) @ layer["wo"].astype(cfg.dtype)
    return x + _tp_combine(o, tp_axis, cfg)


def layer_forward(
    layer,
    x,
    positions,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
):
    """One transformer block on hidden states ``x`` (B, T_local, d).

    ``positions``: (T_local,) global token positions (RoPE + causal mask).
    Factored out of :func:`forward` so the pipeline-parallel runner
    (``flextree_tpu.parallel.pipeline``) can ``lax.scan`` it over a stacked
    per-stage parameter slice.
    """
    x = attention_block(
        layer, x, positions, cfg, tp_axis=tp_axis, sp_axis=sp_axis
    )
    return mlp_block(layer, x, cfg, tp_axis=tp_axis)


def mlp_block(layer, x, cfg: TransformerConfig, *, tp_axis: str | None = None):
    """Pre-norm GELU MLP residual half (column/row-parallel over tp)."""
    h = rms_norm(x, layer["ln2"])
    u = jax.nn.gelu(h @ layer["w1"].astype(cfg.dtype))
    y = u @ layer["w2"].astype(cfg.dtype)
    return x + _tp_combine(y, tp_axis, cfg)


def final_logits(embed, ln_f, h):
    """The LM head: final RMSNorm + tied-embedding projection to f32
    logits.  The ONE definition shared by :func:`forward`,
    ``moe.moe_forward``, and the overlap engines' per-segment head
    (``parallel.overlap``) — the overlap path's bitwise contract depends
    on these never drifting apart."""
    x = rms_norm(h, ln_f)
    return x.astype(jnp.float32) @ embed.T.astype(jnp.float32)


def global_positions(t_local: int, sp_axis: str | None):
    """(T_local,) global positions for this device's sequence shard."""
    offset = lax.axis_index(sp_axis) * t_local if sp_axis is not None else 0
    return offset + jnp.arange(t_local)


def forward(
    params,
    tokens,
    cfg: TransformerConfig,
    *,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
):
    """Logits for ``tokens`` (B, T_local) int32.

    With no axes bound this is a plain single-device forward.  Inside
    ``shard_map``: batch may be sharded over a data axis (invisible here),
    sequence over ``sp_axis``, and heads/hidden over ``tp_axis`` (params
    pre-sliced by ``param_specs``).  Returns (B, T_local, vocab) logits in
    float32, replicated over ``tp_axis``.
    """
    positions = global_positions(tokens.shape[1], sp_axis)
    x = params["embed"][tokens].astype(cfg.dtype)
    for layer in params["layers"]:
        x = layer_forward(
            layer, x, positions, cfg, tp_axis=tp_axis, sp_axis=sp_axis
        )
    return final_logits(params["embed"], params["ln_f"], x)


def cross_entropy_loss(logits, targets):
    """Per-token cross entropy, summed — (loss_sum, token_count).

    Summed (not meaned) so callers can normalize by a *global* token count
    psum'd over the mesh, which keeps gradients exact under dp/sp sharding.
    """
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    loss = (logz - gold).sum()
    count = jnp.asarray(targets.size, jnp.float32)
    return loss, count
