"""Mixture-of-Experts transformer with expert parallelism over an ``ep`` axis.

The framework's second model family (next to the dense
``flextree_tpu.models.transformer``), built TPU-first:

- **Router**: top-k gating (softmax over experts, k greedy picks), with a
  *static* per-device expert capacity ``C = ceil(S * k * capacity_factor /
  E)`` — tokens beyond an expert's capacity are dropped (their combine
  weight is zero, the residual stream carries them unchanged).  Everything
  is dense masked einsums over (tokens, experts, capacity) one-hots: no
  dynamic shapes, no sorting — the layout XLA can tile onto the MXU.
- **Expert parallelism**: the stacked expert weights shard their leading
  expert axis over the ``ep`` mesh axis; dispatch is one
  ``lax.all_to_all`` sending each device's per-expert capacity slots to
  the expert's owner, and a second all-to-all brings outputs back — the
  all-to-all counterpart of the hierarchical allreduce's grouped stages
  (the reference parameterizes *how* a collective routes,
  ``allreduce_over_mpi/mpi_mod.hpp:882-929``; here the route is the
  expert assignment itself).
- **Composition**: expert FFNs are also tensor-parallel (hidden dim over
  ``tp``, row-parallel combine through the FlexTree allreduce), attention
  is the dense model's (ring/Ulysses sequence parallelism over ``sp``),
  so one MoE mesh runs dp x ep x sp x tp.
- **Load balancing**: the Switch-style auxiliary loss ``E * sum_e(
  token_frac_e * prob_mass_e)`` (1.0 at perfect balance), returned per
  layer and weighted into the training loss by ``router_aux_weight``.

Determinism note: routing is greedy argmax with first-come-first-served
capacity slots (position = running count of earlier same-expert tokens), so
a sharded run equals the single-device oracle exactly whenever capacity is
not exceeded *per shard* — the equivalence the tests pin down.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.allreduce import allreduce
from .transformer import (
    TransformerConfig,
    _dense_init,
    attention_block,
    final_logits,
    global_positions,
    mlp_block,
    rms_norm,
)

__all__ = [
    "MoEConfig",
    "init_moe_params",
    "moe_param_specs",
    "moe_forward",
    "moe_layer",
    "route_topk",
    "expert_capacity",
]


@dataclasses.dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    # every ``moe_every``-th block uses an MoE FFN (1 = all blocks);
    # blocks are counted 1-based so moe_every=2 -> layers 1, 3, ... are MoE
    moe_every: int = 1
    router_aux_weight: float = 1e-2
    # topology spec for the ep-axis collectives is implicit: dispatch is a
    # single all-to-all, which has no tree analog — the FlexTree topology
    # applies to the tp combine (tp_topo) and the gradient sync (grad_topo)

    def is_moe_layer(self, i: int) -> bool:
        return (i % self.moe_every) == (self.moe_every - 1)


def expert_capacity(n_tokens: int, cfg: MoEConfig) -> int:
    """Static per-shard, per-expert capacity."""
    return max(
        1,
        math.ceil(n_tokens * cfg.top_k * cfg.capacity_factor / cfg.n_experts),
    )


def init_moe_params(key, cfg: MoEConfig) -> dict:
    d, ff, e = cfg.d_model, cfg.d_ff, cfg.n_experts
    keys = jax.random.split(key, 2 + cfg.n_layers)
    params = {
        "embed": _dense_init(keys[0], (cfg.vocab_size, d), 1.0 / math.sqrt(d)),
        "ln_f": jnp.ones((d,), jnp.float32),
        "layers": [],
    }
    out_scale = 1.0 / math.sqrt(d * 2 * cfg.n_layers)
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 7)
        layer = {
            "ln1": jnp.ones((d,), jnp.float32),
            "wq": _dense_init(k[0], (d, d), 1.0 / math.sqrt(d)),
            "wk": _dense_init(k[1], (d, d), 1.0 / math.sqrt(d)),
            "wv": _dense_init(k[2], (d, d), 1.0 / math.sqrt(d)),
            "wo": _dense_init(k[3], (d, d), out_scale),
            "ln2": jnp.ones((d,), jnp.float32),
        }
        if cfg.is_moe_layer(i):
            layer["router"] = _dense_init(k[6], (d, e), 1.0 / math.sqrt(d))
            layer["w1e"] = _dense_init(k[4], (e, d, ff), 1.0 / math.sqrt(d))
            layer["w2e"] = _dense_init(k[5], (e, ff, d), out_scale)
        else:
            layer["w1"] = _dense_init(k[4], (d, ff), 1.0 / math.sqrt(d))
            layer["w2"] = _dense_init(k[5], (ff, d), out_scale)
        params["layers"].append(layer)
    return params


def moe_param_specs(
    cfg: MoEConfig,
    tp_axis: str | None = "tp",
    ep_axis: str | None = "ep",
) -> dict:
    """Expert leaves shard (expert axis over ep, hidden over tp); the rest
    matches the dense model's specs."""
    t, e = tp_axis, ep_axis
    layers = []
    for i in range(cfg.n_layers):
        layer = {
            "ln1": P(None),
            "wq": P(None, t),
            "wk": P(None, t),
            "wv": P(None, t),
            "wo": P(t, None),
            "ln2": P(None),
        }
        if cfg.is_moe_layer(i):
            layer["router"] = P(None, None)
            layer["w1e"] = P(e, None, t)
            layer["w2e"] = P(e, t, None)
        else:
            layer["w1"] = P(None, t)
            layer["w2"] = P(t, None)
        layers.append(layer)
    return {"embed": P(None, None), "ln_f": P(None), "layers": layers}


# ------------------------------------------------------------------ router


def route_topk(probs: jax.Array, k: int, capacity: int):
    """Greedy top-k routing with first-come-first-served capacity.

    ``probs``: (S, E) router probabilities.  Returns ``(dispatch, combine)``
    with ``dispatch`` (S, E, C) in {0,1} — token s occupies capacity slot c
    of expert e — and ``combine`` (S, E, C) the normalized gate weights.
    Greedy pick ``i`` routes each token to its i-th-highest expert; a
    token's slot is its running count among earlier tokens routed to the
    same expert this pick plus all previous picks (dropped tokens still
    consume positions, keeping the assignment a pure prefix-sum — no
    compaction, fully static shapes).
    """
    s, e = probs.shape
    if k > e:
        raise ValueError(f"top_k={k} cannot exceed n_experts={e}")
    dispatch = jnp.zeros((s, e, capacity), probs.dtype)
    gates = jnp.zeros((s, e), probs.dtype)
    counts = jnp.zeros((e,), jnp.int32)
    masked = probs
    for _ in range(k):
        sel = jnp.argmax(masked, axis=-1)  # (S,)
        onehot = jax.nn.one_hot(sel, e, dtype=probs.dtype)  # (S, E)
        oh_i = onehot.astype(jnp.int32)
        pos = counts[None, :] + jnp.cumsum(oh_i, axis=0) - oh_i  # (S, E)
        pos_sel = jnp.take_along_axis(pos, sel[:, None], axis=1)[:, 0]
        keep = (pos_sel < capacity).astype(probs.dtype)
        slot = jax.nn.one_hot(pos_sel, capacity, dtype=probs.dtype)  # (S, C)
        dispatch = dispatch + (
            onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
        )
        gates = gates + probs * onehot * keep[:, None]
        counts = counts + oh_i.sum(axis=0)
        masked = masked * (1.0 - onehot)
    denom = gates.sum(axis=-1, keepdims=True)
    norm = gates / jnp.where(denom > 0, denom, 1.0)
    combine = dispatch * norm[:, :, None]
    return dispatch, combine


def _aux_loss(probs: jax.Array, dispatch: jax.Array) -> jax.Array:
    """Switch-style load-balance loss on local tokens: ``E * sum_e(
    token_frac_e * prob_mass_e)`` — 1.0 at perfect balance."""
    s, e = probs.shape
    token_frac = dispatch.sum(axis=(0, 2)) / jnp.maximum(
        dispatch.sum(), 1.0
    )  # (E,)
    prob_mass = probs.mean(axis=0)  # (E,)
    return e * jnp.sum(token_frac * prob_mass)


# ------------------------------------------------------------------- layer


def moe_layer(
    layer: dict,
    x: jax.Array,
    cfg: MoEConfig,
    *,
    tp_axis: str | None = None,
    ep_axis: str | None = None,
):
    """MoE FFN on hidden states ``x`` (B, T_local, d) -> (out, aux).

    Dispatch -> all-to-all -> local experts (tp-parallel hidden) ->
    all-to-all back -> combine.  With ``ep_axis=None`` all experts are
    local and the all-to-alls vanish — that path is the single-device
    oracle the sharded path must match.
    """
    b, t, d = x.shape
    s = b * t
    e = cfg.n_experts
    cap = expert_capacity(s, cfg)
    tokens = x.reshape(s, d)

    probs = jax.nn.softmax(
        tokens.astype(jnp.float32) @ layer["router"].astype(jnp.float32), axis=-1
    )
    dispatch, combine = route_topk(probs, cfg.top_k, cap)
    aux = _aux_loss(probs, dispatch)

    # (S, E, C) x (S, d) -> (E, C, d) expert inboxes
    slots = jnp.einsum(
        "sec,sd->ecd", dispatch.astype(cfg.dtype), tokens.astype(cfg.dtype)
    )

    n_ep = lax.axis_size(ep_axis) if ep_axis is not None else 1
    if n_ep > 1:
        if e % n_ep:
            raise ValueError(
                f"n_experts={e} must be divisible by ep axis size {n_ep}"
            )
        # (E, C, d) -> (E/n, n*C, d): each device keeps its local experts,
        # holding every source device's capacity slots
        slots = lax.all_to_all(
            slots, ep_axis, split_axis=0, concat_axis=1, tiled=True
        )

    # local experts: w1e/w2e leading axis is the *local* expert slice
    w1 = layer["w1e"].astype(cfg.dtype)
    w2 = layer["w2e"].astype(cfg.dtype)
    hidden = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", slots, w1))
    out_slots = jnp.einsum("ecf,efd->ecd", hidden, w2)
    if tp_axis is not None:  # row-parallel combine of the tp-sharded hidden
        out_slots = allreduce(out_slots, tp_axis, topo=cfg.tp_topo, op="sum")

    if n_ep > 1:
        out_slots = lax.all_to_all(
            out_slots, ep_axis, split_axis=1, concat_axis=0, tiled=True
        )

    out = jnp.einsum(
        "sec,ecd->sd", combine.astype(jnp.float32), out_slots.astype(jnp.float32)
    )
    return out.reshape(b, t, d).astype(x.dtype), aux


def moe_forward(
    params,
    tokens,
    cfg: MoEConfig,
    *,
    tp_axis: str | None = None,
    sp_axis: str | None = None,
    ep_axis: str | None = None,
):
    """Logits + mean router aux loss for ``tokens`` (B, T_local) int32.

    Attention blocks are the dense model's (``layer_forward`` attention
    half); FFNs alternate dense / MoE per ``cfg.moe_every``.
    """
    b, t_local = tokens.shape
    positions = global_positions(t_local, sp_axis)
    x = params["embed"][tokens].astype(cfg.dtype)
    aux_total = jnp.zeros((), jnp.float32)
    n_moe = 0
    for i, layer in enumerate(params["layers"]):
        x = attention_block(
            layer, x, positions, cfg, tp_axis=tp_axis, sp_axis=sp_axis
        )
        if cfg.is_moe_layer(i):
            h = rms_norm(x, layer["ln2"])
            y, aux = moe_layer(
                layer, h, cfg, tp_axis=tp_axis, ep_axis=ep_axis
            )
            x = x + y
            aux_total = aux_total + aux
            n_moe += 1
        else:
            x = mlp_block(layer, x, cfg, tp_axis=tp_axis)
    logits = final_logits(params["embed"], params["ln_f"], x)
    aux_mean = aux_total / max(n_moe, 1)
    return logits, aux_mean
