"""Autoregressive generation with a static KV cache.

Completes the dense model family's serving path: prefill runs the full
forward once while recording every layer's K/V; decode then advances one
token at a time, attending over the cache.  Everything is static-shaped
for XLA: the cache is allocated at ``max_len`` up front, the causal bound
is a mask on cached positions (not a dynamic slice), and the decode loop
is a ``lax.scan`` — so the whole ``generate`` call jits to two compiled
programs (prefill + scanned decode) regardless of token count.

Single-device by design: generation is latency-bound, and the framework's
sharded story lives in the training steps; a tp-sharded decode would reuse
the same cache layout with heads split over the axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import (
    TransformerConfig,
    apply_rope,
    mlp_block,
    rms_norm,
)

__all__ = ["init_kv_cache", "prefill", "decode_step", "generate"]


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int) -> dict:
    """Per-layer (B, max_len, H, Dh) K/V buffers in the compute dtype."""
    shape = (batch, max_len, cfg.n_heads, cfg.head_dim)
    return {
        "k": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
        "v": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
        "length": jnp.zeros((), jnp.int32),
    }


def _qkv(layer, h, cfg: TransformerConfig):
    b, t = h.shape[:2]
    shape = (b, t, cfg.n_heads, cfg.head_dim)
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(shape)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(shape)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(shape)
    return q, k, v


def _cached_attention(q, k_cache, v_cache, q_pos):
    """Attend (B, Tq, H, D) queries over cached positions ``<= q_pos``
    (global query positions); the causal bound alone masks out every
    not-yet-written cache slot.  Math order mirrors ``attention_reference``
    exactly (einsum in the compute dtype, then f32) so decode logits are
    teacher-forcing-exact in every dtype."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache).astype(jnp.float32) * scale
    kpos = jnp.arange(k_cache.shape[1])
    mask = kpos[None, :] <= q_pos[:, None]
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


def _forward_cached(params, tokens, cache, start_pos, cfg: TransformerConfig):
    """Forward ``tokens`` (B, T) writing K/V at ``start_pos..start_pos+T``;
    returns (logits, cache).  ``start_pos`` may be traced (decode)."""
    b, t = tokens.shape
    positions = start_pos + jnp.arange(t)
    x = params["embed"][tokens].astype(cfg.dtype)
    new_k, new_v = [], []
    for layer, kc, vc in zip(params["layers"], cache["k"], cache["v"]):
        h = rms_norm(x, layer["ln1"])
        q, k, v = _qkv(layer, h, cfg)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        kc = lax.dynamic_update_slice_in_dim(kc, k, start_pos, axis=1)
        vc = lax.dynamic_update_slice_in_dim(vc, v, start_pos, axis=1)
        new_k.append(kc)
        new_v.append(vc)
        attn = _cached_attention(q, kc, vc, positions)
        o = attn.reshape(b, t, -1) @ layer["wo"].astype(cfg.dtype)
        x = x + o
        x = mlp_block(layer, x, cfg)
    x = rms_norm(x, params["ln_f"])
    logits = x.astype(jnp.float32) @ params["embed"].T.astype(jnp.float32)
    cache = {"k": new_k, "v": new_v, "length": start_pos + t}
    return logits, cache


def prefill(params, tokens, cfg: TransformerConfig, max_len: int):
    """Run the prompt through the model once.  Returns
    ``(last_logits, cache)`` with the cache filled for ``tokens``."""
    b, t = tokens.shape
    if t > max_len:
        raise ValueError(f"prompt length {t} exceeds max_len {max_len}")
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = _forward_cached(params, tokens, cache, 0, cfg)
    return logits[:, -1], cache


def decode_step(params, cache, token, cfg: TransformerConfig):
    """One decode step: ``token`` (B,) int32 at position ``cache['length']``.
    Returns ``(logits, cache)`` for the next position."""
    logits, cache = _forward_cached(
        params, token[:, None], cache, cache["length"], cfg
    )
    return logits[:, 0], cache


def generate(
    params,
    prompt,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int,
    max_len: int | None = None,
    temperature: float = 0.0,
    key=None,
):
    """Greedy (``temperature=0``) or sampled continuation of ``prompt``
    (B, T) int32 -> (B, max_new_tokens) int32.  Sampling requires an
    explicit ``key``."""
    b, t = prompt.shape
    if max_len is None:
        max_len = t + max_new_tokens
    if t + max_new_tokens > max_len:
        raise ValueError(
            f"prompt ({t}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_len ({max_len})"
        )
    sampling = temperature > 0
    if sampling and key is None:
        raise ValueError("temperature > 0 requires an explicit key=")

    logits, cache = prefill(params, prompt, cfg, max_len)

    def pick(logits, k):
        if not sampling:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(k, logits / temperature, axis=-1).astype(
            jnp.int32
        )

    keys = jax.random.split(key, max_new_tokens) if sampling else None
    # first token comes straight from the prefill logits; the scan then
    # decodes exactly max_new_tokens - 1 times (no trailing wasted forward)
    tok0 = pick(logits, keys[0] if sampling else None)

    def step(carry, k):
        tok, cache = carry
        logits, cache = decode_step(params, cache, tok, cfg)
        nxt = pick(logits, k)
        return (nxt, cache), nxt

    xs = keys[1:] if sampling else None
    (_, _), rest = lax.scan(
        step, (tok0, cache), xs, length=None if sampling else max_new_tokens - 1
    )
    return jnp.concatenate([tok0[:, None], rest.T], axis=1)
