"""Autoregressive generation with a static KV cache.

Completes the dense model family's serving path: prefill runs the full
forward once while recording every layer's K/V; decode then advances one
token at a time, attending over the cache.  Everything is static-shaped
for XLA: the cache is allocated at ``max_len`` up front, the causal bound
is a mask on cached positions (not a dynamic slice), and the decode loop
is a ``lax.scan`` — so the whole ``generate`` call jits to two compiled
programs (prefill + scanned decode) regardless of token count.

Cache lengths are **per sequence** (``cache["length"]`` is a ``(B,)``
int32 vector): a freshly-prefilled request can join a batch of mid-decode
sequences at a different position, which is what the continuous batcher
(``flextree_tpu.serving``) needs.  RoPE positions and the causal mask
honor the per-row position; cache writes go through a vmapped dynamic
update so each row lands at its own offset.

Sampling is deterministic and key-threaded (no RNG inside the trace):
greedy is the default, ``temperature``/``top_k`` sampling requires an
explicit ``key=``.  ``stop_tokens=`` switches the decode loop from
``lax.scan`` to ``lax.while_loop`` so generation exits as soon as every
sequence has emitted a stop token — the per-sequence retirement signal
the serving batcher consumes one request at a time.

Single-device by design: generation is latency-bound, and the framework's
sharded story lives in the training steps; a tp-sharded decode would reuse
the same cache layout with heads split over the axis.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import (
    TransformerConfig,
    apply_rope,
    final_logits,
    mlp_block,
    rms_norm,
)

__all__ = [
    "init_kv_cache",
    "prefill",
    "prefill_suffix",
    "prefill_ragged",
    "decode_step",
    "generate",
    "sample_token",
    "cached_attention",
]


def init_kv_cache(cfg: TransformerConfig, batch: int, max_len: int) -> dict:
    """Per-layer (B, max_len, H, Dh) K/V buffers in the compute dtype.
    ``length`` is per-sequence (B,) so ragged batches can share a cache."""
    shape = (batch, max_len, cfg.n_heads, cfg.head_dim)
    return {
        "k": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
        "v": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
        "length": jnp.zeros((batch,), jnp.int32),
    }


def _qkv(layer, h, cfg: TransformerConfig):
    b, t = h.shape[:2]
    shape = (b, t, cfg.n_heads, cfg.head_dim)
    q = (h @ layer["wq"].astype(cfg.dtype)).reshape(shape)
    k = (h @ layer["wk"].astype(cfg.dtype)).reshape(shape)
    v = (h @ layer["wv"].astype(cfg.dtype)).reshape(shape)
    return q, k, v


def cached_attention(q, k_cache, v_cache, q_pos):
    """Attend (B, Tq, H, D) queries over cached positions ``<= q_pos``
    (global query positions, (Tq,) shared or (B, Tq) per-sequence); the
    causal bound alone masks out every not-yet-written cache slot — masked
    scores softmax to exactly 0.0 in f32, so whatever a masked slot holds
    contributes exactly nothing (the paged cache's gather path leans on
    this).  Math order mirrors ``attention_reference`` exactly (einsum in
    the compute dtype, then f32) so decode logits are teacher-forcing-exact
    in every dtype."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache).astype(jnp.float32) * scale
    kpos = jnp.arange(k_cache.shape[1])
    if q_pos.ndim == 1:  # shared positions: (Tq, K) mask over all rows
        mask = (kpos[None, :] <= q_pos[:, None])[None, None]
    else:  # per-sequence positions: (B, 1, Tq, K)
        mask = (kpos[None, None, :] <= q_pos[:, :, None])[:, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


def _forward_cached(params, tokens, cache, start_pos, cfg: TransformerConfig):
    """Forward ``tokens`` (B, T) writing K/V at ``start_pos..start_pos+T``;
    returns (logits, cache).  ``start_pos`` may be traced, scalar (all rows
    at the same offset — the prefill case) or (B,) per-sequence (ragged
    decode); the returned ``cache["length"]`` is always (B,)."""
    b, t = tokens.shape
    start = jnp.asarray(start_pos, jnp.int32)
    ragged = start.ndim == 1
    positions = (start[:, None] if ragged else start) + jnp.arange(t)
    if ragged:
        # each row lands at its own offset: vmap the length-axis update
        upd = jax.vmap(
            lambda c, u, s: lax.dynamic_update_slice_in_dim(c, u, s, axis=0)
        )
    x = params["embed"][tokens].astype(cfg.dtype)
    new_k, new_v = [], []
    for layer, kc, vc in zip(params["layers"], cache["k"], cache["v"]):
        h = rms_norm(x, layer["ln1"])
        q, k, v = _qkv(layer, h, cfg)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        if ragged:
            kc = upd(kc, k, start)
            vc = upd(vc, v, start)
        else:
            kc = lax.dynamic_update_slice_in_dim(kc, k, start, axis=1)
            vc = lax.dynamic_update_slice_in_dim(vc, v, start, axis=1)
        new_k.append(kc)
        new_v.append(vc)
        attn = cached_attention(q, kc, vc, positions)
        o = attn.reshape(b, t, -1) @ layer["wo"].astype(cfg.dtype)
        x = x + o
        x = mlp_block(layer, x, cfg)
    logits = final_logits(params["embed"], params["ln_f"], x)
    length = jnp.broadcast_to(start + t, (b,)).astype(jnp.int32)
    cache = {"k": new_k, "v": new_v, "length": length}
    return logits, cache


def prefill(params, tokens, cfg: TransformerConfig, max_len: int):
    """Run the prompt through the model once.  Returns
    ``(last_logits, cache)`` with the cache filled for ``tokens``."""
    b, t = tokens.shape
    if t > max_len:
        raise ValueError(f"prompt length {t} exceeds max_len {max_len}")
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = _forward_cached(params, tokens, cache, 0, cfg)
    return logits[:, -1], cache


def prefill_suffix(params, tokens, prefix_kv, cfg: TransformerConfig,
                   max_len: int):
    """Suffix-only prefill over an already-computed prefix: run ONLY the
    ``tokens`` (B, Ts) that follow a cached prefix whose per-layer K/V is
    ``prefix_kv = {"k": [(B, C, H, Dh)], "v": [...]}``.  Returns
    ``(last_logits, cache)`` exactly like :func:`prefill` of the full
    ``C + Ts`` prompt would.

    Offset-aware by construction: RoPE positions and the causal mask
    start at the cached length ``C`` (the prefix shape carries it, so it
    is static per compile — one program per (C, Ts) bucket), and the
    cache writes land at ``C ..`` so cached positions are never
    rewritten.  Bitwise identity with the full prefill follows from two
    facts the paged stack already leans on: a prefix position's K/V is a
    pure function of the prefix tokens (absolute positions, causal
    masking), and every masked cache slot contributes exactly 0.0 —
    so the suffix queries attend over the very same values, in the same
    ``max_len``-wide reduction, full prefill's suffix rows see.

    Caveat the batcher's admission math honors: a ONE-token suffix puts
    the attention matmuls in the ``Tq=1`` shape class, which XLA lowers
    with a different accumulation order than the multi-row prefill —
    numerically fine, but not bitwise against the full prefill.  Callers
    that need the bitwise guarantee must pass at least two suffix
    tokens.
    """
    b, t = tokens.shape
    ks = prefix_kv["k"]
    if len(ks) != cfg.n_layers or len(prefix_kv["v"]) != cfg.n_layers:
        raise ValueError(
            f"prefix_kv holds {len(ks)} layers, model has {cfg.n_layers}"
        )
    c = int(ks[0].shape[1])
    if t < 1:
        raise ValueError("prefill_suffix needs at least one suffix token "
                         "(the last prompt token's logits come from it)")
    if c + t > max_len:
        raise ValueError(
            f"cached {c} + suffix {t} exceeds max_len {max_len}"
        )
    cache = init_kv_cache(cfg, b, max_len)
    cache["k"] = [
        kc.at[:, :c].set(pk.astype(kc.dtype))
        for kc, pk in zip(cache["k"], prefix_kv["k"])
    ]
    cache["v"] = [
        vc.at[:, :c].set(pv.astype(vc.dtype))
        for vc, pv in zip(cache["v"], prefix_kv["v"])
    ]
    logits, cache = _forward_cached(params, tokens, cache, c, cfg)
    return logits[:, -1], cache


def prefill_ragged(params, tokens, lengths, cfg: TransformerConfig,
                   max_len: int):
    """Right-padded batched prefill: row ``b`` of ``tokens`` (B, T) is
    real up to ``lengths[b]`` and padding after.  Returns ``(logits,
    cache)`` with ``logits[b]`` taken at row ``b``'s LAST REAL token and
    ``cache["length"] = lengths`` — so the first decode write lands at
    each row's own length, progressively overwriting the pad K/V, and
    the causal mask keeps not-yet-overwritten pad entries invisible
    (every attended position <= q_pos has been written by then).  Decoded
    continuations are therefore exactly what each row would produce
    alone."""
    b, t = tokens.shape
    if t > max_len:
        raise ValueError(f"padded prompt length {t} exceeds max_len {max_len}")
    cache = init_kv_cache(cfg, b, max_len)
    logits, cache = _forward_cached(params, tokens, cache, 0, cfg)
    lengths = jnp.asarray(lengths, jnp.int32)
    last = logits[jnp.arange(b), lengths - 1]
    return last, {**cache, "length": lengths}


def decode_step(params, cache, token, cfg: TransformerConfig):
    """One decode step: ``token`` (B,) int32, each row at its own position
    ``cache['length'][b]``.  Returns ``(logits, cache)`` for the next
    position."""
    logits, cache = _forward_cached(
        params, token[:, None], cache, cache["length"], cfg
    )
    return logits[:, 0], cache


def sample_token(logits, *, temperature: float = 0.0, top_k: int | None = None,
                 key=None):
    """Next-token choice from (B, vocab) f32 logits — deterministic and
    key-threaded, never RNG-in-trace.

    ``temperature <= 0`` is greedy argmax (the default; ``key`` unused).
    Otherwise ``key`` is required: logits are scaled by ``1/temperature``,
    optionally truncated to the ``top_k`` highest (ties at the k-th value
    are all kept), and sampled via ``jax.random.categorical``.  The same
    ``(logits, key)`` always yields the same token.
    """
    if temperature <= 0:
        if top_k is not None:
            # greedy over top-k IS greedy — a silently ignored knob is the
            # artifact-comparison hazard; fail loudly instead
            raise ValueError("top_k requires temperature > 0")
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if key is None:
        raise ValueError("temperature > 0 requires an explicit key=")
    scaled = logits / temperature
    if top_k is not None:
        if not 1 <= top_k:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        kth = lax.top_k(scaled, min(top_k, scaled.shape[-1]))[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)


def generate(
    params,
    prompt,
    cfg: TransformerConfig,
    *,
    max_new_tokens: int,
    max_len: int | None = None,
    temperature: float = 0.0,
    top_k: int | None = None,
    key=None,
    stop_tokens=None,
    pad_token: int = 0,
):
    """Greedy (``temperature=0``) or sampled continuation of ``prompt``
    (B, T) int32 -> (B, max_new_tokens) int32.  Sampling requires an
    explicit ``key``; ``top_k`` truncates the sampled distribution.

    With ``stop_tokens`` (a sequence of token ids) the decode loop becomes
    a ``lax.while_loop`` that exits as soon as every row has emitted a
    stop token (per-sequence early exit): rows that already stopped emit
    ``pad_token``, and the return value becomes ``(tokens, lengths)`` with
    ``lengths`` (B,) counting each row's real tokens (stop token included).
    """
    b, t = prompt.shape
    if max_len is None:
        max_len = t + max_new_tokens
    if t + max_new_tokens > max_len:
        raise ValueError(
            f"prompt ({t}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_len ({max_len})"
        )
    sampling = temperature > 0
    if sampling and key is None:
        raise ValueError("temperature > 0 requires an explicit key=")

    logits, cache = prefill(params, prompt, cfg, max_len)

    def pick(logits, k):
        return sample_token(logits, temperature=temperature, top_k=top_k, key=k)

    keys = jax.random.split(key, max_new_tokens) if sampling else None
    # first token comes straight from the prefill logits; the loop then
    # decodes at most max_new_tokens - 1 times (no trailing wasted forward)
    tok0 = pick(logits, keys[0] if sampling else None)

    if stop_tokens is None:
        def step(carry, k):
            tok, cache = carry
            logits, cache = decode_step(params, cache, tok, cfg)
            nxt = pick(logits, k)
            return (nxt, cache), nxt

        xs = keys[1:] if sampling else None
        (_, _), rest = lax.scan(
            step, (tok0, cache), xs,
            length=None if sampling else max_new_tokens - 1,
        )
        return jnp.concatenate([tok0[:, None], rest.T], axis=1)

    stop = jnp.asarray(tuple(stop_tokens), jnp.int32).reshape(-1)

    def hit(tok):  # (B,) bool: did this token retire its row?
        return (tok[:, None] == stop[None, :]).any(axis=1)

    # pad-initialized so columns past an early all-rows exit read as pad
    out0 = jnp.full((b, max_new_tokens), pad_token, jnp.int32).at[:, 0].set(tok0)
    carry0 = (
        jnp.int32(1), tok0, cache, hit(tok0), out0, jnp.ones((b,), jnp.int32)
    )

    def cond(carry):
        i, _, _, done, _, _ = carry
        return (i < max_new_tokens) & ~done.all()

    def body(carry):
        i, tok, cache, done, out, lens = carry
        logits, cache = decode_step(params, cache, tok, cfg)
        k = (
            lax.dynamic_index_in_dim(keys, i, keepdims=False)
            if sampling else None
        )
        nxt = jnp.where(done, jnp.int32(pad_token), pick(logits, k))
        out = out.at[:, i].set(nxt)
        lens = lens + (~done).astype(jnp.int32)
        return (i + 1, nxt, cache, done | hit(nxt), out, lens)

    _, _, _, _, out, lens = lax.while_loop(cond, body, carry0)
    return out, lens
