"""NumPy simulator backend: executes a FlexTree schedule over N per-rank
arrays in a single process, at message granularity.

This is the ground-truth oracle for every other backend (the rebuild's answer
to the reference's missing test suite, SURVEY §4) and a faithful model of the
reference execution:

- phase 1 = per-stage send -> recv -> reduce, sends sourced from ``data`` at
  stage 0 and from ``dst`` afterwards (``tree_allreduce``,
  ``mpi_mod.hpp:988-1029``);
- phase 2 = reversed stages with send/recv op lists swapped, received blocks
  landing at their final offsets (``accordingly=true``,
  ``mpi_mod.hpp:1050-1060``);
- tail blocks clamped to the true element count, possibly empty
  (``mpi_mod.hpp:679-696``), rather than padded;
- ring = the 2(N-1)-step neighbor schedule (``mpi_mod.hpp:1113-1163``).

Every transfer goes through an explicit :class:`Mailbox` so tests catch
schedule bugs (sending a block the sender doesn't hold, receiving one nobody
sent) instead of silently reading global state.

Chaos mode: the mailbox is also a *fault-injection* point.  A
:class:`FaultPlan` can drop, duplicate, reorder, corrupt, delay, or hang
any (phase, stage, src, dst, block) message, or kill a rank at a given
stage,
turning the simulator from a correctness oracle into a chaos oracle: every
injected fault is either **recovered** (duplicates are deduplicated by
message tag and record a ``recovered`` event; reorders are absorbed
implicitly because receives match on tag, not arrival order, so only
their injection is recorded) or **detected** with a :class:`FaultDetected` diagnostic
naming the faulty (phase, stage, src, dst, block).  No injected fault can
yield a silently wrong allreduce result: payloads carry CRC32 checksums
computed at send time, verified at receive time (see docs/FAILURE_MODEL.md).
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Mapping

import numpy as np

from ..ops.reduce import ReduceOp, get_op
from ..schedule.blocks import BlockLayout
from ..schedule.plan import owned_blocks, recv_plan, ring_plan, send_plan
from ..schedule.stages import LonelyTopology, Topology

__all__ = [
    "simulate_allreduce",
    "simulate_tree_allreduce",
    "simulate_ring_allreduce",
    "Fault",
    "FaultPlan",
    "FaultEvent",
    "FaultDetected",
    "StageTimeout",
    "ScheduleViolation",
    "FAULT_KINDS",
    "WHOLE_PAYLOAD",
]


class ScheduleViolation(AssertionError):
    """A rank tried to send data it does not hold, or a receive had no
    matching send — the simulator's race/consistency detector."""


class FaultDetected(ScheduleViolation):
    """An injected transport fault was caught by the receiver.

    Carries the structured coordinates of the faulty message so harnesses
    (and tests) can assert the diagnostic names the right (stage, src, dst)
    rather than pattern-matching prose.
    """

    def __init__(self, kind, phase, stage, src, dst, block, note=""):
        self.kind, self.phase, self.stage = kind, phase, stage
        self.src, self.dst, self.block = src, dst, block
        blk = "whole payload" if block == WHOLE_PAYLOAD else f"block {block}"
        super().__init__(
            f"{kind} fault detected at phase {phase} stage {stage}: "
            f"src {src} -> dst {dst}, {blk}" + (f" ({note})" if note else "")
        )


class StageTimeout(FaultDetected):
    """A per-recv deadline expired waiting on a *hung* sender — the
    watchdog conversion of an infinite block into a typed error.

    A ``hang`` fault models a stalled-but-alive peer (SIGSTOP, a wedged
    host): unlike ``drop`` the message was never even posted, and unlike
    ``kill`` the sender still holds its lease.  With
    ``FaultPlan.recv_timeout`` configured the receive bounds its wait and
    raises this (``code == "FT_STEP_TIMEOUT"``, the same taxonomy tag the
    step-level watchdog in ``runtime.watchdog`` uses); without a deadline
    the simulator refuses to model an infinite block silently and raises
    :class:`ScheduleViolation` naming the missing watchdog.
    """

    code = "FT_STEP_TIMEOUT"

    def __init__(self, phase, stage, src, dst, block, timeout_s):
        self.timeout_s = timeout_s
        super().__init__(
            "hang", phase, stage, src, dst, block,
            note=f"recv deadline {timeout_s:g}s exceeded ({self.code})",
        )


FAULT_KINDS = ("drop", "duplicate", "reorder", "corrupt", "delay", "hang")

# block sentinel for single-message transfers carrying a rank's whole buffer
# (the lonely-topology buddy fold/return hops)
WHOLE_PAYLOAD = -1

# execution phases, in time order: 0 = lonely buddy fold, 1 = reduce-scatter
# (ring: every step), 2 = allgather, 3 = lonely buddy return
_PHASE_NAMES = {0: "lonely-fold", 1: "reduce", 2: "gather", 3: "lonely-return"}


@dataclass(frozen=True)
class Fault:
    """One injected transport fault.  ``None`` coordinates match anything,
    so ``Fault("corrupt")`` corrupts every message while
    ``Fault("drop", stage=1, src=2, dst=0, block=3)`` snipes one block."""

    kind: str
    stage: int | None = None
    src: int | None = None
    dst: int | None = None
    block: int | None = None
    phase: int | None = None

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; choose from {FAULT_KINDS}"
            )

    def matches(self, phase, stage, src, dst, block) -> bool:
        return (
            (self.phase is None or self.phase == phase)
            and (self.stage is None or self.stage == stage)
            and (self.src is None or self.src == src)
            and (self.dst is None or self.dst == dst)
            and (self.block is None or self.block == block)
        )


@dataclass(frozen=True)
class FaultEvent:
    """What the transport did about one injected fault occurrence."""

    kind: str
    action: str  # "injected" | "recovered" | "detected"
    phase: int
    stage: int
    src: int
    dst: int
    block: int
    note: str = ""


@dataclass
class FaultPlan:
    """A chaos scenario: transport faults plus rank kills.

    ``faults``: :class:`Fault` specs matched against every message.
    ``kill``: ``{rank: stage}`` — the rank stops sending *and* receiving
    from phase-1 stage ``stage`` onward (stage ``0`` kills it before its
    first tree message; for the ring, ``stage`` is the step index).  Kills
    at or past the schedule's last step are never observable and therefore
    never detected.
    ``recv_timeout``: the modeled per-recv deadline in seconds (the
    message-granularity twin of the step watchdog's ``FT_STEP_TIMEOUT``).
    With it set, a receive whose sender *hung* (a ``hang`` fault) raises
    a typed :class:`StageTimeout` instead of blocking forever; without
    it, the hang surfaces as a :class:`ScheduleViolation` naming the
    missing deadline — the simulator never silently models an infinite
    block.
    ``events``: populated during simulation — one entry per injection,
    plus one per dedup recovery or detection (reorder recovery is implicit
    in tag matching and records injection only), so harnesses can assert
    faults were *exercised*, not silently unmatched.
    """

    faults: tuple[Fault, ...] = ()
    kill: Mapping[int, int] = field(default_factory=dict)
    recv_timeout: float | None = None
    events: list[FaultEvent] = field(default_factory=list)

    def __post_init__(self):
        self.faults = tuple(
            Fault(**f) if isinstance(f, dict) else f for f in self.faults
        )

    def find(self, kind, phase, stage, src, dst, block) -> Fault | None:
        for f in self.faults:
            if f.kind == kind and f.matches(phase, stage, src, dst, block):
                return f
        return None

    def dead_at(self, rank: int, time: int) -> bool:
        """Whether ``rank`` is dead at schedule time ``time`` (phase-1 stage
        index; phase-2 stage ``i`` of a k-stage tree is time ``2k-1-i``)."""
        s = self.kill.get(rank)
        return s is not None and time >= s

    def record(self, kind, action, phase, stage, src, dst, block, note=""):
        self.events.append(
            FaultEvent(kind, action, phase, stage, src, dst, block, note)
        )


class Mailbox:
    """The per-stage message transport: tag-matched, checksummed, and the
    single fault-injection point.

    Every message is addressed by the tag (phase, stage, src, dst, block)
    and carries a CRC32 of its payload computed at *send* time — the
    receive path re-verifies it, so in-flight corruption is detected, not
    absorbed.  Duplicate deliveries of the same tag are deduplicated
    (recovered); reordered deliveries are absorbed because receives match
    on the tag, not arrival order.  Dropped, delayed, and dead-sender
    messages surface as :class:`FaultDetected` at the receive that needed
    them, naming the faulty coordinates.
    """

    def __init__(self, plan: FaultPlan, phase: int, stage: int, time: int):
        self.plan, self.phase, self.stage, self.time = plan, phase, stage, time
        # (dst, src) -> list of (block, data, crc) in delivery order
        self._queues: dict[tuple[int, int], list] = {}
        self._lost: dict[tuple[int, int, int], str] = {}  # tag tail -> cause
        self._boxes: dict[tuple[int, int], dict] = {}

    # ---- send side --------------------------------------------------------

    def open(self, src: int, dst: int) -> bool:
        """Announce a (possibly empty) message from ``src`` to ``dst``;
        returns False when the sender is dead (nothing will arrive)."""
        if self.plan.dead_at(src, self.time):
            return False
        self._queues.setdefault((dst, src), [])
        return True

    def post(self, src: int, dst: int, block: int, data: np.ndarray):
        args = (self.phase, self.stage, src, dst, block)
        if not self.open(src, dst):
            return
        crc = zlib.crc32(np.ascontiguousarray(data).tobytes())
        if self.plan.find("hang", *args):
            # a stalled sender: the message is never posted at all (vs drop,
            # where it was sent and lost) — the receive path converts this
            # into StageTimeout when a recv deadline is configured
            self.plan.record(
                "hang", "injected", *args, note="sender stalled mid-stage"
            )
            self._lost[(src, dst, block)] = "sender hung mid-stage"
            return
        if self.plan.find("drop", *args):
            self.plan.record("drop", "injected", *args, note="message lost")
            self._lost[(src, dst, block)] = "dropped in transit"
            return
        if self.plan.find("delay", *args):
            self.plan.record(
                "delay", "injected", *args, note="held past stage deadline"
            )
            self._lost[(src, dst, block)] = "delayed past the stage deadline"
            return
        if self.plan.find("corrupt", *args) and data.size:
            # a real in-flight bit flip, post-checksum; zero-length payloads
            # (empty tail blocks when count < n) have no bytes to flip, so
            # the fault is unobservable there and not recorded as injected
            data = np.array(data, copy=True)
            raw = data.view(np.uint8)
            raw.flat[0] ^= 0xFF
            self.plan.record("corrupt", "injected", *args, note="bit flip")
        q = self._queues[(dst, src)]
        q.append((block, data, crc))
        if self.plan.find("duplicate", *args):
            self.plan.record("duplicate", "injected", *args)
            q.append((block, data, crc))
        if self.plan.find("reorder", *args):
            self.plan.record(
                "reorder", "injected", *args, note="delivery order scrambled"
            )
            q.reverse()

    # ---- receive side -----------------------------------------------------

    def _box(self, dst: int, src: int) -> dict:
        """Tag-match the delivery queue into {block: (data, crc)} once."""
        key = (dst, src)
        if key not in self._boxes:
            box = {}
            for block, data, crc in self._queues.get(key, ()):
                if block in box:  # same tag delivered twice: dedup
                    self.plan.record(
                        "duplicate", "recovered",
                        self.phase, self.stage, src, dst, block,
                        note="deduplicated by message tag",
                    )
                    continue
                box[block] = (data, crc)
            self._boxes[key] = box
        return self._boxes[key]

    def expect(self, dst: int, src: int):
        """The receiver's handshake: raise when no message was announced."""
        if (dst, src) in self._queues:
            return
        if self.plan.dead_at(src, self.time):
            raise FaultDetected(
                "kill", self.phase, self.stage, src, dst, WHOLE_PAYLOAD,
                note=f"rank {src} died at stage {self.plan.kill[src]}",
            )
        raise ScheduleViolation(
            f"stage {self.stage}: rank {dst} expects data from {src}, none sent"
        )

    def fetch(self, dst: int, src: int, block: int) -> np.ndarray:
        self.expect(dst, src)
        box = self._box(dst, src)
        if block not in box:
            cause = self._lost.get((src, dst, block))
            if cause is not None:
                if "hung" in cause:
                    if self.plan.recv_timeout is None:
                        # refusing to model an infinite block silently: a
                        # hung sender with no recv deadline IS the hang-
                        # forever bug the watchdog exists to prevent
                        raise ScheduleViolation(
                            f"{_PHASE_NAMES[self.phase]} stage {self.stage}: "
                            f"rank {dst} would block FOREVER on hung sender "
                            f"{src} (block {block}) — no recv deadline "
                            f"configured (FaultPlan.recv_timeout / "
                            f"FT_STEP_TIMEOUT)"
                        )
                    self.plan.record(
                        "hang", "detected", self.phase, self.stage, src, dst,
                        block, note=cause,
                    )
                    raise StageTimeout(
                        self.phase, self.stage, src, dst, block,
                        self.plan.recv_timeout,
                    )
                kind = "delay" if "delay" in cause else "drop"
                self.plan.record(
                    kind, "detected", self.phase, self.stage, src, dst, block,
                    note=cause,
                )
                raise FaultDetected(
                    kind, self.phase, self.stage, src, dst, block, note=cause
                )
            raise ScheduleViolation(
                f"{_PHASE_NAMES[self.phase]} stage {self.stage}: rank {dst} "
                f"needs block {block} from {src}, not sent"
            )
        data, crc = box[block]
        if zlib.crc32(np.ascontiguousarray(data).tobytes()) != crc:
            self.plan.record(
                "corrupt", "detected", self.phase, self.stage, src, dst,
                block, note="checksum mismatch",
            )
            raise FaultDetected(
                "corrupt", self.phase, self.stage, src, dst, block,
                note="checksum mismatch",
            )
        return data


_NO_FAULTS = None  # lazily-built shared empty plan


def _resolve_plan(faults) -> FaultPlan:
    global _NO_FAULTS
    if faults is None:
        if _NO_FAULTS is None:
            _NO_FAULTS = FaultPlan()
        return _NO_FAULTS
    if isinstance(faults, FaultPlan):
        return faults
    return FaultPlan(faults=tuple(faults))


def _as_matrix(inputs) -> np.ndarray:
    arr = np.asarray(inputs)
    if arr.ndim != 2:
        raise ValueError(
            f"inputs must be 2-D (num_ranks, count), one row per rank; got shape {arr.shape}"
        )
    return arr


def simulate_allreduce(inputs, topo=None, op="sum", faults=None) -> np.ndarray:
    """Allreduce over ``inputs[r]`` per rank; returns the (N, count) result
    (every row identical).  Routes ring vs tree exactly like the reference
    entry point (``MPI_Allreduce_FT``, ``mpi_mod.hpp:1193-1215``).

    ``faults``: an optional :class:`FaultPlan` (or iterable of
    :class:`Fault`) driving the transport through failure — see the module
    docstring for the detect/recover contract.
    """
    data = _as_matrix(inputs)
    n = data.shape[0]
    topo = Topology.resolve(n, topo)
    rop = get_op(op)
    rop.check_dtype(data.dtype)
    plan = _resolve_plan(faults)
    if n <= 1:  # trivial world, reference memcpy fast path (mpi_mod.hpp:1181-1188)
        return data.copy()
    if isinstance(topo, LonelyTopology):
        # the lonely protocol (stages.LonelyTopology): fold each lonely
        # rank m+i into buddy i, tree over the first m rows, hand back.
        # Both buddy hops ride the mailbox so chaos reaches them too
        # (phase 0 = fold at time -1, phase 3 = return past the tree's end).
        m = topo.tree.num_nodes
        steps = 2 * topo.tree.num_stages
        # the fold shares time 0 with tree stage 0 (a rank killed "at stage
        # 0" is dead from the very start, fold included); the return runs
        # one tick past the tree's end
        fold = Mailbox(plan, phase=0, stage=0, time=0)
        back = Mailbox(plan, phase=3, stage=0, time=steps)
        folded = data[:m].copy()
        for i in range(topo.lonely):
            fold.post(m + i, i, WHOLE_PAYLOAD, data[m + i])
        for i in range(topo.lonely):
            folded[i] = rop.np_fn(folded[i], fold.fetch(i, m + i, WHOLE_PAYLOAD))
        out = simulate_tree_allreduce(folded, topo.tree, rop, plan)
        for i in range(topo.lonely):
            back.post(i, m + i, WHOLE_PAYLOAD, out[i])
        result = np.tile(out[0], (n, 1))
        for i in range(topo.lonely):
            if plan.dead_at(m + i, steps):
                # a dead lonely rank receives nothing; the collective still
                # completes for survivors (its contribution was folded in
                # before it died) — degrade-to-survivors, recorded
                plan.record(
                    "kill", "recovered", 3, 0, i, m + i, WHOLE_PAYLOAD,
                    note="dead lonely rank skipped at result return",
                )
                continue
            result[m + i] = back.fetch(m + i, i, WHOLE_PAYLOAD)
        return result
    if topo.is_ring:
        return simulate_ring_allreduce(data, rop, plan)
    return simulate_tree_allreduce(data, topo, rop, plan)


def simulate_tree_allreduce(
    data: np.ndarray, topo: Topology, rop: ReduceOp, faults=None
) -> np.ndarray:
    plan = _resolve_plan(faults)
    n, count = data.shape
    layout = BlockLayout(n, count)
    sp = [send_plan(topo, r) for r in range(n)]
    rp = [recv_plan(topo, r) for r in range(n)]
    k = topo.num_stages
    # dst starts poisoned: anything not written by the schedule must never
    # be read, and the final check below proves full coverage.
    if np.issubdtype(data.dtype, np.floating):
        dst = np.full_like(data, np.nan)
    else:
        dst = np.full_like(data, 0)
    written = np.zeros((n, count), dtype=bool)

    # ---- phase 1: hierarchical reduce-scatter -------------------------------
    for i in range(k):
        src_buf = data if i == 0 else dst
        box = Mailbox(plan, phase=1, stage=i, time=i)
        for r in range(n):
            if plan.dead_at(r, i):
                continue
            held = set(owned_blocks(topo, r, i)) if i else set(range(n))
            for op_ in sp[r][i]:
                if op_.peer == r:
                    continue  # transport skips self (mpi_mod.hpp:676)
                box.open(r, op_.peer)
                for b in op_.blocks:
                    if b not in held:
                        raise ScheduleViolation(
                            f"stage {i}: rank {r} sends block {b} it does not hold"
                        )
                    s, l = layout.span(b)
                    if l == 0:
                        continue  # empty tail block skipped (mpi_mod.hpp:692-696)
                    box.post(r, op_.peer, b, src_buf[r, s : s + l].copy())
        for r in range(n):
            if plan.dead_at(r, i):
                continue  # a dead rank stops receiving/reducing
            mine = owned_blocks(topo, r, i + 1)
            for recv_op in rp[r][i]:
                if recv_op.peer == r:
                    continue
                box.expect(r, recv_op.peer)
            for b in mine:
                s, l = layout.span(b)
                if l == 0:
                    continue
                acc = src_buf[r, s : s + l].copy()
                for peer in topo.group_members(i, r):
                    if peer == r:
                        continue
                    acc = rop.np_fn(acc, box.fetch(r, peer, b))
                dst[r, s : s + l] = acc
                written[r, s : s + l] = True

    # ---- phase 2: hierarchical allgather (reversed, roles swapped) ----------
    for i in reversed(range(k)):
        t = 2 * k - 1 - i
        box = Mailbox(plan, phase=2, stage=i, time=t)
        for r in range(n):
            if plan.dead_at(r, t):
                continue
            held = set(owned_blocks(topo, r, i + 1))
            # phase-2 send uses the *recv* op list (mpi_mod.hpp:1056)
            for op_ in rp[r][i]:
                if op_.peer == r:
                    continue
                box.open(r, op_.peer)
                for b in op_.blocks:
                    if b not in held:
                        raise ScheduleViolation(
                            f"phase2 stage {i}: rank {r} sends unheld block {b}"
                        )
                    s, l = layout.span(b)
                    if l == 0:
                        continue
                    box.post(r, op_.peer, b, dst[r, s : s + l].copy())
        for r in range(n):
            if plan.dead_at(r, t):
                continue
            # phase-2 recv uses the *send* op list, accordingly=true
            # (mpi_mod.hpp:1057): blocks land at their final offsets.
            for op_ in sp[r][i]:
                if op_.peer == r:
                    continue
                for b in op_.blocks:
                    s, l = layout.span(b)
                    if l == 0:
                        continue
                    dst[r, s : s + l] = box.fetch(r, op_.peer, b)
                    written[r, s : s + l] = True

    survivors = [r for r in range(n) if not plan.dead_at(r, 2 * k - 1)]
    if count and not written[survivors].all():
        missing = np.argwhere(~written[survivors])[:4]
        raise ScheduleViolation(f"blocks never written, e.g. (rank, elem) {missing.tolist()}")
    return dst


def simulate_ring_allreduce(data: np.ndarray, rop: ReduceOp, faults=None) -> np.ndarray:
    """Classic 2(N-1)-step ring (``ring_allreduce``, ``mpi_mod.hpp:1113-1163``):
    N-1 reduce-scatter steps + N-1 allgather steps, one block per step."""
    plan = _resolve_plan(faults)
    n, count = data.shape
    layout = BlockLayout(n, count)
    plans = [ring_plan(n, r) for r in range(n)]
    dst = data.copy()
    for step in range(2 * (n - 1)):
        reduce_phase = step < n - 1
        box = Mailbox(plan, phase=1 if reduce_phase else 2, stage=step, time=step)
        for r in range(n):
            if plan.dead_at(r, step):
                continue
            send_op, _ = plans[r][step]
            (b,) = send_op.blocks
            s, l = layout.span(b)
            box.post(r, send_op.peer, b, dst[r, s : s + l].copy())
        for r in range(n):
            if plan.dead_at(r, step):
                continue
            _, recv_op = plans[r][step]
            (b,) = recv_op.blocks
            payload = box.fetch(r, recv_op.peer, b)
            s, l = layout.span(b)
            if reduce_phase:
                dst[r, s : s + l] = rop.np_fn(dst[r, s : s + l], payload)
            else:
                dst[r, s : s + l] = payload
    return dst
