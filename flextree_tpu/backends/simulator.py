"""NumPy simulator backend: executes a FlexTree schedule over N per-rank
arrays in a single process, at message granularity.

This is the ground-truth oracle for every other backend (the rebuild's answer
to the reference's missing test suite, SURVEY §4) and a faithful model of the
reference execution:

- phase 1 = per-stage send -> recv -> reduce, sends sourced from ``data`` at
  stage 0 and from ``dst`` afterwards (``tree_allreduce``,
  ``mpi_mod.hpp:988-1029``);
- phase 2 = reversed stages with send/recv op lists swapped, received blocks
  landing at their final offsets (``accordingly=true``,
  ``mpi_mod.hpp:1050-1060``);
- tail blocks clamped to the true element count, possibly empty
  (``mpi_mod.hpp:679-696``), rather than padded;
- ring = the 2(N-1)-step neighbor schedule (``mpi_mod.hpp:1113-1163``).

Every transfer goes through an explicit mailbox so tests catch schedule bugs
(sending a block the sender doesn't hold, receiving one nobody sent) instead
of silently reading global state.
"""

from __future__ import annotations

import numpy as np

from ..ops.reduce import ReduceOp, get_op
from ..schedule.blocks import BlockLayout
from ..schedule.plan import owned_blocks, recv_plan, ring_plan, send_plan
from ..schedule.stages import LonelyTopology, Topology

__all__ = ["simulate_allreduce", "simulate_tree_allreduce", "simulate_ring_allreduce"]


class ScheduleViolation(AssertionError):
    """A rank tried to send data it does not hold, or a receive had no
    matching send — the simulator's race/consistency detector."""


def _as_matrix(inputs) -> np.ndarray:
    arr = np.asarray(inputs)
    if arr.ndim != 2:
        raise ValueError(
            f"inputs must be 2-D (num_ranks, count), one row per rank; got shape {arr.shape}"
        )
    return arr


def simulate_allreduce(inputs, topo=None, op="sum") -> np.ndarray:
    """Allreduce over ``inputs[r]`` per rank; returns the (N, count) result
    (every row identical).  Routes ring vs tree exactly like the reference
    entry point (``MPI_Allreduce_FT``, ``mpi_mod.hpp:1193-1215``)."""
    data = _as_matrix(inputs)
    n = data.shape[0]
    topo = Topology.resolve(n, topo)
    rop = get_op(op)
    rop.check_dtype(data.dtype)
    if n <= 1:  # trivial world, reference memcpy fast path (mpi_mod.hpp:1181-1188)
        return data.copy()
    if isinstance(topo, LonelyTopology):
        # the lonely protocol (stages.LonelyTopology): fold each lonely
        # rank m+i into buddy i, tree over the first m rows, hand back
        m = topo.tree.num_nodes
        folded = data[:m].copy()
        for i in range(topo.lonely):
            folded[i] = rop.np_fn(folded[i], data[m + i])
        out = simulate_tree_allreduce(folded, topo.tree, rop)
        return np.tile(out[0], (n, 1))
    if topo.is_ring:
        return simulate_ring_allreduce(data, rop)
    return simulate_tree_allreduce(data, topo, rop)


def simulate_tree_allreduce(data: np.ndarray, topo: Topology, rop: ReduceOp) -> np.ndarray:
    n, count = data.shape
    layout = BlockLayout(n, count)
    sp = [send_plan(topo, r) for r in range(n)]
    rp = [recv_plan(topo, r) for r in range(n)]
    # dst starts poisoned: anything not written by the schedule must never
    # be read, and the final check below proves full coverage.
    if np.issubdtype(data.dtype, np.floating):
        dst = np.full_like(data, np.nan)
    else:
        dst = np.full_like(data, 0)
    written = np.zeros((n, count), dtype=bool)

    # ---- phase 1: hierarchical reduce-scatter -------------------------------
    for i in range(topo.num_stages):
        src_buf = data if i == 0 else dst
        mailbox: dict[tuple[int, int], dict[int, np.ndarray]] = {}
        for r in range(n):
            held = set(owned_blocks(topo, r, i)) if i else set(range(n))
            for op_ in sp[r][i]:
                if op_.peer == r:
                    continue  # transport skips self (mpi_mod.hpp:676)
                payload = {}
                for b in op_.blocks:
                    if b not in held:
                        raise ScheduleViolation(
                            f"stage {i}: rank {r} sends block {b} it does not hold"
                        )
                    s, l = layout.span(b)
                    if l == 0:
                        continue  # empty tail block skipped (mpi_mod.hpp:692-696)
                    payload[b] = src_buf[r, s : s + l].copy()
                mailbox[(op_.peer, r)] = payload
        for r in range(n):
            mine = owned_blocks(topo, r, i + 1)
            for recv_op in rp[r][i]:
                if recv_op.peer == r:
                    continue
                if (r, recv_op.peer) not in mailbox:
                    raise ScheduleViolation(
                        f"stage {i}: rank {r} expects data from {recv_op.peer}, none sent"
                    )
            for b in mine:
                s, l = layout.span(b)
                if l == 0:
                    continue
                acc = src_buf[r, s : s + l].copy()
                for peer in topo.group_members(i, r):
                    if peer == r:
                        continue
                    sent = mailbox[(r, peer)]
                    if b not in sent:
                        raise ScheduleViolation(
                            f"stage {i}: rank {r} needs block {b} from {peer}, not sent"
                        )
                    acc = rop.np_fn(acc, sent[b])
                dst[r, s : s + l] = acc
                written[r, s : s + l] = True

    # ---- phase 2: hierarchical allgather (reversed, roles swapped) ----------
    for i in reversed(range(topo.num_stages)):
        mailbox = {}
        for r in range(n):
            held = set(owned_blocks(topo, r, i + 1))
            # phase-2 send uses the *recv* op list (mpi_mod.hpp:1056)
            for op_ in rp[r][i]:
                if op_.peer == r:
                    continue
                payload = {}
                for b in op_.blocks:
                    if b not in held:
                        raise ScheduleViolation(
                            f"phase2 stage {i}: rank {r} sends unheld block {b}"
                        )
                    s, l = layout.span(b)
                    if l == 0:
                        continue
                    payload[b] = dst[r, s : s + l].copy()
                mailbox[(op_.peer, r)] = payload
        for r in range(n):
            # phase-2 recv uses the *send* op list, accordingly=true
            # (mpi_mod.hpp:1057): blocks land at their final offsets.
            for op_ in sp[r][i]:
                if op_.peer == r:
                    continue
                sent = mailbox[(r, op_.peer)]
                for b in op_.blocks:
                    s, l = layout.span(b)
                    if l == 0:
                        continue
                    if b not in sent:
                        raise ScheduleViolation(
                            f"phase2 stage {i}: rank {r} missing block {b} from {op_.peer}"
                        )
                    dst[r, s : s + l] = sent[b]
                    written[r, s : s + l] = True

    if count and not written.all():
        missing = np.argwhere(~written)[:4]
        raise ScheduleViolation(f"blocks never written, e.g. (rank, elem) {missing.tolist()}")
    return dst


def simulate_ring_allreduce(data: np.ndarray, rop: ReduceOp) -> np.ndarray:
    """Classic 2(N-1)-step ring (``ring_allreduce``, ``mpi_mod.hpp:1113-1163``):
    N-1 reduce-scatter steps + N-1 allgather steps, one block per step."""
    n, count = data.shape
    layout = BlockLayout(n, count)
    plans = [ring_plan(n, r) for r in range(n)]
    dst = data.copy()
    for step in range(2 * (n - 1)):
        reduce_phase = step < n - 1
        mailbox = {}
        for r in range(n):
            send_op, _ = plans[r][step]
            (b,) = send_op.blocks
            s, l = layout.span(b)
            mailbox[(send_op.peer, r)] = (b, dst[r, s : s + l].copy())
        for r in range(n):
            _, recv_op = plans[r][step]
            b, payload = mailbox[(r, recv_op.peer)]
            if (b,) != recv_op.blocks:
                raise ScheduleViolation(
                    f"ring step {step}: rank {r} expected block {recv_op.blocks}, got {b}"
                )
            s, l = layout.span(b)
            if reduce_phase:
                dst[r, s : s + l] = rop.np_fn(dst[r, s : s + l], payload)
            else:
                dst[r, s : s + l] = payload
    return dst
