"""Execution backends for FlexTree schedules.

- ``simulator``: single-process NumPy oracle (message-granular, clamped
  tails) — also the chaos oracle: a ``FaultPlan`` injects transport faults
  and rank kills, and the mailbox detects or recovers every one (see
  docs/FAILURE_MODEL.md).
- ``xla``: the real TPU path — schedules lowered to XLA collectives under
  ``shard_map`` (see ``flextree_tpu.parallel``).
"""

from .simulator import (
    Fault,
    FaultDetected,
    FaultEvent,
    FaultPlan,
    ScheduleViolation,
    StageTimeout,
    simulate_allreduce,
    simulate_ring_allreduce,
    simulate_tree_allreduce,
)

__all__ = [
    "simulate_allreduce",
    "simulate_ring_allreduce",
    "simulate_tree_allreduce",
    "Fault",
    "FaultPlan",
    "FaultEvent",
    "FaultDetected",
    "StageTimeout",
    "ScheduleViolation",
]
