"""Execution backends for FlexTree schedules.

- ``simulator``: single-process NumPy oracle (message-granular, clamped tails).
- ``xla``: the real TPU path — schedules lowered to XLA collectives under
  ``shard_map`` (see ``flextree_tpu.parallel``).
"""

from .simulator import simulate_allreduce, simulate_ring_allreduce, simulate_tree_allreduce

__all__ = ["simulate_allreduce", "simulate_ring_allreduce", "simulate_tree_allreduce"]
