"""Utilities: timing, logging, profiling, checkpointing, result files."""

from .buildstamp import artifact_meta, build_info, version_string
from .checkpoint import (
    CheckpointCorrupt,
    latest_checkpoint,
    list_checkpoints,
    restore_checkpoint,
    restore_train_state,
    save_checkpoint,
    save_train_state,
    verify_checkpoint,
)
from .logging import get_logger, result_file_name, write_result_file
from .profiling import PhaseTimer, debug_dump_schedule, debug_enabled, phase_timer, trace
from .timing import BenchResult, Timer, time_jax_fn

__all__ = [
    "artifact_meta",
    "build_info",
    "version_string",
    "save_checkpoint",
    "restore_checkpoint",
    "save_train_state",
    "restore_train_state",
    "latest_checkpoint",
    "list_checkpoints",
    "verify_checkpoint",
    "CheckpointCorrupt",
    "get_logger",
    "result_file_name",
    "write_result_file",
    "BenchResult",
    "Timer",
    "time_jax_fn",
    "PhaseTimer",
    "phase_timer",
    "trace",
    "debug_dump_schedule",
    "debug_enabled",
]
