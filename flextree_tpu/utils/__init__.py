"""Utilities: timing, logging, profiling, and result-file conventions."""

from .logging import get_logger, result_file_name, write_result_file
from .profiling import PhaseTimer, debug_dump_schedule, debug_enabled, phase_timer, trace
from .timing import BenchResult, Timer, time_jax_fn

__all__ = [
    "get_logger",
    "result_file_name",
    "write_result_file",
    "BenchResult",
    "Timer",
    "time_jax_fn",
    "PhaseTimer",
    "phase_timer",
    "trace",
    "debug_dump_schedule",
    "debug_enabled",
]
