"""Utilities: timing, logging, and result-file conventions."""

from .logging import get_logger, result_file_name, write_result_file
from .timing import BenchResult, Timer, time_jax_fn

__all__ = [
    "get_logger",
    "result_file_name",
    "write_result_file",
    "BenchResult",
    "Timer",
    "time_jax_fn",
]
