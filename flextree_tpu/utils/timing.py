"""Timing utilities: a chrono-style stopwatch and a device-aware benchmark
loop.

The stopwatch mirrors the reference planner's ``newplan::Timer``
(``cost_model/timer.h:15-130``: Start/Stop/elapsed in s/ms/µs/ns).  The
benchmark loop is the analog of the reference harness's barrier+MPI_Wtime
pattern (``benchmark.cpp:149-174``) done right for an async dispatch model:
``block_until_ready`` gates both the warmup and every timed repetition (the
reference relied on the collective being blocking — SURVEY §8 notes the
missing completion gate).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax

__all__ = [
    "Timer",
    "BenchResult",
    "time_jax_fn",
    "time_jax_fn_inplace",
    "time_chained",
    "time_device_loop",
]


class Timer:
    """Minimal stopwatch: ``Timer()`` starts it; ``elapsed_*`` reads it."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._stopped: float | None = None

    def restart(self) -> None:
        self._t0 = time.perf_counter()
        self._stopped = None

    def stop(self) -> float:
        self._stopped = time.perf_counter()
        return self._stopped - self._t0

    @property
    def elapsed_s(self) -> float:
        end = self._stopped if self._stopped is not None else time.perf_counter()
        return end - self._t0

    @property
    def elapsed_ms(self) -> float:
        return self.elapsed_s * 1e3

    @property
    def elapsed_us(self) -> float:
        return self.elapsed_s * 1e6

    @property
    def elapsed_ns(self) -> float:
        return self.elapsed_s * 1e9


@dataclass(frozen=True)
class BenchResult:
    """Per-repetition wall times plus the min/avg summary the reference
    harness logs (``benchmark.cpp:215``)."""

    times_s: tuple[float, ...]
    compile_s: float

    @property
    def min_s(self) -> float:
        return min(self.times_s)

    @property
    def avg_s(self) -> float:
        return sum(self.times_s) / len(self.times_s)

    @property
    def median_s(self) -> float:
        ts = sorted(self.times_s)
        n = len(ts)
        mid = n // 2
        return ts[mid] if n % 2 else 0.5 * (ts[mid - 1] + ts[mid])


def time_jax_fn(fn, *args, repeat: int = 10, warmup: int = 2) -> BenchResult:
    """Time ``fn(*args)`` with compile excluded and every rep fully gated.

    The first call (compile + run) is timed separately; ``warmup`` extra
    calls absorb autotuning; then ``repeat`` reps are timed individually
    with ``jax.block_until_ready`` inside the timed region (the
    ``MPI_Barrier``/``MPI_Wtime`` analog of ``benchmark.cpp:151-157``).
    """
    t = Timer()
    jax.block_until_ready(fn(*args))
    compile_s = t.stop()
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    times = []
    for _ in range(repeat):
        t.restart()
        jax.block_until_ready(fn(*args))
        times.append(t.stop())
    return BenchResult(tuple(times), compile_s)


def time_jax_fn_inplace(fn, x, repeat: int = 10, warmup: int = 2) -> BenchResult:
    """Time ``fn`` in-place: each output feeds the next call's input.

    This is the protocol of the reference benchmark's compounding
    ``MPI_IN_PLACE`` loop (``benchmark.cpp:149-159``): the same buffer is
    reduced again and again.  It is the only valid way to time a *donating*
    jit (the donated input is consumed, so re-calling on the original array
    would die), and it works identically for non-donating ``fn`` — so both
    sides of an A/B can share it.  ``fn``'s output must match its input in
    shape/dtype/sharding.
    """
    t = Timer()
    acc = fn(x)
    jax.block_until_ready(acc)
    compile_s = t.stop()
    for _ in range(warmup):
        acc = fn(acc)
    jax.block_until_ready(acc)
    times = []
    for _ in range(repeat):
        t.restart()
        acc = fn(acc)
        jax.block_until_ready(acc)
        times.append(t.stop())
    return BenchResult(tuple(times), compile_s)


def time_device_loop(
    fn,
    x0,
    *rest,
    n_lo: int = 2,
    n_hi: int = 12,
    best_of: int = 4,
    samples: int = 1,
) -> float:
    """Device-only per-call seconds for ``fn(x0, *rest)`` via an in-jit
    chained loop at two iteration counts.

    Protocol: jit ``lax.fori_loop(0, n, lambda i, a: fn(a, *rest), x0)``
    followed by a host scalar fetch, at ``n_lo`` and ``n_hi`` iterations;
    per-call time is the slope ``(t_hi - t_lo) / (n_hi - n_lo)`` with each
    endpoint the best of ``best_of`` runs.  The output→input chain makes
    every iteration data-dependent (unfakeable by an async backend) and the
    slope cancels the *fixed* dispatch cost per jit call — which over this
    container's tunneled TPU is tens of milliseconds and swings 2-4x
    run-to-run, enough to bury the kernel entirely (r02 reported 33 TFLOP/s
    for a kernel whose device time is ~95; see PROFILE_ATTENTION.md).
    Requires ``fn``'s output to match its first argument in shape/dtype.
    ``samples > 1`` repeats the slope measurement (reusing the compiled
    loops — recompiling per sample over a tunneled backend is both slow and
    the kind of long in-flight compile that has wedged it) and returns the
    median slope.
    """
    import statistics

    import jax.numpy as jnp
    from jax import lax

    def make_loop(n):
        def loop(x, *r):
            acc = lax.fori_loop(0, n, lambda i, a: fn(a, *r), x)
            return jnp.sum(acc.astype(jnp.float32))

        return jax.jit(loop)

    loop_lo, loop_hi = make_loop(n_lo), make_loop(n_hi)
    float(loop_lo(x0, *rest))  # compile + warm
    float(loop_hi(x0, *rest))

    def best(loop, k):
        b = float("inf")
        for _ in range(k):
            t0 = time.perf_counter()
            float(loop(x0, *rest))
            b = min(b, time.perf_counter() - t0)
        return b

    slopes = []
    for _ in range(samples):
        # dispatch noise can exceed the added work when fn is tiny, making
        # the slope non-positive; retry with more best-of samples before
        # giving up loudly rather than returning a <=0 "time" (which would
        # publish as a negative/infinite TFLOP/s)
        k = best_of
        for attempt in range(3):
            slope = (best(loop_hi, k) - best(loop_lo, k)) / (n_hi - n_lo)
            if slope > 0:
                break
            k *= 2
        else:
            raise RuntimeError(
                f"time_device_loop: non-positive slope ({slope:.3e}s) after "
                f"3 attempts — fn is too small relative to dispatch noise "
                f"at n_hi={n_hi}; raise n_hi or time it with time_jax_fn"
            )
        slopes.append(slope)
    return statistics.median(slopes)


def time_chained(fn, q, *rest, n_calls: int = 10) -> float:
    """Per-call seconds for ``fn(q, *rest)`` with each output fed back as
    the next first argument and a final host scalar fetch.

    The data-dependency chain is the one completion gate a remote/tunneled
    backend cannot fake: ``block_until_ready`` there can return before
    long-running work finishes (and measures round-trip latency on short
    work), but the final fetch cannot produce bytes until every chained
    call has executed.  Requires ``fn``'s output to have the shape/dtype
    of its first argument.
    """
    import jax.numpy as jnp

    warm = fn(q, *rest)
    float(jnp.sum(warm.astype(jnp.float32)))  # compile + forced warmup
    t0 = time.perf_counter()
    acc = q
    for _ in range(n_calls):
        acc = fn(acc, *rest)
    float(jnp.sum(acc.astype(jnp.float32)))
    return (time.perf_counter() - t0) / n_calls
