"""Version-compatibility shims for the pinned JAX.

The codebase targets the current JAX API surface (``jax.shard_map``, the
``jax_num_cpu_devices`` config option); older pins (0.4.x, as baked into
some containers) spell both differently.  Importing this module — which
``flextree_tpu/__init__`` does — installs the aliases, so every call site
can keep using the modern spelling.
"""

from __future__ import annotations

import os

import jax

__all__ = ["request_cpu_devices"]

if not hasattr(jax, "shard_map"):  # JAX < 0.6: experimental namespace
    import functools

    from jax.experimental.shard_map import shard_map as _exp_shard_map

    @functools.wraps(_exp_shard_map)
    def _shard_map(f, *args, check_vma=None, **kw):
        # modern spelling of the replication check; same False-to-disable
        if check_vma is not None and "check_rep" not in kw:
            kw["check_rep"] = check_vma
        return _exp_shard_map(f, *args, **kw)

    jax.shard_map = _shard_map

if not hasattr(jax.lax, "axis_size"):
    # psum of a Python literal is special-cased to the concrete axis size
    # at trace time (no collective); capture psum now so the interposer
    # (flextree_tpu.interpose) shadowing jax.lax.psum can't recurse into it
    _psum = jax.lax.psum

    def _axis_size(axis_name):
        return _psum(1, axis_name)

    jax.lax.axis_size = _axis_size


def request_cpu_devices(n: int) -> None:
    """Pin ``n`` virtual CPU devices on either config spelling.

    Like the option it wraps, this must run before the CPU backend
    initializes; on JAX < 0.5 it falls back to the XLA host-platform flag
    (same lever, read at backend init).  An inherited flag is *replaced*,
    not respected: XLA_FLAGS leaks through os.environ into subprocesses
    (the multi-process bring-up tools spawn children from a test process
    that pinned a different count), and keeping the parent's value would
    silently hand every child the wrong device count.  Mirrors the config
    option's contract by raising RuntimeError once backends exist.
    """
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            raise RuntimeError(
                "request_cpu_devices must run before backends initialize"
            )
        flags = [
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count=")
        ]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
