"""Logging + result-file conventions.

The reference uses glog with rank-0 gating (``LOG_IF(INFO, rank == 0)``)
and writes per-run timing files named
``{tag}.{N}.{size}.{topo}.{ar_test|comm_test}.{unix_time}.txt``
(``benchmark.cpp:193-213``).  We keep the same file-name scheme (so tooling
built for the reference's outputs keeps working) but write JSON payloads,
and use stdlib logging with an explicit process-0 gate instead of glog.
"""

from __future__ import annotations

import json
import logging
import os
import time
from pathlib import Path

__all__ = ["get_logger", "result_file_name", "write_result_file"]

_FMT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"


def get_logger(name: str = "flextree") -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(_FMT))
        logger.addHandler(h)
        logger.setLevel(os.environ.get("FT_LOG_LEVEL", "INFO"))
        logger.propagate = False
    return logger


def result_file_name(
    tag: str, num_devices: int, size: int, topo: str, comm_test: bool = False
) -> str:
    """``{tag}.{N}.{size}.{topo}.{ar_test|comm_test}.{unix_time}.json`` —
    the reference's scheme (``benchmark.cpp:196-200``) with a json suffix."""
    kind = "comm_test" if comm_test else "ar_test"
    topo_s = topo.replace(",", "-").replace("*", "-") or "flat"
    return f"{tag}.{num_devices}.{size}.{topo_s}.{kind}.{int(time.time())}.json"


def write_result_file(path: str | Path, payload: dict) -> Path:
    """Write one benchmark result as pretty JSON; returns the path."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return p
