"""Logging + result-file conventions.

The reference uses glog with rank-0 gating (``LOG_IF(INFO, rank == 0)``)
and writes per-run timing files named
``{tag}.{N}.{size}.{topo}.{ar_test|comm_test}.{unix_time}.txt``
(``benchmark.cpp:193-213``).  We keep the same file-name scheme (so tooling
built for the reference's outputs keeps working) but write JSON payloads,
and use stdlib logging with an explicit process-0 gate instead of glog.

Multi-process attribution: when ``FT_RANK`` is set (the chaos drivers and
real-process launchers export it) every log line carries an ``r{rank}``
field, so interleaved chaos logs are attributable without grepping PIDs;
``get_logger(rank=...)`` forces it for in-process callers (the serving
pool's replicas).
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import time
from pathlib import Path

__all__ = ["get_logger", "logger_rank", "result_file_name", "write_result_file"]

_FMT = "%(asctime)s %(levelname).1s %(name)s] %(message)s"
_FMT_RANK = "%(asctime)s %(levelname).1s r{rank} %(name)s] %(message)s"


def logger_rank() -> int | None:
    """The rank the process-wide loggers should stamp, from ``FT_RANK``
    (exported by the multi-process launchers/chaos drivers).  None when
    unset or unparsable — a single-process run stays unstamped."""
    raw = os.environ.get("FT_RANK", "").strip()
    try:
        return int(raw) if raw else None
    except ValueError:
        return None


def get_logger(name: str = "flextree", rank: int | None = None) -> logging.Logger:
    """A configured logger.  ``rank`` (or ambient ``FT_RANK``) adds an
    ``r{rank}`` field to the format — resolved when the logger's handler
    is FIRST built, matching the launcher contract that ``FT_RANK`` is
    exported before the child imports anything."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        if rank is None:
            rank = logger_rank()
        fmt = _FMT if rank is None else _FMT_RANK.format(rank=rank)
        h = logging.StreamHandler()
        h.setFormatter(logging.Formatter(fmt))
        logger.addHandler(h)
        logger.setLevel(os.environ.get("FT_LOG_LEVEL", "INFO"))
        logger.propagate = False
    return logger


# per-process monotonic disambiguator for result file names: two results
# written in the same wall-clock second must never collide (the reference
# scheme's silent-overwrite hazard), and a counter is collision-free where
# a finer timestamp would only shrink the window
_result_seq = itertools.count()


def result_file_name(
    tag: str, num_devices: int, size: int, topo: str, comm_test: bool = False
) -> str:
    """``{tag}.{N}.{size}.{topo}.{ar_test|comm_test}.{unix_time}-{seq}.json``
    — the reference's scheme (``benchmark.cpp:196-200``) with a json
    suffix and a monotonic per-process sequence number appended to the
    timestamp field (same dotted-field positions, so field-indexed
    tooling keeps working)."""
    kind = "comm_test" if comm_test else "ar_test"
    topo_s = topo.replace(",", "-").replace("*", "-") or "flat"
    stamp = f"{int(time.time())}-{next(_result_seq):04d}"
    return f"{tag}.{num_devices}.{size}.{topo_s}.{kind}.{stamp}.json"


def write_result_file(path: str | Path, payload: dict) -> Path:
    """Write one benchmark result as pretty JSON; returns the path."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return p
