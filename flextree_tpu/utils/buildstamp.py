"""Git build stamping: make every artifact traceable to a commit.

The reference bakes ``GIT_REPO_VERSION/DATE/HASH`` defines into its binary at
build time (``allreduce_over_mpi/CMakeLists.txt:10-31``) and prints them under
``--version`` (``benchmark.cpp:109-115``).  Python has no build step, so we
resolve the stamp lazily at first use from the repo the package is imported
from, and cache it for the process lifetime.

Outside a git checkout (e.g. an installed wheel) every git field degrades to
``"unknown"`` — the stamp never raises.
"""

from __future__ import annotations

import datetime
import functools
import os
import subprocess


def _git(*args: str) -> str:
    """One git query against the package's repo; '' on any failure."""
    repo_dir = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ("git", "-C", repo_dir, *args),
            capture_output=True,
            text=True,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return ""
    return out.stdout.strip() if out.returncode == 0 else ""


@functools.lru_cache(maxsize=1)
def build_info() -> dict:
    """Version + git provenance, mirroring the reference's three stamps.

    Keys: ``version`` (package), ``git_hash`` (short), ``git_date`` (commit
    ISO date), ``git_describe`` (``git describe --always --dirty``).  Git
    fields are ``"unknown"`` when not running from a checkout.
    """
    from flextree_tpu import __version__

    return {
        "version": __version__,
        "git_hash": _git("rev-parse", "--short", "HEAD") or "unknown",
        "git_date": _git("log", "-1", "--format=%cI") or "unknown",
        "git_describe": _git("describe", "--always", "--dirty") or "unknown",
    }


def version_string() -> str:
    """One-line ``--version`` text (the ``benchmark.cpp:109-115`` analog)."""
    info = build_info()
    return (
        f"flextree-tpu {info['version']} "
        f"(git {info['git_describe']}, committed {info['git_date']})"
    )


def artifact_meta() -> dict:
    """Standard provenance block for every committed JSON artifact.

    Includes the generation timestamp so regenerated artifacts are
    distinguishable even at the same commit.
    """
    meta = dict(build_info())
    meta["generated_at"] = datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )
    return meta
