"""Device-kind normalization — dependency-free (no jax import).

Single source of truth for every consumer that keys off the TPU chip
generation: MFU peaks (``bench/harness.py``), HBM roofline peaks
(``tools/roofline_reduce.py``), and calibration section names
(``tools/calibrate_host.py``).  Living here, the host-side tools can
normalize a device string without paying the jax-based bench harness's
import chain.
"""

from __future__ import annotations

__all__ = ["TPU_GENERATIONS", "tpu_generation"]

#: device_kind substring -> canonical generation name.  Order matters:
#: most-specific first ("v5 lite" before bare "v5", which is how v5p can
#: report itself).
TPU_GENERATIONS = (
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5e", "v5e"),
    ("v6 lite", "v6e"),
    ("v6e", "v6e"),
    ("v5p", "v5p"),
    ("v5", "v5p"),
    ("v4", "v4"),
    ("v3", "v3"),
    ("v2", "v2"),
)


def tpu_generation(device_kind: str) -> str | None:
    """Canonical generation name ("v5e", "v5p", ...) for a device_kind
    string, or None when unrecognized."""
    kind = device_kind.lower()
    for sub, gen in TPU_GENERATIONS:
        if sub in kind:
            return gen
    return None
