"""Checkpoint/resume: host-gathered pytree snapshots with sharded restore.

The reference has no checkpointing (SURVEY §5 — "Checkpoint / resume:
none"); a training framework needs it, so this subsystem completes the
gap the TPU way:

- **Format**: one ``.npz`` per checkpoint — every pytree leaf as a named
  array plus a JSON structure descriptor, so restore needs no template
  pytree and no pickle (robust across refactors, inspectable with plain
  NumPy).  Writes are durable-atomic (tmp file fsynced + ``os.replace``
  + directory fsync) so a crash mid-save never corrupts the latest
  checkpoint — and the committed rename survives host crash, not just
  process crash.
- **Sharded restore**: ``restore_checkpoint(..., mesh=, specs=)`` places
  each leaf with ``jax.device_put`` under a ``NamedSharding``, so a
  checkpoint saved from one mesh resumes on another (e.g. 8 -> 16 chips,
  or a dp/sp/tp layout change) as long as the specs divide the shapes —
  the resharding is XLA's, not ours.
- **Rotation**: ``save_train_state`` names files by step
  (``ckpt_{step:08d}.npz``) and prunes beyond ``max_to_keep``;
  ``latest_checkpoint``/``restore_train_state`` resume from the newest.
- **Integrity**: every leaf's CRC32 is recorded in the structure
  descriptor at save time and re-verified on restore, so a truncated or
  bit-flipped checkpoint raises :class:`CheckpointCorrupt` instead of
  silently resuming from garbage; ``restore_train_state`` then *falls
  back* to the next-newest checkpoint that verifies (the crash-safe
  restore the chaos harness exercises — docs/FAILURE_MODEL.md).

Bitwise-exact resume (same mesh, same data ordering) is pinned by the
tests: train k steps == train j, save, restore, train k-j.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import zlib

import jax
import numpy as np

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "save_train_state",
    "restore_train_state",
    "latest_checkpoint",
    "list_checkpoints",
    "verify_checkpoint",
    "CheckpointCorrupt",
]


class CheckpointCorrupt(RuntimeError):
    """A checkpoint failed integrity verification: unreadable/truncated
    archive, missing leaves, or a per-leaf checksum mismatch."""

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.npz$")


def _encode(tree, leaves: list):
    """Replace leaves with indices into ``leaves``; keep container shape."""
    if isinstance(tree, dict):
        for key in tree:
            if not isinstance(key, str):
                raise TypeError(
                    f"checkpoint dict keys must be strings (JSON structure "
                    f"descriptor); got {key!r} ({type(key).__name__})"
                )
        return {"t": "dict", "items": {k: _encode(v, leaves) for k, v in tree.items()}}
    if isinstance(tree, (list, tuple)):
        kind = "list" if isinstance(tree, list) else "tuple"
        return {"t": kind, "items": [_encode(v, leaves) for v in tree]}
    if tree is None:
        return {"t": "none"}
    a = np.asarray(tree)
    leaves.append(a)
    # npz stores extension dtypes (bfloat16, float8_*) as raw void bytes;
    # record the true dtype so restore can view it back.  The CRC32 covers
    # the raw bytes (dtype-view invariant) so restore can verify integrity.
    return {
        "t": "leaf",
        "i": len(leaves) - 1,
        "dtype": str(a.dtype),
        "crc": _leaf_crc(a),
    }


def _decode(node, leaves):
    t = node["t"]
    if t == "dict":
        return {k: _decode(v, leaves) for k, v in node["items"].items()}
    if t == "list":
        return [_decode(v, leaves) for v in node["items"]]
    if t == "tuple":
        return tuple(_decode(v, leaves) for v in node["items"])
    if t == "none":
        return None
    return _restore_dtype(leaves[node["i"]], node.get("dtype"))


def _leaf_crc(a: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(a).tobytes())


def _verify_leaves(node, leaves, path: str):
    """Walk the structure descriptor, re-checksumming every leaf."""
    t = node["t"]
    if t == "dict":
        for v in node["items"].values():
            _verify_leaves(v, leaves, path)
    elif t in ("list", "tuple"):
        for v in node["items"]:
            _verify_leaves(v, leaves, path)
    elif t == "leaf" and "crc" in node:  # pre-integrity checkpoints lack crc
        if node["i"] >= len(leaves):
            raise CheckpointCorrupt(
                f"{path}: leaf_{node['i']} missing (truncated archive)"
            )
        if _leaf_crc(leaves[node["i"]]) != node["crc"]:
            raise CheckpointCorrupt(
                f"{path}: leaf_{node['i']} checksum mismatch (corrupt data)"
            )


def _restore_dtype(a: np.ndarray, dtype_str: str | None) -> np.ndarray:
    if dtype_str is None or str(a.dtype) == dtype_str:
        return a
    import ml_dtypes  # noqa: F401  registers bfloat16/float8 with numpy

    target = np.dtype(dtype_str)
    if a.dtype.kind == "V" and a.dtype.itemsize == target.itemsize:
        return a.view(target)
    return a.astype(target)


def _fsync_dir(dirpath: str) -> None:
    """fsync a directory so a just-committed rename survives HOST crash.

    ``os.replace`` makes the swap atomic against *process* crash, but the
    new directory entry lives in the directory inode — on a power loss
    before the directory block hits disk, the filesystem can replay to a
    state where neither the tmp file nor the renamed checkpoint exists.
    Fsyncing the containing directory after the replace closes that
    window (the file's own data was fsynced before the rename).
    """
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:  # pragma: no cover - platforms without O_RDONLY dirs
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs without dir fsync (FUSE/NFS):
        pass  # the checkpoint itself is already committed; degrade quietly
    finally:
        os.close(fd)


def save_checkpoint(path: str | os.PathLike, tree) -> str:
    """Write ``tree`` (dict/list/tuple pytree of arrays) to ``path``.

    Device arrays are host-gathered first; the write is durable-atomic:
    tmp file fsynced, ``os.replace``, then the containing directory
    fsynced — so the newest checkpoint survives host crash, not just
    process crash (docs/FAILURE_MODEL.md).
    """
    path = os.fspath(path)
    tree = jax.device_get(tree)
    leaves: list[np.ndarray] = []
    structure = _encode(tree, leaves)
    arrays = {f"leaf_{i}": a for i, a in enumerate(leaves)}
    arrays["__structure__"] = np.frombuffer(
        json.dumps(structure).encode(), dtype=np.uint8
    )
    dirpath = os.path.dirname(path) or "."
    os.makedirs(dirpath, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirpath, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(dirpath)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def restore_checkpoint(path: str | os.PathLike, mesh=None, specs=None, *, verify=True):
    """Load a checkpoint; optionally place leaves sharded over ``mesh``.

    With ``mesh``/``specs`` (a PartitionSpec pytree matching the saved
    structure) every leaf is ``device_put`` under the corresponding
    ``NamedSharding``; otherwise plain NumPy arrays come back.

    ``verify`` (default on) re-checksums every leaf against the CRC32s the
    save recorded; an unreadable archive or a mismatch raises
    :class:`CheckpointCorrupt` (checkpoints from before the integrity
    scheme carry no CRCs and load unverified).
    """
    path = os.fspath(path)
    try:
        # own the file handle: np.load(path) leaks its fd when a truncated/
        # corrupt archive makes it raise after opening (ResourceWarning —
        # an error under the suite's filterwarnings), so the outer `with`
        # guarantees closure on every path
        with open(path, "rb") as fh, np.load(fh) as data:
            structure = json.loads(bytes(data["__structure__"]).decode())
            leaves = [data[f"leaf_{i}"] for i in range(len(data.files) - 1)]
    except FileNotFoundError:
        raise
    except Exception as e:  # truncated zip, missing keys, bad JSON, ...
        raise CheckpointCorrupt(f"unreadable checkpoint {path}: {e}") from e
    if verify:
        _verify_leaves(structure, leaves, path)
    tree = _decode(structure, leaves)
    if mesh is None:
        return tree
    if specs is None:
        raise ValueError("sharded restore needs both mesh= and specs=")
    from jax.sharding import NamedSharding

    def place(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(place, tree, specs, is_leaf=lambda x: x is None)


# ------------------------------------------------------------ train-state


def list_checkpoints(ckpt_dir: str | os.PathLike) -> list[tuple[int, str]]:
    """Sorted [(step, path)] of checkpoints in ``ckpt_dir``."""
    ckpt_dir = os.fspath(ckpt_dir)
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    return sorted(found)


def latest_checkpoint(ckpt_dir: str | os.PathLike) -> str | None:
    ckpts = list_checkpoints(ckpt_dir)
    return ckpts[-1][1] if ckpts else None


def save_train_state(
    ckpt_dir: str | os.PathLike,
    state: dict,
    *,
    max_to_keep: int = 3,
) -> str:
    """Save a train state keyed by its ``state['step']``; prune old ones.

    Pruning never deletes the checkpoint just written, even when the
    directory already holds ``max_to_keep`` higher-step files (e.g. a fresh
    run reusing an old checkpoint dir): the just-written path is exempt and
    stale higher-step checkpoints are pruned *first*, so a later resume
    finds this state — not a silently-restored stale higher step.
    """
    step = int(np.asarray(jax.device_get(state["step"])))
    path = os.path.join(os.fspath(ckpt_dir), f"ckpt_{step:08d}.npz")
    save_checkpoint(path, state)
    if max_to_keep is not None and max_to_keep > 0:
        others = [(s, p) for s, p in list_checkpoints(ckpt_dir) if p != path]
        stale = [(s, p) for s, p in others if s > step]  # from an older run
        fresh = [(s, p) for s, p in others if s <= step]
        keep_others = max_to_keep - 1  # the new file occupies one slot
        for _, old in stale + fresh[: max(0, len(fresh) - keep_others)]:
            os.unlink(old)
    return path


def verify_checkpoint(path: str | os.PathLike) -> bool:
    """Whether ``path`` loads and passes leaf-checksum verification."""
    try:
        restore_checkpoint(path)
        return True
    except (CheckpointCorrupt, FileNotFoundError):
        return False


def restore_train_state(
    ckpt_dir_or_path: str | os.PathLike, mesh=None, specs=None, *, on_fallback=None
):
    """Restore the newest train state from a directory (or an exact path).

    Crash-safe: when the newest checkpoint in a directory is truncated or
    corrupt (a crash mid-write on a non-atomic filesystem, a bad disk), it
    falls back to the next-newest that verifies, oldest-last, calling
    ``on_fallback(bad_path, exc)`` for each rejected file; only when
    *every* checkpoint fails does it raise :class:`CheckpointCorrupt`.
    An exact file path gets no fallback — corruption raises.
    """
    path = os.fspath(ckpt_dir_or_path)
    if not os.path.isdir(path):
        return restore_checkpoint(path, mesh=mesh, specs=specs)
    ckpts = list_checkpoints(path)
    if not ckpts:
        raise FileNotFoundError(f"no checkpoints in {path}")
    last_exc = None
    for _, p in reversed(ckpts):
        try:
            return restore_checkpoint(p, mesh=mesh, specs=specs)
        except CheckpointCorrupt as e:
            from .logging import get_logger

            get_logger("flextree.ckpt").warning(
                "checkpoint %s failed verification (%s); falling back", p, e
            )
            if on_fallback is not None:
                on_fallback(p, e)
            last_exc = e
    raise CheckpointCorrupt(
        f"every checkpoint in {path} failed verification"
    ) from last_exc
