"""Profiling / tracing: named spans, phase timers, span ledgers.

Three layers, host-side unless noted:

- :func:`trace` wraps ``jax.profiler`` so a benchmark run produces a
  TensorBoard-loadable trace; the per-stage ``jax.named_scope`` annotations
  inside :mod:`flextree_tpu.parallel.allreduce` (``ft_rs_stage*`` /
  ``ft_ag_stage*``) make the hierarchical phases visible in it.
- :func:`phase_timer` is the in-process fallback when a full profiler
  trace is overkill: named checkpoints with deltas, rank-0 gated logging.
- :func:`comm_span` names each bucket's collectives; at trace time it
  feeds every active :class:`SpanLedger` *and* the ambient flight
  recorder (:mod:`flextree_tpu.obs`), carrying plan provenance when the
  caller supplies it — the always-on telemetry layer's view of the comm
  plan.

(The reference-lineage note — how the C++ ``SHOW_TIME`` / ``FT_DEBUG``
compile-time knobs map onto these runtime facilities — lives in
``docs/OBSERVABILITY.md``.)
"""

from __future__ import annotations

import contextlib
import os
import re
import time

from .logging import get_logger

__all__ = [
    "trace",
    "phase_timer",
    "PhaseTimer",
    "comm_span",
    "span_bytes",
    "SpanLedger",
    "span_ledger",
    "plan_capture",
    "exposed_split",
    "Ewma",
    "step_scope",
    "debug_dump_schedule",
    "debug_enabled",
]


class Ewma:
    """Exponentially-weighted moving average — the per-rank step-duration
    signal the runtime supervision layer classifies stragglers from.

    Each rank folds its step wall-times into an EWMA (``alpha`` weights
    the newest sample) and publishes it in its heartbeat
    (``runtime.supervisor.Supervisor``); the coordinator's
    ``MembershipView`` flags ranks whose EWMA is an outlier against the
    peer median.  An EWMA rather than the last sample so one noisy step
    (GC pause, page fault) doesn't flap the classification.
    """

    def __init__(self, alpha: float = 0.2):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f"alpha must be in (0, 1], got {alpha}")
        self.alpha = alpha
        self.value: float | None = None
        self.count = 0

    def update(self, sample: float) -> float:
        self.value = (
            sample
            if self.value is None
            else self.alpha * sample + (1.0 - self.alpha) * self.value
        )
        self.count += 1
        return self.value


@contextlib.contextmanager
def step_scope(ewma: "Ewma | None" = None, on_duration=None):
    """Time one host-level training step; feed the duration to an
    :class:`Ewma` and/or ``on_duration(seconds)`` (e.g.
    ``Supervisor.record_step`` partial) on exit.  The host-side sibling
    of :func:`comm_span`: ``comm_span`` names device spans inside jitted
    code, ``step_scope`` accounts the wall-clock of the whole dispatched
    step — the quantity the straggler classifier compares across ranks.
    """
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        if ewma is not None:
            ewma.update(dt)
        if on_duration is not None:
            on_duration(dt)


class SpanLedger:
    """Trace-time accounting of :func:`comm_span` scopes.

    While active (``with span_ledger() as ledger``), every ``comm_span``
    entered — including inside a ``jax.jit`` trace — records its name
    into the ledger.  Bucket-sync span names carry their payload bytes as
    a ``_{nbytes}B`` suffix (``ft_bucket*`` / ``ft_overlap_bucket*``), so
    the ledger can attribute *planned wire bytes per bucket* for a traced
    step: the bench's exposed-vs-hidden comm split uses this to assert
    which buckets actually fired and what they carried, next to the
    measured step-time delta (``exposed_split``).  Host-side bookkeeping
    only — nothing enters the traced program.
    """

    def __init__(self):
        self.spans: list[str] = []

    def record(self, name: str) -> None:
        self.spans.append(name)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(self.spans)

    def total_bytes(self, prefix: str = "") -> int:
        """Sum of the ``_{n}B`` suffixes of recorded spans with ``prefix``."""
        total = 0
        for name in self.spans:
            if not name.startswith(prefix):
                continue
            m = _BYTES_SUFFIX.search(name)
            if m:
                total += int(m.group(1))
        return total


#: The byte-attribution suffix contract: the LAST ``_``-separated token
#: must be exactly ``{digits}B``.  Anchored so a name whose final token
#: merely *ends* in ``B`` (``..._fooB``, ``..._0xB``) never miscounts.
_BYTES_SUFFIX = re.compile(r"_(\d+)B$")


def span_bytes(name: str) -> int | None:
    """The ``_{n}B`` payload suffix of a span name, or None."""
    m = _BYTES_SUFFIX.search(name)
    return int(m.group(1)) if m else None


_ACTIVE_LEDGERS: list[SpanLedger] = []

#: active plan captures: every ``comm_span`` entered with a provenance
#: payload appends ``(name, provenance)`` to each — the trace-time hook
#: the per-step span clock (``obs/stepclock.py``) uses to learn WHICH
#: buckets a freshly-compiled step will run, so per-step measured spans
#: can be keyed to the compile-time provenance without re-deriving it
_ACTIVE_PLAN_CAPTURES: list[list] = []


@contextlib.contextmanager
def span_ledger():
    """Collect every ``comm_span`` entered in this block into a
    :class:`SpanLedger` (trace-time; reentrant — nested ledgers all
    record)."""
    ledger = SpanLedger()
    _ACTIVE_LEDGERS.append(ledger)
    try:
        yield ledger
    finally:
        _ACTIVE_LEDGERS.remove(ledger)


@contextlib.contextmanager
def plan_capture():
    """Collect every provenance-carrying ``comm_span`` entered in this
    block as ``(name, provenance_dict)`` pairs — the compile-time bucket
    plan of whatever traced under it.  Like :func:`span_ledger` this is
    trace-time bookkeeping: under ``jit`` the spans fire while tracing,
    so wrapping a step's FIRST (compiling) call yields its full bucket
    plan and wrapping an already-compiled call yields nothing.  The list
    is shared module state (not thread-local) deliberately: the watchdog
    runs steps on a worker thread and the capture must still see them."""
    cap: list = []
    _ACTIVE_PLAN_CAPTURES.append(cap)
    try:
        yield cap
    finally:
        _ACTIVE_PLAN_CAPTURES.remove(cap)


def exposed_split(step_ms: float, nosync_step_ms: float, comm_total_ms: float):
    """(exposed_ms, hidden_ms) of a train step's comm time.

    ``exposed`` is the step-time delta over the sync-free twin — the sync
    time that extended the step.  ``hidden`` is the remainder of the
    measured sync-only time (``comm_total_ms``, the ``comm_span``-scoped
    collectives timed alone): wire time that ran under compute instead of
    extending the step.  Clamped at zero both ways: on a noisy host the
    deltas can cross zero, and a negative exposure means "fully hidden",
    not negative time.
    """
    exposed = max(float(step_ms) - float(nosync_step_ms), 0.0)
    hidden = max(float(comm_total_ms) - exposed, 0.0)
    return exposed, hidden


@contextlib.contextmanager
def comm_span(
    name: str,
    timer: "PhaseTimer | None" = None,
    provenance: dict | None = None,
):
    """Named communication span: a ``jax.named_scope`` (so the span shows up
    as a named range over its collectives in profiler traces, exactly like
    the per-stage ``ft_rs_stage*`` scopes) plus an optional host-side
    :class:`PhaseTimer` checkpoint on exit.

    This is the per-*bucket* observability layer the fused gradient sync
    uses (``parallel.bucketing``): each bucket's collectives trace under an
    ``ft_bucket{i}_{axis}_{k}leaves_{bytes}B`` range, so a profile (or a
    run_report built from one) can attribute comm time per bucket and
    separate comm from compute per step.  Under ``jit`` the body runs at
    trace time, so the *timer* measures tracing, not execution — pass a
    timer only in eager/host-level phases; inside jitted code the named
    scope is the useful half.

    Every span also feeds the active :class:`SpanLedger`\\ s and the
    ambient flight recorder (:func:`flextree_tpu.obs.record_event`, a
    no-op when none is installed): ``provenance`` — the comm plan behind
    the span (``obs.provenance.bucket_provenance``) — upgrades the
    recorded event from a bare ``collective`` to a ``bucket_planned``
    carrying widths/codec/sharded and the predicted cost breakdown.
    """
    import jax

    for ledger in _ACTIVE_LEDGERS:
        ledger.record(name)
    from ..obs import record_event

    if provenance is not None:
        for cap in _ACTIVE_PLAN_CAPTURES:
            cap.append((name, provenance))
        record_event("bucket_planned", name=name, **provenance)
    else:
        record_event("collective", name=name, bytes=span_bytes(name))
    with jax.named_scope(name):
        yield
    if timer is not None:
        timer.checkpoint(name)


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Profile the enclosed block to ``log_dir`` (TensorBoard/XPlane format).

    Usage::

        with trace("/tmp/ft_trace"):
            jax.block_until_ready(allreduce_over_mesh(x, mesh, topo="4,2"))

    The stage scopes (``ft_rs_stage0_w4`` etc.) appear as named ranges over
    the XLA collective ops they wrap.
    """
    import jax

    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Named phase checkpoints with wall-clock deltas — the ``TIME_RESET`` /
    ``TIME_LOG_IF`` pattern (``mpi_mod.hpp:34-38``) as an object.

    ``log=True`` emits each checkpoint via the framework logger (rank-0
    gating is the caller's concern, as in the reference's
    ``LOG_IF(INFO, rank == 0)``).
    """

    def __init__(self, log: bool = False, logger_name: str = "flextree.phase"):
        self._log = log
        self._logger = get_logger(logger_name)
        self.reset()

    def reset(self) -> None:
        self._t0 = time.perf_counter()
        self._last = self._t0
        self.phases: list[tuple[str, float]] = []

    def checkpoint(self, name: str) -> float:
        """Record time since the previous checkpoint under ``name``."""
        now = time.perf_counter()
        dt = now - self._last
        self._last = now
        self.phases.append((name, dt))
        if self._log:
            self._logger.info("phase %-24s %8.3f ms", name, dt * 1e3)
        return dt

    @property
    def total_s(self) -> float:
        return self._last - self._t0

    def summary(self) -> str:
        lines = [f"{n:<24} {dt * 1e3:8.3f} ms" for n, dt in self.phases]
        lines.append(f"{'total':<24} {self.total_s * 1e3:8.3f} ms")
        return "\n".join(lines)


@contextlib.contextmanager
def phase_timer(log: bool = True):
    """``with phase_timer() as pt: pt.checkpoint("reduce-scatter"); ...``

    On exit the phase summary table is logged (the per-phase deltas plus the
    total), so the scope has a visible end — the ``SHOW_TIME`` run footer.
    """
    pt = PhaseTimer(log=log)
    try:
        yield pt
    finally:
        if log and pt.phases:
            pt._logger.info("phase summary:\n%s", pt.summary())


def debug_enabled() -> bool:
    """True when the ``FT_DEBUG`` env var is set to a truthy value."""
    return os.environ.get("FT_DEBUG", "").strip().lower() not in (
        "",
        "0",
        "false",
        "no",
        "off",
    )


def debug_dump_schedule(topo, rank: int | None = None, force: bool = False) -> str | None:
    """Dump the per-rank schedule when ``FT_DEBUG`` is on (or ``force``).

    ``topo`` is a ``flextree_tpu.schedule.stages.Topology``.  Returns the
    dump string (also logged) or None when debug is off — mirrors the
    reference's ``FT_DEBUG``-gated ``print_ops`` topology dumps
    (``mpi_mod.hpp:105-131``, call sites under ``#ifdef FT_DEBUG``).
    """
    if not (force or debug_enabled()):
        return None
    from ..schedule.plan import format_plan

    ranks = range(topo.num_nodes) if rank is None else (rank,)
    out = "\n".join(format_plan(topo, r) for r in ranks)
    get_logger("flextree.debug").info("\n%s", out)
    return out
