"""Stage-width ("topology") handling for hierarchical allreduce.

A *topology* is a vector of per-level tree widths ``[w0, w1, ..., wk]`` with
``prod(wi) == N`` devices.  Each level performs a width-``wi`` grouped
reduce-scatter; the levels then unwind in reverse as an allgather.

Special cases (mirroring the reference semantics of
``allreduce_over_mpi/mpi_mod.hpp:882-929`` / ``get_stages``):

- width vector ``[N]``        -> flat one-stage allreduce (the default)
- ``[2, 2, ..., 2]``          -> recursive halving-doubling
- any width ``1`` anywhere    -> collapse to ``[1]`` = use the ring algorithm
- product != N                -> hard error (the reference aborts;
                                 ``mpi_mod.hpp:914-918``) — UNLESS the spec
                                 carries a ``+k`` suffix, which resolves to
                                 a ``LonelyTopology`` (tree over N-k ranks
                                 plus k buddy-folded lonely ranks; the
                                 reference's disabled design, executable
                                 here)

The environment variable ``FT_TOPO`` (comma-separated widths, e.g. ``"4,2"``)
is honoured for drop-in compatibility with the reference
(``mpi_mod.hpp:885``).
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field

__all__ = [
    "Topology",
    "LonelyTopology",
    "TopologyError",
    "parse_topo",
    "split_lonely_spec",
    "get_stages",
    "FT_TOPO_ENV",
]

FT_TOPO_ENV = "FT_TOPO"


class TopologyError(ValueError):
    """Raised for invalid stage-width vectors (product mismatch, bad values)."""


def parse_topo(spec: str) -> tuple[int, ...]:
    """Parse a comma-separated width spec like ``"4,2"`` into ``(4, 2)``.

    Mirrors the reference's tokenizer (``mpi_mod.hpp:888-907``): whitespace is
    tolerated, empty string yields an empty tuple (meaning "flat default").
    """
    spec = spec.strip()
    if not spec:
        return ()
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        try:
            out.append(int(tok))
        except ValueError as e:
            raise TopologyError(f"bad width token {tok!r} in topo spec {spec!r}") from e
    return tuple(out)


def split_lonely_spec(spec: str) -> tuple[str, int]:
    """Split a ``"4,2+1"``-style spec into (``"4,2"``, 1).

    The ``+k`` suffix is the reference planner's own notation for shapes
    with ``k`` nodes outside the factorized tree
    (``cost_model/PrintTreeStructure.h``: ``2*3+1``); the reference runtime
    never executed them (its lonely-node code is commented out,
    ``mpi_mod.hpp:983-1086``) — ours does (``LonelyTopology``).
    """
    spec = spec.strip()
    if "+" not in spec:
        return spec, 0
    base, _, tail = spec.rpartition("+")
    try:
        lonely = int(tail.strip())
    except ValueError as e:
        raise TopologyError(f"bad lonely count {tail!r} in spec {spec!r}") from e
    if lonely < 0:
        raise TopologyError(f"lonely count must be >= 0, got {lonely}")
    return base.strip(), lonely


def get_stages(num_nodes: int, spec: str | None = None) -> tuple[int, ...]:
    """Resolve the stage widths for ``num_nodes`` devices.

    ``spec`` defaults to the ``FT_TOPO`` environment variable.  Reference
    semantics (``mpi_mod.hpp:882-929``):

    - empty / unset -> ``(num_nodes,)`` (flat, single stage)
    - any ``1`` in the vector -> ``(1,)``  (ring algorithm sentinel)
    - otherwise the product must equal ``num_nodes`` or we raise.
    """
    if num_nodes < 1:
        raise TopologyError(f"num_nodes must be >= 1, got {num_nodes}")
    if spec is None:
        spec = os.environ.get(FT_TOPO_ENV, "")
    widths = parse_topo(spec) if isinstance(spec, str) else tuple(spec)
    if not widths:
        return (num_nodes,)
    if any(w < 1 for w in widths):
        raise TopologyError(f"widths must be positive, got {widths}")
    if any(w == 1 for w in widths):
        return (1,)
    if math.prod(widths) != num_nodes:
        raise TopologyError(
            f"product of widths {widths} is {math.prod(widths)}, "
            f"but num_nodes is {num_nodes}"
        )
    return widths


@dataclass(frozen=True)
class Topology:
    """A validated hierarchical-allreduce tree shape over ``num_nodes`` devices.

    ``widths[i]`` is the group width at stage ``i``; ``gaps[i]`` is the rank
    stride between members of a stage-``i`` group, i.e. ``prod(widths[:i])``
    (the reference's running ``gap`` in ``Send_Ops::generate_ops``,
    ``mpi_mod.hpp:158-170``).

    ``is_ring`` marks the sentinel shape ``(1,)`` which selects the ring
    algorithm instead of the tree (``mpi_mod.hpp:1194``).
    """

    num_nodes: int
    widths: tuple[int, ...]
    gaps: tuple[int, ...] = field(init=False)

    def __post_init__(self):
        widths = tuple(int(w) for w in self.widths)
        object.__setattr__(self, "widths", widths)
        if self.num_nodes < 1:
            raise TopologyError(f"num_nodes must be >= 1, got {self.num_nodes}")
        if widths == (1,):
            object.__setattr__(self, "gaps", (1,))
            return
        if not widths:
            raise TopologyError("widths must be non-empty")
        if any(w < 2 for w in widths):
            raise TopologyError(
                f"tree widths must all be >= 2 (got {widths}); "
                "use widths=(1,) for the ring algorithm"
            )
        if math.prod(widths) != self.num_nodes:
            raise TopologyError(
                f"product of widths {widths} is {math.prod(widths)}, "
                f"but num_nodes is {self.num_nodes}"
            )
        gaps, g = [], 1
        for w in widths:
            gaps.append(g)
            g *= w
        object.__setattr__(self, "gaps", tuple(gaps))

    # -- constructors ------------------------------------------------------

    @classmethod
    def flat(cls, num_nodes: int) -> "Topology":
        """Single-stage all-to-all-blocks allreduce (reference default)."""
        return cls(num_nodes, (num_nodes,))

    @classmethod
    def ring(cls, num_nodes: int) -> "Topology":
        """Ring-algorithm sentinel, the reference's ``FT_TOPO`` containing 1."""
        return cls(num_nodes, (1,))

    @classmethod
    def halving_doubling(cls, num_nodes: int) -> "Topology":
        """Recursive halving-doubling: widths ``(2, 2, ..., 2)``."""
        widths = []
        n = num_nodes
        while n % 2 == 0 and n > 1:
            widths.append(2)
            n //= 2
        if n != 1:
            raise TopologyError(
                f"halving-doubling needs a power-of-2 device count, got {num_nodes}"
            )
        return cls(num_nodes, tuple(widths))

    @classmethod
    def from_env(cls, num_nodes: int, spec: str | None = None) -> "Topology":
        """Build from an ``FT_TOPO``-style spec (default: the env var)."""
        return cls(num_nodes, get_stages(num_nodes, spec))

    @classmethod
    def resolve(cls, num_nodes: int, topo=None):
        """Coerce ``topo`` (None | Topology | LonelyTopology | width
        sequence | spec string) — specs with a ``+k`` suffix (``"4,2+1"``)
        resolve to a ``LonelyTopology``."""
        if topo is None:
            topo = os.environ.get(FT_TOPO_ENV, "")
        if isinstance(topo, LonelyTopology):
            if topo.num_nodes != num_nodes:
                raise TopologyError(
                    f"topology is for {topo.num_nodes} nodes, mesh has {num_nodes}"
                )
            return topo
        if isinstance(topo, Topology):
            if topo.num_nodes != num_nodes:
                raise TopologyError(
                    f"topology is for {topo.num_nodes} nodes, mesh has {num_nodes}"
                )
            return topo
        if isinstance(topo, str):
            base, lonely = split_lonely_spec(topo)
            if lonely:
                tree = cls(
                    num_nodes - lonely, get_stages(num_nodes - lonely, base)
                )
                return LonelyTopology(num_nodes, tree, lonely)
            return cls(num_nodes, get_stages(num_nodes, base))
        widths = tuple(int(w) for w in topo)
        if any(w == 1 for w in widths):
            return cls.ring(num_nodes)
        return cls(num_nodes, widths)

    # -- properties --------------------------------------------------------

    @property
    def is_ring(self) -> bool:
        return self.widths == (1,)

    @property
    def num_stages(self) -> int:
        return len(self.widths)

    @property
    def message_steps(self) -> int:
        """Point-to-point rounds: ``2*sum(wi-1)`` for the tree, ``2(N-1)`` ring."""
        if self.is_ring:
            return 2 * (self.num_nodes - 1)
        return 2 * sum(w - 1 for w in self.widths)

    def group_members(self, stage: int, rank: int) -> tuple[int, ...]:
        """Ranks in ``rank``'s stage-``stage`` group.

        The group of rank ``r`` at stage ``i`` with width ``w`` and gap ``g``
        is ``{base + j*g : j in [0, w)}`` where
        ``base = (r // (g*w)) * (g*w) + r % g`` (``mpi_mod.hpp:162, 198``).
        """
        g, w = self.gaps[stage], self.widths[stage]
        base = (rank // (g * w)) * (g * w) + rank % g
        return tuple(base + j * g for j in range(w))

    def groups(self, stage: int) -> list[list[int]]:
        """All stage-``stage`` groups, each a sorted list of ranks.

        This is exactly the ``axis_index_groups`` argument that
        ``lax.psum_scatter`` / ``lax.all_gather`` expect for this stage.
        """
        out = []
        for r in range(self.num_nodes):
            m = self.group_members(stage, r)
            if m[0] == r:  # emit once, from the group's minimum member
                out.append(list(m))
        return out

    def __str__(self) -> str:
        return "*".join(str(w) for w in self.widths)


@dataclass(frozen=True)
class LonelyTopology:
    """A tree over ``num_nodes - lonely`` ranks plus ``lonely`` ranks
    outside it — the reference's conceived-but-disabled lonely-node design
    (``mpi_mod.hpp:77``: nodes beyond the factorized tree "sync in parallel
    with the tree"; all its call sites are commented out, SURVEY §2.1)
    made executable, TPU-style:

    - each lonely rank ``m + i`` pairs with buddy rank ``i`` in the tree;
    - pre-phase: one ``ppermute`` moves every lonely payload to its buddy,
      which folds it in (so the tree reduces all ``num_nodes``
      contributions);
    - the tree allreduce runs over the first ``m`` ranks (via the
      ppermute-ring stage machinery — XLA's grouped collectives demand
      equal-size groups, which lonely ranks would break);
    - post-phase: one ``ppermute`` hands each buddy's full result back.

    This is what turns the planner's prime-N "resize to N±1" *advisory*
    (``ChooseWidth.h:16-21``) into a runnable shape: N=7 can execute
    ``"3,2+1"`` instead of being told to use 6 chips.
    """

    num_nodes: int
    tree: Topology
    lonely: int

    def __post_init__(self):
        if self.lonely < 1:
            raise TopologyError(
                f"lonely must be >= 1, got {self.lonely} (use Topology)"
            )
        if self.tree.is_ring:
            raise TopologyError("lonely ranks require a tree, not the ring")
        if self.tree.num_nodes + self.lonely != self.num_nodes:
            raise TopologyError(
                f"tree over {self.tree.num_nodes} + {self.lonely} lonely "
                f"!= {self.num_nodes} nodes"
            )
        if self.lonely > self.tree.num_nodes:
            raise TopologyError(
                f"{self.lonely} lonely ranks need {self.lonely} distinct "
                f"buddies but the tree has only {self.tree.num_nodes}"
            )

    @property
    def is_ring(self) -> bool:
        return False

    @property
    def widths(self) -> tuple[int, ...]:
        return self.tree.widths

    @property
    def num_stages(self) -> int:
        return self.tree.num_stages

    @property
    def message_steps(self) -> int:
        """Tree rounds plus the two buddy exchanges."""
        return self.tree.message_steps + 2

    def __str__(self) -> str:
        return f"{self.tree}+{self.lonely}"
