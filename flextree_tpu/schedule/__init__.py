"""Pure schedule/topology layer — no JAX, no devices.

The TPU-native analog of the reference's L2 layer (``mpi_mod.hpp:45-214,
882-929``), kept transport-free by design.
"""

from .stages import (
    FT_TOPO_ENV,
    LonelyTopology,
    Topology,
    TopologyError,
    get_stages,
    parse_topo,
    split_lonely_spec,
)
from .blocks import BlockLayout
from .ir import (
    IRFamilySpec,
    IRProgram,
    IRStage,
    IRViolationError,
    IRXfer,
    compile_ir,
    emit_ir,
    generalized_ir,
    lonely_ir,
    resolve_collective,
    ring_ir,
    swing_ir,
    tree_ir,
    verify_ir,
)
from .plan import (
    Operation,
    tree_block_set,
    send_plan,
    recv_plan,
    owned_blocks,
    ring_plan,
    format_plan,
)
from .validate import ScheduleError, ValidationStats, validate, validate_ring, validate_topology

__all__ = [
    "ScheduleError",
    "ValidationStats",
    "validate",
    "validate_topology",
    "validate_ring",
    "Topology",
    "LonelyTopology",
    "TopologyError",
    "split_lonely_spec",
    "parse_topo",
    "get_stages",
    "FT_TOPO_ENV",
    "BlockLayout",
    "IRFamilySpec",
    "IRProgram",
    "IRStage",
    "IRViolationError",
    "IRXfer",
    "compile_ir",
    "emit_ir",
    "tree_ir",
    "ring_ir",
    "lonely_ir",
    "swing_ir",
    "generalized_ir",
    "resolve_collective",
    "verify_ir",
    "Operation",
    "tree_block_set",
    "send_plan",
    "recv_plan",
    "owned_blocks",
    "ring_plan",
    "format_plan",
]
