"""Block layout math: how a flat buffer of ``count`` elements is split into
``num_nodes`` equal blocks, including the possibly-empty tail blocks.

Mirrors ``FlexTree_Context`` in the reference
(``allreduce_over_mpi/mpi_mod.hpp:216-243``): ``split_size =
ceil(count / num_nodes)`` and ``data_size_aligned = split_size * num_nodes``,
so with N=10 and count=1 nine of the ten blocks are empty — tail clamping is
therefore a first-class concern (``mpi_mod.hpp:236``, and the clamp sites at
``:679-696``, ``:725-760``, ``:791-800``).

On TPU we instead *pad* the buffer up to ``data_size_aligned`` (XLA
collectives want uniform shards), but the schedule layer still exposes exact
(start, length) spans so the NumPy simulator can reproduce the reference's
clamped semantics bit-for-bit and tests can check the tail handling.
"""

from __future__ import annotations

from dataclasses import dataclass, field

__all__ = ["BlockLayout", "owned_block", "shard_layout"]


@dataclass(frozen=True)
class BlockLayout:
    """Splits ``count`` elements into ``num_nodes`` blocks of ``split_size``.

    Attributes mirror the reference context fields:
      split_size        -> ``mpi_mod.hpp:231``
      count_aligned     -> ``data_size_aligned`` (``mpi_mod.hpp:232``)
    """

    num_nodes: int
    count: int
    split_size: int = field(init=False)
    count_aligned: int = field(init=False)

    def __post_init__(self):
        if self.num_nodes < 1:
            raise ValueError(f"num_nodes must be >= 1, got {self.num_nodes}")
        if self.count < 0:
            raise ValueError(f"count must be >= 0, got {self.count}")
        split = -(-self.count // self.num_nodes) if self.count else 0
        object.__setattr__(self, "split_size", split)
        object.__setattr__(self, "count_aligned", split * self.num_nodes)

    @property
    def pad(self) -> int:
        """Elements of padding needed to reach the aligned size."""
        return self.count_aligned - self.count

    def span(self, block: int) -> tuple[int, int]:
        """(start, length) of ``block`` within the *unpadded* buffer.

        Tail blocks are clamped to the true data size and may be empty —
        the reference's ``start + split_size > data_size`` truncation
        (``mpi_mod.hpp:679-696``).
        """
        if not 0 <= block < self.num_nodes:
            raise IndexError(f"block {block} out of range [0, {self.num_nodes})")
        start = block * self.split_size
        if start >= self.count:
            return (min(start, self.count), 0)
        return (start, min(self.split_size, self.count - start))

    def is_empty(self, block: int) -> bool:
        return self.span(block)[1] == 0

    def slices(self) -> list[slice]:
        """Python slices for every block, clamped to the unpadded buffer."""
        return [slice(s, s + l) for s, l in (self.span(b) for b in range(self.num_nodes))]


# ---------------------------------------------------------------------------
# shard-layout contract (PR 7): which block each rank OWNS after phase 1
# ---------------------------------------------------------------------------
#
# The standalone ``reduce_scatter`` leaves every rank holding exactly one
# fully-reduced 1/N block of the (padded) buffer; ``all_gather`` reassembles
# the blocks in BLOCK order.  Which block a rank owns is a pure function of
# the width vector — the residue-chain ownership of SURVEY §3.2:
#
# - **tree** (widths ``(w0, .., wk)``): stage ``i`` splits the current slice
#   into ``wi`` tiles and the rank at group position ``p_i = (r // gap_i) %
#   wi`` keeps tile ``p_i`` (``lax.psum_scatter(tiled=True)`` ownership), so
#   the final block index is the mixed-radix composition
#   ``sum_i p_i * prod(widths[i+1:])``.  Flat ``(N,)`` degenerates to
#   ``owned_block(r) == r``.
# - **ring** (sentinel ``(1,)``): after ``N-1`` fold steps of the reference
#   block walk (send ``(r - s) % N``, fold ``(r - s - 1) % N``), rank ``r``
#   holds the fully-reduced block ``(r + 1) % N`` (``mpi_mod.hpp:1149``:
#   the gather phase starts by forwarding exactly that block).
# - **lonely** (``m`` tree ranks + ``l`` lonely): only tree ranks own
#   blocks; lonely rank ``m + i`` MIRRORS its buddy ``i``'s block (the
#   reduce-scatter ships the buddy's reduced tile over, so both hold
#   identical bits).  The ``l`` mirrored blocks are duplicates, not a
#   partition — ``all_gather`` ignores the lonely ranks' copies.
#
# This module is imported by the JAX-less static verifier, so everything
# here must stay pure Python.


def owned_block(topo, rank: int) -> int:
    """Block index rank ``rank`` owns after a standalone reduce-scatter
    with ``topo`` (a resolved ``Topology`` or ``LonelyTopology``)."""
    n = topo.num_nodes
    if not 0 <= rank < n:
        raise IndexError(f"rank {rank} out of range [0, {n})")
    if hasattr(topo, "tree"):  # LonelyTopology: buddies mirror
        m = topo.tree.num_nodes
        return owned_block(topo.tree, rank if rank < m else rank - m)
    if topo.is_ring:
        return (rank + 1) % n
    block = 0
    for i, w in enumerate(topo.widths):
        tiles_below = 1
        for wj in topo.widths[i + 1:]:
            tiles_below *= wj
        p = (rank // topo.gaps[i]) % w
        block += p * tiles_below
    return block


def shard_layout(topo) -> tuple[int, ...]:
    """Owned block per rank: ``shard_layout(topo)[r] == owned_block(topo,
    r)``.  For tree/ring shapes this is a permutation of ``range(N)``; for
    lonely shapes the last ``l`` entries duplicate their buddies' blocks
    and the first ``m`` entries form the true partition."""
    return tuple(owned_block(topo, r) for r in range(topo.num_nodes))
