"""Per-rank, per-stage send/recv schedule generation for the k-ary tree.

This is the pure-logic heart of the framework — the reference keeps this layer
deliberately transport-free ("topology generation must depend only on
(total_peers, node_label, stages), not on MPI", ``mpi_mod.hpp:78``) and so do
we: nothing here imports JAX.  The JAX backend lowers these plans to
``axis_index_groups`` collectives; the NumPy simulator executes them directly.

Semantics reimplemented from ``allreduce_over_mpi/mpi_mod.hpp``:

- ``Operation`` (``:45-75``): one peer plus the block indices to exchange.
  Tree constructor: the strided set ``{p % gap, p%gap + gap, ...} < total``.
- ``Send_Ops::generate_ops`` (``:152-179``): at stage ``i`` with width ``w``
  and accumulated gap ``g``, rank ``r``'s group is ``{base + j*g}`` with
  ``base = (r // (g*w)) * (g*w) + r % g``; ``r`` sends to each group peer
  ``p`` the block set ``{b : b ≡ p (mod g*w)}``.
- ``Recv_Ops::generate_ops`` (``:187-213``): same peers, but every op carries
  ``r``'s own block set ``{b : b ≡ r (mod g*w)}``.

Invariants (property-tested in ``tests/test_schedule.py``):
- at stage ``i`` the send sets of a group partition ``{b : b ≡ r (mod g)}``;
- after all stages rank ``r`` exclusively owns ``{b : b ≡ r (mod N)}``,
  i.e. exactly one block per rank when widths multiply to N;
- phase 2 (reversed stages, send/recv roles swapped) restores full ownership.

Since ISSUE 8 the residue-chain math itself lives in ``schedule/ir.py``
(``stage_send_blocks`` / ``stage_keep_blocks``) — the IR emitter is the
single source of truth, and ``send_plan``/``recv_plan`` are thin views
over it, so the NumPy simulator, the plan validator and the IR-driven
model checker can never disagree about which blocks move where.
"""

from __future__ import annotations

from dataclasses import dataclass

from .ir import stage_keep_blocks, stage_send_blocks
from .stages import Topology

__all__ = [
    "Operation",
    "tree_block_set",
    "send_plan",
    "recv_plan",
    "owned_blocks",
    "ring_plan",
    "format_plan",
]


@dataclass(frozen=True)
class Operation:
    """One point-to-point exchange: a peer and the block indices involved."""

    peer: int
    blocks: tuple[int, ...]

    @classmethod
    def strided(cls, peer: int, total: int, gap: int) -> "Operation":
        """Tree-stage op: blocks ``{peer % gap, peer%gap+gap, ...} < total``
        (the reference's first ``Operation`` ctor, ``mpi_mod.hpp:56-64``) —
        a view over ``ir.stage_send_blocks`` with the stride pre-folded."""
        return cls(peer, stage_send_blocks(total, gap, 1, peer))

    @classmethod
    def single(cls, peer: int, block: int) -> "Operation":
        """Ring-step op carrying one block (``mpi_mod.hpp:70-74``)."""
        return cls(peer, (block,))


def tree_block_set(rank: int, total: int, stride: int) -> tuple[int, ...]:
    """``{b : b ≡ rank (mod stride), b < total}`` — the residue chain
    (view over ``ir.stage_keep_blocks``)."""
    return stage_keep_blocks(total, stride, 1, rank)


def send_plan(topo: Topology, rank: int) -> list[list[Operation]]:
    """Phase-1 send ops per stage for ``rank``: ``plan[stage][j]`` sends
    ``plan[stage][j].blocks`` to ``plan[stage][j].peer`` — a per-rank view
    over the IR emitter's block math (``ir.stage_send_blocks``).

    Self-ops (peer == rank) are *included*, as in the reference (the transport
    skips them at ``mpi_mod.hpp:676``); the simulator/backends decide.
    """
    n = topo.num_nodes
    plan: list[list[Operation]] = []
    for i, w in enumerate(topo.widths):
        g = topo.gaps[i]
        stage_ops = [
            Operation(peer, stage_send_blocks(n, g, w, peer))
            for peer in topo.group_members(i, rank)
        ]
        plan.append(stage_ops)
    return plan


def recv_plan(topo: Topology, rank: int) -> list[list[Operation]]:
    """Phase-1 recv ops per stage: same peers as ``send_plan`` but every op
    carries ``rank``'s own residue chain ``{b : b ≡ rank (mod g*w)}``
    (``Recv_Ops::generate_ops``, ``mpi_mod.hpp:192-209``; the chain is
    ``ir.stage_keep_blocks`` — the same function the IR emitter uses)."""
    n = topo.num_nodes
    plan: list[list[Operation]] = []
    for i, w in enumerate(topo.widths):
        g = topo.gaps[i]
        mine = stage_keep_blocks(n, g, w, rank)
        stage_ops = [Operation(peer, mine) for peer in topo.group_members(i, rank)]
        plan.append(stage_ops)
    return plan


def owned_blocks(topo: Topology, rank: int, upto_stage: int | None = None) -> tuple[int, ...]:
    """Blocks whose partial sum ``rank`` holds after stages ``[0, upto_stage)``.

    After all stages this is ``{b : b ≡ rank (mod N)}`` — exactly one block
    when the widths multiply to N (SURVEY §3.2 invariant).
    """
    k = len(topo.widths) if upto_stage is None else upto_stage
    stride = 1
    for w in topo.widths[:k]:
        stride *= w
    return tree_block_set(rank, topo.num_nodes, stride)


def ring_plan(num_nodes: int, rank: int) -> list[tuple[Operation, Operation]]:
    """The 2(N-1)-step ring schedule for ``rank``.

    Returns ``[(send_op, recv_op), ...]`` — first N-1 entries are the
    reduce-scatter steps, last N-1 the allgather steps.  Neighbors and the
    decrementing block indices mirror ``ring_allreduce``
    (``mpi_mod.hpp:1119-1159``): send right, receive from left; the block sent
    starts at ``rank`` (reduce phase) and walks backwards mod N.
    """
    n = num_nodes
    left, right = (rank - 1) % n, (rank + 1) % n
    steps: list[tuple[Operation, Operation]] = []
    block_send, block_recv = rank, left
    for _ in range(n - 1):  # reduce-scatter
        steps.append((Operation.single(right, block_send), Operation.single(left, block_recv)))
        block_send = (block_send - 1) % n
        block_recv = (block_recv - 1) % n
    block_send, block_recv = (rank + 1) % n, rank
    for _ in range(n - 1):  # allgather
        steps.append((Operation.single(right, block_send), Operation.single(left, block_recv)))
        block_send = (block_send - 1) % n
        block_recv = (block_recv - 1) % n
    return steps


def format_plan(topo: Topology, rank: int) -> str:
    """ASCII dump of a rank's schedule, in the spirit of
    ``Operations::print_ops`` (``mpi_mod.hpp:105-131``)."""
    lines = [f"send/recv plan of node {rank} in total {topo.num_nodes} peers (topo {topo}):"]
    sp, rp = send_plan(topo, rank), recv_plan(topo, rank)
    for i in range(topo.num_stages):
        tag = "┕" if i == topo.num_stages - 1 else "┝"
        send_part = " ".join(
            f"| ->{op.peer}: {','.join(map(str, op.blocks))}" for op in sp[i]
        )
        recv_part = " ".join(
            f"| <-{op.peer}: {','.join(map(str, op.blocks))}" for op in rp[i]
        )
        lines.append(f"{tag} stage{i} {send_part}")
        lines.append(f"          {recv_part}")
    return "\n".join(lines)
