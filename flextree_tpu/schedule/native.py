"""ctypes bindings for the native schedule core (``native/flextree_schedule.cpp``).

The reference's L2 schedule engine is native C++ (``mpi_mod.hpp:45-214``);
ours keeps a native core too, sharing the library with the planner
(``native/libflextree_planner.so``) and falling back to the pure-Python
implementation (:mod:`flextree_tpu.schedule.plan`) when it isn't built.
The Python side is the specification — ``tests/test_native_schedule.py``
cross-validates every exported function against it.
"""

from __future__ import annotations

import ctypes

from .plan import Operation

__all__ = [
    "native_available",
    "native_send_plan",
    "native_recv_plan",
    "native_ring_plan",
    "native_validate",
]

_VALIDATE_ERRORS = {
    -1: "invalid topology",
    -2: "double-counted send block",
    -3: "send set != owned set",
    -4: "recv claims un-owned blocks",
    -5: "final ownership not a tiling",
    -6: "phase-2 restoration incomplete",
}


def _lib():
    # the schedule core lives in the same shared object as the planner
    from ..planner.native import load_native

    lib = load_native()
    if lib is None or not hasattr(lib, "ft_plan"):
        return None
    if not getattr(lib, "_ft_schedule_bound", False):
        lib.ft_plan.restype = ctypes.c_int32
        lib.ft_plan.argtypes = [
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32,
            ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.ft_ring_plan.restype = ctypes.c_int32
        lib.ft_ring_plan.argtypes = [
            ctypes.c_uint64,
            ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
        ]
        lib.ft_validate.restype = ctypes.c_int32
        lib.ft_validate.argtypes = [
            ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint32,
        ]
        lib._ft_schedule_bound = True
    return lib


def native_available() -> bool:
    return _lib() is not None


def _plan(topo, rank: int, send: bool) -> list[list[Operation]] | None:
    lib = _lib()
    if lib is None:
        return None
    widths = (ctypes.c_uint32 * len(topo.widths))(*topo.widths)
    needed = ctypes.c_uint64(0)
    k = lib.ft_plan(
        topo.num_nodes, rank, widths, len(topo.widths), int(send), None, 0,
        ctypes.byref(needed),
    )
    if k < 0:
        return None
    buf = (ctypes.c_uint32 * max(1, needed.value))()
    k = lib.ft_plan(
        topo.num_nodes, rank, widths, len(topo.widths), int(send), buf,
        needed.value, ctypes.byref(needed),
    )
    if k < 0:
        return None
    plan: list[list[Operation]] = []
    off = 0
    for _ in range(k):
        num_ops = buf[off]
        off += 1
        ops = []
        for _ in range(num_ops):
            peer, nblocks = buf[off], buf[off + 1]
            off += 2
            ops.append(Operation(int(peer), tuple(int(b) for b in buf[off : off + nblocks])))
            off += nblocks
        plan.append(ops)
    return plan


def native_send_plan(topo, rank: int) -> list[list[Operation]] | None:
    """Native ``send_plan``; None when the library isn't available."""
    return _plan(topo, rank, send=True)


def native_recv_plan(topo, rank: int) -> list[list[Operation]] | None:
    """Native ``recv_plan``; None when the library isn't available."""
    return _plan(topo, rank, send=False)


def native_ring_plan(n: int, rank: int) -> list[tuple[Operation, Operation]] | None:
    """Native ``ring_plan``; None when the library isn't available."""
    lib = _lib()
    if lib is None:
        return None
    steps = 2 * (n - 1)
    buf = (ctypes.c_uint32 * max(1, steps * 4))()
    got = lib.ft_ring_plan(n, rank, buf, steps * 4)
    if got < 0:
        return None
    out = []
    for s in range(got):
        o = s * 4
        out.append(
            (
                Operation.single(int(buf[o]), int(buf[o + 1])),
                Operation.single(int(buf[o + 2]), int(buf[o + 3])),
            )
        )
    return out


def native_validate(topo) -> str | None:
    """Run the native validator: '' on success, an error description on
    violation, or None when the library isn't available.  The tree-only
    native path is used; ring sentinels validate in Python."""
    if topo.is_ring:
        return None
    lib = _lib()
    if lib is None:
        return None
    widths = (ctypes.c_uint32 * len(topo.widths))(*topo.widths)
    code = lib.ft_validate(topo.num_nodes, widths, len(topo.widths))
    if code == 0:
        return ""
    return _VALIDATE_ERRORS.get(code, f"unknown error {code}")
