"""Schedule validator: static race/consistency checking for tree and ring
schedules.

The reference has no sanitizer — correctness rests on ``MPI_Waitall`` before
each reduce and per-stage barriers (``mpi_mod.hpp:1003, 1028``; SURVEY §5
"Race detection" row).  In a pure-functional XLA program the *execution* can't
race, but a malformed schedule can still silently mis-reduce (a block counted
twice, a block never delivered, send/recv plans that disagree).  This module
proves the invariants that make a schedule an allreduce, before it ever
touches a device:

1. **Partition** — at stage ``i``, the blocks rank ``r`` sends to its group
   peers partition ``r``'s currently-owned residue set ``{b : b ≡ r mod g}``:
   every owned block goes to exactly one peer (no duplicate contribution, no
   dropped block).
2. **Send/recv agreement** — for every (sender, receiver, stage), the blocks
   the sender plans to send equal the blocks the receiver expects — the
   static analog of matching ``MPI_Isend``/``MPI_Irecv`` pairs.
3. **Convergence** — after all stages each rank exclusively owns
   ``{b : b ≡ r mod N}`` and the per-rank owned sets tile ``[0, N)``; phase 2
   (stages reversed, roles swapped) restores full ownership everywhere.
4. **Ring walk** — the 2(N−1)-step ring schedule's send/recv block indices
   chain correctly (block received at step s is the block sent at step s+1)
   and every rank ends owning all N blocks.

``validate_topology`` runs 1-3 for a tree shape; ``validate_ring`` runs 4;
``validate`` dispatches on the topology.  All raise :class:`ScheduleError`
with a precise description on the first violation, and return a small stats
summary otherwise (used by tests and the planner's sanity mode).
"""

from __future__ import annotations

from dataclasses import dataclass

from .plan import recv_plan, ring_plan, send_plan
from .stages import Topology

__all__ = [
    "ScheduleError",
    "ValidationStats",
    "validate",
    "validate_topology",
    "validate_ring",
    "stage_matches",
]


class ScheduleError(AssertionError):
    """A schedule violates an allreduce invariant."""


@dataclass(frozen=True)
class ValidationStats:
    num_nodes: int
    widths: tuple[int, ...]
    stages: int
    p2p_messages: int  # total cross-rank (peer != self) ops, both phases


def validate(topo) -> ValidationStats:
    """Validate any topology (ring sentinel, k-ary tree, or tree+lonely)."""
    from .stages import LonelyTopology

    if isinstance(topo, LonelyTopology):
        # the tree part carries all schedule structure; the lonely protocol
        # adds one fold ppermute and one restore ppermute per lonely rank,
        # each a distinct (buddy, lonely) pair — structurally race-free by
        # construction (validated here as message accounting)
        tree_stats = validate_topology(topo.tree)
        return ValidationStats(
            num_nodes=topo.num_nodes,
            widths=tree_stats.widths,
            stages=tree_stats.stages,
            p2p_messages=tree_stats.p2p_messages + 2 * topo.lonely,
        )
    if topo.is_ring:
        return validate_ring(topo.num_nodes)
    return validate_topology(topo)


def validate_topology(topo: Topology) -> ValidationStats:
    n = topo.num_nodes
    messages = 0
    # generate every rank's plans once (the agreement loop below indexes
    # into peers' plans, so recomputing per-op would be O(n^2) plan builds)
    sends = [send_plan(topo, r) for r in range(n)]
    recvs = [recv_plan(topo, r) for r in range(n)]

    # ownership derived from the PLANS (not from widths arithmetic, which
    # Topology already enforces): after stage i, rank r holds the partial
    # sums of exactly the blocks its stage-i recv ops name.
    owned = [set(range(n)) for _ in range(n)]  # before stage 0: all blocks

    for r in range(n):
        sp, rp = sends[r], recvs[r]
        if len(sp) != topo.num_stages or len(rp) != topo.num_stages:
            raise ScheduleError(f"rank {r}: plan has wrong stage count")
        for i in range(topo.num_stages):
            sent: dict[int, int] = {}
            for op in sp[i]:
                for b in op.blocks:
                    if b in sent:
                        raise ScheduleError(
                            f"rank {r} stage {i}: block {b} sent to both "
                            f"peer {sent[b]} and peer {op.peer} (double count)"
                        )
                    sent[b] = op.peer
            if set(sent) != owned[r]:
                missing = owned[r] - set(sent)
                extra = set(sent) - owned[r]
                raise ScheduleError(
                    f"rank {r} stage {i}: send set != owned block set "
                    f"(missing {sorted(missing)}, extra {sorted(extra)})"
                )
            kept: set[int] = set()
            for op in rp[i]:
                kept |= set(op.blocks)
            if not kept <= owned[r]:
                raise ScheduleError(
                    f"rank {r} stage {i}: recv plan claims blocks "
                    f"{sorted(kept - owned[r])} the rank does not hold"
                )
            owned[r] = kept

    for i in range(topo.num_stages):
        # each stage's message count: cross-rank sends, both phases
        for r in range(n):
            messages += sum(1 for op in sends[r][i] if op.peer != r) * 2

    # send/recv agreement: sender's blocks for peer p == p's expected set
    # (stage_matches raises on the first asymmetry; the walk itself is the
    # check, and its match table is what analysis.schedule_check builds its
    # per-rank message program from)
    for _ in stage_matches(topo, sends=sends, recvs=recvs):
        pass

    # convergence: the plan-derived final ownership tiles [0, N) exclusively
    seen: set[int] = set()
    for r, s in enumerate(owned):
        if seen & s:
            raise ScheduleError(f"rank {r}: final owned blocks {sorted(s)} overlap")
        seen |= s
    if seen != set(range(n)):
        raise ScheduleError(f"final ownership covers {sorted(seen)}, not [0, {n})")

    # phase 2 (stages reversed, roles swapped): replay forwarding and prove
    # every rank ends holding all N blocks, never forwarding a block the
    # sender doesn't hold at that point — the docstring's invariant 3.
    holdings = [set(s) for s in owned]
    for i in reversed(range(topo.num_stages)):
        new_holdings = [set(h) for h in holdings]
        for r in range(n):
            # phase-2: rank r receives, via its *send*-plan ops, each peer's
            # currently-held slice of those blocks (roles swap; the blocks
            # land at final offsets — mpi_mod.hpp:1056-1057 with
            # accordingly=true)
            for op in sends[r][i]:
                if op.peer == r:
                    continue
                inbound = set(recvs[op.peer][i][0].blocks)
                if not inbound <= holdings[op.peer]:
                    raise ScheduleError(
                        f"phase2 stage {i}: rank {op.peer} forwards blocks "
                        f"{sorted(inbound - holdings[op.peer])} it does not hold"
                    )
                new_holdings[r] |= inbound
        holdings = new_holdings
    for r in range(n):
        if holdings[r] != set(range(n)):
            raise ScheduleError(
                f"rank {r}: phase 2 restored only {len(holdings[r])}/{n} blocks"
            )

    return ValidationStats(n, topo.widths, topo.num_stages, messages)


def stage_matches(topo: Topology, sends=None, recvs=None):
    """Yield every matched (stage, src, dst, blocks) phase-1 exchange.

    The static analog of pairing each ``MPI_Isend`` with its ``MPI_Irecv``:
    for every cross-rank send op, the receiver must hold *exactly one*
    recv op naming the sender, with the identical block set — the
    agreement invariant (docstring item 2) exposed as an iterable so
    downstream analyses (``flextree_tpu.analysis.schedule_check``'s match
    graph, traffic accounting) can walk the matched pairs instead of
    re-deriving them.  Raises :class:`ScheduleError` on the first
    unmatched or disagreeing pair.  ``sends``/``recvs`` accept
    precomputed plan lists (the validator passes its own to avoid
    rebuilding O(n) plans).
    """
    n = topo.num_nodes
    if sends is None:
        sends = [send_plan(topo, r) for r in range(n)]
    if recvs is None:
        recvs = [recv_plan(topo, r) for r in range(n)]
    for r in range(n):
        for i in range(topo.num_stages):
            for op in sends[r][i]:
                match = [o for o in recvs[op.peer][i] if o.peer == r]
                if len(match) != 1 or set(match[0].blocks) != set(op.blocks):
                    raise ScheduleError(
                        f"stage {i}: rank {r} sends {sorted(op.blocks)} to "
                        f"{op.peer}, but {op.peer} expects "
                        f"{sorted(match[0].blocks) if match else None} from {r}"
                    )
                if op.peer != r:
                    yield i, r, op.peer, tuple(op.blocks)


def validate_ring(n: int) -> ValidationStats:
    if n < 1:
        raise ScheduleError(f"ring needs n >= 1, got {n}")
    plans = [ring_plan(n, r) for r in range(n)]  # build once: O(n^2) total
    for r in range(n):
        steps = plans[r]
        left_steps = plans[(r - 1) % n]
        if len(steps) != 2 * (n - 1):
            raise ScheduleError(f"rank {r}: ring has {len(steps)} steps, want {2*(n-1)}")
        owned = {r}  # blocks whose partial this rank has folded (reduce phase)
        for s, (snd, rcv) in enumerate(steps[: n - 1]):
            if snd.peer != (r + 1) % n or rcv.peer != (r - 1) % n:
                raise ScheduleError(f"rank {r} step {s}: wrong ring neighbors")
            # what the left neighbor sends at step s must be what we receive
            if left_steps[s][0].blocks != rcv.blocks:
                raise ScheduleError(
                    f"rank {r} step {s}: expects block {rcv.blocks} from left, "
                    f"left sends {left_steps[s][0].blocks}"
                )
            owned.add(rcv.blocks[0])
        if owned != set(range(n)):
            raise ScheduleError(
                f"rank {r}: reduce phase touched blocks {sorted(owned)}, "
                f"expected all of [0, {n})"
            )
        # allgather phase: after n-1 forwarding steps every block arrives
        have = {(r + 1) % n}  # the block fully reduced here after phase 1...
        for s, (snd, rcv) in enumerate(steps[n - 1 :]):
            if left_steps[n - 1 + s][0].blocks != rcv.blocks:
                raise ScheduleError(f"rank {r} gather step {s}: send/recv mismatch")
            have.add(rcv.blocks[0])
        if len(have) != n:
            raise ScheduleError(
                f"rank {r}: allgather delivered {len(have)} distinct blocks, want {n}"
            )
    return ValidationStats(n, (1,), 1, 2 * (n - 1) * n)
