"""Declarative schedule IR: one program representation for every collective.

ROADMAP names the problem this module kills: tree / ring / lonely were
three hand-written JAX schedules, and the static verifier reconstructed
each one in a SECOND hand-written expansion (``analysis/schedule_check``),
so schedule and checker could silently drift.  Here an allreduce is a
*program* — a sequence of :class:`IRStage` rows, each a declarative
(peer-group, block-map, combine-op) record — and everything downstream
derives from that one object:

- the **model checker** (``analysis.schedule_check.program_from_ir``)
  expands the IR into the per-rank message program it proves deadlock-free
  and conservation-correct;
- the **compiler** (:func:`compile_ir`, lowering in
  ``parallel/ir_lower.py``) turns the IR into the jitted collective — the
  same grouped ``psum_scatter`` / ``all_gather`` / ``ppermute`` calls
  ``parallel/allreduce.py`` makes today, bitwise-identical to the legacy
  paths (golden-tested in ``tests/test_schedule_ir.py``);
- the **ir_equivalence pass** (``analysis.ir_equivalence``) certifies the
  lowered StableHLO's collective sequence matches the IR stage list.

``compile_ir`` REFUSES a program that fails the model checks — "verified
before compiled" is the module invariant, not a convention (seeded
violations are asserted refused in the mutation self-test).

Two new topology families ride in as pure emitters, proving the point of
the refactor (a new topology = a new emitter; the proofs are free):

- :func:`swing_ir` — Swing short-cut rings (arXiv:2401.09356): pairwise
  distance-swinging exchanges ``peer(r, s) = r ± rho_s`` with
  ``rho_s = (1 - (-2)^(s+1)) / 3`` (1, 1, 3, 5, 11, ...), halving the
  live block set each step.  Non-power-of-two N runs the largest
  power-of-two core plus lonely-style buddy fold/restore hops.
- :func:`generalized_ir` — the generalized allreduce construction
  (arXiv:2004.09362): mixed-radix stage widths × a per-round port count.
  ``widths=(N,), ports=N-1`` is the flat tree's message pattern;
  ``widths=(2,...,2), ports=1`` is recursive halving-doubling;
  ``ports`` between the corners trades rounds against in-flight messages.

Like the rest of ``flextree_tpu.schedule`` this module is pure Python —
no JAX at import time (``compile_ir`` imports the lowering lazily), so
the verifier can run on a JAX-less host.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass

from .stages import FT_TOPO_ENV, LonelyTopology, Topology, TopologyError

__all__ = [
    "IRXfer",
    "IRStage",
    "IRProgram",
    "IRFamilySpec",
    "IRViolationError",
    "stage_send_blocks",
    "stage_keep_blocks",
    "tree_ir",
    "tree_phase_stages",
    "ring_ir",
    "lonely_ir",
    "swing_ir",
    "swing_rho",
    "swing_peer",
    "generalized_ir",
    "emit_ir",
    "parse_ir_family_spec",
    "is_ir_family_spec",
    "resolve_collective",
    "compile_ir",
    "verify_ir",
    "IR_FAMILIES",
]

#: every family an IR program can declare; tree/ring/lonely lower through
#: the proven grouped-collective programs of ``parallel/allreduce.py``,
#: swing/generalized through the generic pair-exchange executor
IR_FAMILIES = ("tree", "ring", "lonely", "swing", "generalized")

SUM, COPY = "sum", "copy"


class IRViolationError(ValueError):
    """``compile_ir`` refused a program: model checks failed or the stage
    list diverged from the family's canonical emission.  ``violations``
    carries the checker's findings (empty for structural divergence)."""

    def __init__(self, msg: str, violations=()):
        super().__init__(msg)
        self.violations = tuple(violations)


# ------------------------------------------------------------ block math
#
# The one residue-chain definition every consumer shares.  ``plan.py``'s
# ``send_plan``/``recv_plan`` are thin views over these two functions, the
# tree emitter builds its block-maps from them, and the verifier expands
# whatever the emitter produced — one source of truth (ISSUE 8 satellite:
# the old duplicated expansion in ``schedule_check`` is gone).


def stage_send_blocks(total: int, gap: int, width: int, dst: int) -> tuple[int, ...]:
    """Blocks a group member sends ``dst`` at a (gap, width) tree stage:
    ``{b : b = dst (mod gap*width), b < total}`` — the reference's
    ``Operation.strided`` chain (``mpi_mod.hpp:56-64``)."""
    stride = gap * width
    return tuple(range(dst % stride, total, stride))


def stage_keep_blocks(total: int, gap: int, width: int, rank: int) -> tuple[int, ...]:
    """Blocks ``rank`` keeps (receives partials for) at a (gap, width)
    stage: its own residue chain ``{b : b = rank (mod gap*width)}``."""
    return stage_send_blocks(total, gap, width, rank)


# ------------------------------------------------------------- data model


@dataclass(frozen=True)
class IRXfer:
    """One cross-rank transfer inside a stage: ``src`` sends the listed
    block indices to ``dst``.  ``blocks=()`` marks a whole-buffer hop
    (fold / restore), whose payload is the full current slice."""

    src: int
    dst: int
    blocks: tuple[int, ...]


@dataclass(frozen=True)
class IRStage:
    """One declarative stage: a peer-group partition, the block-map (every
    cross-rank transfer with its block set), and the combine op.

    ``index`` is the LOGICAL stage id — multiple IRStage rows may share it
    (a generalized stage's rounds; the checker aggregates conservation per
    logical stage while the deadlock machine sees each row as its own
    rendezvous).  ``lowering`` is the compile strategy:

    - ``"grouped"``: one XLA grouped collective over ``groups``
      (``psum_scatter`` for a sum reduce-scatter, ``all_gather`` for the
      gather; the ppermute-ring helpers for non-sum ops or prefix trees);
    - ``"pair"``: one ``ppermute`` exchange of per-rank block sets (swing
      steps, generalized rounds, fold/restore hops).
    - ``"ring-step"``: one step of the rolled ring walk — the compiled
      form is a ``fori_loop`` covering all same-phase ring steps.
    """

    index: int
    phase: str  # "rs" | "ag" | "fold" | "restore"
    combine: str  # "sum" | "copy"
    lowering: str  # "grouped" | "pair" | "ring-step"
    groups: tuple[tuple[int, ...], ...]
    xfers: tuple[IRXfer, ...]
    chunk: int = 0


@dataclass(frozen=True)
class IRProgram:
    """A full collective as data.  ``scheduled`` is the number of ranks
    that own blocks (< ``num_nodes`` for lonely shapes and non-power-of-
    two swing, whose extras fold through buddies); ``num_blocks ==
    scheduled``.  ``topo`` carries the resolved legacy topology for
    tree/ring/lonely lowering; swing/generalized set it ``None``."""

    family: str
    num_nodes: int
    scheduled: int
    num_stages: int
    stages: tuple[IRStage, ...]
    count: int
    head_elems: int
    chunk_spans: tuple[tuple[int, int], ...]
    chunks: int = 1
    widths: tuple[int, ...] = ()
    ports: int = 0
    topo: object = None

    @property
    def num_blocks(self) -> int:
        return self.scheduled

    def spec(self) -> str:
        """The ``FT_TOPO``-style spec string selecting this family."""
        if self.family == "swing":
            return "swing"
        if self.family == "generalized":
            return f"gen:{','.join(map(str, self.widths))}@{self.ports}"
        if self.family == "ring":
            return "1"
        spec = ",".join(map(str, self.widths))
        if self.family == "lonely":
            spec += f"+{self.num_nodes - self.scheduled}"
        return spec

    def __str__(self) -> str:
        return f"{self.family}[{self.spec()}]@{self.num_nodes}"


@dataclass(frozen=True)
class IRFamilySpec:
    """A planner-facing handle for an IR family shape (the analog of
    ``Topology`` for swing/generalized candidates): enough to name, cost
    and cache a plan without emitting the full program.  ``allreduce``
    resolves it (or its ``spec`` string) through :func:`emit_ir`."""

    family: str  # "swing" | "generalized"
    num_nodes: int
    widths: tuple[int, ...] = ()
    ports: int = 0

    def __post_init__(self):
        if self.family not in ("swing", "generalized"):
            raise TopologyError(
                f"IRFamilySpec is for swing/generalized, got {self.family!r}"
            )
        if self.family == "generalized":
            if math.prod(self.widths) != self.num_nodes:
                raise TopologyError(
                    f"generalized widths {self.widths} do not multiply to "
                    f"{self.num_nodes}"
                )
            if not 1 <= self.ports <= max(w - 1 for w in self.widths):
                raise TopologyError(
                    f"ports must be in [1, max_width-1], got {self.ports}"
                )

    @property
    def is_ring(self) -> bool:
        return False

    @property
    def num_stages(self) -> int:
        if self.family == "swing":
            core = 1 << (self.num_nodes.bit_length() - 1)
            return core.bit_length() - 1
        return len(self.widths)

    @property
    def spec(self) -> str:
        if self.family == "swing":
            return "swing"
        return f"gen:{','.join(map(str, self.widths))}@{self.ports}"

    def __str__(self) -> str:
        return self.spec


# ------------------------------------------------------------- emitters


def _head(count: int, owners: int) -> int:
    return (count // owners) * owners


def _pair_groups(pairs) -> tuple[tuple[int, ...], ...]:
    return tuple(tuple(sorted(p)) for p in pairs)


def tree_phase_stages(
    topo: Topology, phase: str, chunk: int = 0
) -> list[IRStage]:
    """The grouped stages of ONE tree phase, in trace order (``rs``
    ascending, ``ag`` descending) — the single expansion the full-program
    emitter, the phase-program builder and the plan views all share."""
    n = topo.num_nodes
    order = (
        range(topo.num_stages) if phase == "rs" else reversed(range(topo.num_stages))
    )
    out = []
    for i in order:
        g, w = topo.gaps[i], topo.widths[i]
        xfers = []
        for r in range(n):
            for peer in topo.group_members(i, r):
                if peer == r:
                    continue
                if phase == "rs":
                    blocks = stage_send_blocks(n, g, w, peer)
                else:  # roles swap: r returns the chain it collected
                    blocks = stage_keep_blocks(n, g, w, r)
                xfers.append(IRXfer(r, peer, blocks))
        out.append(
            IRStage(
                index=i,
                phase=phase,
                combine=SUM if phase == "rs" else COPY,
                lowering="grouped",
                groups=tuple(tuple(grp) for grp in topo.groups(i)),
                xfers=tuple(xfers),
                chunk=chunk,
            )
        )
    return out


def _chunk_sizes(total: int, n: int, chunks: int) -> list[int]:
    """Mirror of ``parallel.allreduce._chunk_sizes`` (balanced contiguous
    pieces, each a multiple of ``n``)."""
    blocks = total // n
    c = max(1, min(chunks, blocks))
    base, rem = divmod(blocks, c)
    return [(base + (1 if i < rem else 0)) * n for i in range(c)]


def tree_ir(topo: Topology, count: int | None = None, chunks: int = 1) -> IRProgram:
    """The k-ary tree program: per-stage grouped reduce-scatter down,
    grouped all-gather back up; ``chunks > 1`` interleaves chunk ``c``'s
    allgather between chunk ``c+1``'s reduce-scatter and its own — the
    exact trace order of ``parallel.allreduce.tree_allreduce``."""
    if isinstance(topo, LonelyTopology):
        return lonely_ir(topo, count=count)
    if topo.is_ring:
        return ring_ir(topo.num_nodes, count=count)
    n = topo.num_nodes
    count = n * n if count is None else count
    head = _head(count, n)
    sizes = _chunk_sizes(head, n, chunks) if head else []
    n_chunks = max(1, len(sizes))
    spans, off = [], 0
    for s in sizes:
        spans.append((off, s))
        off += s
    stages: list[IRStage] = []
    stages += tree_phase_stages(topo, "rs", chunk=0)
    for c in range(1, n_chunks):
        stages += tree_phase_stages(topo, "rs", chunk=c)
        stages += tree_phase_stages(topo, "ag", chunk=c - 1)
    stages += tree_phase_stages(topo, "ag", chunk=n_chunks - 1)
    return IRProgram(
        family="tree",
        num_nodes=n,
        scheduled=n,
        num_stages=topo.num_stages,
        stages=tuple(stages),
        count=count,
        head_elems=head,
        chunk_spans=tuple(spans),
        chunks=n_chunks,
        widths=topo.widths,
        topo=topo,
    )


def ring_ir(n: int, count: int | None = None) -> IRProgram:
    """The 2(N-1)-step ring walk as 2(N-1) pair stages (send right, recv
    left, decrementing block indices) — compiled rolled, as two
    ``fori_loop`` s of one ``ppermute`` each."""
    count = n * n if count is None else count
    head = _head(count, n)
    stages: list[IRStage] = []
    groups = _pair_groups([(r, (r + 1) % n) for r in range(n)])
    for step in range(2 * (n - 1)):
        phase = "rs" if step < n - 1 else "ag"
        xfers = []
        for r in range(n):
            if phase == "rs":
                blk = (r - step) % n
            else:
                blk = (r + 1 - (step - (n - 1))) % n
            xfers.append(IRXfer(r, (r + 1) % n, (blk,)))
        stages.append(
            IRStage(
                index=step,
                phase=phase,
                combine=SUM if phase == "rs" else COPY,
                lowering="ring-step",
                groups=groups,
                xfers=tuple(xfers),
            )
        )
    return IRProgram(
        family="ring",
        num_nodes=n,
        scheduled=n,
        num_stages=1,
        stages=tuple(stages),
        count=count,
        head_elems=head,
        chunk_spans=((0, head),),
        widths=(1,),
        topo=Topology.ring(n),
    )


def lonely_ir(topo: LonelyTopology, count: int | None = None) -> IRProgram:
    """Tree over the first ``m`` ranks, ``l`` lonely ranks folded through
    buddies: fold hop, prefix-tree stages, restore hop — the program of
    ``parallel.allreduce.lonely_allreduce``."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    count = m * m if count is None else count
    head = _head(count, m)
    stages: list[IRStage] = [
        IRStage(
            index=0,
            phase="fold",
            combine=SUM,
            lowering="pair",
            groups=_pair_groups([(m + i, i) for i in range(l)]),
            xfers=tuple(IRXfer(m + i, i, ()) for i in range(l)),
        )
    ]
    stages += tree_phase_stages(tree, "rs")
    stages += tree_phase_stages(tree, "ag")
    stages.append(
        IRStage(
            index=0,
            phase="restore",
            combine=COPY,
            lowering="pair",
            groups=_pair_groups([(i, m + i) for i in range(l)]),
            xfers=tuple(IRXfer(i, m + i, ()) for i in range(l)),
        )
    )
    return IRProgram(
        family="lonely",
        num_nodes=topo.num_nodes,
        scheduled=m,
        num_stages=tree.num_stages,
        stages=tuple(stages),
        count=count,
        head_elems=head,
        chunk_spans=((0, head),),
        widths=tree.widths,
        topo=topo,
    )


# ------------------------------------------------------------------ swing


def swing_rho(s: int) -> int:
    """Swing's step-``s`` displacement ``(1 - (-2)^(s+1)) / 3`` —
    1, -1, 3, -5, 11, ... (arXiv:2401.09356 eq. 1); the sign alternation
    is what keeps cumulative distances short ("swinging")."""
    return (1 - (-2) ** (s + 1)) // 3


def swing_peer(r: int, s: int, n: int) -> int:
    """Swing peer of rank ``r`` at step ``s`` on an ``n``-ring: even ranks
    move ``+rho_s``, odd ranks ``-rho_s`` — an involution (rho is always
    odd, so the peer has opposite parity and maps straight back)."""
    rho = swing_rho(s)
    return (r + rho) % n if r % 2 == 0 else (r - rho) % n


def _swing_reach(n: int) -> list[list[set[int]]]:
    """``reach[s][r]``: final block owners reachable from ``r`` via steps
    ``s..k-1`` — ``reach[k][r] = {r}``; ``reach[s][r] = reach[s+1][r] |
    reach[s+1][peer(r, s)]``.  The emitter asserts the partition property
    (each step's keep/send sets disjoint, step 0 spanning [0, n)) so a
    broken peer function can never emit a silently-wrong program."""
    k = n.bit_length() - 1
    reach = [[set() for _ in range(n)] for _ in range(k + 1)]
    for r in range(n):
        reach[k][r] = {r}
    for s in reversed(range(k)):
        for r in range(n):
            p = swing_peer(r, s, n)
            joint = reach[s + 1][r] | reach[s + 1][p]
            if reach[s + 1][r] & reach[s + 1][p]:
                raise TopologyError(
                    f"swing reach sets collide at step {s}, rank {r}"
                )
            reach[s][r] = joint
    for r in range(n):
        if reach[0][r] != set(range(n)):
            raise TopologyError(
                f"swing steps do not span the ring from rank {r}"
            )
    return reach


def swing_ir(n: int, count: int | None = None) -> IRProgram:
    """Swing short-cut ring (arXiv:2401.09356): ``log2(P)`` pairwise
    exchange steps over the largest power-of-two core ``P <= n``, halving
    the live block set each step; the peer distance swings (1, 1, 3, 5,
    11, ...) so consecutive steps stay near on a physical ring.  Non-
    power-of-two ``n`` folds the ``n - P`` extra ranks into buddies first
    and restores them after (the lonely protocol, reused)."""
    if n < 2:
        raise TopologyError(f"swing needs n >= 2, got {n}")
    core = 1 << (n.bit_length() - 1)
    extras = n - core
    count = core * core if count is None else count
    head = _head(count, core)
    k = core.bit_length() - 1
    reach = _swing_reach(core)

    stages: list[IRStage] = []
    if extras:
        stages.append(
            IRStage(
                index=0,
                phase="fold",
                combine=SUM,
                lowering="pair",
                groups=_pair_groups([(core + i, i) for i in range(extras)]),
                xfers=tuple(IRXfer(core + i, i, ()) for i in range(extras)),
            )
        )
    for s in range(k):
        pairs = set()
        xfers = []
        for r in range(core):
            p = swing_peer(r, s, core)
            pairs.add(tuple(sorted((r, p))))
            xfers.append(IRXfer(r, p, tuple(sorted(reach[s + 1][p]))))
        stages.append(
            IRStage(
                index=s,
                phase="rs",
                combine=SUM,
                lowering="pair",
                groups=_pair_groups(sorted(pairs)),
                xfers=tuple(xfers),
            )
        )
    for s in reversed(range(k)):
        pairs = set()
        xfers = []
        for r in range(core):
            p = swing_peer(r, s, core)
            pairs.add(tuple(sorted((r, p))))
            xfers.append(IRXfer(r, p, tuple(sorted(reach[s + 1][r]))))
        stages.append(
            IRStage(
                index=s,
                phase="ag",
                combine=COPY,
                lowering="pair",
                groups=_pair_groups(sorted(pairs)),
                xfers=tuple(xfers),
            )
        )
    if extras:
        stages.append(
            IRStage(
                index=0,
                phase="restore",
                combine=COPY,
                lowering="pair",
                groups=_pair_groups([(i, core + i) for i in range(extras)]),
                xfers=tuple(IRXfer(i, core + i, ()) for i in range(extras)),
            )
        )
    return IRProgram(
        family="swing",
        num_nodes=n,
        scheduled=core,
        num_stages=k,
        stages=tuple(stages),
        count=count,
        head_elems=head,
        chunk_spans=((0, head),),
        widths=(2,) * k,
    )


# ------------------------------------------------------------ generalized


def generalized_ir(
    widths: tuple[int, ...], ports: int = 1, count: int | None = None
) -> IRProgram:
    """The generalized allreduce construction (arXiv:2004.09362): mixed-
    radix stages like the tree, but each width-``w`` stage executes as
    ``ceil((w-1)/ports)`` ROUNDS of circulant pairwise exchanges — at
    round ``t``, offset ``o``, the member at group position ``pi`` sends
    position ``(pi+o) % w`` the destination's residue chain.  Corners:
    ``widths=(N,), ports=N-1`` reproduces the flat tree's message pattern
    in one round; ``widths=(2,..,2), ports=1`` is recursive halving-
    doubling; intermediate points trade rounds (latency) against
    messages in flight per round."""
    widths = tuple(int(w) for w in widths)
    n = math.prod(widths)
    if any(w < 2 for w in widths):
        raise TopologyError(f"generalized widths must be >= 2, got {widths}")
    max_ports = max(w - 1 for w in widths)
    if not 1 <= ports <= max_ports:
        raise TopologyError(
            f"ports must be in [1, {max_ports}] for widths {widths}, got {ports}"
        )
    topo = Topology(n, widths)
    count = n * n if count is None else count
    head = _head(count, n)

    def rounds(w: int):
        """Offsets grouped into rounds of at most ``ports``."""
        offs = list(range(1, w))
        return [offs[t : t + ports] for t in range(0, len(offs), ports)]

    def stage_rows(i: int, phase: str) -> list[IRStage]:
        g, w = topo.gaps[i], topo.widths[i]
        groups = tuple(tuple(grp) for grp in topo.groups(i))
        rows = []
        for offsets in rounds(w):
            xfers = []
            for grp in groups:
                for pi, r in enumerate(grp):
                    for o in offsets:
                        dst = grp[(pi + o) % w]
                        if phase == "rs":
                            blocks = stage_send_blocks(n, g, w, dst)
                        else:
                            blocks = stage_keep_blocks(n, g, w, r)
                        xfers.append(IRXfer(r, dst, blocks))
            rows.append(
                IRStage(
                    index=i,
                    phase=phase,
                    combine=SUM if phase == "rs" else COPY,
                    lowering="pair",
                    groups=groups,
                    xfers=tuple(xfers),
                )
            )
        return rows

    stages: list[IRStage] = []
    for i in range(topo.num_stages):
        stages += stage_rows(i, "rs")
    for i in reversed(range(topo.num_stages)):
        stages += stage_rows(i, "ag")
    return IRProgram(
        family="generalized",
        num_nodes=n,
        scheduled=n,
        num_stages=topo.num_stages,
        stages=tuple(stages),
        count=count,
        head_elems=head,
        chunk_spans=((0, head),),
        widths=widths,
        ports=ports,
    )


# ----------------------------------------------------------- spec parsing


def parse_ir_family_spec(spec: str) -> IRFamilySpec | None:
    """Parse an IR-family spec string (``"swing"`` or ``"gen:4,2@2"``)
    WITHOUT a device count (the count binds at resolve time); returns
    ``None`` for legacy specs.  ``num_nodes=0`` marks the unbound form."""
    s = spec.strip().lower()
    if s == "swing":
        return IRFamilySpec("swing", 0)
    if s.startswith("gen:"):
        body = s[len("gen:"):]
        ports = 1
        if "@" in body:
            body, _, p = body.rpartition("@")
            try:
                ports = int(p)
            except ValueError as e:
                raise TopologyError(f"bad ports in spec {spec!r}") from e
        try:
            widths = tuple(int(t) for t in body.split(",") if t.strip())
        except ValueError as e:
            raise TopologyError(f"bad widths in spec {spec!r}") from e
        # num_nodes bound later; bypass the product check with a direct build
        fam = object.__new__(IRFamilySpec)
        object.__setattr__(fam, "family", "generalized")
        object.__setattr__(fam, "num_nodes", 0)
        object.__setattr__(fam, "widths", widths)
        object.__setattr__(fam, "ports", ports)
        return fam
    return None


def is_ir_family_spec(topo) -> bool:
    """True when ``topo`` names an IR-only family (swing/generalized)."""
    if isinstance(topo, (IRFamilySpec, IRProgram)):
        return True
    if isinstance(topo, str):
        s = topo.strip().lower()
        return s == "swing" or s.startswith("gen:")
    return False


def resolve_collective(num_nodes: int, topo=None):
    """Resolve ``topo`` to either a legacy ``Topology``/``LonelyTopology``
    or an :class:`IRFamilySpec` — the widened front door ``allreduce``
    uses (legacy specs keep their exact ``Topology.resolve`` semantics)."""
    if topo is None:
        topo = os.environ.get(FT_TOPO_ENV, "")
    if isinstance(topo, IRProgram):
        if topo.num_nodes != num_nodes:
            raise TopologyError(
                f"IR program is for {topo.num_nodes} nodes, mesh has {num_nodes}"
            )
        return topo
    if isinstance(topo, IRFamilySpec):
        if topo.num_nodes == 0:
            return _bind_family(topo, num_nodes)
        if topo.num_nodes != num_nodes:
            raise TopologyError(
                f"family spec is for {topo.num_nodes} nodes, mesh has {num_nodes}"
            )
        return topo
    if isinstance(topo, str):
        fam = parse_ir_family_spec(topo)
        if fam is not None:
            return _bind_family(fam, num_nodes)
    return Topology.resolve(num_nodes, topo)


def _bind_family(fam: IRFamilySpec, num_nodes: int) -> IRFamilySpec:
    if fam.family == "swing":
        if num_nodes < 2:
            raise TopologyError(f"swing needs n >= 2, got {num_nodes}")
        return IRFamilySpec("swing", num_nodes)
    return IRFamilySpec("generalized", num_nodes, fam.widths, fam.ports)


def emit_ir(topo_like, num_nodes: int | None = None, count: int | None = None,
            chunks: int = 1) -> IRProgram:
    """Emit the IR program for any topology handle: resolved legacy
    topologies, :class:`IRFamilySpec`, or spec strings (``"4,2"``,
    ``"1"``, ``"3,2+1"``, ``"swing"``, ``"gen:4,2@2"``)."""
    if isinstance(topo_like, IRProgram):
        return topo_like
    if not isinstance(topo_like, (Topology, LonelyTopology, IRFamilySpec)):
        if num_nodes is None:
            raise ValueError("num_nodes required for unresolved specs")
        topo_like = resolve_collective(num_nodes, topo_like)
    if isinstance(topo_like, IRFamilySpec):
        if topo_like.family == "swing":
            return swing_ir(topo_like.num_nodes, count=count)
        return generalized_ir(topo_like.widths, topo_like.ports, count=count)
    if isinstance(topo_like, LonelyTopology):
        return lonely_ir(topo_like, count=count)
    if topo_like.is_ring:
        return ring_ir(topo_like.num_nodes, count=count)
    return tree_ir(topo_like, count=count, chunks=chunks)


# ------------------------------------------------------ verify + compile


def verify_ir(prog: IRProgram):
    """Model-check an IR program (expand to the per-rank message program,
    run every schedule check) — returns the violation list.  Imported
    lazily so this module stays importable without the analysis package
    being loaded first (no import cycle)."""
    from ..analysis.schedule_check import check_ir

    return check_ir(prog)


def compile_ir(prog: IRProgram, op: str = "sum"):
    """Verify, then lower: returns a collective-context function
    ``f(x, axis_name) -> x`` (call inside ``shard_map``) computing the
    program's allreduce.

    The "verified-before-compiled" invariant: the program is model-checked
    (peer symmetry, deadlock-freedom, per-block conservation, chunk-span
    disjointness) and REFUSED with :class:`IRViolationError` on any
    violation — a corrupted program cannot reach a mesh.  The lowering
    additionally refuses a program whose stage list diverges from its
    family's canonical emission (``parallel.ir_lower``), so the object the
    checker certified is the object that runs.
    """
    if not isinstance(prog, IRProgram):
        raise TypeError(f"compile_ir wants an IRProgram, got {type(prog)}")
    if prog.family not in IR_FAMILIES:
        raise IRViolationError(f"unknown IR family {prog.family!r}")
    violations = verify_ir(prog)
    if violations:
        raise IRViolationError(
            f"refusing to compile {prog}: {len(violations)} model-check "
            f"violation(s); first: {violations[0]}",
            violations,
        )
    from ..parallel.ir_lower import lower_ir

    return lower_ir(prog, op=op)
