"""Traffic analysis of generated schedules: count the bytes each plan
actually exchanges, per stage and across a slice boundary.

This is the executable bridge between the schedule layer and the cost
model: ``allreduce_cost`` *prices* a stage at ``(w-1)/w * S/g`` bytes per
chip per phase (``planner/cost_model.py``), and the functions here *count*
those bytes by walking the very ``send_plan``/``recv_plan`` operations the
backends execute — so the model's bandwidth term can be pinned to the
schedule with equality tests instead of trust
(``tests/test_schedule_properties.py``), and WINS.md's DCN-traffic-
reduction claim is measured on executed plans, not only on lowered HLO.

The reference had no such analysis; its cost model and runtime were
separate binaries that could silently disagree (SURVEY §1: "the planner is
not linked into the runtime").

Slice convention: ranks are slice-major (``parallel/launch.py``'s
``hybrid_mesh``), so rank ``r`` lives in slice ``r // slice_size``.
"""

from __future__ import annotations

from .blocks import BlockLayout
from .plan import recv_plan, send_plan
from .stages import Topology

__all__ = ["stage_sent_bytes", "cross_slice_bytes", "traffic_summary"]


def _op_bytes(op, layout: BlockLayout, itemsize: int) -> int:
    return sum(layout.span(b)[1] for b in op.blocks) * itemsize


def stage_sent_bytes(
    topo: Topology, count: int, itemsize: int, rank: int
) -> list[tuple[int, int]]:
    """Per stage: (phase-1 bytes, phase-2 bytes) ``rank`` sends.

    Phase 1 walks ``send_plan``; phase 2 replays the stages in reverse with
    the roles swapped (SURVEY §3.2), i.e. the rank sends its *own* block
    set — exactly the ops ``recv_plan`` lists.  Self-sends (peer == rank)
    move no bytes and are skipped, as in the executors.
    """
    layout = BlockLayout(topo.num_nodes, count)
    out = []
    for s_ops, r_ops in zip(send_plan(topo, rank), recv_plan(topo, rank)):
        p1 = sum(_op_bytes(o, layout, itemsize) for o in s_ops if o.peer != rank)
        p2 = sum(_op_bytes(o, layout, itemsize) for o in r_ops if o.peer != rank)
        out.append((p1, p2))
    return out


def cross_slice_bytes(
    topo: Topology, count: int, itemsize: int, slice_size: int
) -> dict:
    """Bytes crossing the slice boundary, counted over every rank's plan.

    Returns ``{"per_stage": [(p1, p2), ...], "total": int,
    "per_chip_per_phase_worst": int}`` where a (sender, peer) exchange
    counts iff ``sender // slice_size != peer // slice_size``.
    ``per_chip_per_phase_worst`` is the largest single (rank, stage, phase)
    contribution — the quantity the cost model prices against the DCN
    link's per-chip injection bandwidth.
    """
    if slice_size < 1 or topo.num_nodes % slice_size:
        raise ValueError(
            f"slice_size {slice_size} must divide num_nodes {topo.num_nodes}"
        )
    layout = BlockLayout(topo.num_nodes, count)
    n_stages = topo.num_stages
    per_stage = [[0, 0] for _ in range(n_stages)]
    worst = 0
    for rank in range(topo.num_nodes):
        sl = rank // slice_size
        for i, (s_ops, r_ops) in enumerate(
            zip(send_plan(topo, rank), recv_plan(topo, rank))
        ):
            for phase, ops in ((0, s_ops), (1, r_ops)):
                contrib = sum(
                    _op_bytes(o, layout, itemsize)
                    for o in ops
                    if o.peer != rank and o.peer // slice_size != sl
                )
                per_stage[i][phase] += contrib
                worst = max(worst, contrib)
    total = sum(p1 + p2 for p1, p2 in per_stage)
    return {
        "per_stage": [tuple(x) for x in per_stage],
        "total": total,
        "per_chip_per_phase_worst": worst,
    }


def traffic_summary(topo: Topology, count: int, itemsize: int) -> dict:
    """Whole-collective byte accounting over every rank's executed plan.

    Aggregates :func:`stage_sent_bytes` across ranks into the totals the
    static-analysis report commits alongside its verdicts: total wire
    bytes (both phases), the per-rank worst case, and the per-stage
    split.  Keeping this next to the per-rank counter means the report's
    numbers and the cost-model pin tests share one source of truth.
    """
    n = topo.num_nodes
    per_stage = [[0, 0] for _ in range(topo.num_stages)]
    per_rank_total = []
    for rank in range(n):
        rows = stage_sent_bytes(topo, count, itemsize, rank)
        per_rank_total.append(sum(p1 + p2 for p1, p2 in rows))
        for i, (p1, p2) in enumerate(rows):
            per_stage[i][0] += p1
            per_stage[i][1] += p2
    return {
        "num_nodes": n,
        "widths": list(topo.widths),
        "count": count,
        "itemsize": itemsize,
        "per_stage": [tuple(x) for x in per_stage],
        "total": sum(per_rank_total),
        "per_rank_worst": max(per_rank_total) if per_rank_total else 0,
    }
