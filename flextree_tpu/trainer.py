"""End-to-end training entrypoint: ``python -m flextree_tpu.trainer``.

Ties the whole framework together from the command line: pick a model
family (dense / MoE) and parallelism layout, train on a synthetic corpus
with the FlexTree gradient sync, checkpoint and resume.  Examples::

    # dense LM, 8 virtual CPU devices, (2, 2, 2) dp/sp/tp mesh
    python -m flextree_tpu.trainer --cpu 8 --steps 50

    # pipeline-parallel over (1, 2, 2, 2) dp/pp/sp/tp
    python -m flextree_tpu.trainer --cpu 8 --model pipeline --mesh 1,2,2,2

    # mixture-of-experts over (1, 2, 2, 2) dp/ep/sp/tp with a 2-stage
    # hierarchical gradient-sync topology
    python -m flextree_tpu.trainer --cpu 8 --model moe --mesh 1,2,2,2 --grad-topo 2,2
"""

from __future__ import annotations

import argparse
import os
import shutil
import tempfile


def build(args, init_state=True):
    """(state, step_fn, mesh, restore_specs, state_pack, state_unpack)
    for the chosen model family.  ``restore_specs`` describes the
    CHECKPOINT layout (for sharded runs that is the consolidated
    replicated layout; ``state_pack``/``state_unpack`` convert — None for
    replicated runs).  ``init_state=False`` skips materializing the
    train state (returns None in its slot) — the feedback replan rebuild
    only needs the step fn, and initializing a second full model +
    optimizer state beside the live one doubles peak memory at exactly
    the replan moment."""
    import jax

    from .models.transformer import TransformerConfig
    from .parallel.train import TrainConfig

    tc = TrainConfig(
        lr=args.lr,
        grad_topo=args.grad_topo,
        grad_clip_norm=args.grad_clip,
        schedule=args.schedule,
        warmup_steps=args.warmup_steps,
        total_steps=args.steps if args.schedule == "warmup_cosine" else 0,
        min_lr_frac=args.min_lr_frac,
        codec=args.codec,
        autotune=args.autotune,
        overlap=args.overlap,
        shard_optimizer=args.shard_optimizer,
    )
    key = jax.random.PRNGKey(args.seed)
    mesh_shape = (
        tuple(int(x) for x in args.mesh.split(",")) if args.mesh else None
    )

    common = dict(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_heads=args.n_heads,
        n_layers=args.n_layers,
        d_ff=args.d_ff,
        sp_impl=args.sp_impl,
        attn_impl=args.attn_impl,
    )
    def sharded_hooks(mesh, pspecs, params_shapes, axis_names, sspecs, tc):
        """(state_specs_for_restore, pack, unpack) for the run: sharded
        runs checkpoint CONSOLIDATED (world-size-independent), so the
        restore specs are the replicated layout and pack/unpack are the
        on-device converters (docs/SHARDED.md).  ``tc`` must be the
        RESOLVED config (autotune already pinned into ``grad_topo``) —
        the converters' shard-block permutation has to match the step's.
        """
        if not tc.shard_optimizer:
            return sspecs, None, None
        import dataclasses as _dc

        from .parallel.train import _sync_codec, make_state_specs, zero_layout_for
        from .parallel.zero import make_consolidate_fn, make_reshard_fn

        layout = zero_layout_for(mesh, params_shapes, pspecs, axis_names)
        lossy = _sync_codec(tc).lossy
        packed_specs = make_state_specs(
            pspecs, _dc.replace(tc, shard_optimizer=False)
        )
        pack = make_consolidate_fn(mesh, pspecs, layout, tc.grad_topo, lossy)
        unpack = make_reshard_fn(mesh, pspecs, layout, tc.grad_topo, lossy)
        return packed_specs, pack, unpack

    if args.model == "dense":
        from .models.transformer import init_params, param_specs
        from .parallel.train import (
            init_train_state,
            make_mesh_3d,
            make_train_step,
            maybe_autotune_grad_topo,
            state_specs,
        )

        cfg = TransformerConfig(**common)
        mesh = make_mesh_3d(args.devices, mesh_shape)
        axis_names = ("dp", "sp", "tp")
        # resolve autotune NOW so the checkpoint converters below see the
        # same grad_topo the step will run (make_train_step re-resolves —
        # a no-op after this: autotune=False and the plan cache hits)
        tc = maybe_autotune_grad_topo(mesh, cfg, tc, axis_names)
        sspecs = state_specs(cfg, train_cfg=tc, mesh=mesh)
        params_shapes = jax.eval_shape(
            lambda k: init_params(k, cfg), jax.random.PRNGKey(0)
        )
        restore_specs, pack, unpack = sharded_hooks(
            mesh, param_specs(cfg, "tp"), params_shapes, axis_names, sspecs, tc
        )
        return (
            init_train_state(key, cfg, tc, mesh=mesh) if init_state else None,
            make_train_step(mesh, cfg, tc),
            mesh,
            restore_specs,
            pack,
            unpack,
        )
    if args.model == "pipeline":
        from .models.transformer import init_params
        from .parallel.pipeline import (
            init_pipeline_train_state,
            make_mesh_4d,
            make_pipeline_train_step,
            pipeline_param_specs,
            pipeline_state_specs,
            stack_layer_params,
        )

        cfg = TransformerConfig(**common)
        mesh = make_mesh_4d(args.devices, mesh_shape)
        axis_names = ("dp", "pp", "sp", "tp")
        from .parallel.train import maybe_autotune_grad_topo

        tc = maybe_autotune_grad_topo(
            mesh, cfg, tc, axis_names,
            init_fn=lambda k, c: stack_layer_params(init_params(k, c)),
        )
        sspecs = pipeline_state_specs(cfg, train_cfg=tc, mesh=mesh)
        params_shapes = jax.eval_shape(
            lambda k: stack_layer_params(init_params(k, cfg)),
            jax.random.PRNGKey(0),
        )
        restore_specs, pack, unpack = sharded_hooks(
            mesh, pipeline_param_specs(cfg), params_shapes, axis_names, sspecs,
            tc,
        )
        return (
            init_pipeline_train_state(key, cfg, tc, mesh=mesh)
            if init_state else None,
            make_pipeline_train_step(
                mesh, cfg, tc, n_microbatches=args.microbatches
            ),
            mesh,
            restore_specs,
            pack,
            unpack,
        )
    if args.model == "moe":
        from .models.moe import MoEConfig, init_moe_params, moe_param_specs
        from .parallel.moe_train import (
            init_moe_train_state,
            make_mesh_moe,
            make_moe_train_step,
            moe_state_specs,
        )

        cfg = MoEConfig(
            **common,
            n_experts=args.n_experts,
            top_k=args.top_k,
            capacity_factor=args.capacity_factor,
        )
        mesh = make_mesh_moe(args.devices, mesh_shape)
        axis_names = ("dp", "ep", "sp", "tp")
        from .parallel.train import maybe_autotune_grad_topo

        tc = maybe_autotune_grad_topo(
            mesh, cfg, tc, axis_names, init_fn=init_moe_params
        )
        sspecs = moe_state_specs(cfg, train_cfg=tc, mesh=mesh)
        params_shapes = jax.eval_shape(
            lambda k: init_moe_params(k, cfg), jax.random.PRNGKey(0)
        )
        restore_specs, pack, unpack = sharded_hooks(
            mesh, moe_param_specs(cfg), params_shapes, axis_names, sspecs, tc
        )
        return (
            init_moe_train_state(key, cfg, tc, mesh=mesh)
            if init_state else None,
            make_moe_train_step(mesh, cfg, tc),
            mesh,
            restore_specs,
            pack,
            unpack,
        )
    raise ValueError(f"unknown model {args.model!r}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flextree_tpu.trainer")
    ap.add_argument("--model", choices=["dense", "pipeline", "moe"],
                    default="dense")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=128)
    ap.add_argument("--n-experts", type=int, default=4)
    ap.add_argument("--top-k", type=int, default=2)
    ap.add_argument("--capacity-factor", type=float, default=2.0)
    ap.add_argument("--microbatches", type=int, default=2)
    ap.add_argument(
        "--sp-impl", choices=["ring", "zigzag", "ulysses"], default="ring"
    )
    ap.add_argument("--attn-impl", choices=["reference", "flash"],
                    default="reference")
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument(
        "--grad-clip", type=float, default=0.0,
        help="global-norm gradient clipping (0 = off); the norm psums "
        "tp-sharded leaves so it is the TRUE global norm",
    )
    ap.add_argument(
        "--schedule", choices=["constant", "warmup_cosine"],
        default="constant",
        help="warmup_cosine ramps over --warmup-steps then decays to "
        "min_lr_frac*lr at --steps",
    )
    ap.add_argument("--warmup-steps", type=int, default=0)
    ap.add_argument(
        "--min-lr-frac", type=float, default=0.1,
        help="cosine floor as a fraction of --lr (warmup_cosine only)",
    )
    ap.add_argument("--grad-topo", type=str, default=None,
                    help="FT_TOPO-style widths for the gradient allreduce")
    ap.add_argument(
        "--codec", choices=["f32", "bf16", "int8"], default="f32",
        help="gradient-sync wire codec (docs/QUANTIZED_COLLECTIVES.md): "
        "f32 = identity (bitwise-identical sync), bf16/int8 compress the "
        "collective payload per hop with an error-feedback residual "
        "carried in the train state",
    )
    ap.add_argument(
        "--autotune", action="store_true",
        help="pick the gradient-sync topology by measuring the analytic "
        "top-K candidates on this backend (planner/autotune.py) instead "
        "of trusting the cost-model argmin; cached under "
        "FLEXTREE_PLAN_CACHE so the next run is a pure cache hit "
        "(overlapped and serialized plans never share a cache entry)",
    )
    ap.add_argument(
        "--shard-optimizer", action="store_true",
        help="ZeRO-1 sharded-optimizer path (docs/SHARDED.md): shard "
        "optimizer state (and the f32 master copy for lossy codecs) over "
        "each leaf's first replication axis; the step reduce-scatters "
        "grads (wire-compressed under --codec), updates the owned shard "
        "only, and all-gathers updated params per bucket. Per-rank mu/nu "
        "memory drops by the shard-axis size; bitwise-identical to the "
        "replicated step for the f32 codec. Checkpoints are written "
        "CONSOLIDATED (world-size-independent), so elastic shrink "
        "re-shards them onto the survivors",
    )
    ap.add_argument(
        "--overlap", action=argparse.BooleanOptionalAction, default=False,
        help="readiness-ordered backward/comm overlap (docs/OVERLAP.md): "
        "fire each gradient bucket's collective as soon as its grads are "
        "produced (reverse layer order), boundaries planner-equalized "
        "against remaining backward compute; bitwise-identical to the "
        "serialized sync for the f32 codec. --no-overlap (default) keeps "
        "the historical serialized sync",
    )
    ap.add_argument("--mesh", type=str, default=None,
                    help="comma mesh shape, e.g. 2,2,2 (dense) or 1,2,2,2")
    ap.add_argument("--devices", type=int, default=None)
    ap.add_argument("--cpu", type=int, default=None, metavar="N",
                    help="run on N virtual CPU devices")
    ap.add_argument("--corpus-tokens", type=int, default=200_000)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--ckpt-dir", type=str, default=None)
    ap.add_argument("--ckpt-every", type=int, default=100)
    ap.add_argument("--log-every", type=int, default=10)
    ap.add_argument("--no-resume", action="store_true")
    # runtime supervision (flextree_tpu.runtime; docs/FAILURE_MODEL.md)
    ap.add_argument(
        "--step-timeout", type=float, default=None, metavar="S",
        help="per-step watchdog deadline in seconds (env FT_STEP_TIMEOUT); "
        "a hung step raises a typed FT_STEP_TIMEOUT instead of blocking",
    )
    ap.add_argument(
        "--heartbeat-dir", type=str, default=None,
        help="shared heartbeat directory: this process beats its lease + "
        "step progress there and watches peers (straggler/dead "
        "classification feeds run_report.json)",
    )
    ap.add_argument("--heartbeat-rank", type=int, default=0,
                    help="this process's rank in the heartbeat group")
    ap.add_argument("--heartbeat-world", type=int, default=None,
                    help="configured group size for membership accounting")
    ap.add_argument(
        "--no-preempt-checkpoint", action="store_true",
        help="disable the SIGTERM 'checkpoint now' fast path (on by "
        "default whenever --ckpt-dir is set)",
    )
    # closed-loop planner feedback (planner/feedback.py; docs/FEEDBACK.md)
    ap.add_argument(
        "--feedback-every", type=int, default=0, metavar="K",
        help="arm the closed-loop planner feedback: every K steps (with "
        "the flight recorder on — pair with --obs-dir/--flight-recorder) "
        "probe the live wire, compare measured comm time against the "
        "calibrated prediction, and past the drift band refit the cost "
        "constants, invalidate stale plan-cache entries and swap in a "
        "replanned step in-run. 0 (default) = off; with the recorder off "
        "the armed hook costs one None check per step",
    )
    ap.add_argument(
        "--feedback-band", type=float, default=0.5, metavar="R",
        help="relative-residual drift band for --feedback-every: a replan "
        "triggers when the median |predicted-measured|/measured over the "
        "sliding window exceeds R",
    )
    ap.add_argument(
        "--feedback-calibration", type=str, default=None, metavar="PATH",
        help="write feedback refits back to this CALIBRATION.json "
        "(source=\"feedback\" provenance stamp); defaults to a run-local "
        "CALIBRATION.feedback.json under --obs-dir, seeded as a copy of "
        "$FLEXTREE_CALIBRATION when that is set — the user's measured "
        "file is never overwritten by an in-run fit (the replan rebuild "
        "reads the refit from this file)",
    )
    # telemetry (flextree_tpu.obs; docs/OBSERVABILITY.md)
    ap.add_argument(
        "--obs-dir", type=str, default=None, metavar="DIR",
        help="write this rank's flight-recorder events "
        "(flight_{rank}.jsonl), failure dumps and metrics snapshot under "
        "DIR; merge a run's ranks with `python -m flextree_tpu.obs merge "
        "DIR` into one Perfetto-loadable timeline",
    )
    ap.add_argument(
        "--flight-recorder", action="store_true",
        help="enable the flight recorder with a default directory "
        "({--ckpt-dir}/obs, or ./ft_obs without a checkpoint dir); "
        "equivalent to --obs-dir with that path",
    )
    args = ap.parse_args(argv)

    if args.cpu:
        import jax

        from .utils.compat import request_cpu_devices

        jax.config.update("jax_platforms", "cpu")
        request_cpu_devices(args.cpu)  # both config spellings (compat shim)

    from .data import LMDataset, synthetic_tokens
    from .parallel.loop import FitConfig, Supervision, fit

    # runtime supervision wiring: any flag arms the layer; SIGTERM
    # preemption checkpointing is on by default when checkpointing is
    supervision = None
    want_preempt = args.ckpt_dir and not args.no_preempt_checkpoint
    if args.step_timeout or args.heartbeat_dir or want_preempt:
        from .runtime import (
            MembershipView,
            PreemptionGuard,
            Supervisor,
            SupervisorConfig,
        )

        supervisor = membership = None
        if args.heartbeat_dir:
            cfg_hb = SupervisorConfig.from_env(
                rank=args.heartbeat_rank, dir=args.heartbeat_dir
            )
            supervisor = Supervisor(cfg_hb)
            membership = MembershipView.for_config(
                cfg_hb, configured=args.heartbeat_world
            )
        supervision = Supervision(
            supervisor=supervisor,
            membership=membership,
            configured_world=args.heartbeat_world,
            step_timeout_s=args.step_timeout,
            preemption=PreemptionGuard().install() if want_preempt else None,
        )

    # flight recorder: installed BEFORE build so compile-time events
    # (bucket plans with provenance) land in the record too
    import contextlib

    obs_ctx = contextlib.nullcontext()
    obs_dir = None
    if args.obs_dir or args.flight_recorder:
        from .obs import flight_recorder, install_signal_dump

        obs_dir = args.obs_dir or (
            os.path.join(args.ckpt_dir, "obs") if args.ckpt_dir else "ft_obs"
        )
        obs_ctx = flight_recorder(obs_dir, rank=args.heartbeat_rank)

    with obs_ctx as obs_rec:
        if obs_rec is not None and (
            supervision is None or supervision.preemption is None
        ):
            # no PreemptionGuard routing SIGTERM through fit's dump path:
            # chain a flush+dump onto the default handler so even a bare
            # terminate leaves the forensic record
            install_signal_dump(obs_rec)
        state, step_fn, mesh, sspecs, state_pack, state_unpack = build(args)
        if args.feedback_every > 0:
            # closed-loop planner feedback (docs/FEEDBACK.md): probes ride
            # the largest mesh axis (the dominant sync wire); a drift-
            # triggered replan rebuilds the step so the refreshed
            # calibration re-derives bucket sizes/topology at trace time
            import jax

            from .planner.feedback import FeedbackConfig, FeedbackController

            param_bytes = sum(
                l.size * l.dtype.itemsize
                for l in jax.tree.leaves(state["params"])
            )
            n_fb = max((int(s) for s in mesh.shape.values()), default=1)
            # the refit must land somewhere build() can SEE: the rebuild
            # below re-derives bucket sizes/topology through the planner,
            # which resolves constants from $FLEXTREE_CALIBRATION — so
            # default the write-back path to a run-local file rather than
            # leaving the loop open (refit written nowhere the rebuilt
            # step reads)
            fb_prev_cal = os.environ.get("FLEXTREE_CALIBRATION")
            fb_cal = args.feedback_calibration
            if not fb_cal:
                # the same derived record dir the flight recorder uses
                # (the controller only ever ticks with the recorder on,
                # so a recorder-less run writes nothing anywhere —
                # don't allocate a throwaway dir for it).  NEVER default
                # to $FLEXTREE_CALIBRATION itself: a drift refit calls
                # save_calibration, which replaces the backend's section
                # in place — a noisy in-run fit must not destroy the
                # host's measured tools/calibrate_host.py artifact.
                # Seeding the run-local file from it keeps the other
                # backends' sections and the measured provenance intact.
                # no obs dir: a PER-RUN private dir, never a fixed name
                # in the world-shared tempdir (a foreign-owned or
                # pre-planted file at a fixed /tmp path would abort the
                # copy below or redirect it through a symlink)
                fb_cal = os.path.join(
                    obs_dir
                    if obs_dir is not None
                    else tempfile.mkdtemp(prefix="ft-feedback-"),
                    "CALIBRATION.feedback.json",
                )
                if fb_prev_cal and os.path.exists(fb_prev_cal):
                    shutil.copyfile(fb_prev_cal, fb_cal)

            def _feedback_rebuild(plan, params):
                # rebuild with the refitted constants: point the planner
                # at the calibration the controller just wrote back (the
                # live state stays — only the fn/mesh/specs swap, so the
                # rebuild skips materializing a second train state).
                # The env var must STAY pointed at the refit for the rest
                # of the run: build() only constructs the jitted fn — the
                # swapped step first TRACES on the next fit iteration,
                # where plan_buckets resolves $FLEXTREE_CALIBRATION to
                # derive bucket sizes.  Restoring here would hand that
                # trace the stale constants and silently re-open the
                # loop's bucket half (the fit-end finally below restores
                # the original value for in-process callers).
                os.environ["FLEXTREE_CALIBRATION"] = fb_cal
                _none, f2, m2, sp2, pk2, up2 = build(args, init_state=False)
                return (f2, m2, sp2, pk2, up2)

            controller = FeedbackController(
                n_fb,
                param_bytes,
                FeedbackConfig(
                    every_k=args.feedback_every,
                    band=args.feedback_band,
                    calibration_path=fb_cal,
                    on_replan=_feedback_rebuild,
                ),
            )
            if supervision is None:
                supervision = Supervision()
            supervision.feedback = controller
        dataset = LMDataset(
            synthetic_tokens(args.corpus_tokens, args.vocab, seed=args.seed),
            batch=args.batch,
            seq_len=args.seq_len,
            seed=args.seed,
        )
        try:
            result = fit(
                state,
                step_fn,
                dataset,
                FitConfig(
                    num_steps=args.steps,
                    ckpt_dir=args.ckpt_dir,
                    ckpt_every=args.ckpt_every,
                    log_every=args.log_every,
                    resume=not args.no_resume,
                ),
                mesh=mesh,
                state_specs=sspecs,
                supervision=supervision,
                state_pack=state_pack,
                state_unpack=state_unpack,
            )
        finally:
            if supervision is not None and supervision.preemption is not None:
                supervision.preemption.uninstall()  # in-process callers (tests)
            if args.feedback_every > 0:
                # a replan rebuild repoints $FLEXTREE_CALIBRATION at the
                # refit file for the rest of the run (the swapped step
                # traces lazily); restore the pre-run value so in-process
                # callers (tests) aren't left with a run-local path
                if fb_prev_cal is None:
                    os.environ.pop("FLEXTREE_CALIBRATION", None)
                else:
                    os.environ["FLEXTREE_CALIBRATION"] = fb_prev_cal
    first = result.losses[0][1] if result.losses else float("nan")
    last = result.losses[-1][1] if result.losses else float("nan")
    print(
        f"{args.model}: {result.steps_run} steps on mesh "
        f"{dict(mesh.shape)}; loss {first:.4f} -> {last:.4f}"
        + (f" (resumed from {result.resumed_from})" if result.resumed_from else "")
        + (
            f" (preempted at step {result.report.preempted_at}, checkpointed)"
            if result.report.preempted_at is not None
            else ""
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
