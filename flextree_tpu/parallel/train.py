"""Sharded training step: dp x sp x tp over one mesh, FlexTree grad sync.

This is the framework's end-to-end composition — the role the reference
plays inside a host framework when its allreduce interposes on the data-
parallel gradient sync (``mpi_mod.hpp:1167-1171``): here the gradient
allreduce *is* our topology-parameterized collective, and it also provides
the TP partial-sum combine inside the model forward.

Parallelism layout (one ``shard_map`` over a 3-axis mesh):

- ``dp``  — batch dimension; no collective in the forward, gradients are
  summed across it explicitly (the classic gradient allreduce).
- ``sp``  — sequence dimension; ring attention moves K/V around the ring
  in the forward, and its transpose carries the cross-shard gradient
  contributions back automatically.
- ``tp``  — heads / hidden units; column/row-parallel matmuls with the
  row-parallel partials combined by ``flextree_tpu.parallel.allreduce``.

Gradient-sync rule: automatic differentiation of the per-device loss gives,
on every device, the gradient of the *sum of all devices' losses* with
respect to that device's local parameter copy (collective transposes carry
the cross-device terms).  The true gradient of a logically-shared parameter
is the sum over its distinct copies — so each gradient leaf is explicitly
allreduced over exactly the axes its parameter is *replicated* on: tp-
sharded weights sync over (dp, sp); replicated ones over (dp, sp, tp).  The
per-device loss is normalized by the global token count *including* the
tp-fold redundancy, which makes the total differentiated quantity the true
global mean loss.

Optimizer is an inline AdamW (decoupled weight decay); its moments shard
exactly like the parameters, so optimizer memory scales down with TP.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import (
    TransformerConfig,
    cross_entropy_loss,
    forward,
    init_params,
    param_specs,
)
from ..schedule.stages import Topology, TopologyError
from .allreduce import allreduce
from .bucketing import bucketed_sync_grads, replication_key, spec_axes

__all__ = [
    "TrainConfig",
    "init_train_state",
    "state_specs",
    "make_train_step",
    "make_mesh_3d",
    "factor_devices",
    "resolve_axis_topos",
    "sync_grads",
    "sync_with_feedback",
    "maybe_autotune_grad_topo",
    "adamw_apply",
    "schedule_lr",
    "global_grad_norm",
    "clip_by_global_norm",
    "maybe_clip_grads",
    "metric_specs",
]


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    lr: float = 1e-3
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    weight_decay: float = 0.0
    # topology spec for the gradient-sync allreduce (None -> FT_TOPO/flat).
    # Either one spec — used on every mesh axis whose size matches its
    # product, flat elsewhere — or a dict {axis_name: spec}.  The sentinel
    # "psum" selects the native XLA all-reduce instead of FlexTree — the
    # A/B oracle (and escape hatch) inside the production train step.
    grad_topo: Any = None
    # global-norm gradient clipping (0 = off).  The norm is the TRUE global
    # norm: tp-sharded leaves psum their shard's square-sum over the tp
    # axis before the total (see global_grad_norm).
    grad_clip_norm: float = 0.0
    # learning-rate schedule: "constant", or "warmup_cosine" (linear ramp
    # over warmup_steps, cosine decay to min_lr_frac*lr at total_steps —
    # total_steps required then)
    schedule: str = "constant"
    warmup_steps: int = 0
    total_steps: int = 0
    min_lr_frac: float = 0.1
    # gradient bucketing/fusion (parallel/bucketing.py): the sync packs
    # gradient leaves grouped by (replication-axis-set, dtype) into fused
    # flat buckets and runs ONE FlexTree allreduce per bucket — bitwise-
    # identical to per-leaf, but buckets x stages collectives instead of
    # leaves x stages.  None (default) -> bucket size derived from the
    # calibrated planner (planner.choose_bucket_bytes); 0 -> per-leaf sync
    # (the A/B oracle / escape hatch); > 0 -> explicit bucket-size cap in
    # bytes.
    bucket_bytes: int | None = None
    # chunk-pipelined allreduce: > 1 splits each bucket's tree collective
    # into C chunks with phase-2/phase-1 interleaving (allreduce chunks=C);
    # bitwise-identical for the sum sync, 1 = off.
    grad_chunks: int = 1
    # wire codec for the gradient sync (ops/quantize.py): "f32" (identity,
    # the default — bitwise-identical to the historical sync), "bf16", or
    # "int8" (block-scaled, deterministic stochastic rounding keyed off
    # the step counter).  Lossy codecs carry an EF21-style error-feedback
    # residual in the train state ("ef", zeros at init — see
    # init_train_state / docs/QUANTIZED_COLLECTIVES.md), so the long-run
    # synced gradient converges to exact.
    codec: str = "f32"
    # measured plan autotuner (planner/autotune.py): when True and
    # grad_topo is None, the step builders resolve the sync topology per
    # mesh axis by timing the analytic top-K candidates on the live
    # backend (cached under FLEXTREE_PLAN_CACHE — the second build is a
    # pure cache hit) instead of trusting the cost-model argmin.
    autotune: bool = False
    # readiness-ordered backward/comm overlap (parallel/overlap.py): the
    # dense/MoE steps decompose the backward per layer and fire each
    # gradient bucket's collective as soon as its grads exist (reverse
    # layer order), with bucket boundaries chosen by the planner to
    # equalize per-bucket comm time against the remaining backward
    # compute (planner.choose.choose_overlap_boundaries); the pipeline
    # step schedules its bucket collectives into the post-backward bubble
    # (the scan transpose is a dataflow barrier — docs/OVERLAP.md).
    # Bitwise-identical to the serialized sync for the identity codec;
    # EF/codec semantics carried through unchanged.  False (default) is
    # the historical serialized path, byte-for-byte.
    overlap: bool = False
    # ZeRO-1 sharded-optimizer path (parallel/zero.py, docs/SHARDED.md):
    # optimizer state (and, for lossy codecs, the f32 master param copy)
    # shards over each leaf's FIRST replication axis; the step
    # reduce-scatters gradients (wire-compressed under ``codec``), applies
    # AdamW on the owned shard only, and all-gathers updated parameters
    # per bucket.  Per-rank mu/nu memory drops by the shard-axis size;
    # the quantized sharded step moves ~wire_ratio x the bytes of the
    # replicated fused f32 sync (BOTH phases ride the codec).  For the
    # identity codec the step is BITWISE-equal to the replicated step
    # across flat/tree/ring shard topologies (lonely shapes fall back to
    # the flat tree for the sharded collectives).  Composes with
    # ``overlap`` (per-bucket reduce-scatter fires at grad readiness; the
    # parameter all-gathers overlap the remaining per-bucket optimizer
    # work).  State init/specs need the mesh (init_train_state(mesh=...)).
    shard_optimizer: bool = False


def prime_factors(n: int) -> list[int]:
    """Prime factors of ``n`` by trial division (ascending, with
    multiplicity) — the planner-side twin is
    ``flextree_tpu.planner.factorize``."""
    factors = []
    m, p = n, 2
    while m > 1:
        while m % p == 0:
            factors.append(p)
            m //= p
        p += 1
    return factors


def spread_factors(n: int, n_dims: int, order: list[int] | None = None) -> tuple:
    """Split ``n`` into ``n_dims`` near-balanced dims: largest prime factors
    first, assigned round-robin over ``order`` (default 0..n_dims-1)."""
    if order is None:
        order = list(range(n_dims))
    dims = [1] * n_dims
    for i, f in enumerate(sorted(prime_factors(n), reverse=True)):
        dims[order[i % n_dims]] *= f
    return tuple(dims)


def make_mesh_nd(n_devices: int | None, shape, axis_names) -> Mesh:
    """A mesh of ``shape`` x ``axis_names`` over the first n local devices."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if n > len(devs):
        raise ValueError(f"asked for {n} devices, only {len(devs)} visible")
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} does not cover {n} devices")
    return jax.make_mesh(shape, axis_names, devices=devs[:n])


def factor_devices(n: int) -> tuple[int, int, int]:
    """Split ``n`` devices into a (dp, sp, tp) shape, most-square-first.

    Greedy largest-prime-first assignment cycling dp -> sp -> tp, so 8 ->
    (2, 2, 2), 4 -> (2, 2, 1), 12 -> (3, 2, 2), 1 -> (1, 1, 1).
    """
    return spread_factors(n, 3)


def make_mesh_3d(
    n_devices: int | None = None,
    shape: tuple[int, int, int] | None = None,
    axis_names: tuple[str, str, str] = ("dp", "sp", "tp"),
) -> Mesh:
    """A (dp, sp, tp) mesh over the first ``n_devices`` local devices."""
    if shape is None:
        shape = factor_devices(
            len(jax.devices()) if n_devices is None else n_devices
        )
    return make_mesh_nd(n_devices, shape, axis_names)


def make_train_state(
    params, train_cfg: "TrainConfig | None" = None, *, layout=None
) -> dict:
    """Fresh AdamW state around a parameter pytree (any layout).

    A lossy gradient-sync codec (``train_cfg.codec``) adds the
    error-feedback residual tree ``"ef"`` (zeros, param-shaped): each step
    syncs ``grad + ef`` and stores what the wire's input quantization lost
    back into ``ef``, so no gradient mass is ever dropped — only delayed.

    ``train_cfg.shard_optimizer`` replaces the full ``mu``/``nu`` trees
    with the sharded layout of ``parallel.zero`` (owned head block +
    replicated tail per leaf, plus the f32 master shards for lossy
    codecs) — pass the :class:`~flextree_tpu.parallel.zero.ZeroLayout`
    built for the mesh (``zero_layout_for`` / ``init_train_state(mesh=)``).
    """
    sharded = train_cfg is not None and train_cfg.shard_optimizer
    if sharded:
        from .zero import init_zero_entries

        if layout is None:
            raise ValueError(
                "shard_optimizer=True needs the mesh's ZeroLayout — call "
                "init_train_state(..., mesh=mesh) or pass layout="
            )
        state = {"params": params, "step": jnp.zeros((), jnp.int32)}
        state.update(
            init_zero_entries(params, layout, _sync_codec(train_cfg).lossy)
        )
    else:
        state = {
            "params": params,
            "mu": jax.tree.map(jnp.zeros_like, params),
            "nu": jax.tree.map(jnp.zeros_like, params),
            "step": jnp.zeros((), jnp.int32),
        }
    if train_cfg is not None and _sync_codec(train_cfg).lossy:
        state["ef"] = jax.tree.map(jnp.zeros_like, params)
    return state


def _sync_codec(train_cfg: "TrainConfig"):
    from ..ops.quantize import get_codec

    return get_codec(train_cfg.codec)


def validate_tp(model_cfg: TransformerConfig, tp_size: int) -> None:
    """Shared precondition check for every train-step builder."""
    if model_cfg.d_model % model_cfg.n_heads or model_cfg.n_heads % tp_size:
        raise ValueError(
            f"n_heads={model_cfg.n_heads} must divide d_model="
            f"{model_cfg.d_model} and be divisible by tp={tp_size}"
        )
    if model_cfg.d_ff % tp_size:
        raise ValueError(
            f"d_ff={model_cfg.d_ff} must be divisible by tp={tp_size}"
        )


def zero_layout_for(mesh: Mesh, params_shapes, pspecs, axis_names):
    """The mesh's :class:`~flextree_tpu.parallel.zero.ZeroLayout` for a
    parameter tree — shared by state init, spec building and the step
    builders so the three can never disagree on who owns which block."""
    from .zero import build_zero_layout

    axis_sizes = {ax: int(mesh.shape[ax]) for ax in axis_names}
    return build_zero_layout(params_shapes, pspecs, tuple(axis_names), axis_sizes)


def init_train_state(
    key,
    cfg: TransformerConfig,
    train_cfg: "TrainConfig | None" = None,
    mesh: Mesh | None = None,
    axis_names: tuple[str, str, str] = ("dp", "sp", "tp"),
) -> dict:
    params = init_params(key, cfg)
    layout = None
    if train_cfg is not None and train_cfg.shard_optimizer:
        if mesh is None:
            raise ValueError("shard_optimizer=True: init_train_state needs mesh=")
        layout = zero_layout_for(
            mesh, params, param_specs(cfg, axis_names[-1]), axis_names
        )
    return make_train_state(params, train_cfg, layout=layout)


def make_state_specs(
    pspecs, train_cfg: "TrainConfig | None" = None, *, layout=None
) -> dict:
    """Optimizer-state specs around parameter specs (moments shard alike;
    the error-feedback residual of a lossy sync codec shards alike too).
    Under ``shard_optimizer`` the moment specs come from the
    ``ZeroLayout`` instead (owned blocks ``P(shard_ax)``, tails ``P()``)."""
    if train_cfg is not None and train_cfg.shard_optimizer:
        from .zero import zero_state_specs

        if layout is None:
            raise ValueError("shard_optimizer=True needs layout= for specs")
        specs = {"params": pspecs, "step": P()}
        specs.update(
            zero_state_specs(pspecs, layout, _sync_codec(train_cfg).lossy)
        )
    else:
        specs = {"params": pspecs, "mu": pspecs, "nu": pspecs, "step": P()}
    if train_cfg is not None and _sync_codec(train_cfg).lossy:
        specs["ef"] = pspecs
    return specs


def state_specs(
    cfg: TransformerConfig,
    tp_axis: str | None = "tp",
    train_cfg: "TrainConfig | None" = None,
    mesh: Mesh | None = None,
    axis_names: tuple[str, str, str] = ("dp", "sp", "tp"),
) -> dict:
    pspecs = param_specs(cfg, tp_axis)
    layout = None
    if train_cfg is not None and train_cfg.shard_optimizer:
        if mesh is None:
            raise ValueError("shard_optimizer=True: state_specs needs mesh=")
        shapes = jax.eval_shape(
            lambda k: init_params(k, cfg), jax.random.PRNGKey(0)
        )
        layout = zero_layout_for(mesh, shapes, pspecs, axis_names)
    return make_state_specs(pspecs, train_cfg, layout=layout)


def resolve_axis_topos(mesh: Mesh, mesh_axes, grad_topo) -> dict:
    """Per-axis FlexTree topology for the gradient sync.

    ``grad_topo``: a single spec (used on each axis whose size its product
    matches, flat elsewhere) or a dict ``{axis_name: spec}``.
    """

    def axis_topo(ax):
        spec = grad_topo
        if isinstance(spec, dict):
            spec = spec.get(ax)
        if spec == "psum":
            return None  # sentinel: native XLA all-reduce on this axis
        from ..schedule.ir import is_ir_family_spec

        if is_ir_family_spec(spec):
            # the train sync seam (bucketing, cost model, zero layout)
            # prices and executes legacy topologies only — refusing loudly
            # beats the flat fallback silently discarding a measured plan
            # (IR families on this seam are the named ROADMAP follow-up)
            raise TopologyError(
                f"grad_topo {spec!r} on axis {ax!r}: IR families "
                f"(swing/generalized) are not supported on the train sync "
                f"seam yet — use a widths-vector spec or 'psum'"
            )
        try:
            return Topology.resolve(mesh.shape[ax], spec)
        except TopologyError:
            return Topology.flat(mesh.shape[ax])

    return {ax: axis_topo(ax) for ax in mesh_axes}


def sync_grads(
    grads,
    pspecs,
    mesh_axes,
    topos: dict,
    bucket_bytes: int | None = 0,
    chunks: int = 1,
    codec="f32",
    step=0,
    return_residual: bool = False,
):
    """FlexTree gradient sync: sum each leaf over its replication axes.

    An axis whose topology is ``None`` (the ``"psum"`` sentinel) uses the
    native all-reduce — the in-step analog of the benchmark's
    ``--comm-type xla`` baseline.

    ``bucket_bytes`` selects the execution strategy: ``0`` (default, the
    historical behavior) syncs per leaf — one allreduce sequence per
    gradient leaf; any other value routes through the bucketed/fused sync
    (``parallel.bucketing.bucketed_sync_grads`` — ``None`` derives the
    bucket size from the calibrated planner, ``> 0`` is an explicit cap),
    which is bitwise-identical but runs one fused collective per *bucket*.
    The train-step builders pass their ``TrainConfig.bucket_bytes`` through,
    so the bucketed path is the production default.  ``chunks > 1`` runs
    tree collectives chunk-pipelined (both paths).

    ``codec`` selects the wire format (``ops/quantize.py``): the identity
    keeps both paths exactly as before (bitwise contract intact); a lossy
    codec routes FlexTree axes through ``compressed_allreduce`` with
    ``step`` keying the deterministic stochastic rounding.  ``"psum"``
    sentinel axes stay native f32 — compression is a FlexTree property.
    ``return_residual=True`` additionally returns the per-leaf input-
    quantization residual for error feedback: the wire-exact residual of
    the first compressed axis (the one that sees this rank's local data),
    or the canonical ``x - C(x)`` when the first synced axis is exact.
    """
    from ..ops.quantize import get_codec
    from .allreduce import _NATIVE_PSUM
    from .compressed import compressed_allreduce, local_residual

    codec = get_codec(codec)
    if bucket_bytes != 0:
        return bucketed_sync_grads(
            grads, pspecs, mesh_axes, topos,
            bucket_bytes=bucket_bytes, chunks=chunks,
            codec=codec, step=step, return_residual=return_residual,
        )

    def sync(g, spec):
        res = None
        for k, ax in enumerate(replication_key(spec, mesh_axes)):
            topo = topos[ax]
            if topo is None:
                g = _NATIVE_PSUM(g, ax)
            elif not codec.lossy:
                g = allreduce(g, ax, topo=topo, op="sum", chunks=chunks)
            elif k == 0:
                # only the FIRST axis sees this rank's local data, so only
                # its wire residual has per-rank EF semantics: a residual
                # taken after an exact psum axis would be replicated over
                # that axis and re-injected once PER RANK next step,
                # over-counting by the axis size.  Later-axis (and
                # post-psum) losses fall back to the canonical residual —
                # same rule as the bucketed path.
                g, res = compressed_allreduce(
                    g, ax, topo=topo, codec=codec, chunks=chunks, step=step,
                    return_residual=True,
                )
            else:
                g = compressed_allreduce(
                    g, ax, topo=topo, codec=codec, chunks=chunks, step=step
                )
        return g, res

    flat_g, treedef = jax.tree.flatten(grads)
    flat_s = treedef.flatten_up_to(pspecs)
    synced, residuals = [], []
    for g, spec in zip(flat_g, flat_s):
        out, res = sync(g, spec)
        synced.append(out)
        if return_residual:
            residuals.append(
                res if res is not None else local_residual(g, codec, step)
            )
    out_tree = treedef.unflatten(synced)
    if return_residual:
        return out_tree, treedef.unflatten(residuals)
    return out_tree


def sync_with_feedback(state, grads, pspecs, mesh_axes, topos, train_cfg):
    """The train-step gradient sync under ``train_cfg``: identity codec ->
    the plain (bitwise) sync and ``None``; lossy codec -> error-feedback
    sync — add the carried residual, sync ``grad + ef`` compressed, return
    the new residual (what the wire's input quantization lost) for the
    caller to store back into ``state['ef']``.  Shared by the dense,
    pipeline and MoE steps so their EF accounting cannot diverge."""
    codec = _sync_codec(train_cfg)
    if not codec.lossy:
        return (
            sync_grads(
                grads, pspecs, mesh_axes, topos,
                bucket_bytes=train_cfg.bucket_bytes,
                chunks=train_cfg.grad_chunks,
            ),
            None,
        )
    v = jax.tree.map(lambda g, e: g + e.astype(g.dtype), grads, state["ef"])
    return sync_grads(
        v, pspecs, mesh_axes, topos,
        bucket_bytes=train_cfg.bucket_bytes, chunks=train_cfg.grad_chunks,
        codec=codec, step=state["step"], return_residual=True,
    )


def maybe_autotune_grad_topo(
    mesh: Mesh, model_cfg, train_cfg: "TrainConfig", axis_names,
    init_fn=None,
) -> "TrainConfig":
    """Resolve the gradient-sync topology by *measurement* when
    ``train_cfg.autotune`` is set and no explicit ``grad_topo`` was given.

    Host-level (runs once at step-build time, never inside the trace):
    for each mesh axis with size > 1, time the analytic top-K candidates
    for the model's total parameter bytes under the configured codec
    (``planner.autotune.autotune_plan``) and pin the measured winner into
    ``grad_topo``.  Results persist in the ``FLEXTREE_PLAN_CACHE`` plan
    cache, so rebuilding the step (or re-running the trainer) is a pure
    cache hit; axes with equal size share one cache entry by construction.
    """
    if not train_cfg.autotune or train_cfg.grad_topo is not None:
        return train_cfg
    from ..planner.autotune import autotune_plan

    if init_fn is None:
        init_fn = init_params  # dense; pipeline/MoE builders pass theirs
    shapes = jax.eval_shape(
        lambda k: init_fn(k, model_cfg), jax.random.PRNGKey(0)
    )
    nbytes = sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(shapes))
    spec: dict = {}
    for ax in axis_names:
        n = int(mesh.shape[ax])
        if n <= 1:
            continue
        plan = autotune_plan(
            n, nbytes, dtype="float32", codecs=(train_cfg.codec,), top_k=3,
            repeat=3, overlap=train_cfg.overlap,
            sharded=train_cfg.shard_optimizer,
            # the train sync seam executes legacy topologies only (see
            # resolve_axis_topos): never offer the measured search a
            # winner the step builder would have to refuse
            ir_families=(),
        )
        spec[ax] = plan.to_ft_topo()
    return dataclasses.replace(train_cfg, grad_topo=spec, autotune=False)


def schedule_lr(train_cfg: "TrainConfig", step):
    """Learning rate at (1-based) ``step`` under the config's schedule.

    "constant": ``lr``.  "warmup_cosine": linear 0 -> lr over
    ``warmup_steps``, then cosine from lr down to ``min_lr_frac * lr`` at
    ``total_steps`` (flat at the floor beyond).  Pure jnp on a traced
    step, so it lives inside the jitted train step.
    """
    if train_cfg.schedule == "constant":
        return jnp.float32(train_cfg.lr)
    if train_cfg.schedule != "warmup_cosine":
        raise ValueError(f"unknown schedule {train_cfg.schedule!r}")
    if train_cfg.total_steps <= 0:
        raise ValueError("schedule='warmup_cosine' needs total_steps > 0")
    t = step.astype(jnp.float32) if hasattr(step, "astype") else jnp.float32(step)
    warm = jnp.float32(train_cfg.warmup_steps)
    ramp = jnp.minimum(t / jnp.maximum(warm, 1.0), 1.0)
    span = jnp.float32(max(train_cfg.total_steps - train_cfg.warmup_steps, 1))
    frac = jnp.clip((t - warm) / span, 0.0, 1.0)
    floor = jnp.float32(train_cfg.min_lr_frac)
    cos = floor + (1.0 - floor) * 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
    return jnp.float32(train_cfg.lr) * ramp * jnp.where(t <= warm, 1.0, cos)


def global_grad_norm(grads, pspecs):
    """True global L2 norm of a sharded gradient tree.

    A leaf holds only this device's shard along every mesh axis its
    PartitionSpec names — its square-sum psums over exactly those axes
    before joining the total; axes NOT in the spec see the leaf
    replicated, where a psum would overcount by the axis size.  (After
    ``sync_grads``, gradients are replicated across data axes, which
    never appear in param specs — so the rule is uniform across the
    dense, pipeline, and MoE steps: tp-column shards, pp stage stacks,
    and ep expert shards all sum once each.)  Leaves are grouped by
    their axis-set and each group's local total psums ONCE per set
    (psum is linear) — 2-3 scalar collectives per step, not one per leaf.
    """
    flat_g, treedef = jax.tree.flatten(grads)
    flat_s = treedef.flatten_up_to(pspecs)
    by_axes: dict[tuple, Any] = {}
    for g, spec in zip(flat_g, flat_s):
        sq = jnp.sum(jnp.square(g.astype(jnp.float32)))
        key = spec_axes(spec)
        by_axes[key] = by_axes.get(key, jnp.float32(0.0)) + sq
    total = jnp.float32(0.0)
    for axes, sq in by_axes.items():
        for axis in axes:
            sq = lax.psum(sq, axis)
        total = total + sq
    return jnp.sqrt(total)


def clip_by_global_norm(grads, norm, clip: float):
    """Scale the tree so its global norm is at most ``clip`` (> 0)."""
    if clip <= 0:
        raise ValueError(f"clip must be positive, got {clip}")
    scale = jnp.minimum(1.0, clip / jnp.maximum(norm, 1e-12))
    return jax.tree.map(lambda g: g * scale.astype(g.dtype), grads)


def maybe_clip_grads(grads, pspecs, train_cfg: "TrainConfig", metrics: dict):
    """Shared clip-and-record step for every train-step builder: when
    ``grad_clip_norm`` is set (must be positive), clips ``grads`` to it
    and records the pre-clip norm in ``metrics['grad_norm']``."""
    if not train_cfg.grad_clip_norm:
        return grads
    if train_cfg.grad_clip_norm < 0:
        raise ValueError(
            f"grad_clip_norm must be positive, got {train_cfg.grad_clip_norm}"
        )
    norm = global_grad_norm(grads, pspecs)
    metrics["grad_norm"] = norm
    return clip_by_global_norm(grads, norm, train_cfg.grad_clip_norm)


def metric_specs(train_cfg: "TrainConfig", base: dict) -> dict:
    """Out-specs for a step's metrics dict: ``base`` plus the clip norm
    when clipping is on — must mirror :func:`maybe_clip_grads`."""
    out = dict(base)
    if train_cfg.grad_clip_norm:
        out["grad_norm"] = P()
    return out


def adamw_apply(state: dict, grads, train_cfg: "TrainConfig") -> dict:
    """One AdamW update on (sharded) state; moments shard like the params."""
    step = state["step"] + 1
    t = step.astype(jnp.float32)
    c1 = 1.0 - train_cfg.b1**t
    c2 = 1.0 - train_cfg.b2**t
    lr = schedule_lr(train_cfg, step)

    def upd(p, g, mu, nu):
        mu = train_cfg.b1 * mu + (1.0 - train_cfg.b1) * g
        nu = train_cfg.b2 * nu + (1.0 - train_cfg.b2) * (g * g)
        delta = (mu / c1) / (jnp.sqrt(nu / c2) + train_cfg.eps)
        if train_cfg.weight_decay:
            delta = delta + train_cfg.weight_decay * p
        return p - lr * delta, mu, nu

    flat_p, treedef = jax.tree.flatten(state["params"])
    flat_g = treedef.flatten_up_to(grads)
    flat_mu = treedef.flatten_up_to(state["mu"])
    flat_nu = treedef.flatten_up_to(state["nu"])
    out = [upd(p, g, m, v) for p, g, m, v in zip(flat_p, flat_g, flat_mu, flat_nu)]
    return {
        "params": treedef.unflatten([o[0] for o in out]),
        "mu": treedef.unflatten([o[1] for o in out]),
        "nu": treedef.unflatten([o[2] for o in out]),
        "step": step,
    }


def make_train_step(
    mesh: Mesh,
    model_cfg: TransformerConfig,
    train_cfg: TrainConfig = TrainConfig(),
    axis_names: tuple[str, str, str] = ("dp", "sp", "tp"),
    serialize_overlap: bool = False,
):
    """Build the jitted full train step ``(state, tokens, targets) ->
    (state, metrics)``.

    ``tokens``/``targets``: (B, T) int32, batch sharded over dp, sequence
    over sp.  ``metrics``: {'loss': global mean token loss}.

    ``serialize_overlap`` (with ``train_cfg.overlap``) builds the
    serialized TWIN of the overlapped step: the identical program with a
    full-backward ``optimization_barrier`` before the first sync
    collective — the bench/verifier comparator (equal collective counts,
    bitwise-equal results) and the ``overlap-serialization`` mutant.
    """
    dp, sp, tp = axis_names
    for a in axis_names:
        if a not in mesh.shape:
            raise ValueError(f"mesh is missing axis {a!r}; has {mesh.axis_names}")
    validate_tp(model_cfg, mesh.shape[tp])
    train_cfg = maybe_autotune_grad_topo(
        mesh, model_cfg, train_cfg, axis_names
    )

    sspecs = state_specs(
        model_cfg, tp, train_cfg, mesh=mesh, axis_names=axis_names
    )
    data_spec = P(dp, sp)
    mesh_axes = axis_names
    zero_layout = None
    if train_cfg.shard_optimizer:
        shapes = jax.eval_shape(
            lambda k: init_params(k, model_cfg), jax.random.PRNGKey(0)
        )
        zero_layout = zero_layout_for(
            mesh, shapes, sspecs["params"], axis_names
        )

    def device_step(state, tokens, targets):
        n_total_tokens = (
            tokens.size
            * lax.axis_size(dp)
            * lax.axis_size(sp)
            * lax.axis_size(tp)  # tp-fold redundancy, see module docstring
        )

        topos = resolve_axis_topos(mesh, mesh_axes, train_cfg.grad_topo)
        if train_cfg.overlap:
            from .overlap import dense_overlap_step_grads

            loss, grads, new_ef = dense_overlap_step_grads(
                state, tokens, targets, model_cfg, train_cfg,
                sspecs["params"], mesh_axes, topos, n_total_tokens,
                tp_axis=tp, sp_axis=sp, serialize=serialize_overlap,
                zero_layout=zero_layout,
            )
        else:

            def local_loss(params):
                logits = forward(
                    params, tokens, model_cfg, tp_axis=tp, sp_axis=sp
                )
                loss_sum, _ = cross_entropy_loss(logits, targets)
                return loss_sum / n_total_tokens

            loss, grads = jax.value_and_grad(local_loss)(state["params"])
            if not train_cfg.shard_optimizer:
                grads, new_ef = sync_with_feedback(
                    state, grads, sspecs["params"], mesh_axes, topos, train_cfg
                )
            else:
                new_ef = None  # the zero path carries EF itself
        global_loss = lax.psum(lax.psum(lax.psum(loss, dp), sp), tp)

        metrics = {"loss": global_loss}
        if train_cfg.shard_optimizer:
            from .zero import (
                maybe_clip_shards,
                zero_apply_and_gather,
                zero_sync_and_update,
            )

            if train_cfg.overlap:
                # the engine already reduce-scattered per fired bucket;
                # grads is a tree of ZeroShard (and new_ef the residuals)
                shard_tree = maybe_clip_shards(
                    grads, sspecs["params"], train_cfg, zero_layout, metrics
                )
                new_state = zero_apply_and_gather(
                    state, shard_tree, sspecs["params"], mesh_axes, topos,
                    train_cfg, zero_layout,
                )
                if new_ef is not None:
                    new_state["ef"] = new_ef
            else:
                new_state = zero_sync_and_update(
                    state, grads, sspecs["params"], mesh_axes, topos,
                    train_cfg, zero_layout, metrics,
                )
        else:
            grads = maybe_clip_grads(grads, sspecs["params"], train_cfg, metrics)
            new_state = adamw_apply(state, grads, train_cfg)
            if new_ef is not None:
                new_state["ef"] = new_ef
        return new_state, metrics

    mspec = metric_specs(train_cfg, {"loss": P()})
    sharded = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(sspecs, data_spec, data_spec),
        out_specs=(sspecs, mspec),
        check_vma=False,
    )
    return jax.jit(sharded)
