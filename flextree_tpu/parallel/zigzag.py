"""Zigzag (load-balanced) causal ring attention.

The plain causal ring (``ring_attention``) is SPMD-lockstep: at every hop
some device still faces a fully-visible K/V block, so the ring's wall time
is ~n full block-attentions even though half the score matrix is masked.
The classic fix is the **zigzag layout**: split the global sequence into
``2n`` chunks and give device ``i`` the pair ``(i, 2n-1-i)`` — one early
chunk and one late chunk.  Under causal masking every device then owns the
same visible work at every hop (one full chunk-pair: the early-vs-early
and late-vs-late pairs trade visibility as the ring rotates, and the
late-q-vs-early-k pair is always visible), so the ring finishes in
roughly half the wall time at identical math.

Everything here is collective-context (call inside ``shard_map`` with the
sequence axis bound), like the rest of this package.  The layout
converters move chunks with ``lax.ppermute`` (ICI neighbor DMAs — the same
transport primitive as the ring itself; no all-gather, so per-device
memory stays O(T/n)).  Chunk pairs are size-aligned, so each (q-chunk,
kv-chunk) block is *exactly* one of future / diagonal / past — the same
3-way ``lax.switch`` the flash ring uses (``_ring_attention_flash``),
never a partially-shifted mask.

Reference relation: the reference has no model layer (SURVEY §2.6); this
extends the framework's sequence-parallel substrate (``ring_attention``,
``ulysses``) with the balanced schedule long-context training actually
uses.  The ring transport itself is the ``mpi_mod.hpp:1119-1147``
decrementing block walk, unchanged.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["zigzag_split", "zigzag_merge", "zigzag_ring_attention"]

_NEG_INF = -1e30


def _owner(g: int, n: int) -> int:
    """Zigzag owner of global chunk ``g``: device ``g`` for the early half,
    device ``2n-1-g`` for the late half."""
    return g if g < n else 2 * n - 1 - g


def zigzag_split(x, axis_name):
    """Contiguous sequence shards -> zigzag shards, via two ppermutes.

    ``x``: (B, T_local, ...) with the global sequence = concatenation of
    shards in axis-index order (device ``i`` holds chunks ``(2i, 2i+1)``);
    T_local must be even.  Returns the same shape holding chunks
    ``(idx, 2n-1-idx)``.  Both ppermutes are bijections: a device's two
    chunks have opposite parity, and so do a zigzag owner's — each device
    sends and receives exactly one chunk per permute.
    """
    n = lax.axis_size(axis_name)
    t_local = x.shape[1]
    if t_local % 2:
        raise ValueError(f"zigzag needs an even local length, got {t_local}")
    if n == 1:
        return x
    c = t_local // 2
    idx = lax.axis_index(axis_name)
    perm_even = [(i, _owner(2 * i, n)) for i in range(n)]
    perm_odd = [(i, _owner(2 * i + 1, n)) for i in range(n)]
    recv_even = lax.ppermute(x[:, :c], axis_name, perm_even)  # chunk 2*src
    recv_odd = lax.ppermute(x[:, c:], axis_name, perm_odd)    # chunk 2*src+1
    # this device's early chunk is g=idx (even iff idx is even); its late
    # chunk 2n-1-idx has the opposite parity
    early_is_even = idx % 2 == 0
    early = jnp.where(early_is_even, recv_even, recv_odd)
    late = jnp.where(early_is_even, recv_odd, recv_even)
    return jnp.concatenate([early, late], axis=1)


def zigzag_merge(x, axis_name):
    """Inverse of :func:`zigzag_split` (zigzag shards -> contiguous).

    Two parity-separated ppermute rounds: every device holds exactly one
    even-numbered and one odd-numbered chunk, and every contiguous owner
    ``i`` expects exactly one of each (``2i``, ``2i+1``) — both rounds are
    bijections.
    """
    n = lax.axis_size(axis_name)
    t_local = x.shape[1]
    if t_local % 2:
        raise ValueError(f"zigzag needs an even local length, got {t_local}")
    if n == 1:
        return x
    c = t_local // 2
    idx = lax.axis_index(axis_name)
    early_is_even = idx % 2 == 0

    # device j holds chunks g_early=j (slot 0) and g_late=2n-1-j (slot 1)
    def even_chunk_of(j):
        return j if j % 2 == 0 else 2 * n - 1 - j

    def odd_chunk_of(j):
        return j if j % 2 == 1 else 2 * n - 1 - j

    perm_e = [(j, even_chunk_of(j) // 2) for j in range(n)]
    perm_o = [(j, odd_chunk_of(j) // 2) for j in range(n)]
    send_e = jnp.where(early_is_even, x[:, :c], x[:, c:])
    send_o = jnp.where(early_is_even, x[:, c:], x[:, :c])
    recv_e = lax.ppermute(send_e, axis_name, perm_e)  # lands as chunk 2i
    recv_o = lax.ppermute(send_o, axis_name, perm_o)  # lands as chunk 2i+1
    return jnp.concatenate([recv_e, recv_o], axis=1)


def hop_branches(src, idx):
    """Visibility branch selection for one hop, shared by the kernel and
    the balance test: for the visiting source ``src`` and this device
    ``idx``, returns ``(br_early, br_late)`` with 0=diagonal, 1=past
    (full), 2=future (masked) — the early pair compares chunk ``src`` vs
    ``idx``, the late pair ``2n-1-src`` vs ``2n-1-idx`` (order flips)."""
    br_e = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
    br_l = jnp.where(src == idx, 0, jnp.where(src > idx, 1, 2))
    return br_e, br_l


def zigzag_ring_attention(q, k, v, axis_name, *, scale: float | None = None,
                          layout: str = "contiguous", impl: str = "flash"):
    """Causal exact attention, sequence-parallel, load-balanced.

    ``q``/``k``/``v``: (B, T_local, H, D).  ``layout="contiguous"`` (the
    trainer's natural sharding) converts in and out with
    :func:`zigzag_split`/:func:`zigzag_merge`; ``layout="zigzag"`` expects
    and returns zigzag shards (zero conversion cost — a model can stay in
    zigzag layout end-to-end, since every other transformer op is
    position-elementwise along the sequence).

    Causal only — the balance argument is about the causal triangle; use
    ``ring_attention`` for non-causal.  ``impl``: "flash" (fused Pallas
    chunk kernels) or "reference" (jnp full-matrix chunk blocks — the CPU
    oracle path).
    """
    from ..ops.pallas_attention import flash_attention
    from .ring_attention import (
        attention_reference,
        hop_finalize,
        hop_merge,
        varying_zeros,
    )

    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    if impl not in ("flash", "reference"):
        raise ValueError(f"unknown attention impl {impl!r}")
    n = lax.axis_size(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    local = (
        (lambda q, k, v, causal: flash_attention(
            q, k, v, causal=causal, scale=scale, return_lse=True))
        if impl == "flash"
        else (lambda q, k, v, causal: attention_reference(
            q, k, v, causal=causal, scale=scale, return_lse=True))
    )
    if n == 1:
        # no split at n=1 — odd local lengths are fine here
        if impl == "flash":
            return flash_attention(q, k, v, causal=True, scale=scale)
        return attention_reference(q, k, v, causal=True, scale=scale)
    if t_local % 2:
        # validate on the zigzag-layout path too (it never calls
        # zigzag_split); an odd length would otherwise die as a branch
        # shape mismatch deep inside lax.switch
        raise ValueError(f"zigzag needs an even local length, got {t_local}")
    if layout == "contiguous":
        # one split for all three tensors: batch-concatenate so the layout
        # exchange is 2 ppermutes moving 3x payload, not 6 latency-bound
        # launches per attention call
        qkv = zigzag_split(jnp.concatenate([q, k, v], axis=0), axis_name)
        q, k, v = qkv[:b], qkv[b:2 * b], qkv[2 * b:]
    c = t_local // 2
    idx = lax.axis_index(axis_name)

    def full_hop(qb, kb, vb):
        return local(qb, kb, vb, False)

    def diag_hop(qb, kb, vb):
        # chunk-aligned: equal global offsets cancel, offset-0 causal exact
        return local(qb, kb, vb, True)

    def masked_hop(qb, kb, vb):
        # derive both outputs from qb so they inherit its varying manual
        # axes — a bare jnp.full constant is unvarying and fails the
        # enclosing shard_map's vma check against the other switch branches.
        # varying_zeros, not qb*0: the hop must contribute exact zeros even
        # when qb carries an injected NaN/Inf (ADVICE r5)
        return (
            varying_zeros(qb),
            varying_zeros(qb[..., 0], jnp.float32) + _NEG_INF,
        )

    q_e, q_l = q[:, :c], q[:, c:]
    right = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, s):
        k_blk, v_blk, acc_e, acc_l = carry
        src = (idx - s) % n
        k_e, k_l = k_blk[:, :c], k_blk[:, c:]
        v_e, v_l = v_blk[:, :c], v_blk[:, c:]
        # visiting early chunk g=src vs our early chunk g=idx; late chunk
        # 2n-1-src vs our late 2n-1-idx (comparison flips) — hop_branches
        br_e, br_l = hop_branches(src, idx)
        acc_e = hop_merge(
            acc_e,
            *lax.switch(br_e, [diag_hop, full_hop, masked_hop], q_e, k_e, v_e),
        )
        acc_l = hop_merge(
            acc_l,
            *lax.switch(br_l, [diag_hop, full_hop, masked_hop], q_l, k_l, v_l),
        )
        # our late chunk always sees the visiting EARLY chunk (2n-1-idx >=
        # n > src): statically full, no switch.  (Our early chunk never
        # sees a late chunk: 2n-1-src >= n > idx — statically skipped.)
        acc_l = hop_merge(acc_l, *full_hop(q_l, k_e, v_e))
        k_blk = lax.ppermute(k_blk, axis_name, right)
        v_blk = lax.ppermute(v_blk, axis_name, right)
        return (k_blk, v_blk, acc_e, acc_l), None

    def init_acc(qb):
        zero_bth = (qb[..., 0] * 0).astype(jnp.float32)  # inherit vma axes
        return (zero_bth + _NEG_INF, (qb * 0).astype(jnp.float32), zero_bth)

    (k, v, acc_e, acc_l), _ = lax.scan(
        step, (k, v, init_acc(q_e), init_acc(q_l)), jnp.arange(n)
    )
    out = jnp.concatenate(
        [hop_finalize(acc_e), hop_finalize(acc_l)], axis=1
    ).astype(q.dtype)
    if layout == "contiguous":
        out = zigzag_merge(out, axis_name)
    return out
