"""Ulysses sequence parallelism: all-to-all head/sequence re-sharding.

The second of the framework's two long-context strategies (the first is
``ring_attention``).  Where ring attention keeps queries resident and walks
K/V around the ring — communication O(n) neighbor hops overlapping compute —
Ulysses re-shards *once* per direction: an all-to-all converts the layout
from sequence-sharded/(all heads) to head-sharded/(full sequence), exact
attention runs locally over the full sequence, and a second all-to-all
restores the sequence-sharded layout.  On a TPU mesh ``lax.all_to_all``
lowers to a single XLA AllToAll over ICI, so the whole exchange is two
collectives regardless of sequence length — the better trade when the head
count comfortably covers the axis and the sequence is long enough that the
ring's n-step latency chain dominates.

This is the all-to-all counterpart of the reference's configurable-topology
idea (``allreduce_over_mpi/mpi_mod.hpp:882-929``): the same computation,
parameterized by *which* communication schedule realizes it; callers pick
per workload (``flextree_tpu.models.transformer.TransformerConfig.sp_impl``).

Collective-context functions: call inside ``shard_map`` with the sequence
axis bound, like ``lax.psum``.  Differentiable — ``all_to_all`` transposes
to the inverse all-to-all, so gradients re-shard exactly.
"""

from __future__ import annotations

import jax
from jax import lax

from .ring_attention import local_attention

__all__ = ["ulysses_attention", "seq_to_heads", "heads_to_seq"]


def seq_to_heads(x, axis_name):
    """(B, T/n, H, D) sequence-sharded -> (B, T, H/n, D) head-sharded.

    One ``lax.all_to_all`` over ``axis_name``: splits the head axis into
    ``n`` groups, concatenates the sequence shards — afterwards each device
    holds the *full* sequence for ``H/n`` of the heads.
    """
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    if x.shape[2] % n:
        raise ValueError(
            f"Ulysses needs heads ({x.shape[2]}) divisible by the sequence "
            f"axis size ({n}); use ring attention for head-poor models"
        )
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def heads_to_seq(x, axis_name):
    """Inverse of :func:`seq_to_heads`: back to sequence-sharded layout."""
    n = lax.axis_size(axis_name)
    if n == 1:
        return x
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, axis_name, *, causal: bool = True,
                      scale: float | None = None, impl: str = "reference",
                      **flash_kwargs):
    """Exact attention with sequence sharded over ``axis_name``.

    Same contract as ``ring_attention``: ``q``/``k``/``v`` are
    (B, T_local, H, D) sequence shards (global sequence = concatenation over
    the axis in index order), result is the (B, T_local, H, D) attention
    output for the local queries in ``q``'s dtype.  Requires ``H`` divisible
    by the axis size.  Causality falls out naturally: after the re-shard the
    full sequence is local, so the plain causal mask is already global.

    ``impl``: the local attention compute — "reference" (jnp full matrix)
    or "flash" (the fused Pallas kernel, ``ops.pallas_attention``; the
    enclosing ``shard_map`` must pass ``check_vma=False`` because
    ``pallas_call`` outputs carry no varying-mesh-axes type).
    ``flash_kwargs`` (block_q / block_k / variant, ...) forward to the
    inner :func:`local_attention` — the re-shard makes it a
    full-sequence-local call, so a tuned flash config applies here just
    like on the unsharded path (rejected for non-flash impls).
    """
    with jax.named_scope("ulysses_seq2head"):
        qh = seq_to_heads(q, axis_name)
        kh = seq_to_heads(k, axis_name)
        vh = seq_to_heads(v, axis_name)
    with jax.named_scope("ulysses_local_attn"):
        out = local_attention(qh, kh, vh, causal=causal, scale=scale,
                              impl=impl, **flash_kwargs)
    with jax.named_scope("ulysses_head2seq"):
        return heads_to_seq(out, axis_name)
