"""JAX/XLA collective backend + parallelism strategies.

Collectives (transport + algorithm layers, TPU-native), ring-attention
sequence parallelism, and the dp/sp/tp sharded training step.
"""

from .allreduce import (
    all_gather,
    allgather,
    allreduce,
    lonely_allreduce,
    reduce_scatter,
    ring_allreduce,
    tree_allreduce,
)
from .launch import (
    BringupConfigError,
    BringupError,
    BringupReport,
    BringupTimeout,
    ClusterConfig,
    dcn_axis_names,
    flatten_mesh,
    hybrid_mesh,
    init_distributed,
    init_distributed_or_degrade,
    plan_for_mesh,
    topology_for_hybrid,
)
from .bucketing import (
    Bucket,
    bucketed_sync_grads,
    plan_buckets,
    replication_key,
    spec_axes,
)
from .compressed import compressed_allreduce, local_residual
from .mesh import allreduce_over_mesh, flat_mesh, topology_from_mesh
from .ring_attention import attention_reference, local_attention, ring_attention
from .ulysses import heads_to_seq, seq_to_heads, ulysses_attention
from .zigzag import zigzag_merge, zigzag_ring_attention, zigzag_split

__all__ = [
    "allreduce",
    "tree_allreduce",
    "lonely_allreduce",
    "ring_allreduce",
    "reduce_scatter",
    "all_gather",
    "allgather",
    "allreduce_over_mesh",
    "flat_mesh",
    "topology_from_mesh",
    "ClusterConfig",
    "init_distributed",
    "init_distributed_or_degrade",
    "BringupError",
    "BringupConfigError",
    "BringupTimeout",
    "BringupReport",
    "hybrid_mesh",
    "flatten_mesh",
    "dcn_axis_names",
    "plan_for_mesh",
    "topology_for_hybrid",
    "ring_attention",
    "attention_reference",
    "local_attention",
    "ulysses_attention",
    "zigzag_ring_attention",
    "zigzag_split",
    "zigzag_merge",
    "seq_to_heads",
    "heads_to_seq",
    "TrainConfig",
    "factor_devices",
    "init_train_state",
    "make_mesh_3d",
    "make_train_step",
    "state_specs",
    "resolve_axis_topos",
    "sync_grads",
    "adamw_apply",
    "schedule_lr",
    "global_grad_norm",
    "clip_by_global_norm",
    "Bucket",
    "plan_buckets",
    "bucketed_sync_grads",
    "replication_key",
    "spec_axes",
]

# Lazy (PEP 562): .train/.pipeline import ..models.transformer, which
# imports .allreduce from this package — importing them eagerly here would
# close that loop into a circular import for any models-first import order.
_TRAIN_EXPORTS = (
    "TrainConfig",
    "factor_devices",
    "init_train_state",
    "make_mesh_3d",
    "make_train_step",
    "state_specs",
    "resolve_axis_topos",
    "sync_grads",
    "adamw_apply",
    "schedule_lr",
    "global_grad_norm",
    "clip_by_global_norm",
)

_PIPELINE_EXPORTS = (
    "stack_layer_params",
    "unstack_layer_params",
    "pipeline_param_specs",
    "pipeline_state_specs",
    "init_pipeline_train_state",
    "make_pipeline_train_step",
    "make_mesh_4d",
    "factor_devices_4d",
)

_MOE_EXPORTS = (
    "init_moe_train_state",
    "moe_state_specs",
    "make_moe_train_step",
    "make_mesh_moe",
    "factor_devices_moe",
)

__all__ += list(_PIPELINE_EXPORTS) + list(_MOE_EXPORTS)


def __getattr__(name):
    if name in _TRAIN_EXPORTS:
        from . import train

        return getattr(train, name)
    if name in _PIPELINE_EXPORTS:
        from . import pipeline

        return getattr(pipeline, name)
    if name in _MOE_EXPORTS:
        from . import moe_train

        return getattr(moe_train, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
