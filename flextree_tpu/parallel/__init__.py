"""JAX/XLA collective backend: the TPU-native transport + algorithm layers."""

from .allreduce import allgather, allreduce, reduce_scatter, ring_allreduce, tree_allreduce
from .mesh import allreduce_over_mesh, flat_mesh, topology_from_mesh

__all__ = [
    "allreduce",
    "tree_allreduce",
    "ring_allreduce",
    "reduce_scatter",
    "allgather",
    "allreduce_over_mesh",
    "flat_mesh",
    "topology_from_mesh",
]
