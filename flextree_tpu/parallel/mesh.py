"""Host-level convenience wrappers: run FlexTree collectives over a Mesh.

The reference's standalone entry point takes per-rank buffers already living
on N processes (``benchmark.cpp:119-153``); the JAX analog is a stacked
``(N, ...)`` array laid out one row per device, reduced under ``shard_map``.
Also provides torus-aware topology selection: on a real TPU slice the stage
widths should factor along physical mesh axes (SURVEY §7 "hard parts").
"""

from __future__ import annotations

import functools
import math

import jax
from jax.sharding import Mesh, PartitionSpec as P

from ..schedule.ir import resolve_collective
from ..schedule.stages import Topology
from .allreduce import allreduce

__all__ = ["allreduce_over_mesh", "topology_from_mesh", "flat_mesh"]


def flat_mesh(n_devices: int | None = None, axis_name: str = "ft") -> Mesh:
    """A 1-D mesh over the first ``n_devices`` local devices."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    if not 1 <= n <= len(devs):
        raise ValueError(f"asked for {n} devices, have {len(devs)}")
    return jax.make_mesh((n,), (axis_name,), devices=devs[:n])


def topology_from_mesh(mesh: Mesh, axis_name=None) -> Topology:
    """Derive stage widths from the mesh's physical shape.

    A multi-axis mesh maps naturally onto hierarchical stages: one stage per
    mesh axis, width = axis size — e.g. a (4, 2) mesh gives widths ``(4, 2)``,
    so each stage's groups ride one torus axis.  For a 1-D mesh this
    degenerates to flat.  This is the TPU retarget of the planner's role:
    factoring N *along torus axes* rather than abstractly.
    """
    if axis_name is not None:
        names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        widths = tuple(mesh.shape[a] for a in names)
        n = math.prod(widths)
    else:
        widths = tuple(mesh.shape[a] for a in mesh.axis_names)
        n = mesh.size
    if n == 1:
        return Topology.flat(1)
    widths = tuple(w for w in widths if w > 1) or (n,)
    return Topology(n, widths)


def allreduce_over_mesh(
    stacked, mesh: Mesh, topo=None, op="sum", axis_name=None, in_place: bool = False
):
    """Allreduce a stacked ``(N, ...)`` array: row ``i`` lives on device ``i``
    of ``mesh``'s ``axis_name`` axis; every output row is the full reduction.

    This is the host-side harness the benchmark and tests use — the analog of
    the reference benchmark calling ``MPI_Allreduce_FT`` on each rank's local
    buffer (``benchmark.cpp:153``).

    ``in_place=True`` donates ``stacked`` to the computation — the analog of
    the reference's ``MPI_IN_PLACE`` path (``mpi_mod.hpp:1193-1215``; the
    reference benchmark always runs in-place, ``benchmark.cpp:153``).  The
    caller's array is consumed; XLA reuses its buffer for the output, which
    removes the output allocation + copy from the hot path.
    """
    axis = axis_name or mesh.axis_names[0]
    n = mesh.shape[axis]
    if stacked.shape[0] != n:
        raise ValueError(
            f"stacked.shape[0]={stacked.shape[0]} must equal mesh axis {axis!r} size {n}"
        )
    # resolve through the widened front door so the IR families
    # ("swing", "gen:4,2@2", IRFamilySpec) work at the host level too
    topo = resolve_collective(n, topo)
    return _jitted_allreduce(
        mesh, axis, topo, op if isinstance(op, str) else op.name, in_place
    )(stacked)


@functools.lru_cache(maxsize=256)
def _jitted_allreduce(mesh: Mesh, axis: str, topo, op: str, donate: bool = False):
    """Cache the compiled collective per (mesh, axis, topo, op) so repeated
    host-level calls (benchmark loops) hit the jit cache instead of
    rebuilding a fresh closure every call."""

    def per_device(row):
        return allreduce(row[0], axis, topo, op)[None]

    return jax.jit(
        jax.shard_map(per_device, mesh=mesh, in_specs=P(axis), out_specs=P(axis)),
        donate_argnums=(0,) if donate else (),
    )
