"""Training loop driver: steps + checkpointing + logging + resume + recovery.

Composes the pieces the rest of the package provides — any of the three
train steps (dense dp/sp/tp, pipeline, MoE), the ``LMDataset`` batch
addressing, and the checkpoint subsystem — into the run loop a framework
user actually calls.  Resume is exact: the loop reads ``state['step']``
after restoring and continues with ``dataset.batch_at(step)``, so a run
interrupted at any step and resumed produces the same parameters as a
straight-through run (pinned by tests).

Crash safety (docs/FAILURE_MODEL.md): a NaN/Inf guard on the step metrics
skips anomalous steps (the update is discarded, the batch is not retried
this run), rewinds to the last verified checkpoint after
``max_bad_steps`` *consecutive* anomalies, and gives up with
:class:`TrainingDiverged` once ``max_rewinds`` rewinds have not cured the
divergence.  Restores go through ``restore_train_state``'s integrity
fallback, so a truncated newest checkpoint silently falls back one.  The
run's :class:`RunReport` (anomalies, skipped steps, rewinds, checkpoint
fallbacks) is returned on the :class:`FitResult` and, when a checkpoint
dir is configured, written there as ``RUN_REPORT.json`` — including when
the run dies with :class:`TrainingDiverged`, which is exactly when the
postmortem needs it.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, Callable

import jax
import numpy as np

from ..utils.checkpoint import (
    latest_checkpoint,
    restore_train_state,
    save_train_state,
)
from ..utils.logging import get_logger

__all__ = ["FitConfig", "FitResult", "RunReport", "TrainingDiverged", "fit"]

log = get_logger("flextree.train")


class TrainingDiverged(RuntimeError):
    """The NaN/Inf guard exhausted its recovery budget: ``max_bad_steps``
    consecutive anomalies with no checkpoint to rewind to, or
    ``max_rewinds`` rewinds that did not cure the divergence."""


@dataclasses.dataclass(frozen=True)
class FitConfig:
    num_steps: int = 100
    ckpt_dir: str | None = None
    ckpt_every: int = 100
    max_to_keep: int = 3
    log_every: int = 10
    resume: bool = True  # restore from ckpt_dir's latest checkpoint if any
    # background-prefetch depth (0 disables): batches are pulled this many
    # steps ahead on a daemon thread (``flextree_tpu.data.prefetch``) while
    # the current step runs on device
    prefetch: int = 2
    # NaN/Inf guard: skip steps whose loss (or grad_norm, when the step
    # reports one) is non-finite; after max_bad_steps CONSECUTIVE skips,
    # rewind to the last verified checkpoint; after max_rewinds rewinds
    # raise TrainingDiverged.  The check device_gets the metrics every
    # step, so it synchronizes host and device (on accelerators this
    # trades dispatch pipelining for catching the FIRST bad update before
    # it compounds); nan_guard=False restores the fail-fast async loop.
    nan_guard: bool = True
    max_bad_steps: int = 3
    max_rewinds: int = 2


@dataclasses.dataclass
class RunReport:
    """End-of-run accounting of everything the recovery machinery did."""

    anomalies: int = 0  # non-finite steps skipped
    skipped_steps: list = dataclasses.field(default_factory=list)
    rewinds: int = 0  # checkpoint rewinds after consecutive anomalies
    ckpt_fallbacks: int = 0  # corrupt checkpoints skipped during restore
    resumed_from: int = 0
    init_retries: int = 0  # bring-up attempts beyond the first (launch layer)

    def to_payload(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FitResult:
    state: Any
    losses: list  # (step, loss) pairs at log points
    steps_run: int
    resumed_from: int
    report: RunReport = dataclasses.field(default_factory=RunReport)


def _metrics_finite(metrics) -> bool:
    """Host-side finiteness of the guard metrics (loss + grad norm)."""
    for key in ("loss", "grad_norm"):
        if key in metrics:
            v = float(np.asarray(jax.device_get(metrics[key])))
            if not math.isfinite(v):
                return False
    return True


def _stamp_step(state: dict, step: int) -> dict:
    """A copy of ``state`` with ``state['step']`` set to ``step`` (keeps
    the step leaf the single source of truth when a step is skipped)."""
    import jax.numpy as jnp

    old = state["step"]
    new = dict(state)
    new["step"] = jnp.asarray(step, np.asarray(jax.device_get(old)).dtype)
    return new


def fit(
    state,
    step_fn: Callable,
    dataset,
    cfg: FitConfig = FitConfig(),
    *,
    mesh=None,
    state_specs=None,
) -> FitResult:
    """Run ``step_fn(state, tokens, targets) -> (state, metrics)`` for
    ``cfg.num_steps`` total steps over ``dataset`` (an ``LMDataset``).

    ``state['step']`` is the single source of truth for progress: batches
    are addressed by it, checkpoints are named by it, and resume reads it
    back.  Pass ``mesh``/``state_specs`` to restore sharded.
    """
    report = RunReport()

    def _fallback(bad_path, exc):
        report.ckpt_fallbacks += 1

    def _restore():
        return restore_train_state(
            cfg.ckpt_dir, mesh=mesh, specs=state_specs, on_fallback=_fallback
        )

    resumed_from = 0
    if cfg.resume and cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir):
        state = _restore()
        resumed_from = int(np.asarray(jax.device_get(state["step"])))
        report.resumed_from = resumed_from
        log.info("resumed from step %d (%s)", resumed_from, cfg.ckpt_dir)

    losses: list = []
    start = int(np.asarray(jax.device_get(state["step"])))
    t0 = time.perf_counter()
    step = start
    bad_streak = 0

    def _batches(from_step):
        if cfg.prefetch and from_step < cfg.num_steps and hasattr(dataset, "iter_from"):
            from ..data import prefetch as _prefetch

            return _prefetch(dataset.iter_from(from_step), size=cfg.prefetch)
        return None

    batches = _batches(start)
    try:
        while step < cfg.num_steps:
            tokens, targets = (
                next(batches) if batches is not None else dataset.batch_at(step)
            )
            new_state, metrics = step_fn(state, tokens, targets)
            if cfg.nan_guard and not _metrics_finite(metrics):
                report.anomalies += 1
                report.skipped_steps.append(step)
                bad_streak += 1
                log.warning(
                    "step %d: non-finite loss/grad (%d consecutive) — update skipped",
                    step, bad_streak,
                )
                if bad_streak >= cfg.max_bad_steps:
                    if not (cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir)):
                        raise TrainingDiverged(
                            f"{bad_streak} consecutive non-finite steps at step "
                            f"{step} and no checkpoint to rewind to"
                        )
                    if report.rewinds >= cfg.max_rewinds:
                        raise TrainingDiverged(
                            f"still diverging after {report.rewinds} rewinds "
                            f"(step {step})"
                        )
                    state = _restore()
                    report.rewinds += 1
                    bad_streak = 0
                    step = int(np.asarray(jax.device_get(state["step"])))
                    log.warning("rewound to checkpointed step %d", step)
                    batches = _batches(step)
                    continue
                # skip: discard the poisoned update, advance past the batch
                step += 1
                state = _stamp_step(state, step)
                continue
            state = new_state
            bad_streak = 0
            step += 1
            if cfg.log_every and (step % cfg.log_every == 0 or step == cfg.num_steps):
                loss = float(metrics["loss"])
                losses.append((step, loss))
                rate = (step - start) / (time.perf_counter() - t0)
                log.info("step %d loss %.4f (%.1f steps/s)", step, loss, rate)
            if cfg.ckpt_dir and cfg.ckpt_every and step % cfg.ckpt_every == 0:
                save_train_state(cfg.ckpt_dir, state, max_to_keep=cfg.max_to_keep)
        if cfg.ckpt_dir and step > start:
            save_train_state(cfg.ckpt_dir, state, max_to_keep=cfg.max_to_keep)
    finally:
        # the accounting matters MOST for runs that die (a TrainingDiverged
        # postmortem needs the anomaly/rewind trail) — write it regardless
        if cfg.ckpt_dir:
            os.makedirs(cfg.ckpt_dir, exist_ok=True)
            with open(os.path.join(cfg.ckpt_dir, "RUN_REPORT.json"), "w") as f:
                json.dump(report.to_payload(), f, indent=2, sort_keys=True)
    return FitResult(state, losses, step - start, resumed_from, report)
