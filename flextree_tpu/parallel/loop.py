"""Training loop driver: steps + checkpointing + logging + resume.

Composes the pieces the rest of the package provides — any of the three
train steps (dense dp/sp/tp, pipeline, MoE), the ``LMDataset`` batch
addressing, and the checkpoint subsystem — into the run loop a framework
user actually calls.  Resume is exact: the loop reads ``state['step']``
after restoring and continues with ``dataset.batch_at(step)``, so a run
interrupted at any step and resumed produces the same parameters as a
straight-through run (pinned by tests).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable

import jax
import numpy as np

from ..utils.checkpoint import latest_checkpoint, restore_train_state, save_train_state
from ..utils.logging import get_logger

__all__ = ["FitConfig", "FitResult", "fit"]

log = get_logger("flextree.train")


@dataclasses.dataclass(frozen=True)
class FitConfig:
    num_steps: int = 100
    ckpt_dir: str | None = None
    ckpt_every: int = 100
    max_to_keep: int = 3
    log_every: int = 10
    resume: bool = True  # restore from ckpt_dir's latest checkpoint if any
    # background-prefetch depth (0 disables): batches are pulled this many
    # steps ahead on a daemon thread (``flextree_tpu.data.prefetch``) while
    # the current step runs on device
    prefetch: int = 2


@dataclasses.dataclass
class FitResult:
    state: Any
    losses: list  # (step, loss) pairs at log points
    steps_run: int
    resumed_from: int


def fit(
    state,
    step_fn: Callable,
    dataset,
    cfg: FitConfig = FitConfig(),
    *,
    mesh=None,
    state_specs=None,
) -> FitResult:
    """Run ``step_fn(state, tokens, targets) -> (state, metrics)`` for
    ``cfg.num_steps`` total steps over ``dataset`` (an ``LMDataset``).

    ``state['step']`` is the single source of truth for progress: batches
    are addressed by it, checkpoints are named by it, and resume reads it
    back.  Pass ``mesh``/``state_specs`` to restore sharded.
    """
    resumed_from = 0
    if cfg.resume and cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir):
        state = restore_train_state(
            cfg.ckpt_dir, mesh=mesh, specs=state_specs
        )
        resumed_from = int(np.asarray(jax.device_get(state["step"])))
        log.info("resumed from step %d (%s)", resumed_from, cfg.ckpt_dir)

    losses: list = []
    start = int(np.asarray(jax.device_get(state["step"])))
    t0 = time.perf_counter()
    step = start
    batches = None
    if cfg.prefetch and start < cfg.num_steps and hasattr(dataset, "iter_from"):
        from ..data import prefetch as _prefetch

        batches = _prefetch(dataset.iter_from(start), size=cfg.prefetch)
    while step < cfg.num_steps:
        tokens, targets = next(batches) if batches is not None else dataset.batch_at(step)
        state, metrics = step_fn(state, tokens, targets)
        step += 1
        if cfg.log_every and (step % cfg.log_every == 0 or step == cfg.num_steps):
            loss = float(metrics["loss"])
            losses.append((step, loss))
            rate = (step - start) / (time.perf_counter() - t0)
            log.info("step %d loss %.4f (%.1f steps/s)", step, loss, rate)
        if cfg.ckpt_dir and cfg.ckpt_every and step % cfg.ckpt_every == 0:
            save_train_state(cfg.ckpt_dir, state, max_to_keep=cfg.max_to_keep)
    if cfg.ckpt_dir and step > start:
        save_train_state(cfg.ckpt_dir, state, max_to_keep=cfg.max_to_keep)
    return FitResult(state, losses, step - start, resumed_from)
