"""Training loop driver: steps + checkpointing + logging + resume + recovery.

Composes the pieces the rest of the package provides — any of the three
train steps (dense dp/sp/tp, pipeline, MoE), the ``LMDataset`` batch
addressing, and the checkpoint subsystem — into the run loop a framework
user actually calls.  Resume is exact: the loop reads ``state['step']``
after restoring and continues with ``dataset.batch_at(step)``, so a run
interrupted at any step and resumed produces the same parameters as a
straight-through run (pinned by tests).

Crash safety (docs/FAILURE_MODEL.md): a NaN/Inf guard on the step metrics
skips anomalous steps (the update is discarded, the batch is not retried
this run), rewinds to the last verified checkpoint after
``max_bad_steps`` *consecutive* anomalies, and gives up with
:class:`TrainingDiverged` once ``max_rewinds`` rewinds have not cured the
divergence.  Restores go through ``restore_train_state``'s integrity
fallback, so a truncated newest checkpoint silently falls back one.

Runtime supervision (the in-run half of the failure model): pass a
:class:`Supervision` and the loop gains a step watchdog (a hung step
raises a typed ``FT_STEP_TIMEOUT`` instead of blocking forever, with a
bounded retry for transient stalls), heartbeat-driven membership (this
rank beats through a ``runtime.Supervisor``; dead peers confirmed by the
``membership`` view trigger **live shrink-to-survivors**: drain in-flight
work, restore the latest CRC-verified checkpoint, replan the collective
topology via ``planner.replan_for_survivors``, optionally rebuild the
step through ``on_shrink``, and resume — no process restart), straggler
accounting from per-rank step-duration EWMAs, and preemption-aware
checkpointing (a :class:`~flextree_tpu.runtime.PreemptionGuard`'s SIGTERM
flag takes a synchronous "checkpoint now" fast path within one step; a
:class:`~flextree_tpu.runtime.BackgroundSaver` moves periodic saves off
the step path so the rewind window stays small).

The run's :class:`RunReport` (anomalies, skipped steps, rewinds,
checkpoint fallbacks, step timeouts, stragglers, membership epoch
transitions, preemption point) is returned on the :class:`FitResult`
and, when a checkpoint dir is configured, written there as
``run_report.json`` (via :meth:`RunReport.to_json`) — including when the
run dies with :class:`TrainingDiverged`, which is exactly when the
postmortem needs it.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import math
import os
import time
from typing import Any, Callable

import jax
import numpy as np

from ..obs import dump_current, get_registry, record_event
from ..utils.checkpoint import (
    latest_checkpoint,
    restore_train_state,
    save_train_state,
)
from ..utils.logging import get_logger
from ..utils.profiling import plan_capture, step_scope

__all__ = [
    "FitConfig",
    "FitResult",
    "RunReport",
    "ShrinkExhausted",
    "Supervision",
    "TrainingDiverged",
    "fit",
]

log = get_logger("flextree.train")


class TrainingDiverged(RuntimeError):
    """The NaN/Inf guard exhausted its recovery budget: ``max_bad_steps``
    consecutive anomalies with no checkpoint to rewind to, or
    ``max_rewinds`` rewinds that did not cure the divergence."""


class ShrinkExhausted(RuntimeError):
    """Peers kept dying past the ``Supervision.max_shrinks`` budget — the
    run refuses to keep replanning around a collapsing world."""


@dataclasses.dataclass(frozen=True)
class FitConfig:
    num_steps: int = 100
    ckpt_dir: str | None = None
    ckpt_every: int = 100
    max_to_keep: int = 3
    log_every: int = 10
    resume: bool = True  # restore from ckpt_dir's latest checkpoint if any
    # background-prefetch depth (0 disables): batches are pulled this many
    # steps ahead on a daemon thread (``flextree_tpu.data.prefetch``) while
    # the current step runs on device
    prefetch: int = 2
    # NaN/Inf guard: skip steps whose loss (or grad_norm, when the step
    # reports one) is non-finite; after max_bad_steps CONSECUTIVE skips,
    # rewind to the last verified checkpoint; after max_rewinds rewinds
    # raise TrainingDiverged.  The check device_gets the metrics every
    # step, so it synchronizes host and device (on accelerators this
    # trades dispatch pipelining for catching the FIRST bad update before
    # it compounds); nan_guard=False restores the fail-fast async loop.
    nan_guard: bool = True
    max_bad_steps: int = 3
    max_rewinds: int = 2


@dataclasses.dataclass
class Supervision:
    """Runtime-supervision wiring for :func:`fit` (every field optional —
    a ``None`` field leaves that feature off, so ``Supervision()`` is the
    no-op and the unsupervised loop is byte-for-byte the historical one).

    ``supervisor``: a ``runtime.Supervisor`` — this rank's heartbeat
    emitter; started/stopped by ``fit`` and fed each step's duration (the
    straggler EWMA peers classify against).  ``membership``: the liveness
    view — a ``runtime.MembershipView`` (or any callable returning
    ``{rank: state_str}``) polled every ``check_every`` steps.
    ``configured_world``: the membership roster size at start (defaults
    to the first poll's).  ``step_timeout_s``: the per-step watchdog
    deadline (``None`` reads ``FT_STEP_TIMEOUT``; unset = watchdog off);
    a timed-out step is retried up to ``max_step_retries`` times when no
    death is confirmed, then the :class:`~flextree_tpu.runtime.StepTimeout`
    propagates.  ``on_shrink(n_alive, plan)``: rebuild hook for the
    shrink path — return ``None`` to keep the current step, a
    ``(step_fn, mesh, state_specs)`` triple for the survivor world (the
    plan carries the replanned widths), or a 5-tuple additionally
    carrying ``(state_pack, state_unpack)`` converters for the survivor
    world — the ZeRO-1 re-shard path: sharded runs checkpoint in the
    CONSOLIDATED layout (``fit``'s ``state_pack``), so after a shrink the
    survivors restore the full CRC-verified checkpoint and re-partition
    it into their new owned shards (``state_unpack`` =
    ``parallel.zero.make_reshard_fn`` for the new world).  ``nbytes_hint``
    prices that replan.  ``preemption``: a ``runtime.PreemptionGuard``
    polled every iteration for the checkpoint-now fast path.
    ``background_saver``: a ``runtime.BackgroundSaver`` — periodic saves
    go through it instead of blocking the step path (the final save
    stays synchronous, after a drain).

    ``feedback``: a ``planner.feedback.FeedbackController`` — the
    closed-loop planner hook (ISSUE 12, docs/FEEDBACK.md).  Every
    ``every_k`` steps *with the flight recorder on* it probes the live
    wire, pairs measured against predicted comm cost, and — past the
    drift band — refits the calibration constants, invalidates stale
    plan-cache entries, and hands back a replanned step that ``fit``
    swaps through the SAME rebuild path the shrink handler uses (its
    ``on_replan`` hook returns the same 3-/5-tuple ``on_shrink`` does,
    minus the restore: the world didn't change, only the plan).  With no
    recorder installed the per-step cost is one ``None`` check — the
    identical check ``record_event`` makes — so telemetry-off runs pay
    nothing.

    ``coordination``: a ``runtime.CoordinationHandle`` — arms the
    coordinated elastic control plane (docs/COORDINATION.md) for
    multi-process groups.  Elastic decisions then stop being rank-local:
    confirmed deaths make the group's *coordinator* (lowest-rank healthy
    member) PROPOSE a shrink whose survivor set and replanned topology
    every rank applies from the committed control epoch; the feedback
    controller's drift refits propose group-wide replans the same way
    (arm it with the same handle); and arbiter lease resizes ride the
    identical commit path via ``TrainLeaseClient(coordination=...)``.
    The loop calls ``gate(step)`` once per iteration; a rank excluded
    from a committed epoch exits loudly with ``runtime.EpochFenced``
    rather than training on a stale plan.
    """

    supervisor: Any = None
    membership: Any = None
    configured_world: int | None = None
    check_every: int = 1
    step_timeout_s: float | None = None
    max_step_retries: int = 1
    on_shrink: Callable | None = None
    nbytes_hint: int = 4 << 20
    max_shrinks: int = 2
    preemption: Any = None
    background_saver: Any = None
    feedback: Any = None
    coordination: Any = None


@dataclasses.dataclass
class RunReport:
    """End-of-run accounting of everything the recovery machinery did."""

    anomalies: int = 0  # non-finite steps skipped
    skipped_steps: list = dataclasses.field(default_factory=list)
    rewinds: int = 0  # checkpoint rewinds after consecutive anomalies
    ckpt_fallbacks: int = 0  # corrupt checkpoints skipped during restore
    resumed_from: int = 0
    init_retries: int = 0  # bring-up attempts beyond the first (launch layer)
    # --- runtime supervision (all zero/empty when fit ran unsupervised) ---
    step_timeouts: int = 0  # watchdog deadlines hit (FT_STEP_TIMEOUT)
    step_retries: int = 0  # timed-out steps retried (no death confirmed)
    stragglers: list = dataclasses.field(default_factory=list)
    # --- closed-loop planner feedback (zero/empty without a controller) ---
    feedback_refits: int = 0  # drift-triggered constant refits
    feedback_replans: int = 0  # refits whose on_replan hook swapped the step
    feedback_refusals: int = 0  # refits refused (starved/degenerate samples)
    # --- arbiter chip leases (empty when fit ran without an arbiter) ---
    # one entry per applied grant change — {"step", "epoch", "chips",
    # "topo", "bitwise_resume"}: the checkpoint→rebuild→restore cycle's
    # in-run proof that the resize lost nothing (docs/ARBITER.md)
    lease_epochs: list = dataclasses.field(default_factory=list)
    # membership epochs: entry 0 is the starting world, one more per live
    # shrink — {"step", "alive", "configured", "topo", "dead"}
    membership_epochs: list = dataclasses.field(default_factory=list)
    # --- coordinated control plane (empty without a coordination handle) ---
    # one entry per APPLIED committed control epoch — {"step", "epoch",
    # "kind", "fingerprint"}: the per-rank audit the chaos floors compare
    # (same final epoch + fingerprint on every survivor, no double-applies)
    control_epochs: list = dataclasses.field(default_factory=list)
    preempted_at: int | None = None  # step the SIGTERM checkpoint ran at
    background_saves: int = 0  # off-step-path checkpoint writes
    # the ambient obs registry's snapshot (None when the run carried no
    # telemetry): run_report.json is then a VIEW over the same counters /
    # histograms the flight recorder's metrics export carries
    metrics: dict | None = None

    def to_payload(self) -> dict:
        return dataclasses.asdict(self)

    def to_json(self) -> str:
        """The machine-readable form ``fit`` persists as run_report.json
        (recovery events as stable keys, so tooling can gate on them the
        way ``bench.py`` gates on ``analysis_violations``)."""
        return json.dumps(self.to_payload(), indent=2, sort_keys=True)


@dataclasses.dataclass
class FitResult:
    state: Any
    losses: list  # (step, loss) pairs at log points
    steps_run: int
    resumed_from: int
    report: RunReport = dataclasses.field(default_factory=RunReport)


def _metrics_finite(metrics) -> bool:
    """Host-side finiteness of the guard metrics (loss + grad norm)."""
    for key in ("loss", "grad_norm"):
        if key in metrics:
            v = float(np.asarray(jax.device_get(metrics[key])))
            if not math.isfinite(v):
                return False
    return True


def _apply_rebuild(rebuilt, cur_pack, cur_unpack):
    """Normalize a rebuild-hook result to the full 5-tuple swap.

    Both step-swap seams — ``Supervision.on_shrink`` (world shrank) and
    ``FeedbackConfig.on_replan`` (plan changed) — return either a
    ``(step_fn, mesh, specs)`` 3-tuple or the re-shard path's 5-tuple
    with the checkpoint-layout converters.  A 3-tuple keeps the current
    converters; one helper owns the dispatch so the two swap paths
    cannot diverge."""
    if len(rebuilt) == 5:
        return rebuilt
    step_fn, mesh, specs = rebuilt
    return step_fn, mesh, specs, cur_pack, cur_unpack


def _stamp_step(state: dict, step: int) -> dict:
    """A copy of ``state`` with ``state['step']`` set to ``step`` (keeps
    the step leaf the single source of truth when a step is skipped)."""
    import jax.numpy as jnp

    old = state["step"]
    new = dict(state)
    new["step"] = jnp.asarray(step, np.asarray(jax.device_get(old)).dtype)
    return new


def fit(
    state,
    step_fn: Callable,
    dataset,
    cfg: FitConfig = FitConfig(),
    *,
    mesh=None,
    state_specs=None,
    supervision: Supervision | None = None,
    arbiter: Any = None,
    state_pack: Callable | None = None,
    state_unpack: Callable | None = None,
) -> FitResult:
    """Run ``step_fn(state, tokens, targets) -> (state, metrics)`` for
    ``cfg.num_steps`` total steps over ``dataset`` (an ``LMDataset``).

    ``state['step']`` is the single source of truth for progress: batches
    are addressed by it, checkpoints are named by it, and resume reads it
    back.  Pass ``mesh``/``state_specs`` to restore sharded.

    ``state_pack``/``state_unpack`` (optional) convert the live state to
    and from its on-disk checkpoint layout: every save writes
    ``state_pack(state)`` and every restore returns
    ``state_unpack(loaded)``.  The ZeRO-1 sharded trainer wires
    ``parallel.zero.make_consolidate_fn``/``make_reshard_fn`` here, so
    its checkpoints are the replicated (world-size-independent) layout —
    ``state_specs`` then describes the PACKED layout, since that is what
    the restore reads.  A live shrink may swap both hooks via
    ``Supervision.on_shrink``'s 5-tuple return.

    ``supervision`` (optional) arms the runtime-supervision layer — step
    watchdog, heartbeat membership with live shrink-to-survivors,
    straggler accounting, preemption checkpointing; see
    :class:`Supervision`.  Without it the loop is the historical one.

    ``arbiter`` (optional) is this run's chip-lease handle — a
    :class:`~flextree_tpu.runtime.TrainLeaseClient` (or anything with the
    same ``poll(step)`` / ``ack(directive)`` / ``on_resize`` surface).
    When the pool arbiter moves chips (docs/ARBITER.md), the loop rides
    the preemption-checkpoint machinery in place: drain pending saves,
    checkpoint NOW, rebuild for the new chip count through the handle's
    ``on_resize`` hook (the same 3-/5-tuple swap ``on_shrink`` uses),
    restore, verify the restored packed state is BITWISE the one just
    saved, and ack the lease epoch — only then may the arbiter hand the
    revoked chips to serving.  Each applied change is recorded in
    ``RunReport.lease_epochs``.
    """
    report = RunReport()
    sup = supervision
    # mutable current-epoch execution context: live shrink swaps these
    cur_step_fn, cur_mesh, cur_specs = step_fn, mesh, state_specs
    cur_pack, cur_unpack = state_pack, state_unpack

    def _fallback(bad_path, exc):
        report.ckpt_fallbacks += 1

    def _restore():
        loaded = restore_train_state(
            cfg.ckpt_dir, mesh=cur_mesh, specs=cur_specs, on_fallback=_fallback
        )
        return cur_unpack(loaded) if cur_unpack is not None else loaded

    def _packed(s):
        return cur_pack(s) if cur_pack is not None else s

    resumed_from = 0
    if cfg.resume and cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir):
        state = _restore()
        resumed_from = int(np.asarray(jax.device_get(state["step"])))
        report.resumed_from = resumed_from
        log.info("resumed from step %d (%s)", resumed_from, cfg.ckpt_dir)

    losses: list = []
    start = int(np.asarray(jax.device_get(state["step"])))
    t0 = time.perf_counter()
    step = start
    bad_streak = 0

    def _batches(from_step):
        if cfg.prefetch and from_step < cfg.num_steps and hasattr(dataset, "iter_from"):
            from ..data import prefetch as _prefetch

            return _prefetch(dataset.iter_from(from_step), size=cfg.prefetch)
        return None

    batches = _batches(start)

    def _lease_resize(at_step, directive):
        """Apply an arbiter grant change: checkpoint now, rebuild for the
        new chip count, restore, prove the resume bitwise, ack.

        The cycle is the SIGTERM-preemption fast path composed with the
        shrink path's rebuild — but triggered by the lease ledger and
        resumed IN-PROCESS (the world changed size, the process did not).
        The bitwise proof compares the packed (world-independent) state
        on both sides of the cycle: what the preempt checkpoint saved
        must be exactly what the resized world runs from — zero steps
        lost, by construction and by check.
        """
        nonlocal state, step, batches
        nonlocal cur_step_fn, cur_mesh, cur_specs, cur_pack, cur_unpack
        from ..planner.choose import replan_for_survivors

        n = directive.n
        if n < 1:
            raise ValueError(
                f"lease epoch {directive.epoch} grants training zero chips "
                "— the arbiter's min_train_chips floor should forbid this"
            )
        configured = max(getattr(arbiter, "configured", None) or n, n)
        nbytes = getattr(arbiter, "nbytes_hint", 4 << 20)
        plan = replan_for_survivors(n, nbytes, configured=configured)
        if getattr(directive, "topo", None):
            # a coordinated resize broadcasts the coordinator's plan —
            # every rank must run IT, not its own chooser's winner
            from ..runtime.coordination import apply_spec_override

            plan = apply_spec_override(plan, directive.topo, n)
        log.warning(
            "lease resize at step %d: epoch %d grants chips %s (%d); "
            "replanned topo %s",
            at_step, directive.epoch, list(directive.chips), n,
            plan.to_ft_topo(),
        )
        if sup is not None and sup.background_saver is not None:
            # the restore below must never race an in-flight save's
            # rotation (the background saver forbids two writers)
            sup.background_saver.drain(None)
        old_pack = cur_pack
        packed = _packed(state)
        pre_host = jax.device_get(packed)
        if cfg.ckpt_dir:
            # checkpoint NOW — the preemption fast path's save, so the
            # revoked chips carry no un-persisted work when they leave
            save_train_state(cfg.ckpt_dir, packed, max_to_keep=cfg.max_to_keep)
        on_resize = getattr(arbiter, "on_resize", None)
        rebuilt = (
            on_resize(directive.chips, plan) if on_resize is not None else None
        )
        if rebuilt is not None:
            (cur_step_fn, cur_mesh, cur_specs,
             cur_pack, cur_unpack) = _apply_rebuild(
                 rebuilt, cur_pack, cur_unpack)
        if cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir):
            state = _restore()
            step = int(np.asarray(jax.device_get(state["step"])))
        elif old_pack is not None or cur_unpack is not None:
            # no checkpoint dir: convert the live state through the
            # packed layout, exactly what the shrink path does
            state = (
                cur_unpack(pre_host) if cur_unpack is not None else pre_host
            )
        # the bitwise-resume proof: the new world's packed view of the
        # restored state vs the packed state the checkpoint saved
        post_host = jax.device_get(_packed(state))
        pre_leaves = jax.tree.leaves(pre_host)
        post_leaves = jax.tree.leaves(post_host)
        bitwise = len(pre_leaves) == len(post_leaves) and all(
            np.asarray(a).tobytes() == np.asarray(b).tobytes()
            for a, b in zip(pre_leaves, post_leaves)
        )
        if not bitwise:
            log.error(
                "lease resize at step %d is NOT a bitwise resume — the "
                "packed state changed across the preempt/restore cycle",
                at_step,
            )
        report.lease_epochs.append(
            {
                "step": at_step,
                "epoch": directive.epoch,
                "chips": list(directive.chips),
                "topo": plan.to_ft_topo(),
                "bitwise_resume": bitwise,
            }
        )
        record_event(
            "lease_resize", step=at_step, epoch=directive.epoch,
            chips=list(directive.chips), n=n, topo=plan.to_ft_topo(),
            bitwise_resume=bitwise,
        )
        arbiter.ack(directive)
        batches = _batches(step)

    # ---- runtime supervision wiring (sup=None leaves the historical loop)
    watchdog = None
    step_timeout = None
    world: int | None = None  # current epoch's alive count
    known_dead: set = set()
    pending_dead: set = set()  # observed deaths awaiting a group decision
    flagged_stragglers: set = set()
    shrinks = 0
    timeout_retries = 0
    feedback_dead = False  # a tick raised: feedback disarmed for the run
    coordn = sup.coordination if sup is not None else None
    if sup is not None:
        from ..runtime.watchdog import StepTimeout, StepWatchdog, step_timeout_from_env

        step_timeout = (
            sup.step_timeout_s
            if sup.step_timeout_s is not None
            else step_timeout_from_env()
        )
        if step_timeout is not None:
            watchdog = StepWatchdog()
        if sup.supervisor is not None:
            sup.supervisor.start()

        def _poll_membership() -> dict | None:
            """Normalize the liveness source to ``{rank: state_str}``."""
            m = sup.membership
            if m is None:
                return None
            if hasattr(m, "poll"):
                return {r: s.state for r, s in m.poll().items()}
            return dict(m())

        def _drained_saves(timeout=30.0) -> bool:
            """True when no background save is pending/in flight.  A False
            return means a slow save still owns the directory — the caller
            must NOT start a second writer (or a restore) against it."""
            if sup.background_saver is None:
                return True
            ok = sup.background_saver.drain(timeout)
            if not ok:
                log.warning(
                    "background save still in flight after %.0fs drain; "
                    "skipping the conflicting synchronous writer", timeout,
                )
            return ok

        def _feed_supervisor(dur_s):
            if sup.supervisor is not None:
                sup.supervisor.record_step(step, dur_s)
            reg = get_registry()
            if reg is not None:
                reg.histogram("train.step_ms").observe(dur_s * 1e3)

        def _materialized_step(st, tk, tg):
            # JAX dispatch is async: a jitted step returns unmaterialized
            # futures in milliseconds even when a dead peer has wedged the
            # collective — the block would then happen OUTSIDE the deadline
            # at the metrics fetch.  Materialize inside the watchdogged
            # call so FT_STEP_TIMEOUT covers device execution, not just
            # dispatch.  (The nan_guard device_gets the metrics every step
            # anyway, so this adds no extra host-device sync per step.)
            return jax.block_until_ready(cur_step_fn(st, tk, tg))

        def _shrink(at_step, new_dead, *, alive=None, plan=None):
            """Live shrink-to-survivors: drain, rebuild, restore, resume.

            ``alive``/``plan`` are the coordinated-broadcast overrides: a
            committed group shrink carries the coordinator's survivor
            count and replanned topology so every rank applies THE SAME
            decision instead of each computing its own."""
            nonlocal state, world, shrinks, step, batches
            nonlocal cur_step_fn, cur_mesh, cur_specs, cur_pack, cur_unpack
            from ..planner.choose import replan_for_survivors

            prev_world = world
            n_alive = (
                int(alive) if alive is not None
                else max(1, world - len(new_dead))
            )
            if plan is None:
                plan = replan_for_survivors(
                    n_alive, sup.nbytes_hint, configured=prev_world
                )
            log.warning(
                "membership shrink at step %d: ranks %s dead, %d/%d alive; "
                "replanned topo %s",
                at_step, new_dead, n_alive, prev_world, plan.to_ft_topo(),
            )
            # drain in-flight work: pending background saves first (the old
            # epoch's prefetcher is dropped below when batches reseek)
            _drained_saves(timeout=None)  # restore must never race a save
            old_pack = cur_pack  # the OLD world's consolidator, pre-swap
            rebuilt = (
                sup.on_shrink(n_alive, plan) if sup.on_shrink is not None else None
            )
            if rebuilt is not None:
                # 5-tuple = the re-shard path: the survivor world gets its
                # own checkpoint-layout converters (ZeRO state re-carved
                # from the consolidated checkpoint)
                (cur_step_fn, cur_mesh, cur_specs,
                 cur_pack, cur_unpack) = _apply_rebuild(
                     rebuilt, cur_pack, cur_unpack)
            if cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir):
                state = _restore()
                step = int(np.asarray(jax.device_get(state["step"])))
                log.warning(
                    "restored checkpointed step %d for the survivor world", step
                )
            elif old_pack is not None or cur_unpack is not None:
                # no checkpoint yet, but the state layout is
                # world-size-dependent (ZeRO shards): convert the LIVE
                # state through the packed (world-independent) layout —
                # the old world consolidates, the new world re-shards.
                # The old mesh's devices are still alive in-process, so
                # the old consolidator can run one last time.
                packed = old_pack(state) if old_pack is not None else state
                # host round-trip: the packed state lives on the OLD
                # mesh's devices; the survivor world's converter places
                # it fresh (exactly what a checkpoint restore would do)
                packed = jax.device_get(packed)
                state = (
                    cur_unpack(packed) if cur_unpack is not None else packed
                )
                log.warning(
                    "no checkpoint to restore: re-sharded the live state "
                    "for the survivor world"
                )
            world = n_alive
            shrinks += 1
            report.membership_epochs.append(
                {
                    "step": at_step,
                    "alive": n_alive,
                    "configured": prev_world,
                    "topo": plan.to_ft_topo(),
                    "dead": list(new_dead),
                }
            )
            record_event(
                "shrink", step=at_step, dead=list(new_dead), alive=n_alive,
                configured=prev_world, topo=plan.to_ft_topo(),
            )
            # the forensic record of WHAT the survivor saw around the
            # death: ring context + the shrink decision, guaranteed —
            # with the handshake phase attached when the shrink was a
            # group decision (which phase the fault interrupted)
            dump_current(
                "peer_shrink", step=at_step, dead=list(new_dead),
                **({"coord_phase": coordn.phase} if coordn is not None else {}),
            )
            batches = _batches(step)

        def _membership_tick(at_step) -> str:
            """One liveness poll: record stragglers, shrink on new deaths.
            Returns "shrunk" | "ok" | "unknown" (no membership source)."""
            nonlocal world
            statuses = _poll_membership()
            if statuses is None:
                return "unknown"
            if world is None:
                world = sup.configured_world or len(statuses)
            for r, st in sorted(statuses.items()):
                if st == "straggler" and r not in flagged_stragglers:
                    flagged_stragglers.add(r)
                    report.stragglers.append({"rank": r, "step": at_step})
                    record_event("straggler", peer=r, step=at_step)
                    log.warning(
                        "rank %d classified straggler at step %d", r, at_step
                    )
            new_dead = sorted(
                r
                for r, st in statuses.items()
                if st == "dead" and r not in known_dead
            )
            if not new_dead:
                return "ok"
            known_dead.update(new_dead)
            if shrinks >= sup.max_shrinks:
                raise ShrinkExhausted(
                    f"ranks {new_dead} died at step {at_step} after "
                    f"{shrinks} shrink(s); max_shrinks={sup.max_shrinks}"
                )
            if coordn is not None:
                # coordinated group: a local death observation is not
                # authority.  Park it; the coordination gate below turns
                # it into a propose→ack→commit group decision (this rank
                # proposes only while it IS the coordinator), and the
                # shrink applies when the committed epoch arrives.
                pending_dead.update(new_dead)
                return "ok"
            _shrink(at_step, new_dead)
            return "shrunk"

        def _apply_committed(at_step, decision):
            """Apply one committed group decision (the coordination gate's
            output) and advance this rank's fence.  Every branch applies
            EXACTLY what the commit carries — the local machinery only
            executes, it never re-decides."""
            nonlocal cur_step_fn, cur_mesh, cur_specs, cur_pack, cur_unpack
            payload = decision.payload
            if decision.kind == "shrink":
                if shrinks >= sup.max_shrinks:
                    raise ShrinkExhausted(
                        f"committed shrink epoch {decision.epoch} at step "
                        f"{at_step} after {shrinks} shrink(s); "
                        f"max_shrinks={sup.max_shrinks}"
                    )
                from ..runtime.coordination import committed_shrink_plan

                dead = [int(r) for r in payload.get("dead", ())]
                known_dead.update(dead)
                pending_dead.difference_update(dead)
                plan = committed_shrink_plan(payload, sup.nbytes_hint)
                _shrink(
                    at_step, dead, alive=int(payload["alive"]), plan=plan
                )
            elif decision.kind == "replan":
                if sup.feedback is not None:
                    dec = sup.feedback.apply_committed(payload, step=at_step)
                    report.feedback_refits += 1
                    if dec.rebuilt is not None:
                        (cur_step_fn, cur_mesh, cur_specs,
                         cur_pack, cur_unpack) = _apply_rebuild(
                             dec.rebuilt, cur_pack, cur_unpack)
                        report.feedback_replans += 1
                    record_event(
                        "feedback_replan", step=at_step,
                        topo=dec.plan.to_ft_topo(),
                        invalidated=dec.invalidated,
                        swapped=dec.rebuilt is not None,
                        control_epoch=decision.epoch,
                    )
                else:
                    # a committed replan this rank CANNOT execute: the
                    # peers are swapping comm plans and we would keep the
                    # old one — the exact split-brain the protocol
                    # exists to prevent.  Loud exit, never silent
                    # divergence (the fencing ethos).
                    from ..runtime.coordination import ProtocolViolation

                    raise ProtocolViolation(
                        f"committed replan epoch {decision.epoch} but this "
                        "rank has no feedback controller to apply it — arm "
                        "Supervision.feedback with a coordinated "
                        "FeedbackController on every rank, or on none"
                    )
            elif decision.kind == "resize":
                if arbiter is not None:
                    from ..runtime.leases import ResizeDirective

                    _lease_resize(
                        at_step,
                        ResizeDirective(
                            epoch=int(payload["lease_epoch"]),
                            chips=tuple(payload.get("chips", ())),
                            reason=str(payload.get("reason", "")),
                            control_epoch=decision.epoch,
                            topo=payload.get("topo"),
                        ),
                    )
                else:
                    from ..runtime.coordination import ProtocolViolation

                    raise ProtocolViolation(
                        f"committed resize epoch {decision.epoch} but this "
                        "rank has no lease client — pass the coordinated "
                        "TrainLeaseClient as fit(arbiter=...) on every rank"
                    )
            else:
                from ..runtime.coordination import ProtocolViolation

                raise ProtocolViolation(
                    f"committed decision kind {decision.kind!r} (epoch "
                    f"{decision.epoch}) is unknown to this rank — version "
                    "skew across the group; refusing to train on a "
                    "possibly-stale plan"
                )
            coordn.mark_applied(decision)
            report.control_epochs.append(
                {
                    "step": at_step,
                    "epoch": decision.epoch,
                    "kind": decision.kind,
                    "fingerprint": decision.fingerprint,
                }
            )

        def _coordination_gate(at_step) -> bool:
            """One control-plane tick: apply at most one committed
            decision, else propose parked deaths (coordinator only).
            True when a decision was applied (the loop re-enters: the
            world/plan just changed under it).  Apply-before-propose +
            the handle's refusal to propose over an unapplied commit
            keep a parked death from double-proposing while its own
            shrink is mid-delivery."""
            decision = coordn.gate(at_step)
            if decision is not None:
                _apply_committed(at_step, decision)
                return True
            if (
                pending_dead
                and shrinks < sup.max_shrinks
                and coordn.is_coordinator
            ):
                from ..planner.choose import replan_for_survivors

                n_alive = max(1, (world or 1) - len(pending_dead))
                plan = replan_for_survivors(
                    n_alive, sup.nbytes_hint, configured=world
                )
                # None while another decision is mid-handshake — the
                # parked deaths re-propose on a later tick
                proposed = coordn.propose(
                    "shrink",
                    {
                        "dead": sorted(pending_dead),
                        "alive": n_alive,
                        "configured": world,
                        "topo": plan.to_ft_topo(),
                    },
                )
                if proposed is not None:
                    # the ledger now carries the survivor set (a dying
                    # proposer's successor re-proposes from THERE): the
                    # local parking is done; the apply path re-derives
                    # the dead list from the committed payload
                    pending_dead.clear()
            return False

        # epoch 0: the starting world
        if sup.membership is not None or sup.configured_world:
            statuses0 = _poll_membership() or {}
            world = sup.configured_world or (len(statuses0) or None)
            if world:
                report.membership_epochs.append(
                    {
                        "step": start,
                        "alive": world,
                        "configured": world,
                        "topo": None,
                        "dead": [],
                    }
                )

    # id pairs fit_start with fit_end in the merged timeline (their step
    # fields legitimately differ: the run starts at `start`, ends later)
    record_event(
        "fit_start", id=start, step=start, num_steps=cfg.num_steps,
        resumed_from=resumed_from,
    )
    try:
        while step < cfg.num_steps:
            if sup is not None:
                if sup.preemption is not None and sup.preemption.preempted:
                    # the checkpoint-now fast path: at most one step lost
                    if cfg.ckpt_dir and _drained_saves():
                        # drain timed out -> the in-flight background save
                        # IS a recent checkpoint; racing its rotation with
                        # a second writer would be worse than one lost step
                        save_train_state(
                            cfg.ckpt_dir, _packed(state),
                            max_to_keep=cfg.max_to_keep,
                        )
                    report.preempted_at = step
                    record_event("preempt", step=step)
                    dump_current("preempted", step=step)
                    log.warning(
                        "preemption: checkpointed at step %d, exiting", step
                    )
                    break
                if (
                    sup.membership is not None
                    and step % max(1, sup.check_every) == 0
                    and _membership_tick(step) == "shrunk"
                ):
                    continue
                if coordn is not None and _coordination_gate(step):
                    # a committed group decision just applied (shrink /
                    # replan / resize): re-enter the loop on the new world
                    continue
            if arbiter is not None:
                # the arbiter moved chips: apply the grant before the next
                # step (checkpoint → rebuild → restore → ack), then loop —
                # the resized world re-reads its batch stream from `step`
                directive = arbiter.poll(step)
                if directive is not None:
                    _lease_resize(step, directive)
                    continue
            tokens, targets = (
                next(batches) if batches is not None else dataset.batch_at(step)
            )
            record_event("step_start", step=step)
            if sup is None:
                new_state, metrics = cur_step_fn(state, tokens, targets)
            else:
                # probe-free feedback (docs/FEEDBACK.md): when the
                # controller wants per-step spans (probe_free=True with
                # the recorder on — recorder off costs one None check),
                # capture the compile-time bucket plan while a fresh step
                # traces, MATERIALIZE the step (async dispatch would time
                # the enqueue, not the execution), and feed the host-timed
                # duration to the span clock below.
                fb = sup.feedback
                fb_spans = (
                    fb is not None
                    and not feedback_dead
                    and hasattr(fb, "wants_step_spans")
                    and fb.wants_step_spans()
                )
                fb_cap = None
                t_step0 = time.perf_counter()
                try:
                    with contextlib.ExitStack() as _stack:
                        _stack.enter_context(
                            step_scope(on_duration=_feed_supervisor)
                        )
                        if fb_spans:
                            fb_cap = _stack.enter_context(plan_capture())
                        new_state, metrics = (
                            watchdog.run(
                                _materialized_step, state, tokens, targets,
                                timeout_s=step_timeout, step=step,
                            )
                            if watchdog is not None
                            else (
                                _materialized_step(state, tokens, targets)
                                if fb_spans
                                else cur_step_fn(state, tokens, targets)
                            )
                        )
                except StepTimeout as e:
                    report.step_timeouts += 1
                    log.warning("%s", e)
                    # the watchdog recorded the timeout event; the dump is
                    # fit's to guarantee — this is a failure path even when
                    # the retry below saves the run
                    dump_current("watchdog_timeout", step=step)
                    batches = _batches(step)  # reseek: the batch was consumed
                    if _membership_tick(step) == "shrunk":
                        timeout_retries = 0
                        continue
                    if timeout_retries < sup.max_step_retries:
                        timeout_retries += 1
                        report.step_retries += 1
                        log.warning(
                            "retrying step %d after timeout (%d/%d)",
                            step, timeout_retries, sup.max_step_retries,
                        )
                        continue
                    raise
                timeout_retries = 0
                if fb_spans:
                    try:
                        if fb_cap:
                            fb.set_step_plan(fb_cap)
                        fb.observe_step(
                            step, time.perf_counter() - t_step0
                        )
                    except Exception as e:  # noqa: BLE001 — obs contract
                        # span bookkeeping must never kill the run: same
                        # disarm semantics as a raising tick below
                        feedback_dead = True
                        record_event(
                            "feedback_error", step=step,
                            reason=f"{type(e).__name__}: {e}"[:300],
                        )
                        log.exception(
                            "per-step span clock failed at step %d; "
                            "planner feedback disarmed for the run", step,
                        )
            record_event("step_end", step=step)
            if cfg.nan_guard and not _metrics_finite(metrics):
                report.anomalies += 1
                report.skipped_steps.append(step)
                bad_streak += 1
                record_event("nan_skip", step=step, streak=bad_streak)
                log.warning(
                    "step %d: non-finite loss/grad (%d consecutive) — update skipped",
                    step, bad_streak,
                )
                if bad_streak >= cfg.max_bad_steps:
                    if not (cfg.ckpt_dir and latest_checkpoint(cfg.ckpt_dir)):
                        raise TrainingDiverged(
                            f"{bad_streak} consecutive non-finite steps at step "
                            f"{step} and no checkpoint to rewind to"
                        )
                    if report.rewinds >= cfg.max_rewinds:
                        raise TrainingDiverged(
                            f"still diverging after {report.rewinds} rewinds "
                            f"(step {step})"
                        )
                    if sup is not None:
                        # never race an in-flight background save's rotation
                        # with the restore (the saver forbids two writers)
                        _drained_saves(timeout=None)
                    dump_current("nan_rewind", step=step)  # pre-rewind context
                    state = _restore()
                    report.rewinds += 1
                    bad_streak = 0
                    step = int(np.asarray(jax.device_get(state["step"])))
                    record_event("nan_rewind", step=step)
                    log.warning("rewound to checkpointed step %d", step)
                    batches = _batches(step)
                    continue
                # skip: discard the poisoned update, advance past the batch
                step += 1
                state = _stamp_step(state, step)
                continue
            state = new_state
            bad_streak = 0
            step += 1
            if (sup is not None and sup.feedback is not None
                    and not feedback_dead and step < cfg.num_steps):
                # closed-loop planner feedback (docs/FEEDBACK.md): with no
                # recorder installed maybe_tick is ONE None check — the
                # same check record_event makes — so telemetry-off runs
                # pay nothing; on the every_k cadence it probes the wire,
                # and past the drift band hands back a refitted replan.
                # Gated on step < num_steps: a tick after the FINAL step
                # would spend a probe round (and possibly a refit + full
                # step rebuild) on a plan no step will ever run.
                try:
                    decision = sup.feedback.maybe_tick(step)
                    if decision is not None and getattr(
                        decision, "rotation", False
                    ):
                        # a probe-free plan-rotation swap: a bucket-size
                        # variant of the same plan (bitwise-invariant),
                        # applied through the replan swap path but NOT a
                        # refit — the controller recorded feedback_rotate
                        if decision.rebuilt is not None:
                            (cur_step_fn, cur_mesh, cur_specs,
                             cur_pack, cur_unpack) = _apply_rebuild(
                                 decision.rebuilt, cur_pack, cur_unpack)
                    elif decision is not None:
                        report.feedback_refits += 1
                        if decision.rebuilt is not None:
                            # the same swap the shrink path runs, minus the
                            # restore: the world didn't change, only the plan
                            (cur_step_fn, cur_mesh, cur_specs,
                             cur_pack, cur_unpack) = _apply_rebuild(
                                 decision.rebuilt, cur_pack, cur_unpack)
                            report.feedback_replans += 1
                        record_event(
                            "feedback_replan",
                            step=step,
                            topo=decision.plan.to_ft_topo(),
                            invalidated=decision.invalidated,
                            swapped=decision.rebuilt is not None,
                        )
                        log.warning(
                            "feedback replan at step %d: topo %s, %d cache "
                            "entr%s invalidated%s",
                            step, decision.plan.to_ft_topo(),
                            decision.invalidated,
                            "y" if decision.invalidated == 1 else "ies",
                            "" if decision.rebuilt is not None
                            else " (no rebuild hook: plan recorded only)",
                        )
                except Exception as e:
                    # telemetry never kills the run (the obs contract:
                    # spill errors drop, predicted_error spans skip) — an
                    # unwritable calibration path, a failed probe compile,
                    # or a broken rebuild hook disarms feedback for the
                    # rest of the run and training continues on the
                    # current plan.  A half-applied swap is impossible:
                    # _apply_rebuild returns before any of the five
                    # loop-state names is reassigned.
                    feedback_dead = True
                    # the reason must land in the FLIGHT record, not only
                    # the process log: a later SIGKILL takes the log with
                    # it while the spilled record survives (the same
                    # post-mortem parity feedback_refused already has)
                    record_event(
                        "feedback_error", step=step,
                        reason=f"{type(e).__name__}: {e}"[:300],
                    )
                    log.exception(
                        "feedback tick failed at step %d; planner feedback "
                        "disarmed for the rest of the run", step,
                    )
            if cfg.log_every and (step % cfg.log_every == 0 or step == cfg.num_steps):
                loss = float(metrics["loss"])
                losses.append((step, loss))
                rate = (step - start) / (time.perf_counter() - t0)
                log.info("step %d loss %.4f (%.1f steps/s)", step, loss, rate)
            if cfg.ckpt_dir and cfg.ckpt_every and step % cfg.ckpt_every == 0:
                if sup is not None and sup.background_saver is not None:
                    # off-step-path save: the step loop never blocks on
                    # serialization + fsync, so ckpt_every can be small
                    # (the pack conversion, when set, runs on-path — it
                    # is the consolidation collective, not the fsync)
                    sup.background_saver.submit(_packed(state))
                else:
                    save_train_state(
                        cfg.ckpt_dir, _packed(state), max_to_keep=cfg.max_to_keep
                    )
        # the preemption fast path already saved this exact state — a second
        # serialize+fsync would double the cost inside the grace window
        if cfg.ckpt_dir and step > start and report.preempted_at is None:
            if sup is None or _drained_saves():
                save_train_state(
                    cfg.ckpt_dir, _packed(state), max_to_keep=cfg.max_to_keep
                )
    finally:
        if sup is not None:
            if sup.feedback is not None:
                # refusals happen inside the controller (a refused refit
                # returns no decision); mirror its count into the report
                report.feedback_refusals = getattr(
                    sup.feedback, "refusals", 0
                )
            if sup.background_saver is not None:
                sup.background_saver.drain()
                report.background_saves = sup.background_saver.saves
            if sup.supervisor is not None:
                sup.supervisor.stop()
            if watchdog is not None:
                watchdog.close()
        # mirror the recovery accounting into the ambient registry (when
        # telemetry is on) and embed its snapshot: run_report.json becomes
        # a view over the same counters the obs metrics export carries
        reg = get_registry()
        if reg is not None:
            reg.counter("train.steps").inc(max(step - start, 0))
            reg.counter("train.anomalies").inc(report.anomalies)
            reg.counter("train.rewinds").inc(report.rewinds)
            reg.counter("train.step_timeouts").inc(report.step_timeouts)
            reg.counter("train.shrinks").inc(
                max(len(report.membership_epochs) - 1, 0)
            )
            reg.counter("train.background_saves").inc(report.background_saves)
            reg.counter("train.feedback_refits").inc(report.feedback_refits)
            reg.counter("train.feedback_replans").inc(report.feedback_replans)
            reg.counter("train.feedback_refusals").inc(report.feedback_refusals)
            reg.counter("train.lease_resizes").inc(len(report.lease_epochs))
            reg.counter("train.control_applies").inc(
                len(report.control_epochs)
            )
            reg.gauge("train.last_step").set(step)
            report.metrics = reg.snapshot()
        record_event("fit_end", id=start, step=step)
        # the accounting matters MOST for runs that die (a TrainingDiverged
        # postmortem needs the anomaly/rewind trail) — write it regardless
        if cfg.ckpt_dir:
            os.makedirs(cfg.ckpt_dir, exist_ok=True)
            with open(os.path.join(cfg.ckpt_dir, "run_report.json"), "w") as f:
                f.write(report.to_json())
    return FitResult(state, losses, step - start, resumed_from, report)
