"""TPU-native FlexTree collectives: schedules lowered to XLA collectives.

This is the rebuild of the reference's L1+L3 (transport + algorithm) layers
(``allreduce_over_mpi/mpi_mod.hpp:663-765, 953-1163``) the TPU way: instead of
hand-rolled ``MPI_Isend``/``MPI_Irecv`` plus OpenMP reduction kernels, each
tree stage lowers to a *grouped* XLA collective over the mesh axis —
``lax.psum_scatter`` (phase 1) and ``lax.all_gather`` (phase 2) with
``axis_index_groups`` computed from the same group/gap math as the reference's
``Send_Ops``/``Recv_Ops`` — and the ring algorithm lowers to a
``lax.ppermute`` neighbor-exchange loop (ICI neighbor DMAs).  XLA handles
overlap, buffering and synchronization, so there is no analog of the
reference's per-stage ``MPI_Barrier`` (``mpi_mod.hpp:1028``) — nothing here
serializes stages beyond their data dependencies.

All functions in this module are *collective-context* functions: call them
inside ``shard_map`` (or any context where ``axis_name`` is bound), exactly
like ``jax.lax.psum``.  For a host-level convenience wrapper see
``flextree_tpu.parallel.mesh.allreduce_over_mesh``.

Mapping from the reference:

- phase-1 stage ``i`` (send/recv/reduce, ``mpi_mod.hpp:988-1029``)
    -> ``psum_scatter(axis_index_groups=topo.groups(i), tiled=True)``
       (sum) or all_gather+fold+slice (any op);
- phase-2 stage ``i`` (``mpi_mod.hpp:1050-1060``)
    -> ``all_gather(axis_index_groups=topo.groups(i), tiled=True)``;
- ``ring_allreduce`` (``mpi_mod.hpp:1113-1163``) -> ``ppermute`` ring with
  the same decrementing block walk;
- non-divisible counts: the reference clamps trailing blocks
  (``mpi_mod.hpp:679-696``); XLA wants uniform shards, so the first
  ``(count//N)*N`` elements run through the scheduled collective unpadded
  and the <N-element tail is reduced by one tiny dense collective
  (``_split_main_tail`` — no full-buffer pad/slice copies, and buffer
  donation stays intact).
"""

from __future__ import annotations

import os
from functools import lru_cache

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.reduce import ReduceOp, get_op
from ..schedule.ir import (
    IRFamilySpec,
    IRProgram,
    compile_ir,
    emit_ir,
    resolve_collective,
)
from ..schedule.stages import LonelyTopology, Topology

__all__ = [
    "allreduce",
    "tree_allreduce",
    "lonely_allreduce",
    "ring_allreduce",
    "reduce_scatter",
    "all_gather",
    "allgather",
]

#: Above this axis size ``allreduce`` skips the IR emit+verify round trip
#: at trace time (emission is O(N^2) pure Python) and dispatches straight
#: to the identical legacy executors; the IR route stays mandatory for
#: explicitly-requested IR families (swing/generalized) at any size.
#: Env override: ``FT_IR_ROUTE_MAX`` (0 disables the implicit IR route).
IR_ROUTE_MAX_ENV = "FT_IR_ROUTE_MAX"


def _ir_route_max() -> int:
    try:
        return int(os.environ.get(IR_ROUTE_MAX_ENV, "64"))
    except ValueError:
        return 64


@lru_cache(maxsize=512)
def _emit_cached(resolved, chunks: int) -> IRProgram:
    n = resolved.num_nodes
    return emit_ir(resolved, count=n * n * max(1, chunks), chunks=chunks)


@lru_cache(maxsize=512)
def _compile_cached(prog: IRProgram, op_name: str):
    return compile_ir(prog, op=op_name)


def _ir_route(x, axis_name, resolved, rop: ReduceOp, chunks: int):
    """Verified-before-compiled execution: emit (or accept) the IR
    program, model-check it, lower it (``schedule.ir.compile_ir``) — the
    checker and the executable derive from the same object.  Emission
    and verification are memoized per (shape, chunks, op), so a jit
    re-trace pays nothing."""
    if isinstance(resolved, IRProgram):
        return _compile_cached(resolved, rop.name)(x, axis_name)
    eff_chunks = 1
    if (
        isinstance(resolved, Topology)
        and not resolved.is_ring
        and chunks > 1
    ):
        n = resolved.num_nodes
        head = (x.size // n) * n
        eff_chunks = len(_chunk_sizes(head, n, chunks)) if head else 1
    prog = _emit_cached(resolved, eff_chunks)
    return _compile_cached(prog, rop.name)(x, axis_name)

# captured at import time so the interposer (``flextree_tpu.interpose``)
# shadowing ``jax.lax.psum`` can never make our own tail reduction recurse
# back into ``allreduce``
_NATIVE_PSUM = lax.psum


def _jnp_fn(rop: ReduceOp):
    return getattr(jnp, rop.jnp_name)


def _groups_or_none(topo: Topology, stage: int):
    """``axis_index_groups`` for ``stage`` — or ``None`` when the stage's one
    group spans the whole axis (XLA's ungrouped collectives take a faster
    path than a single explicit full group)."""
    groups = topo.groups(stage)
    return None if len(groups) == 1 else groups


def _split_main_tail(x: jax.Array, n: int):
    """Split a flat buffer into an evenly-divisible head and a tiny tail.

    The reference handles counts not divisible by N by clamping/emptying
    trailing blocks per-message (``mpi_mod.hpp:679-696``).  XLA collectives
    want uniform shards; padding the whole buffer to ``split_size*N``
    (round 1's approach) costs a full-buffer copy in and out *and* defeats
    buffer donation.  Instead the first ``(count//N)*N`` elements go through
    the scheduled collective unpadded and the <N-element tail is reduced by
    a single tiny dense collective.
    """
    v = x.reshape(-1)
    main = (v.size // n) * n
    if main == 0:
        return None, v
    if main == v.size:
        return v, None
    return v[:main], v[main:]


def _small_dense_allreduce(t, axis_name, rop: ReduceOp):
    """Allreduce for a sub-N-element tail: one dense collective."""
    if rop.name == "sum":
        return _NATIVE_PSUM(t, axis_name)
    stacked = lax.all_gather(t, axis_name, axis=0, tiled=False)
    fn = _jnp_fn(rop)
    red = stacked[0]
    for j in range(1, stacked.shape[0]):
        red = fn(red, stacked[j])
    return red


# --------------------------------------------------------------------------
# public entry — the TPU analog of MPI_Allreduce_FT (mpi_mod.hpp:1167-1221)
# --------------------------------------------------------------------------


def allreduce(x: jax.Array, axis_name, topo=None, op="sum", chunks: int = 1) -> jax.Array:
    """Topology-parameterized allreduce of ``x`` over ``axis_name``.

    Drop-in for ``jax.lax.psum(x, axis_name)`` (when ``op='sum'``) inside
    ``shard_map``; ``topo`` accepts anything ``Topology.resolve`` does
    (None -> ``FT_TOPO`` env or flat; width tuple; ``"4,2"`` spec string;
    a ``Topology``).  Routing mirrors the reference entry point: trivial
    world sizes return immediately (``mpi_mod.hpp:1181-1188``), the ring
    sentinel selects the ring algorithm (``:1194``), otherwise the k-ary
    tree runs.

    ``chunks > 1`` selects the chunk-pipelined execution mode for tree
    shapes (see :func:`tree_allreduce`); the ring is already pipelined at
    block granularity and the lonely buddy fold is not separable, so both
    ignore ``chunks``.

    Since ISSUE 8 every schedule is a verified IR program: ``topo`` also
    accepts the IR families (``"swing"``, ``"gen:4,2@2"``, an
    ``IRFamilySpec`` or a pre-built ``IRProgram``), and legacy shapes
    route through ``schedule.ir.compile_ir`` too (emit -> model-check ->
    lower, bitwise-identical to the direct executors, which remain the
    dispatch target above :data:`IR_ROUTE_MAX_ENV` where trace-time
    emission would not be free).
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if n <= 1:
        return x
    resolved = resolve_collective(n, topo)
    if isinstance(resolved, (IRFamilySpec, IRProgram)):
        return _ir_route(x, axis_name, resolved, rop, chunks)
    if 0 < n <= _ir_route_max():
        return _ir_route(x, axis_name, resolved, rop, chunks)
    topo = resolved
    if isinstance(topo, LonelyTopology):
        return lonely_allreduce(x, axis_name, topo, op=rop)
    if topo.is_ring:
        return ring_allreduce(x, axis_name, op=rop)
    return tree_allreduce(x, axis_name, topo, op=rop, chunks=chunks)


# --------------------------------------------------------------------------
# k-ary tree (mpi_mod.hpp:953-1111)
# --------------------------------------------------------------------------


def _chunk_sizes(total: int, n: int, chunks: int) -> list[int]:
    """Split ``total`` (a multiple of ``n``) into at most ``chunks`` contiguous
    pieces, each a multiple of ``n``, sizes as balanced as possible."""
    blocks = total // n
    c = max(1, min(chunks, blocks))
    base, rem = divmod(blocks, c)
    return [(base + (1 if i < rem else 0)) * n for i in range(c)]


def tree_allreduce(
    x: jax.Array, axis_name, topo=None, op="sum", chunks: int = 1
) -> jax.Array:
    """Hierarchical allreduce with per-stage widths ``topo.widths``.

    Non-divisible element counts run as an unpadded scheduled collective on
    the divisible head plus one tiny dense collective on the <N-element
    tail (``_split_main_tail``) — no full-buffer pad/slice copies.

    ``chunks > 1`` enables the **chunk-pipelined** execution mode: the
    divisible head is split into at most ``chunks`` contiguous pieces (each
    a multiple of N) and the stage schedule is interleaved so chunk ``c``'s
    phase-2 allgather is traced between chunk ``c+1``'s phase-1
    reduce-scatter and its own — the reference overlaps phases with
    nonblocking MPI progress (``mpi_mod.hpp:988-1060``); here the chunks
    carry no data dependency on each other, so the interleaving hands XLA
    the same slack to overlap an allgather with the next reduce-scatter
    inside one jitted program.  Chunk boundaries sit at multiples of N and
    every stage collective is elementwise across ranks, so the result is
    bitwise-identical to the unchunked schedule for ``op='sum'``.
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    topo = Topology.resolve(n, topo)
    if isinstance(topo, LonelyTopology):
        return lonely_allreduce(x, axis_name, topo, op=rop)
    shape = x.shape
    head, tail = _split_main_tail(x, n)
    parts = []
    if head is not None:
        sizes = _chunk_sizes(head.size, n, chunks)
        if len(sizes) == 1:
            h = _tree_reduce_scatter(head, axis_name, topo, rop)
            parts.append(_tree_allgather(h, axis_name, topo))
        else:
            pieces, off = [], 0
            for s in sizes:
                pieces.append(head[off : off + s])
                off += s
            outs, scattered = [], None
            for c, piece in enumerate(pieces):
                with jax.named_scope(f"ft_chunk{c}_rs"):
                    cur = _tree_reduce_scatter(piece, axis_name, topo, rop)
                if scattered is not None:
                    with jax.named_scope(f"ft_chunk{c - 1}_ag"):
                        outs.append(_tree_allgather(scattered, axis_name, topo))
                scattered = cur
            with jax.named_scope(f"ft_chunk{len(pieces) - 1}_ag"):
                outs.append(_tree_allgather(scattered, axis_name, topo))
            parts.append(jnp.concatenate(outs))
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    v = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return v.reshape(shape)


def lonely_allreduce(x: jax.Array, axis_name, topo, op="sum") -> jax.Array:
    """Allreduce for ``"4,2+1"``-style shapes: a tree over the first ``m``
    ranks plus ``l`` lonely ranks folded in through buddies.

    The reference conceived exactly this (lonely nodes syncing alongside
    the factorized tree, ``mpi_mod.hpp:77``) but shipped it disabled — its
    runtime aborts on any ``FT_TOPO`` whose product != N
    (``mpi_mod.hpp:914-918``), and its planner can only *advise* resizing
    prime worlds (``ChooseWidth.h:16-21``).  TPU realization:

    1. one ``ppermute`` moves each lonely rank's payload to its buddy
       (rank ``i`` buddies lonely rank ``m + i``), which folds it;
    2. the tree stages run restricted to ranks ``< m`` through the
       ppermute-ring stage machinery — XLA's grouped collectives require
       equal-size groups covering every rank, which a partial tree can't
       satisfy, but a ``ppermute`` permutation can simply omit ranks
       (they receive zeros; their results are overwritten in step 3);
    3. one ``ppermute`` hands the buddies' full results back to the
       lonely ranks.

    The <m-element tail of non-divisible counts goes through one dense
    collective over ALL ranks (lonely included), so it skips the fold.
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    topo = Topology.resolve(n, topo)
    if not isinstance(topo, LonelyTopology):
        return tree_allreduce(x, axis_name, topo, op=rop)
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    fn = _jnp_fn(rop)
    idx = lax.axis_index(axis_name)
    shape = x.shape
    v = x.reshape(-1)
    head, tail = _split_main_tail(v, m)
    parts = []
    if head is not None:
        with jax.named_scope("ft_lonely_fold"):
            got = lax.ppermute(head, axis_name, [(m + i, i) for i in range(l)])
            # only buddy ranks (idx < l) fold; everyone else keeps its data
            # (got is zeros there, which is NOT the identity for min/band/..)
            head = jnp.where(idx < l, fn(head, got), head)
        for i, w in enumerate(tree.widths):
            with jax.named_scope(f"ft_lonely_rs_stage{i}_w{w}"):
                head = _grouped_reduce_scatter_generic(
                    head, axis_name, tree, i, rop
                )
        for i in reversed(range(tree.num_stages)):
            with jax.named_scope(f"ft_lonely_ag_stage{i}_w{tree.widths[i]}"):
                head = _grouped_allgather_generic(head, axis_name, tree, i)
        with jax.named_scope("ft_lonely_restore"):
            got2 = lax.ppermute(head, axis_name, [(i, m + i) for i in range(l)])
            parts.append(jnp.where(idx >= m, got2, head))
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return out.reshape(shape)


def _tree_reduce_scatter(v, axis_name, topo: Topology, rop: ReduceOp):
    """Phase 1: per-stage grouped reduce-scatter (``mpi_mod.hpp:988-1029``).

    Each stage runs under a ``jax.named_scope`` so profiler traces show the
    per-stage breakdown the reference's ``SHOW_TIME`` phase logs gave
    (``mpi_mod.hpp:34-38, 977-1031``).
    """
    for i, w in enumerate(topo.widths):
        with jax.named_scope(f"ft_rs_stage{i}_w{w}"):
            groups = _groups_or_none(topo, i)
            if rop.name == "sum":
                v = lax.psum_scatter(
                    v,
                    axis_name,
                    scatter_dimension=0,
                    axis_index_groups=groups,
                    tiled=True,
                )
            else:
                v = _grouped_reduce_scatter_generic(v, axis_name, topo, i, rop)
    return v


def _tree_allgather(v, axis_name, topo: Topology):
    """Phase 2: stages unwound in reverse (``mpi_mod.hpp:1050-1060``)."""
    for i in reversed(range(topo.num_stages)):
        with jax.named_scope(f"ft_ag_stage{i}_w{topo.widths[i]}"):
            v = lax.all_gather(
                v, axis_name, axis_index_groups=_groups_or_none(topo, i),
                axis=0, tiled=True,
            )
    return v


def _next_in_group(r: int, w: int, gap: int) -> int:
    """Successor of rank ``r`` on its stage group's ring (group of ``r`` =
    ``{base + j*gap}``, ``mpi_mod.hpp:162``) — shared by the RS and AG
    ring helpers so their walks can't diverge."""
    g0 = (r // (gap * w)) * (gap * w) + r % gap
    p = (r // gap) % w
    return g0 + ((p + 1) % w) * gap


def _grouped_reduce_scatter_generic(
    v, axis_name, topo: Topology, stage: int, rop: ReduceOp
):
    """Width-w grouped reduce-scatter for non-sum ops: a true ring exchange.

    ``psum_scatter`` only sums, so band/bor/bxor/max/min/prod run the
    classic ring reduce-scatter *within each stage group*, all groups in
    parallel through one global ``ppermute`` per step: ``w-1`` steps, each
    moving ``1/w`` of the tile and folding the op — the same
    ``(w-1)/w``-of-the-tile traffic as the reference's per-block
    send/recv/reduce path (``mpi_mod.hpp:454-660, 769-878``), unlike the
    round-1 all_gather+fold which moved the whole group payload to every
    member.

    Block walk: group member at position ``p`` (ranks ``base + j*gap``)
    plays the reference ring with label ``p-1``, so after ``w-1`` folds it
    owns fully-reduced block ``p`` — matching ``psum_scatter(tiled=True)``
    ownership so the sum and non-sum stage outputs are interchangeable.

    The permutation covers ``topo.num_nodes`` ranks; when the topology is
    a lonely tree over a PREFIX of the axis, ranks beyond it are simply
    absent from the permutation (they receive zeros and compute garbage
    that ``lonely_allreduce`` overwrites).
    """
    w, gap = topo.widths[stage], topo.gaps[stage]
    fn = _jnp_fn(rop)
    tile = v.shape[0] // w
    idx = lax.axis_index(axis_name)
    pos = (idx // gap) % w
    perm = [(r, _next_in_group(r, w, gap)) for r in range(topo.num_nodes)]

    def step(s, carry):
        acc, cur_send = carry
        # cur_send: the block index this rank sends this step
        chunk = lax.dynamic_slice_in_dim(acc, cur_send * tile, tile, axis=0)
        got = lax.ppermute(chunk, axis_name, perm)
        recv_b = (cur_send - 1) % w
        cur = lax.dynamic_slice_in_dim(acc, recv_b * tile, tile, axis=0)
        acc = lax.dynamic_update_slice_in_dim(acc, fn(cur, got), recv_b * tile, axis=0)
        return acc, recv_b

    acc, _ = lax.fori_loop(0, w - 1, step, (v, (pos - 1) % w), unroll=False)
    return lax.dynamic_slice_in_dim(acc, pos * tile, tile, axis=0)


def _grouped_allgather_generic(v, axis_name, topo: Topology, stage: int):
    """Width-w grouped allgather as a ring broadcast (phase-2 counterpart
    of ``_grouped_reduce_scatter_generic`` for restricted rank sets, where
    ``lax.all_gather``'s equal-size-groups requirement can't hold).

    On entry each group member at position ``p`` owns the fully-reduced
    block ``p`` (the RS ownership convention); ``w-1`` forwarding steps
    later every member holds all ``w`` blocks in group order — matching
    ``lax.all_gather(tiled=True)`` layout.
    """
    w, gap = topo.widths[stage], topo.gaps[stage]
    tile = v.shape[0]
    idx = lax.axis_index(axis_name)
    pos = (idx // gap) % w
    perm = [(r, _next_in_group(r, w, gap)) for r in range(topo.num_nodes)]

    out = jnp.zeros((tile * w,) + v.shape[1:], v.dtype)
    out = lax.dynamic_update_slice_in_dim(out, v, pos * tile, axis=0)

    def step(s, acc):
        send_b = (pos - s) % w
        chunk = lax.dynamic_slice_in_dim(acc, send_b * tile, tile, axis=0)
        got = lax.ppermute(chunk, axis_name, perm)
        recv_b = (pos - s - 1) % w
        return lax.dynamic_update_slice_in_dim(acc, got, recv_b * tile, axis=0)

    return lax.fori_loop(0, w - 1, step, out, unroll=False)


# --------------------------------------------------------------------------
# ring (mpi_mod.hpp:1113-1163)
# --------------------------------------------------------------------------


def ring_allreduce(x: jax.Array, axis_name, op="sum") -> jax.Array:
    """Classic 2(N-1)-step ring over ``axis_name`` via ``lax.ppermute``.

    Follows the reference's block walk: send right / receive from left; at
    reduce step ``s`` rank ``r`` sends block ``(r - s) mod N`` and reduces
    the received block ``(r - s - 1) mod N`` (``mpi_mod.hpp:1119-1147``);
    the allgather phase repeats the walk forwarding fully-reduced blocks
    (``:1149-1159``).  Steps run under ``lax.fori_loop`` so the compiled
    program is O(1) in N, not an unrolled 2(N-1)-deep graph.
    ``unroll=True`` was measured (VERDICT r2 item 4): 30% SLOWER on the
    virtual-CPU mesh (6.5 -> 8.5 ms at N=4, 31 -> 43 ms at N=8, 1 MB) —
    the dispatch per ppermute is unchanged and the unrolled graph only
    bloats compilation, so the rolled loop stays.
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if n <= 1:
        return x
    fn = _jnp_fn(rop)
    shape = x.shape
    head, tail = _split_main_tail(x, n)
    parts = []
    if head is not None:
        v = head
        split = v.shape[0] // n
        idx = lax.axis_index(axis_name)
        right_perm = [(j, (j + 1) % n) for j in range(n)]

        def reduce_step(s, v):
            send_b = (idx - s) % n
            recv_b = (idx - s - 1) % n
            chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
            got = lax.ppermute(chunk, axis_name, right_perm)
            cur = lax.dynamic_slice_in_dim(v, recv_b * split, split, axis=0)
            return lax.dynamic_update_slice_in_dim(v, fn(cur, got), recv_b * split, axis=0)

        def gather_step(s, v):
            send_b = (idx + 1 - s) % n
            recv_b = (idx - s) % n
            chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
            got = lax.ppermute(chunk, axis_name, right_perm)
            return lax.dynamic_update_slice_in_dim(v, got, recv_b * split, axis=0)

        v = lax.fori_loop(0, n - 1, reduce_step, v, unroll=False)
        v = lax.fori_loop(0, n - 1, gather_step, v, unroll=False)
        parts.append(v)
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    v = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return v.reshape(shape)


# --------------------------------------------------------------------------
# separable phases (reference phases 1/2 as standalone collectives, §2.6)
#
# First-class split collectives (PR 7): ``all_gather(reduce_scatter(x)) ==
# allreduce(x)`` BITWISE for op='sum', any count, any tree/ring/lonely
# shape — because both halves are literally the code paths ``allreduce``
# composes.  The shard-layout contract (``schedule.blocks.owned_block``):
# the divisible head splits into N blocks and rank ``r`` owns block
# ``owned_block(topo, r)`` (mixed-radix residue chain for trees, ``(r+1) %
# N`` for the ring, buddy-mirrored for lonely shapes); the <N-element tail
# is reduced by ONE dense collective and returned REPLICATED on every
# rank, appended after the owned block — the same head/tail split
# ``tree_allreduce`` uses, so no pad/slice copies and no association
# change.  A rank's shard is therefore ``head/N + tail`` elements; for
# divisible counts it is a pure 1/N partition.
# --------------------------------------------------------------------------


def _shard_split(count: int, n: int) -> tuple[int, int]:
    """(head, tile) for a ``count``-element buffer over ``n`` owners."""
    tile = count // n
    return tile * n, tile


def _ring_reduce_scatter(head, axis_name, n: int, rop: ReduceOp):
    """Phase 1 of the ring alone: the (N-1)-step fold walk of
    ``ring_allreduce``; on exit this rank's fully-reduced block is
    ``(idx + 1) % N`` (the block the gather phase starts forwarding,
    ``mpi_mod.hpp:1149``), which is what gets returned."""
    fn = _jnp_fn(rop)
    split = head.shape[0] // n
    idx = lax.axis_index(axis_name)
    right_perm = [(j, (j + 1) % n) for j in range(n)]

    def reduce_step(s, v):
        send_b = (idx - s) % n
        recv_b = (idx - s - 1) % n
        chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
        got = lax.ppermute(chunk, axis_name, right_perm)
        cur = lax.dynamic_slice_in_dim(v, recv_b * split, split, axis=0)
        return lax.dynamic_update_slice_in_dim(v, fn(cur, got), recv_b * split, axis=0)

    v = lax.fori_loop(0, n - 1, reduce_step, head, unroll=False)
    own_b = (idx + 1) % n
    return lax.dynamic_slice_in_dim(v, own_b * split, split, axis=0)


def _ring_allgather(tile_v, axis_name, n: int):
    """Phase 2 of the ring alone: place the owned block ``(idx + 1) % N``
    into a zero buffer and run the (N-1)-step forwarding walk — every
    block this rank receives is some rank's fully-reduced block, so the
    assembled buffer is bitwise the ``ring_allreduce`` result."""
    split = tile_v.shape[0]
    idx = lax.axis_index(axis_name)
    right_perm = [(j, (j + 1) % n) for j in range(n)]
    out = jnp.zeros((n * split,) + tile_v.shape[1:], tile_v.dtype)
    own_b = (idx + 1) % n
    out = lax.dynamic_update_slice_in_dim(out, tile_v, own_b * split, axis=0)

    def gather_step(s, v):
        send_b = (idx + 1 - s) % n
        recv_b = (idx - s) % n
        chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
        got = lax.ppermute(chunk, axis_name, right_perm)
        return lax.dynamic_update_slice_in_dim(v, got, recv_b * split, axis=0)

    return lax.fori_loop(0, n - 1, gather_step, out, unroll=False)


def _lonely_reduce_scatter(head, axis_name, topo: LonelyTopology, rop: ReduceOp):
    """Phase 1 of the lonely shape alone: buddy fold, prefix-tree RS
    stages, then ONE extra ppermute shipping each buddy's reduced tile to
    its lonely rank — lonely rank ``m + i`` ends holding a bitwise COPY of
    buddy ``i``'s owned block (the mirror contract of
    ``schedule.blocks.owned_block``)."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    fn = _jnp_fn(rop)
    idx = lax.axis_index(axis_name)
    with jax.named_scope("ft_lonely_fold"):
        got = lax.ppermute(head, axis_name, [(m + i, i) for i in range(l)])
        head = jnp.where(idx < l, fn(head, got), head)
    for i, w in enumerate(tree.widths):
        with jax.named_scope(f"ft_lonely_rs_stage{i}_w{w}"):
            head = _grouped_reduce_scatter_generic(head, axis_name, tree, i, rop)
    with jax.named_scope("ft_lonely_ship_shard"):
        shipped = lax.ppermute(head, axis_name, [(i, m + i) for i in range(l)])
        return jnp.where(idx >= m, shipped, head)


def _lonely_allgather(tile_v, axis_name, topo: LonelyTopology):
    """Phase 2 of the lonely shape alone: prefix-tree AG stages over the
    tree ranks (lonely ranks' mirrored tiles are ignored — they are
    outside every stage permutation and compute garbage), then the
    restore ppermute hands the assembled head to the lonely ranks —
    exactly ``lonely_allreduce``'s phase 2, so the composition is bitwise
    the full lonely allreduce."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    idx = lax.axis_index(axis_name)
    head = tile_v
    for i in reversed(range(tree.num_stages)):
        with jax.named_scope(f"ft_lonely_ag_stage{i}_w{tree.widths[i]}"):
            head = _grouped_allgather_generic(head, axis_name, tree, i)
    with jax.named_scope("ft_lonely_restore"):
        got = lax.ppermute(head, axis_name, [(i, m + i) for i in range(l)])
        return jnp.where(idx >= m, got, head)


def reduce_scatter(
    x: jax.Array, axis_name, topo=None, op="sum", codec=None, step=0,
    return_residual: bool = False,
):
    """Phase 1 alone: this rank's reduced shard of ``x``.

    Returns a 1-D buffer of ``count // N + count % N`` elements: the owned
    1/N head block (``schedule.blocks.owned_block`` says which) followed
    by the <N-element tail, reduced by one dense collective and replicated
    on every rank (``tree_allreduce``'s exact tail path, so the
    ``all_gather ∘ reduce_scatter == allreduce`` contract is bitwise).
    Lonely shapes: lonely ranks hold a bitwise copy of their buddy's
    shard.  For lonely topologies the head splits over the ``m`` TREE
    ranks (shard is ``count // m + count % m`` elements).

    ``codec`` (``ops/quantize.py``): a lossy codec compresses the phase-1
    wire per hop (``parallel.compressed.compressed_reduce_scatter``);
    ``return_residual=True`` additionally returns the local
    input-quantization residual for error feedback (zeros when exact).
    """
    from ..ops.quantize import get_codec

    c = get_codec(codec)
    if c.lossy:
        from .compressed import compressed_reduce_scatter

        return compressed_reduce_scatter(
            x, axis_name, topo=topo, codec=c, step=step,
            return_residual=return_residual,
        )
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if n <= 1:
        out = x.reshape(-1)
        return (out, jnp.zeros_like(out)) if return_residual else out
    topo = Topology.resolve(n, topo)
    owners = topo.tree.num_nodes if isinstance(topo, LonelyTopology) else n
    v = x.reshape(-1)
    head, tail = _split_main_tail(v, owners)
    parts = []
    if head is not None:
        if isinstance(topo, LonelyTopology):
            parts.append(_lonely_reduce_scatter(head, axis_name, topo, rop))
        elif topo.is_ring:
            parts.append(_ring_reduce_scatter(head, axis_name, n, rop))
        else:
            parts.append(_tree_reduce_scatter(head, axis_name, topo, rop))
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return (out, jnp.zeros_like(x)) if return_residual else out


def all_gather(
    x: jax.Array, axis_name, topo=None, out_shape=None, codec=None, step=0
) -> jax.Array:
    """Phase 2 alone: inverse of ``reduce_scatter`` on the same topology.

    ``x`` is a shard in ``reduce_scatter``'s layout (owned head block +
    replicated tail); the head blocks are gathered in block order and the
    local tail appended, so the result is the full reduced buffer —
    bitwise what ``allreduce`` would have produced.  ``out_shape``
    restores the original array shape (the flat result already has the
    exact element count).

    ``codec``: a lossy codec forwards the head block encoded
    (``parallel.compressed.compressed_all_gather``) — one lossy event for
    the whole phase; every rank decodes identical bytes, so replicas
    cannot drift.
    """
    from ..ops.quantize import get_codec

    c = get_codec(codec)
    if c.lossy:
        from .compressed import compressed_all_gather

        return compressed_all_gather(
            x, axis_name, topo=topo, out_shape=out_shape, codec=c, step=step
        )
    n = lax.axis_size(axis_name)
    if n > 1:
        topo = Topology.resolve(n, topo)
        owners = topo.tree.num_nodes if isinstance(topo, LonelyTopology) else n
        v = x.reshape(-1)
        # shard layout = [owned head block (T elems) || replicated tail (t
        # elems, t < owners)].  The split is ambiguous from the shard
        # length alone (T + t), so derive it from ``out_shape`` when given
        # (T = count // owners); without it the shard is taken as a pure
        # partition (t = 0) — the divisible-count case.
        shard_len = v.shape[0]
        if out_shape is not None:
            count = 1
            for d in out_shape:
                count *= d
            tile = count // owners
            if tile + count % owners != shard_len:
                raise ValueError(
                    f"shard of {shard_len} elements does not match "
                    f"out_shape {out_shape} over {owners} owners "
                    f"(expected {tile + count % owners})"
                )
        else:
            tile = shard_len
        head_tile, tail = v[:tile], v[tile:]
        parts = []
        if tile:
            if isinstance(topo, LonelyTopology):
                parts.append(_lonely_allgather(head_tile, axis_name, topo))
            elif topo.is_ring:
                parts.append(_ring_allgather(head_tile, axis_name, n))
            else:
                parts.append(_tree_allgather(head_tile, axis_name, topo))
        if tail.shape[0]:
            parts.append(tail)
        x = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if out_shape is not None:
        count = 1
        for d in out_shape:
            count *= d
        x = x.reshape(-1)[:count].reshape(out_shape)
    return x


def allgather(x: jax.Array, axis_name, topo=None, out_shape=None) -> jax.Array:
    """Backward-compatible alias for :func:`all_gather`."""
    return all_gather(x, axis_name, topo=topo, out_shape=out_shape)
