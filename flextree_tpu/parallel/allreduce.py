"""TPU-native FlexTree collectives: schedules lowered to XLA collectives.

This is the rebuild of the reference's L1+L3 (transport + algorithm) layers
(``allreduce_over_mpi/mpi_mod.hpp:663-765, 953-1163``) the TPU way: instead of
hand-rolled ``MPI_Isend``/``MPI_Irecv`` plus OpenMP reduction kernels, each
tree stage lowers to a *grouped* XLA collective over the mesh axis —
``lax.psum_scatter`` (phase 1) and ``lax.all_gather`` (phase 2) with
``axis_index_groups`` computed from the same group/gap math as the reference's
``Send_Ops``/``Recv_Ops`` — and the ring algorithm lowers to a
``lax.ppermute`` neighbor-exchange loop (ICI neighbor DMAs).  XLA handles
overlap, buffering and synchronization, so there is no analog of the
reference's per-stage ``MPI_Barrier`` (``mpi_mod.hpp:1028``) — nothing here
serializes stages beyond their data dependencies.

All functions in this module are *collective-context* functions: call them
inside ``shard_map`` (or any context where ``axis_name`` is bound), exactly
like ``jax.lax.psum``.  For a host-level convenience wrapper see
``flextree_tpu.parallel.mesh.allreduce_over_mesh``.

Mapping from the reference:

- phase-1 stage ``i`` (send/recv/reduce, ``mpi_mod.hpp:988-1029``)
    -> ``psum_scatter(axis_index_groups=topo.groups(i), tiled=True)``
       (sum) or all_gather+fold+slice (any op);
- phase-2 stage ``i`` (``mpi_mod.hpp:1050-1060``)
    -> ``all_gather(axis_index_groups=topo.groups(i), tiled=True)``;
- ``ring_allreduce`` (``mpi_mod.hpp:1113-1163``) -> ``ppermute`` ring with
  the same decrementing block walk;
- non-divisible counts: the reference clamps trailing blocks
  (``mpi_mod.hpp:679-696``); XLA wants uniform shards, so we pad to
  ``split_size * N`` (the reference's ``data_size_aligned``,
  ``mpi_mod.hpp:232``) with the op's identity and slice the result back.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.reduce import ReduceOp, get_op
from ..schedule.blocks import BlockLayout
from ..schedule.stages import Topology

__all__ = ["allreduce", "tree_allreduce", "ring_allreduce", "reduce_scatter", "allgather"]


def _jnp_fn(rop: ReduceOp):
    return getattr(jnp, rop.jnp_name)


def _flatten_pad(x: jax.Array, n: int, rop: ReduceOp):
    """Flatten to 1-D and pad to ``split_size * n`` with the op identity."""
    v = x.reshape(-1)
    layout = BlockLayout(n, v.size)
    if layout.pad:
        v = jnp.pad(v, (0, layout.pad), constant_values=rop.identity_for(x.dtype))
    return v, layout


# --------------------------------------------------------------------------
# public entry — the TPU analog of MPI_Allreduce_FT (mpi_mod.hpp:1167-1221)
# --------------------------------------------------------------------------


def allreduce(x: jax.Array, axis_name, topo=None, op="sum") -> jax.Array:
    """Topology-parameterized allreduce of ``x`` over ``axis_name``.

    Drop-in for ``jax.lax.psum(x, axis_name)`` (when ``op='sum'``) inside
    ``shard_map``; ``topo`` accepts anything ``Topology.resolve`` does
    (None -> ``FT_TOPO`` env or flat; width tuple; ``"4,2"`` spec string;
    a ``Topology``).  Routing mirrors the reference entry point: trivial
    world sizes return immediately (``mpi_mod.hpp:1181-1188``), the ring
    sentinel selects the ring algorithm (``:1194``), otherwise the k-ary
    tree runs.
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if n <= 1:
        return x
    topo = Topology.resolve(n, topo)
    if topo.is_ring:
        return ring_allreduce(x, axis_name, op=rop)
    return tree_allreduce(x, axis_name, topo, op=rop)


# --------------------------------------------------------------------------
# k-ary tree (mpi_mod.hpp:953-1111)
# --------------------------------------------------------------------------


def tree_allreduce(x: jax.Array, axis_name, topo=None, op="sum") -> jax.Array:
    """Hierarchical allreduce with per-stage widths ``topo.widths``."""
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    topo = Topology.resolve(n, topo)
    shape = x.shape
    v, layout = _flatten_pad(x, n, rop)
    v = _tree_reduce_scatter(v, axis_name, topo, rop)
    v = _tree_allgather(v, axis_name, topo)
    if layout.pad:
        v = v[: layout.count]
    return v.reshape(shape)


def _tree_reduce_scatter(v, axis_name, topo: Topology, rop: ReduceOp):
    """Phase 1: per-stage grouped reduce-scatter (``mpi_mod.hpp:988-1029``).

    Each stage runs under a ``jax.named_scope`` so profiler traces show the
    per-stage breakdown the reference's ``SHOW_TIME`` phase logs gave
    (``mpi_mod.hpp:34-38, 977-1031``).
    """
    for i, w in enumerate(topo.widths):
        with jax.named_scope(f"ft_rs_stage{i}_w{w}"):
            groups = topo.groups(i)
            if rop.name == "sum":
                v = lax.psum_scatter(
                    v,
                    axis_name,
                    scatter_dimension=0,
                    axis_index_groups=groups,
                    tiled=True,
                )
            else:
                v = _grouped_reduce_scatter_generic(v, axis_name, topo, i, rop)
    return v


def _tree_allgather(v, axis_name, topo: Topology):
    """Phase 2: stages unwound in reverse (``mpi_mod.hpp:1050-1060``)."""
    for i in reversed(range(topo.num_stages)):
        with jax.named_scope(f"ft_ag_stage{i}_w{topo.widths[i]}"):
            v = lax.all_gather(
                v, axis_name, axis_index_groups=topo.groups(i), axis=0, tiled=True
            )
    return v


def _grouped_reduce_scatter_generic(v, axis_name, topo: Topology, stage: int, rop: ReduceOp):
    """Width-w grouped reduce-scatter for non-sum ops.

    ``psum_scatter`` only sums, so for band/bor/bxor/max/min/prod we gather
    the w group copies (stacked), fold the op (statically unrolled — XLA
    fuses the elementwise chain; this is the moral equivalent of the
    reference's per-source-count unrolled ``reduce_band``,
    ``mpi_mod.hpp:454-660``), then keep our group-position tile.
    """
    w, gap = topo.widths[stage], topo.gaps[stage]
    fn = _jnp_fn(rop)
    stacked = lax.all_gather(
        v, axis_name, axis_index_groups=topo.groups(stage), axis=0, tiled=False
    )
    red = stacked[0]
    for j in range(1, w):
        red = fn(red, stacked[j])
    tile = v.shape[0] // w
    pos = (lax.axis_index(axis_name) // gap) % w
    return lax.dynamic_slice_in_dim(red, pos * tile, tile, axis=0)


# --------------------------------------------------------------------------
# ring (mpi_mod.hpp:1113-1163)
# --------------------------------------------------------------------------


def ring_allreduce(x: jax.Array, axis_name, op="sum") -> jax.Array:
    """Classic 2(N-1)-step ring over ``axis_name`` via ``lax.ppermute``.

    Follows the reference's block walk: send right / receive from left; at
    reduce step ``s`` rank ``r`` sends block ``(r - s) mod N`` and reduces
    the received block ``(r - s - 1) mod N`` (``mpi_mod.hpp:1119-1147``);
    the allgather phase repeats the walk forwarding fully-reduced blocks
    (``:1149-1159``).  Steps run under ``lax.fori_loop`` so the compiled
    program is O(1) in N, not an unrolled 2(N-1)-deep graph.
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if n <= 1:
        return x
    fn = _jnp_fn(rop)
    shape = x.shape
    v, layout = _flatten_pad(x, n, rop)
    split = v.shape[0] // n
    idx = lax.axis_index(axis_name)
    right_perm = [(j, (j + 1) % n) for j in range(n)]

    def reduce_step(s, v):
        send_b = (idx - s) % n
        recv_b = (idx - s - 1) % n
        chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
        got = lax.ppermute(chunk, axis_name, right_perm)
        cur = lax.dynamic_slice_in_dim(v, recv_b * split, split, axis=0)
        return lax.dynamic_update_slice_in_dim(v, fn(cur, got), recv_b * split, axis=0)

    def gather_step(s, v):
        send_b = (idx + 1 - s) % n
        recv_b = (idx - s) % n
        chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
        got = lax.ppermute(chunk, axis_name, right_perm)
        return lax.dynamic_update_slice_in_dim(v, got, recv_b * split, axis=0)

    v = lax.fori_loop(0, n - 1, reduce_step, v, unroll=False)
    v = lax.fori_loop(0, n - 1, gather_step, v, unroll=False)
    if layout.pad:
        v = v[: layout.count]
    return v.reshape(shape)


# --------------------------------------------------------------------------
# separable phases (reference phases 1/2 as standalone collectives, §2.6)
# --------------------------------------------------------------------------


def reduce_scatter(x: jax.Array, axis_name, topo=None, op="sum") -> jax.Array:
    """Phase 1 alone: returns this rank's reduced 1/N tile (padded layout).

    The tile this rank owns is the composition of its per-stage group
    positions — the residue-chain ownership of SURVEY §3.2 in the padded,
    contiguous-tile coordinate system the XLA lowering uses.
    """
    n = lax.axis_size(axis_name)
    rop = get_op(op)
    rop.check_dtype(x.dtype)
    if n <= 1:
        return x.reshape(-1)
    topo = Topology.resolve(n, topo)
    v, _ = _flatten_pad(x, n, rop)
    if topo.is_ring:
        flat = Topology.flat(n)
        return _tree_reduce_scatter(v, axis_name, flat, rop)
    return _tree_reduce_scatter(v, axis_name, topo, rop)


def allgather(x: jax.Array, axis_name, topo=None, out_shape=None) -> jax.Array:
    """Phase 2 alone: inverse of ``reduce_scatter`` on the same topology.

    ``out_shape``: the original (pre-``reduce_scatter``) array shape.  When
    the element count wasn't divisible by N, ``reduce_scatter`` padded to
    ``split_size*N`` (``data_size_aligned``, ``mpi_mod.hpp:232``); passing
    ``out_shape`` slices that padding back off and restores the shape, so
    ``allgather(reduce_scatter(x, ...), ..., out_shape=x.shape)`` is a full
    allreduce for any count.
    """
    n = lax.axis_size(axis_name)
    if n <= 1:
        pass
    else:
        topo = Topology.resolve(n, topo)
        if topo.is_ring:
            topo = Topology.flat(n)
        x = _tree_allgather(x, axis_name, topo)
    if out_shape is not None:
        count = 1
        for d in out_shape:
            count *= d
        x = x.reshape(-1)[:count].reshape(out_shape)
    return x
