"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context support built on the same ICI neighbor-exchange primitive as the
ring allreduce (``ring_allreduce``, this package): the sequence is sharded
over the ``sp`` mesh axis, queries stay put, and the K/V block walks the ring
via ``lax.ppermute`` — one neighbor hop per step, exactly the communication
pattern of the reference's ring reduce-scatter block walk
(``allreduce_over_mpi/mpi_mod.hpp:1119-1147``), but carrying K/V tiles instead
of gradient blocks.  Attention over the rotating blocks is accumulated with a
numerically-stable online softmax (flash-attention style running max /
normalizer), so the full ``T x T`` score matrix never materializes and the
per-device memory is O(T/n * T/n) per step.

The reference repo has no model layer; this module is part of the framework's
model substrate that the hierarchical-collective layer (SURVEY §2.6) exists
to serve.  Everything here is a *collective-context* function: call inside
``shard_map`` with the sequence axis bound, like ``lax.psum``.

Differentiable: the loop is a ``lax.scan`` of ``ppermute`` + elementwise math,
all of which have exact transposes, so ``jax.grad`` through ring attention
yields the true global gradient (cross-shard K/V contributions flow back
through the permute transpose).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "ring_attention",
    "attention_reference",
    "local_attention",
    "local_attention_block",
]

_NEG_INF = -1e30


def varying_zeros(ref, dtype=None):
    """Exact zeros that inherit ``ref``'s varying mesh axes (vma).

    The obvious derivation — ``ref * 0`` — is NaN wherever ``ref`` is
    non-finite, so a masked hop built from it leaks a poisoned q's NaN/Inf
    into hops that must contribute *exact zeros* (ADVICE r5), and the
    training loop's NaN guard then sees divergence in rows the causal mask
    says were never touched.  ``where`` on a ``ref``-derived predicate
    keeps the varying axes while pinning every element to a finite 0.
    """
    z = jnp.where(jnp.isfinite(ref), 0.0, 0.0)
    return z.astype(ref.dtype if dtype is None else dtype)


def local_attention_block(q, k, v, q_pos, k_pos, *, causal: bool, scale: float,
                          m, l, acc):
    """One online-softmax accumulation step over a single K/V block.

    ``q``: (B, Tq, H, D); ``k``/``v``: (B, Tk, H, D); ``q_pos``/(Tq,) and
    ``k_pos``/(Tk,) are *global* token positions for causal masking.
    ``m``/(B, H, Tq) running max, ``l``/(B, H, Tq) running normalizer,
    ``acc``/(B, Tq, H, D) running weighted-value sum.  Returns updated
    ``(m, l, acc)``.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # (Tq, Tk)
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # mask the *probabilities*, not just the scores: for a fully-masked row
    # m_new stays at the -inf sentinel and exp(s - m_new) would be 1, not 0.
    p = jnp.exp(s - m_new[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + p.sum(axis=-1)
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis_name, *, causal: bool = True,
                   scale: float | None = None, impl: str = "reference"):
    """Exact attention with sequence sharded over ``axis_name``.

    ``q``/``k``/``v``: (B, T_local, H, D) — this device's sequence shard; the
    global sequence is the concatenation over the axis in index order.
    Returns (B, T_local, H, D) attention output for the local queries, in
    ``q``'s dtype.

    Each of the ``n`` steps computes one (local-Q x visiting-KV) block and
    rotates K/V one hop to the right neighbor — ``(j, (j+1) % n)`` — so at
    step ``s`` device ``i`` holds the block originating at ``(i - s) mod n``
    (the decrementing source walk of the reference ring,
    ``mpi_mod.hpp:1145-1146``).  Causality is enforced with global positions,
    so blocks strictly in the future contribute nothing (they still traverse
    the ring: uniform steps keep the program SPMD and the schedule static).

    ``impl``: the per-hop block compute — "reference" (jnp online-softmax
    accumulation) or "flash" (each hop is one fused Pallas kernel emitting
    (out, logsumexp); hops merge by stable logsumexp combination).
    """
    if impl == "flash":
        return _ring_attention_flash(q, k, v, axis_name, causal=causal,
                                     scale=scale)
    if impl != "reference":
        raise ValueError(f"unknown attention impl {impl!r}")
    n = lax.axis_size(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    idx = lax.axis_index(axis_name)
    q_pos = idx * t_local + jnp.arange(t_local)

    # derive the accumulators from q so they inherit q's varying mesh axes
    # (q may vary over sp AND tp when heads are tensor-parallel): a fresh
    # constant would be typed as replicated and fail the scan-carry check.
    zero_bht = (q[..., 0] * 0).astype(jnp.float32).transpose(0, 2, 1)
    m0 = zero_bht + _NEG_INF
    l0 = zero_bht
    acc0 = (q * 0).astype(jnp.float32)

    if n == 1:
        m, l, acc = m0, l0, acc0
        m, l, acc = local_attention_block(
            q, k, v, q_pos, q_pos, causal=causal, scale=scale, m=m, l=l, acc=acc
        )
        return _finalize(acc, l).astype(q.dtype)

    right = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, s):
        k_blk, v_blk, m, l, acc = carry
        src = (idx - s) % n
        k_pos = src * t_local + jnp.arange(t_local)
        m, l, acc = local_attention_block(
            q, k_blk, v_blk, q_pos, k_pos, causal=causal, scale=scale,
            m=m, l=l, acc=acc,
        )
        k_blk = lax.ppermute(k_blk, axis_name, right)
        v_blk = lax.ppermute(v_blk, axis_name, right)
        return (k_blk, v_blk, m, l, acc), None

    init = (k, v, m0, l0, acc0)
    (k, v, m, l, acc), _ = lax.scan(step, init, jnp.arange(n))
    return _finalize(acc, l).astype(q.dtype)


def _ring_attention_flash(q, k, v, axis_name, *, causal: bool,
                          scale: float | None):
    """Ring attention whose per-hop block compute is the fused Pallas flash
    kernel (``flextree_tpu.ops.pallas_attention``).

    Block-level causality depends only on where the visiting K/V block
    *originates* relative to this device: strictly-past blocks are fully
    visible (non-causal kernel call), the resident diagonal block is
    locally causal (equal offsets cancel, so offset-0 causal is exact),
    and strictly-future blocks contribute nothing.  ``lax.switch`` on the
    hop's origin picks the kernel; per-hop (out, logsumexp) pairs merge
    with the numerically stable running-max combination — the same math
    as ``local_attention_block``, lifted from per-element to per-hop.
    """
    from ..ops.pallas_attention import flash_attention

    n = lax.axis_size(axis_name)
    b, t_local, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    idx = lax.axis_index(axis_name)

    def full_hop(k_blk, v_blk):
        return flash_attention(
            q, k_blk, v_blk, causal=False, scale=scale, return_lse=True
        )

    def diag_hop(k_blk, v_blk):
        return flash_attention(
            q, k_blk, v_blk, causal=True, scale=scale, return_lse=True
        )

    def masked_hop(k_blk, v_blk):
        # outputs derive from q to inherit its varying manual axes (vma):
        # a bare jnp.full constant is unvarying and fails shard_map's vma
        # check against the other lax.switch branches — but they must be
        # *finite* zeros even for a non-finite q (varying_zeros, not q*0)
        return (
            varying_zeros(q),
            varying_zeros(q[..., 0], jnp.float32) + _NEG_INF,
        )

    if n == 1:
        out, _ = (diag_hop if causal else full_hop)(k, v)
        return out

    right = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, s):
        k_blk, v_blk, m, so, sd = carry
        src = (idx - s) % n
        if causal:
            # 0: diagonal (src == idx), 1: past (visible), 2: future (masked)
            branch = jnp.where(src == idx, 0, jnp.where(src < idx, 1, 2))
            out_j, lse_j = lax.switch(
                branch, [diag_hop, full_hop, masked_hop], k_blk, v_blk
            )
        else:
            out_j, lse_j = full_hop(k_blk, v_blk)
        m, so, sd = hop_merge((m, so, sd), out_j, lse_j)
        k_blk = lax.ppermute(k_blk, axis_name, right)
        v_blk = lax.ppermute(v_blk, axis_name, right)
        return (k_blk, v_blk, m, so, sd), None

    zero_bth = (q[..., 0] * 0).astype(jnp.float32)  # varying-axes inherit q
    m0 = zero_bth + _NEG_INF
    sd0 = zero_bth
    so0 = (q * 0).astype(jnp.float32)
    (k, v, m, so, sd), _ = lax.scan(
        step, (k, v, m0, so0, sd0), jnp.arange(n)
    )
    return hop_finalize((m, so, sd)).astype(q.dtype)


def _finalize(acc, l):
    """Divide the weighted-value sum by the normalizer; fully-masked rows
    (possible only for non-causal edge cases) yield zeros, not NaNs."""
    denom = l.transpose(0, 2, 1)[..., None]
    return jnp.where(denom > 0, acc / jnp.where(denom > 0, denom, 1.0), 0.0)


def hop_merge(carry, out_j, lse_j):
    """Fold one hop's ``(out, lse)`` into the running ``(m, so, sd)``
    accumulators — the per-hop analog of the per-element online softmax.
    THE implementation: the flash ring and the zigzag ring both use it; a
    numerics change here changes every ring schedule identically.
    ``m``/``sd``: (B, Tq, H) running max / normalizer; ``so``: (B, Tq, H, D)
    scaled weighted-value sum."""
    m, so, sd = carry
    m_new = jnp.maximum(m, lse_j)
    c_old = jnp.exp(m - m_new)
    c_new = jnp.exp(lse_j - m_new)
    so = so * c_old[..., None] + out_j.astype(jnp.float32) * c_new[..., None]
    sd = sd * c_old + c_new
    return m_new, so, sd


def hop_finalize(carry):
    """Normalize merged hop accumulators; rows no hop touched (lse still at
    the -inf sentinel, sd == 0) yield zeros, not NaNs."""
    _, so, sd = carry
    denom = sd[..., None]
    return jnp.where(denom > 0, so / jnp.where(denom > 0, denom, 1.0), 0.0)


def local_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None, impl: str = "reference",
                    **flash_kwargs):
    """Full-sequence-local attention, dispatched by implementation name:
    "reference" (jnp full matrix) or "flash" (the fused Pallas kernel,
    ``flextree_tpu.ops.pallas_attention``) — the single switch shared by
    the model forward and the Ulysses inner attention.

    ``flash_kwargs`` (block_q / block_k / variant, ...) forward to the
    flash kernel so callers can run a tuned config; rejected for the
    reference impl, which has no such knobs."""
    if impl == "flash":
        from ..ops.pallas_attention import flash_attention

        return flash_attention(q, k, v, causal=causal, scale=scale,
                               **flash_kwargs)
    if flash_kwargs:
        raise TypeError(
            f"attention impl {impl!r} takes no flash kwargs: "
            f"{sorted(flash_kwargs)}"
        )
    if impl == "reference":
        return attention_reference(q, k, v, causal=causal, scale=scale)
    raise ValueError(f"unknown attention impl {impl!r}")


def attention_reference(q, k, v, *, causal: bool = True,
                        scale: float | None = None,
                        return_lse: bool = False):
    """Single-device full-matrix attention — the oracle for ring attention.

    Same semantics on unsharded (B, T, H, D) inputs; used by the tests the
    way ``--comm-type mpi`` served as the reference's A/B oracle
    (``benchmark.cpp:147-174``).

    ``return_lse=True`` additionally returns the per-row logsumexp of the
    masked scores, (B, T, H) float32 with fully-masked rows at the -1e30
    sentinel — the same contract as ``flash_attention(return_lse=True)``,
    so blockwise consumers (the zigzag ring) can use either as the hop
    compute.
    """
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        pos = jnp.arange(t)
        mask = pos[:, None] >= pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    if not return_lse:
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        return out.astype(q.dtype)
    m = s.max(axis=-1)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l = p.sum(axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    denom = l.transpose(0, 2, 1)[..., None]
    out = jnp.where(denom > 0, out / jnp.where(denom > 0, denom, 1.0), 0.0)
    lse = jnp.where(
        l > 0, m + jnp.log(jnp.maximum(l, 1e-38)), _NEG_INF
    ).transpose(0, 2, 1)
    return out.astype(q.dtype), lse
