"""Multi-host launch + hybrid DCN×ICI meshes — the deployment layer (L5).

The reference's L5 is a ``Makefile`` that scp-deploys the binary to 16 hosts
and an MPI hostfile naming the ranks (``allreduce_over_mpi/Makefile:8-24``,
``mpi_config_file:1-16``; SURVEY §2.5).  On TPU the moral equivalents are:

- **process bring-up**: ``jax.distributed.initialize`` — every host runs the
  same program, the coordinator assigns process ids, and all devices become
  globally addressable (the role ``mpirun -np N --hostfile`` plays for MPI);
- **hostfile**: a small JSON cluster config naming the coordinator, process
  count and this process's id (TPU pods auto-detect all three, so the file is
  only needed off-pod / on GPU-style clusters);
- **topology**: a *hybrid* mesh whose outer axes cross DCN (between slices)
  and inner axes ride ICI (within a slice).  The planner prices DCN stages
  with DCN constants (``flextree_tpu.planner.cost_model``), so the chosen
  stage widths naturally do the hierarchical thing the reference's FlexTree
  does across its two-level Ethernet fabric: few wide stages over the slow
  links, more stages over the fast ones.

Everything here degrades gracefully to single-process virtual-device runs so
the full path is testable on 8 CPU devices (SURVEY §4's strategy).
"""

from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass
from pathlib import Path

import numpy as np

import jax
from jax.sharding import Mesh

from ..schedule.stages import Topology

__all__ = [
    "ClusterConfig",
    "init_distributed",
    "hybrid_mesh",
    "flatten_mesh",
    "dcn_axis_names",
    "plan_for_mesh",
    "topology_for_hybrid",
]


# --------------------------------------------------------------------------
# cluster config — the mpi_config_file analog
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ClusterConfig:
    """Process-level launch description (one file shared by every host).

    ``coordinator``: ``host:port`` of process 0 (the reference's first
    hostfile line is the de-facto coordinator).  ``num_processes``: total
    JAX processes.  ``process_id``: this host's id — usually *not* stored in
    the shared file but taken from the ``FT_PROCESS_ID`` env var or CLI, the
    way MPI ranks come from the launcher, so the same file deploys
    everywhere.  All fields optional: on TPU pods the runtime auto-detects
    everything and ``ClusterConfig()`` is valid.
    """

    coordinator: str | None = None
    num_processes: int | None = None
    process_id: int | None = None

    @classmethod
    def from_file(cls, path: str | Path) -> "ClusterConfig":
        raw = json.loads(Path(path).read_text())
        unknown = set(raw) - {"coordinator", "num_processes", "process_id"}
        if unknown:
            raise ValueError(f"unknown cluster-config keys: {sorted(unknown)}")
        return cls(**raw)

    @classmethod
    def from_env(cls) -> "ClusterConfig":
        """Read ``FT_COORDINATOR`` / ``FT_NUM_PROCESSES`` / ``FT_PROCESS_ID``
        — the launcher-provided triple, like MPI rank env vars."""
        num = os.environ.get("FT_NUM_PROCESSES")
        pid = os.environ.get("FT_PROCESS_ID")
        return cls(
            coordinator=os.environ.get("FT_COORDINATOR"),
            num_processes=int(num) if num else None,
            process_id=int(pid) if pid else None,
        )

    def merged(self, other: "ClusterConfig") -> "ClusterConfig":
        """Fields of ``other`` win where set (env overrides file)."""
        return ClusterConfig(
            coordinator=other.coordinator or self.coordinator,
            num_processes=other.num_processes or self.num_processes,
            process_id=other.process_id if other.process_id is not None else self.process_id,
        )


def init_distributed(config: ClusterConfig | str | Path | None = None) -> None:
    """Bring up the multi-host runtime (idempotent).

    ``config``: a :class:`ClusterConfig`, a path to its JSON file, or None.
    Env vars (``FT_*``) override file values, mirroring how the reference's
    runtime lets ``FT_TOPO`` override compiled-in defaults.  On TPU pods all
    fields may be None — ``jax.distributed.initialize`` auto-detects.  No-op
    when already initialized or when the world is one process with no
    coordinator configured (the single-host dev loop).
    """
    if _distributed_client_active():
        return  # already initialized by us or the runtime
    cfg = (
        config
        if isinstance(config, ClusterConfig)
        else ClusterConfig.from_file(config)
        if config is not None
        else ClusterConfig()
    )
    cfg = cfg.merged(ClusterConfig.from_env())
    if cfg.coordinator is None and cfg.num_processes in (None, 1):
        return  # single-process run: nothing to initialize
    jax.distributed.initialize(
        coordinator_address=cfg.coordinator,
        num_processes=cfg.num_processes,
        process_id=cfg.process_id,
    )


def _distributed_client_active() -> bool:
    """Whether ``jax.distributed`` is already up, WITHOUT touching backends.

    ``jax.process_count()`` initializes the XLA backends, after which
    ``jax.distributed.initialize`` unconditionally raises — so idempotence
    must be probed through the distributed global state instead.
    """
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:  # pragma: no cover - private-API drift
        return False


# --------------------------------------------------------------------------
# hybrid DCN x ICI meshes
# --------------------------------------------------------------------------


def dcn_axis_names(mesh: Mesh) -> tuple[str, ...]:
    """Axis names this module marked as DCN when building ``mesh``."""
    return tuple(n for n in mesh.axis_names if str(n).startswith("dcn"))


def hybrid_mesh(
    ici_shape: tuple[int, ...],
    dcn_shape: tuple[int, ...] = (),
    axis_names: tuple[str, ...] | None = None,
    devices=None,
) -> Mesh:
    """A mesh whose leading axes cross DCN and trailing axes ride ICI.

    ``ici_shape``: per-slice torus factorization, e.g. ``(4, 2)``.
    ``dcn_shape``: slice grid, e.g. ``(2,)`` for two slices.  Axis names
    default to ``("dcn0", ..., "ici0", ...)`` so :func:`dcn_axis_names`
    (and through it :func:`plan_for_mesh`) can recover which axes pay DCN
    constants.

    On real multi-slice hardware this delegates to
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` (which groups
    devices by slice so each DCN axis really crosses slices); on
    single-granule hardware or virtual CPU devices it falls back to a plain
    reshape — same logical mesh, no physical grouping to respect.
    """
    if axis_names is None:
        axis_names = tuple(f"dcn{i}" for i in range(len(dcn_shape))) + tuple(
            f"ici{i}" for i in range(len(ici_shape))
        )
    if len(axis_names) != len(dcn_shape) + len(ici_shape):
        raise ValueError(
            f"{len(dcn_shape) + len(ici_shape)} axes but {len(axis_names)} names"
        )
    devs = list(devices) if devices is not None else jax.devices()
    n = math.prod(dcn_shape) * math.prod(ici_shape)
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    devs = devs[:n]

    full_shape = tuple(dcn_shape) + tuple(ici_shape)
    if dcn_shape and ici_shape and _is_multi_granule(devs):
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh wants dcn_mesh_shape the same length as
        # mesh_shape and returns their ELEMENTWISE product as the shape,
        # granule-major along each combined axis.  Fold the whole slice grid
        # into the first axis, then split it back out: the result's axis 0
        # has size prod(dcn)*ici_shape[0] with granules outermost, so a
        # row-major reshape to (dcn..., ici...) keeps every dcn index on a
        # single slice.  Granule kind: slices when the devices expose
        # distinct slice_index (real multi-slice TPU); otherwise processes
        # (multi-process CPU/GPU worlds set no slice_index — discovered by
        # the executed 2-process bring-up, tools/multiproc_bringup.py).
        slice_ids = {getattr(d, "slice_index", None) for d in devs}
        by_process = len(slice_ids) <= 1  # no distinct slices -> processes
        g = math.prod(dcn_shape)
        dcn_full = (g,) + (1,) * (len(ici_shape) - 1)
        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), dcn_full, devices=devs,
            process_is_granule=by_process,
        )
        return Mesh(arr.reshape(full_shape), axis_names)
    return Mesh(np.asarray(devs).reshape(full_shape), axis_names)


def _is_multi_granule(devs) -> bool:
    """True when devices span >1 slice/process granule (real DCN exists)."""
    keys = set()
    for d in devs:
        keys.add(getattr(d, "slice_index", None))
    if len(keys) > 1 and keys != {None}:
        return True
    return len({d.process_index for d in devs}) > 1


# --------------------------------------------------------------------------
# planner bridge: mesh -> DCN-aware topology
# --------------------------------------------------------------------------


def flatten_mesh(mesh: Mesh, axis_name: str = "ft") -> Mesh:
    """Collapse a multi-axis mesh to 1-D, preserving device order.

    The FlexTree allreduce runs over a *single* named axis (like
    ``lax.psum``); a hybrid mesh is flattened row-major, so the linear rank
    varies fastest along the *last* (innermost ICI) axis — early small-gap
    stages then exchange between ICI neighbors and only the late wide-gap
    stages cross DCN, exactly the hierarchy :func:`plan_for_mesh` prices.
    """
    return Mesh(mesh.devices.reshape(-1), (axis_name,))


def plan_for_mesh(mesh: Mesh, nbytes: int, axis_names=None, params=None):
    """Choose stage widths for a flattened allreduce over ``mesh``'s axes.

    Runs the offline planner (``flextree_tpu.planner.choose_topology``) with
    the mesh's physical shape, marking ``dcn*``-named axes so cross-slice
    stages are priced with DCN constants.  Returns the planner's ``Plan``;
    ``plan.topology`` drops into ``allreduce(topo=...)`` over
    ``flatten_mesh(mesh)``.

    Axis order: stage ``i``'s rank stride (gap) is ``prod(widths[:i])``, so
    with the row-major flatten of :func:`flatten_mesh` the *first* widths
    ride the *last* mesh axis.  The planner therefore sees the axis sizes
    reversed (innermost first); the widths it returns are already in
    execution (gap) order.

    ``axis_names``: restrict to a subset of mesh axes (in mesh order) when
    the allreduce spans only those — e.g. gradient sync over ``("dcn0",
    "ici0")`` of a dp/tp mesh.
    """
    from ..planner import choose_topology
    from ..planner.cost_model import TpuCostParams

    names = tuple(axis_names) if axis_names is not None else tuple(mesh.axis_names)
    gap_order = tuple(reversed(names))  # innermost (gap-1) axis first
    shape = tuple(mesh.shape[a] for a in gap_order)
    dcn = tuple(i for i, a in enumerate(gap_order) if str(a).startswith("dcn"))
    n = math.prod(shape)
    return choose_topology(
        n,
        nbytes,
        params=params if params is not None else TpuCostParams(),
        mesh_shape=shape,
        dcn_axes=dcn,
    )


def topology_for_hybrid(mesh: Mesh, nbytes: int, axis_names=None) -> Topology:
    """Shortcut: the winning :class:`Topology` from :func:`plan_for_mesh`."""
    return plan_for_mesh(mesh, nbytes, axis_names=axis_names).topology
