"""Multi-host launch + hybrid DCN×ICI meshes — the deployment layer (L5).

The reference's L5 is a ``Makefile`` that scp-deploys the binary to 16 hosts
and an MPI hostfile naming the ranks (``allreduce_over_mpi/Makefile:8-24``,
``mpi_config_file:1-16``; SURVEY §2.5).  On TPU the moral equivalents are:

- **process bring-up**: ``jax.distributed.initialize`` — every host runs the
  same program, the coordinator assigns process ids, and all devices become
  globally addressable (the role ``mpirun -np N --hostfile`` plays for MPI);
- **hostfile**: a small JSON cluster config naming the coordinator, process
  count and this process's id (TPU pods auto-detect all three, so the file is
  only needed off-pod / on GPU-style clusters);
- **topology**: a *hybrid* mesh whose outer axes cross DCN (between slices)
  and inner axes ride ICI (within a slice).  The planner prices DCN stages
  with DCN constants (``flextree_tpu.planner.cost_model``), so the chosen
  stage widths naturally do the hierarchical thing the reference's FlexTree
  does across its two-level Ethernet fabric: few wide stages over the slow
  links, more stages over the fast ones.

Everything here degrades gracefully to single-process virtual-device runs so
the full path is testable on 8 CPU devices (SURVEY §4's strategy).
"""

from __future__ import annotations

import json
import math
import os
import random
import time
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

import jax
from jax.sharding import Mesh

from ..schedule.stages import Topology

__all__ = [
    "ClusterConfig",
    "init_distributed",
    "init_distributed_or_degrade",
    "BringupError",
    "BringupConfigError",
    "BringupTimeout",
    "BringupReport",
    "hybrid_mesh",
    "flatten_mesh",
    "dcn_axis_names",
    "plan_for_mesh",
    "topology_for_hybrid",
    "FT_INIT_TIMEOUT_ENV",
    "FT_INIT_RETRIES_ENV",
]

# env knobs for the bring-up retry wrapper (documented in
# docs/FAILURE_MODEL.md): overall deadline in seconds, and how many times
# a failed jax.distributed.initialize is retried within it
FT_INIT_TIMEOUT_ENV = "FT_INIT_TIMEOUT"
FT_INIT_RETRIES_ENV = "FT_INIT_RETRIES"

# injection points for the tests (patch these, not time.*)
_sleep = time.sleep
_monotonic = time.monotonic


class BringupError(RuntimeError):
    """Base of the launch-failure taxonomy."""


class BringupConfigError(BringupError):
    """The cluster config itself is invalid — retrying cannot help."""


class BringupTimeout(BringupError):
    """The world did not assemble before the deadline/retry budget.

    Carries ``attempts`` and the per-attempt error strings so the caller
    (or the chaos harness) can see *why* each attempt failed.
    """

    def __init__(self, msg: str, attempts: int, errors: list[str]):
        super().__init__(msg)
        self.attempts = attempts
        self.errors = errors


@dataclass
class BringupReport:
    """What the retry wrapper did to get the runtime up."""

    attempts: int = 0
    elapsed_s: float = 0.0
    errors: list = field(default_factory=list)  # one string per failed attempt
    degraded_to: int | None = None  # survivor world size, when degraded


# --------------------------------------------------------------------------
# cluster config — the mpi_config_file analog
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class ClusterConfig:
    """Process-level launch description (one file shared by every host).

    ``coordinator``: ``host:port`` of process 0 (the reference's first
    hostfile line is the de-facto coordinator).  ``num_processes``: total
    JAX processes.  ``process_id``: this host's id — usually *not* stored in
    the shared file but taken from the ``FT_PROCESS_ID`` env var or CLI, the
    way MPI ranks come from the launcher, so the same file deploys
    everywhere.  All fields optional: on TPU pods the runtime auto-detects
    everything and ``ClusterConfig()`` is valid.
    """

    coordinator: str | None = None
    num_processes: int | None = None
    process_id: int | None = None

    @classmethod
    def from_file(cls, path: str | Path) -> "ClusterConfig":
        raw = json.loads(Path(path).read_text())
        unknown = set(raw) - {"coordinator", "num_processes", "process_id"}
        if unknown:
            raise ValueError(f"unknown cluster-config keys: {sorted(unknown)}")
        return cls(**raw)

    @classmethod
    def from_env(cls) -> "ClusterConfig":
        """Read ``FT_COORDINATOR`` / ``FT_NUM_PROCESSES`` / ``FT_PROCESS_ID``
        — the launcher-provided triple, like MPI rank env vars."""
        num = os.environ.get("FT_NUM_PROCESSES")
        pid = os.environ.get("FT_PROCESS_ID")
        return cls(
            coordinator=os.environ.get("FT_COORDINATOR"),
            num_processes=int(num) if num else None,
            process_id=int(pid) if pid else None,
        )

    def merged(self, other: "ClusterConfig") -> "ClusterConfig":
        """Fields of ``other`` win where set (env overrides file)."""
        return ClusterConfig(
            coordinator=other.coordinator or self.coordinator,
            num_processes=other.num_processes or self.num_processes,
            process_id=other.process_id if other.process_id is not None else self.process_id,
        )


def _resolve_config(config, merge_env: bool = True) -> ClusterConfig:
    """The cluster-config handshake: file/object + env overrides.

    Raises :class:`BringupConfigError` for malformed configs (never worth
    retrying) and lets transient file errors (launcher still writing the
    shared file) propagate as-is so the retry loop can wait them out.
    ``merge_env=False`` skips the env overlay — the degrade path re-forms
    the world with a *different* process count than the launcher's
    ``FT_NUM_PROCESSES`` and must not have it stomped back.
    """
    if isinstance(config, ClusterConfig):
        cfg = config
    elif config is not None:
        try:
            cfg = ClusterConfig.from_file(config)
        except json.JSONDecodeError:
            raise  # possibly mid-write by the launcher: transient, retryable
        except (ValueError, TypeError) as e:  # malformed keys/types
            raise BringupConfigError(f"bad cluster config {config}: {e}") from e
    else:
        cfg = ClusterConfig()
    return cfg.merged(ClusterConfig.from_env()) if merge_env else cfg


def _probe_coordinator(coordinator: str, budget_s: float) -> None:
    """Bounded TCP reachability check of the coordinator's port.

    On the pinned JAX, a deadline exceeded *inside* the
    ``jax.distributed.initialize`` handshake hard-aborts the process (the
    XLA coordination client ``LOG(FATAL)``s when the RegisterTask RPC
    misses its deadline) — a non-coordinator process therefore must not
    enter the handshake until the coordinator is actually listening.  This
    probe is where the retryable wait happens: it raises a catchable
    :class:`ConnectionError` after ``budget_s`` seconds so the retry loop
    can back off and try again.
    """
    import socket

    host, _, port = coordinator.rpartition(":")
    deadline = _monotonic() + budget_s
    last: Exception | None = None
    while True:
        try:
            with socket.create_connection(
                (host or "localhost", int(port)), timeout=min(budget_s, 2.0)
            ):
                return
        except OSError as e:
            last = e
        if _monotonic() >= deadline:
            raise ConnectionError(
                f"coordinator {coordinator} unreachable for {budget_s:.0f}s "
                f"({last})"
            )
        _sleep(0.25)


def init_distributed(
    config: ClusterConfig | str | Path | None = None,
    *,
    timeout: float | None = None,
    retries: int | None = None,
    backoff: float = 0.5,
    max_backoff: float = 8.0,
    merge_env: bool = True,
) -> BringupReport:
    """Bring up the multi-host runtime (idempotent), with retry/backoff.

    ``config``: a :class:`ClusterConfig`, a path to its JSON file, or None.
    Env vars (``FT_*``) override file values, mirroring how the reference's
    runtime lets ``FT_TOPO`` override compiled-in defaults.  On TPU pods all
    fields may be None — ``jax.distributed.initialize`` auto-detects.  No-op
    when already initialized or when the world is one process with no
    coordinator configured (the single-host dev loop).

    Failure handling (the reference's answer to a flaky coordinator port
    was an opaque ``mpirun`` hang; ours is a taxonomy): ``timeout`` is the
    *per-attempt* handshake deadline in seconds (env ``FT_INIT_TIMEOUT``;
    forwarded as ``initialization_timeout``, so an absent coordinator
    turns into a raised error instead of a 300 s default wait),
    ``retries`` is how many failed attempts to retry (env
    ``FT_INIT_RETRIES``, default 2), spaced by exponential backoff with
    jitter starting at ``backoff`` seconds — worst-case wall clock is
    bounded by ``(retries+1)*timeout + sum(backoffs)``.  Malformed configs
    raise :class:`BringupConfigError` immediately; an exhausted budget
    raises :class:`BringupTimeout` carrying every attempt's error.
    Returns a :class:`BringupReport` on success.
    """
    report = BringupReport()
    if _distributed_client_active():
        return report  # already initialized by us or the runtime
    if timeout is None:
        env_t = os.environ.get(FT_INIT_TIMEOUT_ENV)
        timeout = float(env_t) if env_t else None
    if retries is None:
        env_r = os.environ.get(FT_INIT_RETRIES_ENV)
        retries = int(env_r) if env_r else 2
    t_start = _monotonic()
    attempt = 0
    while True:
        attempt += 1
        report.attempts = attempt
        try:
            cfg = _resolve_config(config, merge_env=merge_env)
            if cfg.coordinator is None and cfg.num_processes in (None, 1):
                return report  # single-process run: nothing to initialize
            if (
                timeout is not None
                and cfg.coordinator
                and cfg.process_id not in (None, 0)
            ):
                # with a handshake deadline configured, wait for the
                # coordinator OUTSIDE initialize: a deadline inside the
                # handshake kills the process on this JAX pin (see
                # _probe_coordinator), while a probe failure is retryable
                _probe_coordinator(cfg.coordinator, timeout)
            kw = {}
            if timeout is not None:
                kw["initialization_timeout"] = max(1, int(timeout))
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                **kw,
            )
            report.elapsed_s = _monotonic() - t_start
            return report
        except BringupConfigError:
            raise
        except Exception as e:  # transient: connect refused, timeout, ...
            report.errors.append(f"{type(e).__name__}: {e}")
            _reset_partial_bringup()
            if attempt > retries:
                report.elapsed_s = _monotonic() - t_start
                raise BringupTimeout(
                    f"distributed bring-up failed after {attempt} attempt(s) "
                    f"in {report.elapsed_s:.1f}s; last error: {e}",
                    attempt,
                    report.errors,
                ) from e
            delay = min(backoff * (2 ** (attempt - 1)), max_backoff)
            delay *= 0.5 + random.random() / 2  # jitter: avoid retry stampede
            _sleep(delay)


def _reset_partial_bringup() -> None:
    """Clear half-initialized ``jax.distributed`` state after a failed
    connect: ``initialize`` assigns ``global_state.client`` (and, on
    process 0, ``.service``) *before* the handshake succeeds, and a second
    call raises "should only be called once" unless they are torn down.
    """
    try:
        from jax._src import distributed

        st = distributed.global_state
        for attr in ("client", "service", "preemption_sync_manager"):
            obj = getattr(st, attr, None)
            if obj is not None:
                try:
                    obj.shutdown()
                except Exception:
                    pass
            setattr(st, attr, None)
    except Exception:  # pragma: no cover - private-API drift
        pass


def init_distributed_or_degrade(
    config: ClusterConfig | str | Path | None = None,
    *,
    nbytes: int,
    survivors=None,
    min_processes: int = 1,
    timeout: float | None = None,
    retries: int | None = None,
):
    """Bring up the configured world, or degrade to the survivors.

    The degrade-to-survivors path (docs/FAILURE_MODEL.md §replanning): the
    *launcher* — the only party that knows which processes are alive —
    supplies ``survivors`` (an int, or a callable returning one, e.g. a
    probe of its child processes).  When it reports fewer processes than
    configured, the world is formed with ``num_processes = survivors``
    directly, and the allreduce topology is replanned for the surviving
    count via ``flextree_tpu.planner.replan_for_survivors`` (awkward
    survivor counts fall back to lonely topologies or the ring, so a
    7-of-8 world still gets a real tree).

    The degrade decision is taken *before* attempting the full-world
    barrier when the liveness source already reports a short world: on the
    pinned JAX, a coordinator whose peers never register is hard-aborted
    by the XLA coordination client when the handshake deadline passes
    (``LOG(FATAL)``, not a raisable error), so discovering the shortfall
    by timing out in-process is not survivable.  If the full attempt does
    fail catchably (:class:`BringupTimeout`), the liveness source is
    re-polled and the same degrade applies.  The launcher remains
    responsible for re-assigning contiguous ``process_id``s when the dead
    process was not the highest-numbered one.

    Returns ``(report, plan)``: ``plan`` is None when the full world came
    up, else the replanned :class:`~flextree_tpu.planner.choose.Plan` for
    the degraded world (``report.degraded_to`` names its size).
    """
    try:
        cfg = _resolve_config(config)
    except json.JSONDecodeError:
        # launcher still writing the shared file: transient — skip the
        # upfront liveness decision and let init_distributed's retry loop
        # wait the file out (it re-resolves on every attempt)
        cfg = None

    def _alive():
        return survivors() if callable(survivors) else survivors

    def _short(n_alive):
        configured = cfg.num_processes if cfg is not None else None
        return (
            n_alive is not None
            and configured is not None
            and min_processes <= n_alive < configured
        )

    def _degrade(n_alive, prior_attempts=0, prior_errors=()):
        from ..planner.choose import replan_for_survivors

        configured = cfg.num_processes
        degraded = ClusterConfig(
            coordinator=cfg.coordinator,
            num_processes=n_alive,
            process_id=cfg.process_id,
        )
        report = init_distributed(
            degraded, timeout=timeout, retries=retries, merge_env=False
        )
        report.attempts += prior_attempts
        report.errors = list(prior_errors) + report.errors
        report.degraded_to = n_alive
        plan = replan_for_survivors(n_alive, nbytes, configured=configured)
        return report, plan

    n_alive = _alive()
    if _short(n_alive):
        return _degrade(n_alive)
    try:
        return init_distributed(config, timeout=timeout, retries=retries), None
    except BringupTimeout as full_err:
        if cfg is None:
            try:  # the full attempt's retries may have outlived the mid-write
                cfg = _resolve_config(config)
            except json.JSONDecodeError:
                raise full_err from None
        n_alive = _alive()  # re-poll: the world may have shrunk while waiting
        if not _short(n_alive):
            raise
        return _degrade(n_alive, full_err.attempts, full_err.errors)


def _distributed_client_active() -> bool:
    """Whether ``jax.distributed`` is already up, WITHOUT touching backends.

    ``jax.process_count()`` initializes the XLA backends, after which
    ``jax.distributed.initialize`` unconditionally raises — so idempotence
    must be probed through the distributed global state instead.
    """
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:  # pragma: no cover - private-API drift
        return False


# --------------------------------------------------------------------------
# hybrid DCN x ICI meshes
# --------------------------------------------------------------------------


def dcn_axis_names(mesh: Mesh) -> tuple[str, ...]:
    """Axis names this module marked as DCN when building ``mesh``."""
    return tuple(n for n in mesh.axis_names if str(n).startswith("dcn"))


def hybrid_mesh(
    ici_shape: tuple[int, ...],
    dcn_shape: tuple[int, ...] = (),
    axis_names: tuple[str, ...] | None = None,
    devices=None,
) -> Mesh:
    """A mesh whose leading axes cross DCN and trailing axes ride ICI.

    ``ici_shape``: per-slice torus factorization, e.g. ``(4, 2)``.
    ``dcn_shape``: slice grid, e.g. ``(2,)`` for two slices.  Axis names
    default to ``("dcn0", ..., "ici0", ...)`` so :func:`dcn_axis_names`
    (and through it :func:`plan_for_mesh`) can recover which axes pay DCN
    constants.

    On real multi-slice hardware this delegates to
    ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` (which groups
    devices by slice so each DCN axis really crosses slices); on
    single-granule hardware or virtual CPU devices it falls back to a plain
    reshape — same logical mesh, no physical grouping to respect.
    """
    if axis_names is None:
        axis_names = tuple(f"dcn{i}" for i in range(len(dcn_shape))) + tuple(
            f"ici{i}" for i in range(len(ici_shape))
        )
    if len(axis_names) != len(dcn_shape) + len(ici_shape):
        raise ValueError(
            f"{len(dcn_shape) + len(ici_shape)} axes but {len(axis_names)} names"
        )
    devs = list(devices) if devices is not None else jax.devices()
    n = math.prod(dcn_shape) * math.prod(ici_shape)
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, have {len(devs)}")
    devs = devs[:n]

    full_shape = tuple(dcn_shape) + tuple(ici_shape)
    if dcn_shape and ici_shape and _is_multi_granule(devs):
        from jax.experimental import mesh_utils

        # create_hybrid_device_mesh wants dcn_mesh_shape the same length as
        # mesh_shape and returns their ELEMENTWISE product as the shape,
        # granule-major along each combined axis.  Fold the whole slice grid
        # into the first axis, then split it back out: the result's axis 0
        # has size prod(dcn)*ici_shape[0] with granules outermost, so a
        # row-major reshape to (dcn..., ici...) keeps every dcn index on a
        # single slice.  Granule kind: slices when the devices expose
        # distinct slice_index (real multi-slice TPU); otherwise processes
        # (multi-process CPU/GPU worlds set no slice_index — discovered by
        # the executed 2-process bring-up, tools/multiproc_bringup.py).
        slice_ids = {getattr(d, "slice_index", None) for d in devs}
        by_process = len(slice_ids) <= 1  # no distinct slices -> processes
        g = math.prod(dcn_shape)
        dcn_full = (g,) + (1,) * (len(ici_shape) - 1)
        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), dcn_full, devices=devs,
            process_is_granule=by_process,
        )
        return Mesh(arr.reshape(full_shape), axis_names)
    return Mesh(np.asarray(devs).reshape(full_shape), axis_names)


def _is_multi_granule(devs) -> bool:
    """True when devices span >1 slice/process granule (real DCN exists)."""
    keys = set()
    for d in devs:
        keys.add(getattr(d, "slice_index", None))
    if len(keys) > 1 and keys != {None}:
        return True
    return len({d.process_index for d in devs}) > 1


# --------------------------------------------------------------------------
# planner bridge: mesh -> DCN-aware topology
# --------------------------------------------------------------------------


def flatten_mesh(mesh: Mesh, axis_name: str = "ft") -> Mesh:
    """Collapse a multi-axis mesh to 1-D, preserving device order.

    The FlexTree allreduce runs over a *single* named axis (like
    ``lax.psum``); a hybrid mesh is flattened row-major, so the linear rank
    varies fastest along the *last* (innermost ICI) axis — early small-gap
    stages then exchange between ICI neighbors and only the late wide-gap
    stages cross DCN, exactly the hierarchy :func:`plan_for_mesh` prices.
    """
    return Mesh(mesh.devices.reshape(-1), (axis_name,))


def plan_for_mesh(mesh: Mesh, nbytes: int, axis_names=None, params=None):
    """Choose stage widths for a flattened allreduce over ``mesh``'s axes.

    Runs the offline planner (``flextree_tpu.planner.choose_topology``) with
    the mesh's physical shape, marking ``dcn*``-named axes so cross-slice
    stages are priced with DCN constants.  Returns the planner's ``Plan``;
    ``plan.topology`` drops into ``allreduce(topo=...)`` over
    ``flatten_mesh(mesh)``.

    Axis order: stage ``i``'s rank stride (gap) is ``prod(widths[:i])``, so
    with the row-major flatten of :func:`flatten_mesh` the *first* widths
    ride the *last* mesh axis.  The planner therefore sees the axis sizes
    reversed (innermost first); the widths it returns are already in
    execution (gap) order.

    ``axis_names``: restrict to a subset of mesh axes (in mesh order) when
    the allreduce spans only those — e.g. gradient sync over ``("dcn0",
    "ici0")`` of a dp/tp mesh.
    """
    from ..planner import choose_topology
    from ..planner.cost_model import TpuCostParams

    names = tuple(axis_names) if axis_names is not None else tuple(mesh.axis_names)
    gap_order = tuple(reversed(names))  # innermost (gap-1) axis first
    shape = tuple(mesh.shape[a] for a in gap_order)
    dcn = tuple(i for i, a in enumerate(gap_order) if str(a).startswith("dcn"))
    n = math.prod(shape)
    return choose_topology(
        n,
        nbytes,
        params=params if params is not None else TpuCostParams(),
        mesh_shape=shape,
        dcn_axes=dcn,
    )


def topology_for_hybrid(mesh: Mesh, nbytes: int, axis_names=None) -> Topology:
    """Shortcut: the winning :class:`Topology` from :func:`plan_for_mesh`."""
    return plan_for_mesh(mesh, nbytes, axis_names=axis_names).topology
