"""Readiness-ordered backward/comm overlap for the gradient sync.

The serialized train step runs the whole backward, then pays the whole
sync bill (``sync_with_feedback`` after ``jax.value_and_grad``).  But the
data dependence is finer than that: the last layer's grads exist as soon
as its backward segment runs, long before the first layer's.  This module
decomposes the backward into per-layer segments (``jax.vjp`` per layer
over the layer stack — the same chain rule ``value_and_grad`` runs,
composed explicitly, so gradients are BITWISE identical) and fires each
gradient bucket's FlexTree collective the moment its last segment's grads
exist, in reverse layer order.  Each fired bucket is data-dependent only
on its own segments' grads, so a scheduler with any concurrency (XLA's
thunk executor, a TPU's async collectives) overlaps the wire time with
the remaining backward compute instead of serializing after it.

Readiness order for the ``{embed, ln_f, layers}`` model family:

1. the loss head (``ln_f`` — its grad falls out of the logits backward),
2. layers last-to-first (layer ``i``'s grads exist after its segment),
3. the embedding — its grad is the sum of the logits-matmul contribution
   (ready first) and the input-lookup contribution (ready LAST), so the
   embed bucket always fires at backward end and its wire time is always
   exposed.  Overlap shrinks exposure; it cannot zero it.

Bucket *boundaries* are planner-driven
(``planner.choose.choose_overlap_boundaries``): instead of minimizing
sync time in isolation (``choose_bucket_bytes``), boundaries equalize
each bucket's predicted comm time (α-β + codec terms) against the
remaining backward compute below it — a bucket grows to amortize launch
cost only while its wire time still fits under the compute left to hide
it.

The serialized twin (``serialize=True``) runs the IDENTICAL program with
one change: a ``lax.optimization_barrier`` over every gradient before the
first collective — the full-backward barrier the overlap removes.  Equal
collective counts, equal inputs per collective, bitwise-equal outputs —
the honest A/B comparator (and the ``overlap-serialization`` mutation
class the HLO linter must catch).

Error feedback composes: a lossy codec syncs ``grad + ef`` per fired
bucket and returns the wire's input-quantization residual per leaf, with
the exact same wire dtype and residual semantics as the serialized path
(``train.sync_with_feedback``) — the twin comparison stays bitwise even
for int8, because both paths quantize identical bucket payloads.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..schedule.stages import Topology
from ..utils.profiling import comm_span

__all__ = [
    "OverlapPlan",
    "resolve_bwd_GFLOPs",
    "readiness_segments",
    "segment_flops",
    "plan_overlap",
    "dense_overlap_step_grads",
    "moe_overlap_step_grads",
    "overlap_sync_with_feedback",
]

#: Backend-resolved defaults for ``TpuCostParams.bwd_GFLOPs`` when the
#: calibration leaves it at 0.0: a CPU host sustains single-digit GFLOP/s
#: on f32 matmuls; an accelerator is TFLOP/s-scale (v5e bf16 peak 197,
#: derated to achievable f32 backward throughput).
_BWD_GFLOPS_DEFAULTS = {"cpu": 8.0}
_BWD_GFLOPS_ACCEL = 49_000.0


def resolve_bwd_GFLOPs(params) -> float:
    """The boundary equalizer's compute throughput: the calibrated
    ``bwd_GFLOPs`` when set, else a per-backend default (same resolution
    pattern as ``bucketing._default_max_bucket_bytes``)."""
    if params is not None and getattr(params, "bwd_GFLOPs", 0.0) > 0.0:
        return params.bwd_GFLOPs
    try:
        backend = jax.default_backend()
    except Exception:  # no backend initialized (pure planning)
        backend = "cpu"
    return _BWD_GFLOPS_DEFAULTS.get(backend, _BWD_GFLOPS_ACCEL)


@dataclasses.dataclass(frozen=True)
class OverlapPlan:
    """Host-level overlap schedule: which readiness segments fire
    together, plus the model's prediction for the honesty ledger."""

    labels: tuple[str, ...]  # per segment, readiness order
    boundaries: tuple[tuple[int, ...], ...]  # groups of segment indices
    seg_bytes: tuple[int, ...]
    seg_compute_us: tuple[float, ...]
    predicted_total_us: float
    predicted_exposed_us: float

    @property
    def n_buckets(self) -> int:
        return len(self.boundaries)


def readiness_segments(params) -> list[tuple[str, Any]]:
    """(label, subtree-path) per backward segment in readiness order for
    the ``{embed, ln_f, layers: [...]}`` model family.  The subtree-path
    is ``("ln_f",)``, ``("layers", i)`` or ``("embed",)`` — usable against
    the params tree, the grads tree, and the pspecs tree alike."""
    n_layers = len(params["layers"])
    segs: list[tuple[str, Any]] = [("head", ("ln_f",))]
    for i in reversed(range(n_layers)):
        segs.append((f"layer{i}", ("layers", i)))
    segs.append(("embed", ("embed",)))
    return segs


def _subtree(tree, path):
    out = tree
    for p in path:
        out = out[p]
    return out


def segment_flops(path, params_shapes, n_tokens: int, d_model: int,
                  t_local: int) -> float:
    """Estimated backward FLOPs of one readiness segment — matmul grads
    (dgrad + wgrad ≈ 2x the forward's ``2·P·T``) over the segment's >=2-D
    weight leaves, plus the attention score/value matmuls for layer
    segments (``4·T²·d`` forward, doubled for backward).  An estimate, not
    an oracle: boundary choice degrades gracefully under scale error (a
    mispriced segment shifts one boundary by one layer), and the scale
    constant is calibratable (``TpuCostParams.bwd_GFLOPs``)."""
    sub = _subtree(params_shapes, path)
    weight_params = sum(
        math.prod(l.shape)
        for l in jax.tree.leaves(sub)
        if len(l.shape) >= 2
    )
    flops = 4.0 * weight_params * n_tokens
    if path[0] == "layers":
        flops += 8.0 * t_local * t_local * d_model * (n_tokens / t_local)
    if path == ("ln_f",):
        # the head segment's backward is the vocab-projection (logits)
        # matmul grads — its own leaf (the 1-D norm scale) carries no
        # matmul FLOPs, but d_logits flows through embed.T here, and for
        # a realistic vocab this dominates the segment
        v, d = params_shapes["embed"].shape
        flops += 4.0 * v * d * n_tokens
    if path[0] == "embed":
        # input-lookup backward is a scatter-add, not a matmul (the
        # logits contribution is charged to the head segment above)
        flops = 2.0 * d_model * n_tokens
    return flops


def _cost_topologies(mesh_axes, topos, axis_sizes) -> list:
    """Topologies the boundary chooser prices a fired bucket with: one per
    mesh axis of size > 1, the ``"psum"`` sentinel costed as a flat tree
    (same resolution as ``bucketing._derived_bucket_bytes``).  Priced for
    the fully-replicated leaf group — the dominant-bytes group; tp-sharded
    leaves sync over a subset of these axes, which the model treats as an
    approximation, not a contract."""
    out = []
    for ax in mesh_axes:
        n = int(axis_sizes.get(ax, 1))
        if n <= 1:
            continue
        topo = topos.get(ax)
        out.append(
            Topology.flat(n) if topo is None else Topology.resolve(n, topo)
        )
    return out


def plan_overlap(
    params_shapes,
    pspecs,
    mesh_axes,
    topos,
    axis_sizes,
    n_tokens: int,
    t_local: int,
    d_model: int,
    cost_params=None,
    codec=None,
) -> OverlapPlan:
    """Choose compute-equalized bucket boundaries for the readiness
    segments of ``params_shapes`` (host-level; runs at trace time on
    static shapes only)."""
    from ..planner.choose import choose_overlap_boundaries, predict_overlap_schedule

    if cost_params is None:
        from ..planner.calibrate import default_params

        cost_params = default_params()
    gflops = resolve_bwd_GFLOPs(cost_params)
    segs = readiness_segments(params_shapes)
    labels, seg_bytes, seg_us = [], [], []
    for label, path in segs:
        sub = _subtree(params_shapes, path)
        nbytes = sum(
            l.size * jnp.dtype(l.dtype).itemsize for l in jax.tree.leaves(sub)
        )
        labels.append(label)
        seg_bytes.append(int(nbytes))
        seg_us.append(
            segment_flops(path, params_shapes, n_tokens, d_model, t_local)
            / (gflops * 1e3)
        )
    cost_topos = _cost_topologies(mesh_axes, topos, axis_sizes)
    if not cost_topos:  # single-device mesh: nothing to sync, one bucket
        boundaries = (tuple(range(len(segs))),)
        total = exposed = 0.0
    else:
        boundaries = choose_overlap_boundaries(
            seg_bytes, seg_us, cost_topos, params=cost_params, codec=codec
        )
        total, exposed = predict_overlap_schedule(
            boundaries, seg_bytes, seg_us, cost_topos,
            params=cost_params, codec=codec,
        )
    return OverlapPlan(
        tuple(labels), boundaries, tuple(seg_bytes), tuple(seg_us),
        total, exposed,
    )


# ------------------------------------------------------------- bucket fire


def _sync_fired_bucket(
    bucket_tree, bucket_specs, mesh_axes, topos, train_cfg, step, ef_tree,
    name: str, zero_layout=None,
):
    """Sync one fired bucket with the exact ``sync_with_feedback``
    semantics: identity codec -> plain bitwise sync, residual None; lossy
    codec -> sync ``grad + ef`` wire-compressed, return the per-leaf
    input-quantization residual.  Inner granularity inside the fired
    payload follows ``train_cfg.bucket_bytes`` exactly like the serial
    path (None -> planner argmin under the backend cache cap, 0 ->
    per-leaf oracle, >0 -> explicit cap): the boundary decides WHEN a
    payload fires, the inner argmin its collective granularity —
    measured on the bench host, a fired bucket synced as one monolithic
    collective loses both the cache-locality win the serial path already
    banked (``bucketing.CPU_MAX_BUCKET_BYTES``) and the fine-grained
    interleaving the scheduler needs to hide wire time under compute."""
    from ..obs import bucket_provenance
    from .train import _sync_codec, sync_grads

    codec = _sync_codec(train_cfg)
    from ..utils.profiling import span_bytes

    prov = bucket_provenance(
        mesh_axes, topos, span_bytes(name) or 0,
        codec=codec if codec.lossy else None,
        chunks=train_cfg.grad_chunks, sharded=zero_layout is not None,
        fired=True,
    )
    if zero_layout is not None:
        # ZeRO composition: the fired bucket REDUCE-SCATTERS at readiness
        # (wire-compressed; EF semantics identical) — the optimizer shard
        # update and the parameter all-gather run post-backward, per
        # bucket, in zero_apply_and_gather.  The fired subtree builds its
        # own leaf-local layout (a pure function of shape+spec, so it
        # cannot disagree with the step's global ZeroLayout).
        from .zero import zero_reduce_scatter_grads

        with comm_span(name, provenance=prov):
            if not codec.lossy:
                return (
                    zero_reduce_scatter_grads(
                        bucket_tree, bucket_specs, mesh_axes, topos,
                        bucket_bytes=train_cfg.bucket_bytes,
                    ),
                    None,
                )
            v = jax.tree.map(
                lambda g, e: g + e.astype(g.dtype), bucket_tree, ef_tree
            )
            return zero_reduce_scatter_grads(
                v, bucket_specs, mesh_axes, topos,
                bucket_bytes=train_cfg.bucket_bytes,
                codec=codec, step=step, return_residual=True,
            )
    with comm_span(name, provenance=prov):
        if not codec.lossy:
            return (
                sync_grads(
                    bucket_tree, bucket_specs, mesh_axes, topos,
                    bucket_bytes=train_cfg.bucket_bytes,
                    chunks=train_cfg.grad_chunks,
                ),
                None,
            )
        v = jax.tree.map(
            lambda g, e: g + e.astype(g.dtype), bucket_tree, ef_tree
        )
        return sync_grads(
            v, bucket_specs, mesh_axes, topos,
            bucket_bytes=train_cfg.bucket_bytes,
            chunks=train_cfg.grad_chunks,
            codec=codec, step=step, return_residual=True,
        )


def _fire_boundaries(
    plan: OverlapPlan,
    seg_paths,
    seg_grads,
    state,
    pspecs,
    mesh_axes,
    topos,
    train_cfg,
    fire_at: dict[int, int],
    seg_index: int,
    synced_out: dict,
    ef_out: dict,
    zero_layout=None,
):
    """Fire every bucket whose closing segment is ``seg_index``: merge its
    segments into one tree, sync, scatter results back by path."""
    bi = fire_at.get(seg_index)
    if bi is None:
        return
    bucket = plan.boundaries[bi]
    tree = {str(i): seg_grads[i] for i in bucket}
    specs = {str(i): _subtree(pspecs, seg_paths[i]) for i in bucket}
    ef = None
    if "ef" in state:
        ef = {str(i): _subtree(state["ef"], seg_paths[i]) for i in bucket}
    nbytes = sum(plan.seg_bytes[i] for i in bucket)
    name = f"ft_overlap_bucket{bi}_{plan.labels[bucket[0]]}_{nbytes}B"
    synced, res = _sync_fired_bucket(
        tree, specs, mesh_axes, topos, train_cfg, state["step"], ef, name,
        zero_layout=zero_layout,
    )
    for i in bucket:
        synced_out[i] = synced[str(i)]
        if res is not None:
            ef_out[i] = res[str(i)]


def _assemble(params, seg_paths, synced_by_seg):
    """Rebuild a full {embed, ln_f, layers} tree from per-segment parts."""
    layers = [None] * len(params["layers"])
    out = {"embed": None, "ln_f": None, "layers": layers}
    for (path, sub) in zip(seg_paths, synced_by_seg):
        if path[0] == "layers":
            layers[path[1]] = sub
        else:
            out[path[0]] = sub
    return out


# --------------------------------------------------------------- engines


def _run_overlap_engine(
    state,
    params,
    pspecs,
    mesh_axes,
    topos,
    train_cfg,
    plan: OverlapPlan,
    seg_paths,
    backward_segments: Callable[[], Sequence],
    serialize: bool,
    zero_layout=None,
):
    """Shared core of the dense/MoE engines: walk ``backward_segments()``
    (a generator yielding each segment's raw grads in readiness order),
    firing closed buckets as segments become ready — or, serialized, after
    an ``optimization_barrier`` over every gradient (the full-backward
    barrier; same buckets, same order, bitwise-equal results).  With a
    ``zero_layout`` the fired collective is the ZeRO reduce-scatter and
    the returned "grads" tree carries per-leaf ``ZeroShard``s."""
    fire_at = {b[-1]: bi for bi, b in enumerate(plan.boundaries)}
    n_seg = len(seg_paths)
    seg_grads: list = [None] * n_seg
    synced: dict[int, Any] = {}
    ef_out: dict[int, Any] = {}

    if serialize:
        for i, g in enumerate(backward_segments()):
            seg_grads[i] = g
        # the overlap-serialization barrier: every collective below
        # depends on the COMPLETE backward, exactly like the historical
        # sync-after-value_and_grad step
        seg_grads = list(lax.optimization_barrier(tuple(seg_grads)))
        for i in range(n_seg):
            _fire_boundaries(
                plan, seg_paths, seg_grads, state, pspecs, mesh_axes, topos,
                train_cfg, fire_at, i, synced, ef_out,
                zero_layout=zero_layout,
            )
    else:
        for i, g in enumerate(backward_segments()):
            seg_grads[i] = g
            _fire_boundaries(
                plan, seg_paths, seg_grads, state, pspecs, mesh_axes, topos,
                train_cfg, fire_at, i, synced, ef_out,
                zero_layout=zero_layout,
            )

    grads = _assemble(params, seg_paths, [synced[i] for i in range(n_seg)])
    new_ef = None
    if ef_out:
        new_ef = _assemble(params, seg_paths, [ef_out[i] for i in range(n_seg)])
    return grads, new_ef


def dense_overlap_step_grads(
    state,
    tokens,
    targets,
    model_cfg,
    train_cfg,
    pspecs,
    mesh_axes,
    topos,
    n_total_tokens,
    tp_axis,
    sp_axis,
    serialize: bool = False,
    zero_layout=None,
):
    """Loss + readiness-order-synced grads (+ EF residuals) for the dense
    train step — the overlap twin of ``value_and_grad(local_loss)`` +
    ``sync_with_feedback``, bitwise-identical for the identity codec.
    With ``zero_layout`` each fired bucket reduce-scatters instead
    (ZeRO-1 composition) and the grads tree carries ``ZeroShard``s.

    Collective-context function (call inside ``shard_map``).  Returns
    ``(loss, synced_grads, new_ef_or_None)``.
    """
    from ..models.transformer import (
        cross_entropy_loss,
        final_logits,
        global_positions,
        layer_forward,
    )
    from .train import _sync_codec

    params = state["params"]
    axis_sizes = {ax: lax.axis_size(ax) for ax in mesh_axes}
    t_local = tokens.shape[1]
    codec = _sync_codec(train_cfg)
    plan = plan_overlap(
        params, pspecs, mesh_axes, topos, axis_sizes,
        n_tokens=tokens.size, t_local=t_local, d_model=model_cfg.d_model,
        codec=codec if codec.lossy else None,
    )
    seg_paths = [path for _, path in readiness_segments(params)]
    positions = global_positions(t_local, sp_axis)
    n_layers = len(params["layers"])

    # forward, holding one vjp per segment
    x, vjp_embed = jax.vjp(
        lambda e: e[tokens].astype(model_cfg.dtype), params["embed"]
    )
    layer_vjps = []
    for layer in params["layers"]:
        x, vjp_l = jax.vjp(
            lambda l, h: layer_forward(
                l, h, positions, model_cfg, tp_axis=tp_axis, sp_axis=sp_axis
            ),
            layer, x,
        )
        layer_vjps.append(vjp_l)

    def head(embed, ln_f, h):
        loss_sum, _ = cross_entropy_loss(final_logits(embed, ln_f, h), targets)
        return loss_sum / n_total_tokens

    loss, vjp_head = jax.vjp(head, params["embed"], params["ln_f"], x)

    def backward_segments():
        d_embed_head, d_ln_f, dx = vjp_head(jnp.float32(1.0))
        yield d_ln_f  # segment 0: the loss head
        for i in reversed(range(n_layers)):
            d_layer, dx = layer_vjps[i](dx)
            yield d_layer
        (d_embed_in,) = vjp_embed(dx)
        yield d_embed_head + d_embed_in  # last: embed closes at backward end

    grads, new_ef = _run_overlap_engine(
        state, params, pspecs, mesh_axes, topos, train_cfg, plan, seg_paths,
        backward_segments, serialize, zero_layout=zero_layout,
    )
    return loss, grads, new_ef


def moe_overlap_step_grads(
    state,
    tokens,
    targets,
    model_cfg,
    train_cfg,
    pspecs,
    mesh_axes,
    topos,
    n_total_tokens,
    n_devices,
    tp_axis,
    sp_axis,
    ep_axis,
    serialize: bool = False,
    zero_layout=None,
):
    """MoE twin of :func:`dense_overlap_step_grads`: per-layer segments
    carry an auxiliary router-balance output whose cotangent is the
    constant aux weight, so the composed vjp equals
    ``value_and_grad(local_loss, has_aux=True)`` bitwise.

    Returns ``(ce, aux_mean, grads, new_ef_or_None)``.
    """
    from ..models.moe import moe_layer
    from ..models.transformer import (
        attention_block,
        cross_entropy_loss,
        final_logits,
        global_positions,
        mlp_block,
        rms_norm,
    )
    from .train import _sync_codec

    params = state["params"]
    axis_sizes = {ax: lax.axis_size(ax) for ax in mesh_axes}
    t_local = tokens.shape[1]
    codec = _sync_codec(train_cfg)
    plan = plan_overlap(
        params, pspecs, mesh_axes, topos, axis_sizes,
        n_tokens=tokens.size, t_local=t_local, d_model=model_cfg.d_model,
        codec=codec if codec.lossy else None,
    )
    seg_paths = [path for _, path in readiness_segments(params)]
    positions = global_positions(t_local, sp_axis)
    n_layers = len(params["layers"])
    n_moe = sum(1 for i in range(n_layers) if model_cfg.is_moe_layer(i))

    def apply_layer(i, layer, h):
        h = attention_block(
            layer, h, positions, model_cfg, tp_axis=tp_axis, sp_axis=sp_axis
        )
        if model_cfg.is_moe_layer(i):
            hh = rms_norm(h, layer["ln2"])
            y, aux = moe_layer(
                layer, hh, model_cfg, tp_axis=tp_axis, ep_axis=ep_axis
            )
            return h + y, aux
        return mlp_block(layer, h, model_cfg, tp_axis=tp_axis), jnp.float32(0.0)

    x, vjp_embed = jax.vjp(
        lambda e: e[tokens].astype(model_cfg.dtype), params["embed"]
    )
    layer_vjps = []
    aux_vals = []
    for i, layer in enumerate(params["layers"]):
        (x, aux_i), vjp_l = jax.vjp(
            lambda l, h, i=i: apply_layer(i, l, h), layer, x
        )
        layer_vjps.append(vjp_l)
        aux_vals.append(aux_i)

    def head(embed, ln_f, h):
        loss_sum, _ = cross_entropy_loss(final_logits(embed, ln_f, h), targets)
        return loss_sum / n_total_tokens

    ce, vjp_head = jax.vjp(head, params["embed"], params["ln_f"], x)
    aux_total = jnp.float32(0.0)
    for a in aux_vals:
        aux_total = aux_total + a
    aux_mean = aux_total / max(n_moe, 1)
    # d(total_loss)/d(aux_i): the aux enters the optimized loss as
    # router_aux_weight * (sum(aux_i)/n_moe) / n_devices — a constant
    # cotangent per layer (moe_train.make_moe_train_step's local_loss)
    d_aux = jnp.float32(
        model_cfg.router_aux_weight / (max(n_moe, 1) * n_devices)
    )

    def backward_segments():
        d_embed_head, d_ln_f, dx = vjp_head(jnp.float32(1.0))
        yield d_ln_f
        for i in reversed(range(n_layers)):
            d_layer, dx = layer_vjps[i]((dx, d_aux))
            yield d_layer
        (d_embed_in,) = vjp_embed(dx)
        yield d_embed_head + d_embed_in

    grads, new_ef = _run_overlap_engine(
        state, params, pspecs, mesh_axes, topos, train_cfg, plan, seg_paths,
        backward_segments, serialize, zero_layout=zero_layout,
    )
    return ce, aux_mean, grads, new_ef


# ------------------------------------------------- whole-tree (pipeline)


def overlap_sync_with_feedback(
    state, grads, pspecs, mesh_axes, topos, train_cfg, serialize: bool = False
):
    """Post-backward readiness-ordered sync of a WHOLE gradient tree — the
    pipeline step's overlap path.

    SPMD GPipe's tick loop is a ``lax.scan``, and the scan transpose
    emits every parameter gradient from one fused op: a true dataflow
    barrier that readiness ordering cannot reach inside (that would take
    MPMD per-stage programs).  What overlap CAN do there is schedule the
    bucket collectives into the post-backward bubble — fired per
    readiness bucket (head, layers, embed), each data-dependent only on
    its own leaves, so the scheduler may run them under the loss psum /
    metrics / optimizer tail instead of serializing before it.  Semantics
    (and EF accounting) are exactly ``train.sync_with_feedback``'s;
    ``serialize=True`` adds the same optimization_barrier twin as the
    dense engine, for the A/B and the mutation class.
    """
    seg_paths = []
    seg_grads = []
    # readiness buckets at whole-tree granularity: head norm, the layer
    # stack, then embed (the order the dense backward would free them)
    for key in ("ln_f", "layers", "embed"):
        seg_paths.append((key,))
        seg_grads.append(grads[key])
    if serialize:
        seg_grads = list(lax.optimization_barrier(tuple(seg_grads)))
    synced_parts = {}
    ef_parts = {}
    any_ef = False
    for path, sub in zip(seg_paths, seg_grads):
        specs = _subtree(pspecs, path)
        ef = _subtree(state["ef"], path) if "ef" in state else None
        nbytes = sum(
            l.size * jnp.dtype(l.dtype).itemsize for l in jax.tree.leaves(sub)
        )
        synced, res = _sync_fired_bucket(
            sub, specs, mesh_axes, topos, train_cfg, state["step"], ef,
            f"ft_overlap_tail_{path[0]}_{nbytes}B",
        )
        synced_parts[path[0]] = synced
        if res is not None:
            ef_parts[path[0]] = res
            any_ef = True
    out = {k: synced_parts[k] for k in grads}
    new_ef = {k: ef_parts[k] for k in grads} if any_ef else None
    return out, new_ef
