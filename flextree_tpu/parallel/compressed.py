"""Compression-aware FlexTree collectives: codecs applied per hop on the wire.

``allreduce`` (``parallel/allreduce.py``) chooses the *shape* of the
collective; this module additionally chooses the *bytes*: each hop of the
tree/ring reduce-scatter + allgather carries the payload in a wire codec
(``ops/quantize.py``) instead of the gradient dtype.  The shape of the
implementation mirrors the uncompressed schedules exactly:

- **tree phase 1** (per stage): the local buffer is split into the stage's
  ``w`` tiles, each tile block-scale **encoded**, the encoded tiles (plus
  their f32 scales, ~0.4% of the payload) exchanged by a *grouped*
  ``lax.all_to_all`` over the stage groups — the same group/gap math as
  ``psum_scatter(axis_index_groups=...)``, and the same tile ownership
  (group position ``p`` ends up owning reduced tile ``p``) — then decoded
  and folded in f32.  Partial sums are re-encoded at each subsequent
  stage: compression is per hop, exactly like the wire formats EQuARX
  fuses into XLA's collectives (PAPERS.md).
- **tree phase 2**: the final reduced tile is encoded ONCE and forwarded
  *still encoded* through the stage allgathers (pure data movement — no
  decode/re-encode per hop), decoded once at the end.  One lossy event
  for the whole phase, and the gathers move 1/4 the bytes.
- **ring**: the classic 2(N-1)-step walk with the sent block encoded per
  hop and folded in f32; phase 2 forwards encoded blocks.
- **lonely**: the buddy fold/restore ``ppermute``s carry encoded payload,
  and the prefix-tree stages run a compressed ppermute-ring (grouped
  collectives cannot cover a partial axis — same constraint as
  ``_grouped_reduce_scatter_generic``).

The identity codec routes to the uncompressed ``allreduce`` — bitwise
identical by construction; ``bf16`` rides the existing schedules with a
bf16 payload (the collectives carry and accumulate bf16 on the wire — the
HLO linter holds them to it).  Sum-only: wire compression of a gradient
sync has no business reducing anything else.

Error feedback: ``return_residual=True`` additionally returns
``x - decode(encode(x))`` computed from the *actual* first-hop encode (the
same blocks, salt and stochastic-rounding step the wire used), so the
train state's EF residual telescopes exactly for tree schedules — see
``docs/QUANTIZED_COLLECTIVES.md`` for the contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.quantize import Codec, decode_int8, encode_int8, get_codec
from ..schedule.stages import LonelyTopology, Topology
from .allreduce import (
    _NATIVE_PSUM,
    _groups_or_none,
    _next_in_group,
    _split_main_tail,
    allreduce,
)

__all__ = ["compressed_allreduce", "local_residual"]

# salt namespaces so no two encode sites share a stochastic-rounding
# stream: phase-1 stage i uses salt i (stage 0 == the canonical salt 0 of
# Codec.roundtrip), the others get distinct high bits
_SALT_AG = 0x41470000
_SALT_RING = 0x52490000
_SALT_LONELY = 0x4C4F0000


def _padded(tile: int, block: int) -> int:
    return tile + (-tile) % block


def compressed_allreduce(
    x: jax.Array,
    axis_name,
    topo=None,
    codec="f32",
    chunks: int = 1,
    step=0,
    return_residual: bool = False,
):
    """Sum-allreduce of ``x`` over ``axis_name`` with ``codec`` on the wire.

    Drop-in for ``allreduce(x, axis_name, topo, op='sum', chunks=...)``;
    ``step`` keys the deterministic stochastic rounding (pass the train
    step counter — traced scalars are fine).  ``return_residual=True``
    returns ``(result, residual)`` where ``residual = x - C(x)`` is the
    local input-quantization loss for error feedback (zeros for lossless
    codecs; sub-N tails are reduced in exact f32, so their residual is 0).
    """
    codec = get_codec(codec)
    n = lax.axis_size(axis_name)
    if not codec.lossy or n <= 1:
        out = allreduce(x, axis_name, topo=topo, op="sum", chunks=chunks)
        if return_residual:
            return out, jnp.zeros_like(x)
        return out
    if codec.name == "bf16":
        wire = x.astype(jnp.bfloat16)
        out = allreduce(wire, axis_name, topo=topo, op="sum", chunks=chunks)
        out = out.astype(x.dtype)
        if return_residual:
            return out, x - wire.astype(x.dtype)
        return out

    # ---- int8 block-scaled, per-hop ----
    topo = Topology.resolve(n, topo)
    shape = x.shape
    v = x.reshape(-1).astype(jnp.float32)
    parts: list[jax.Array] = []
    res_parts: list[jax.Array] = []
    if isinstance(topo, LonelyTopology):
        head, tail = _split_main_tail(v, topo.tree.num_nodes)
        if head is not None:
            out, res = _lonely_int8(head, axis_name, topo, codec, step)
            parts.append(out)
            res_parts.append(res)
    else:
        head, tail = _split_main_tail(v, n)
        if head is not None:
            if topo.is_ring:
                out, res = _ring_int8(head, axis_name, n, codec, step)
                parts.append(out)
                res_parts.append(res)
            else:
                out, res = _tree_int8(head, axis_name, topo, codec, chunks, step)
                parts.append(out)
                res_parts.append(res)
    if tail is not None:
        # <N-element remainder: one tiny dense f32 collective, exact —
        # compression has nothing to amortize on sub-N payloads
        parts.append(_NATIVE_PSUM(tail, axis_name))
        res_parts.append(jnp.zeros_like(tail))
    out = (parts[0] if len(parts) == 1 else jnp.concatenate(parts)).reshape(shape)
    out = out.astype(x.dtype)
    if return_residual:
        res = (
            res_parts[0] if len(res_parts) == 1 else jnp.concatenate(res_parts)
        ).reshape(shape)
        return out, res.astype(x.dtype)
    return out


def local_residual(x: jax.Array, codec, step=0) -> jax.Array:
    """Canonical local residual ``x - C(x)`` for error feedback when the
    wire residual is not available (the ``codec.roundtrip`` map over the
    flat buffer, salt 0 — exactly the stage-0 encode of a block-aligned
    tree).  Zeros for lossless codecs."""
    codec = get_codec(codec)
    if not codec.lossy:
        return jnp.zeros_like(x)
    return x - codec.roundtrip(x, step)


# --------------------------------------------------------------- tree


def _stage_rs_int8(v, axis_name, topo: Topology, stage: int, codec: Codec, step):
    """One compressed phase-1 stage: encode the w tiles, grouped
    all_to_all of (int8 payload, f32 scales), decode + fold in f32.
    Returns (reduced tile, this rank's decoded own-encode) — the latter is
    the wire-exact roundtrip used for the stage-0 residual."""
    w = topo.widths[stage]
    tile = v.shape[0] // w
    groups = _groups_or_none(topo, stage)
    q, s = encode_int8(v.reshape(w, tile), step, salt=stage, block=codec.block)
    with jax.named_scope(f"ftq_rs_stage{stage}_w{w}"):
        qx = lax.all_to_all(
            q, axis_name, split_axis=0, concat_axis=0, axis_index_groups=groups
        )
        sx = lax.all_to_all(
            s, axis_name, split_axis=0, concat_axis=0, axis_index_groups=groups
        )
    dec = decode_int8(qx, sx, tile, block=codec.block)
    own = decode_int8(q, s, tile, block=codec.block).reshape(-1)
    return dec.sum(axis=0), own


def _ag_int8(tile_v, axis_name, topo: Topology, codec: Codec, step, salt):
    """Phase 2: encode the reduced tile once, forward it *encoded* through
    the stage allgathers, decode every segment at the end."""
    t = tile_v.shape[0]
    tp = _padded(t, codec.block)
    q, s = encode_int8(tile_v, step, salt=salt, block=codec.block)
    for i in reversed(range(topo.num_stages)):
        groups = _groups_or_none(topo, i)
        with jax.named_scope(f"ftq_ag_stage{i}_w{topo.widths[i]}"):
            q = lax.all_gather(q, axis_name, axis_index_groups=groups, axis=0, tiled=True)
            s = lax.all_gather(s, axis_name, axis_index_groups=groups, axis=0, tiled=True)
    segs = q.shape[0] // tp
    dec = decode_int8(
        q.reshape(segs, tp), s.reshape(segs, -1), t, block=codec.block
    )
    return dec.reshape(-1)


def _tree_int8(head, axis_name, topo: Topology, codec: Codec, chunks: int, step):
    """Compressed k-ary tree on the divisible head, optionally
    chunk-pipelined with the same phase-2/phase-1 interleaving as
    ``tree_allreduce``."""
    from .allreduce import _chunk_sizes

    n = topo.num_nodes

    def rs_all(piece):
        own0 = None
        v = piece
        for i in range(topo.num_stages):
            v, own = _stage_rs_int8(v, axis_name, topo, i, codec, step)
            if i == 0:
                own0 = own
        return v, own0

    sizes = _chunk_sizes(head.size, n, chunks)
    if len(sizes) == 1:
        tile, own0 = rs_all(head)
        out = _ag_int8(tile, axis_name, topo, codec, step, _SALT_AG)
        return out, head - own0
    pieces, off = [], 0
    for sz in sizes:
        pieces.append(head[off : off + sz])
        off += sz
    outs, residuals, scattered = [], [], None
    for c, piece in enumerate(pieces):
        with jax.named_scope(f"ftq_chunk{c}_rs"):
            cur, own0 = rs_all(piece)
        residuals.append(piece - own0)
        if scattered is not None:
            with jax.named_scope(f"ftq_chunk{c - 1}_ag"):
                outs.append(
                    _ag_int8(scattered, axis_name, topo, codec, step, _SALT_AG + c - 1)
                )
        scattered = cur
    with jax.named_scope(f"ftq_chunk{len(pieces) - 1}_ag"):
        outs.append(
            _ag_int8(
                scattered, axis_name, topo, codec, step, _SALT_AG + len(pieces) - 1
            )
        )
    return jnp.concatenate(outs), jnp.concatenate(residuals)


# --------------------------------------------------------------- ring


def _ring_int8(head, axis_name, n: int, codec: Codec, step):
    """Compressed ring: per-hop encode of the sent block, f32 fold at the
    receiver; phase 2 forwards blocks still encoded.  The residual is the
    canonical local map (ring blocks are first encoded at differing fold
    depths, so no single wire encode covers the whole local buffer — see
    docs/QUANTIZED_COLLECTIVES.md)."""
    split = head.shape[0] // n
    sp = _padded(split, codec.block)
    nb = sp // codec.block
    idx = lax.axis_index(axis_name)
    right = [(j, (j + 1) % n) for j in range(n)]
    v = head

    for hop in range(n - 1):
        send_b = (idx - hop) % n
        recv_b = (idx - hop - 1) % n
        chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
        q, s = encode_int8(chunk, step, salt=_SALT_RING + hop, block=codec.block)
        with jax.named_scope(f"ftq_ring_rs{hop}"):
            q = lax.ppermute(q, axis_name, right)
            s = lax.ppermute(s, axis_name, right)
        got = decode_int8(q, s, split, block=codec.block)
        cur = lax.dynamic_slice_in_dim(v, recv_b * split, split, axis=0)
        v = lax.dynamic_update_slice_in_dim(v, cur + got, recv_b * split, axis=0)

    # phase 2: encode the owned (fully-reduced) block once, forward encoded
    own_b = (idx + 1) % n
    own = lax.dynamic_slice_in_dim(v, own_b * split, split, axis=0)
    q, s = encode_int8(own, step, salt=_SALT_RING - 1, block=codec.block)
    qbuf = jnp.zeros((n * sp,), jnp.int8)
    sbuf = jnp.zeros((n * nb,), jnp.float32)
    qbuf = lax.dynamic_update_slice_in_dim(qbuf, q, own_b * sp, axis=0)
    sbuf = lax.dynamic_update_slice_in_dim(sbuf, s, own_b * nb, axis=0)
    for hop in range(n - 1):
        send_b = (idx + 1 - hop) % n
        recv_b = (idx - hop) % n
        cq = lax.dynamic_slice_in_dim(qbuf, send_b * sp, sp, axis=0)
        cs = lax.dynamic_slice_in_dim(sbuf, send_b * nb, nb, axis=0)
        with jax.named_scope(f"ftq_ring_ag{hop}"):
            cq = lax.ppermute(cq, axis_name, right)
            cs = lax.ppermute(cs, axis_name, right)
        qbuf = lax.dynamic_update_slice_in_dim(qbuf, cq, recv_b * sp, axis=0)
        sbuf = lax.dynamic_update_slice_in_dim(sbuf, cs, recv_b * nb, axis=0)
    dec = decode_int8(
        qbuf.reshape(n, sp), sbuf.reshape(n, nb), split, block=codec.block
    )
    res = head - decode_int8(*encode_int8(head, step, salt=0, block=codec.block),
                             head.shape[0], block=codec.block)
    return dec.reshape(-1), res


# --------------------------------------------------------------- lonely


def _compressed_grouped_rs(v, axis_name, topo: Topology, stage: int, codec: Codec, step):
    """Width-w grouped reduce-scatter as a compressed ppermute ring —
    the lossy twin of ``_grouped_reduce_scatter_generic`` (grouped XLA
    collectives cannot cover a partial axis, so lonely prefix trees ride
    permutations; ranks outside ``topo.num_nodes`` receive zeros and are
    overwritten by the caller)."""
    w, gap = topo.widths[stage], topo.gaps[stage]
    tile = v.shape[0] // w
    idx = lax.axis_index(axis_name)
    pos = (idx // gap) % w
    perm = [(r, _next_in_group(r, w, gap)) for r in range(topo.num_nodes)]

    cur_send = (pos - 1) % w
    acc = v
    for hop in range(w - 1):
        chunk = lax.dynamic_slice_in_dim(acc, cur_send * tile, tile, axis=0)
        q, s = encode_int8(
            chunk, step, salt=_SALT_LONELY + 16 * stage + hop, block=codec.block
        )
        q = lax.ppermute(q, axis_name, perm)
        s = lax.ppermute(s, axis_name, perm)
        got = decode_int8(q, s, tile, block=codec.block)
        recv_b = (cur_send - 1) % w
        cur = lax.dynamic_slice_in_dim(acc, recv_b * tile, tile, axis=0)
        acc = lax.dynamic_update_slice_in_dim(acc, cur + got, recv_b * tile, axis=0)
        cur_send = recv_b
    return lax.dynamic_slice_in_dim(acc, pos * tile, tile, axis=0)


def _compressed_grouped_ag(v, axis_name, topo: Topology, stage: int, codec: Codec, step):
    """Width-w grouped allgather forwarding encoded blocks around the
    group ring (phase-2 twin of ``_compressed_grouped_rs``)."""
    w, gap = topo.widths[stage], topo.gaps[stage]
    t = v.shape[0]
    tp = _padded(t, codec.block)
    nb = tp // codec.block
    idx = lax.axis_index(axis_name)
    pos = (idx // gap) % w
    perm = [(r, _next_in_group(r, w, gap)) for r in range(topo.num_nodes)]

    q, s = encode_int8(v, step, salt=_SALT_LONELY + 4096 + stage, block=codec.block)
    qbuf = jnp.zeros((w * tp,), jnp.int8)
    sbuf = jnp.zeros((w * nb,), jnp.float32)
    qbuf = lax.dynamic_update_slice_in_dim(qbuf, q, pos * tp, axis=0)
    sbuf = lax.dynamic_update_slice_in_dim(sbuf, s, pos * nb, axis=0)
    for hop in range(w - 1):
        send_b = (pos - hop) % w
        recv_b = (pos - hop - 1) % w
        cq = lax.dynamic_slice_in_dim(qbuf, send_b * tp, tp, axis=0)
        cs = lax.dynamic_slice_in_dim(sbuf, send_b * nb, nb, axis=0)
        cq = lax.ppermute(cq, axis_name, perm)
        cs = lax.ppermute(cs, axis_name, perm)
        qbuf = lax.dynamic_update_slice_in_dim(qbuf, cq, recv_b * tp, axis=0)
        sbuf = lax.dynamic_update_slice_in_dim(sbuf, cs, recv_b * nb, axis=0)
    dec = decode_int8(qbuf.reshape(w, tp), sbuf.reshape(w, nb), t, block=codec.block)
    return dec.reshape(-1)


def _lonely_int8(head, axis_name, topo: LonelyTopology, codec: Codec, step):
    """Compressed ``m+l`` shape: encoded buddy fold, compressed prefix-tree
    stages, encoded restore (structure mirrors ``lonely_allreduce``)."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    idx = lax.axis_index(axis_name)
    t = head.shape[0]

    with jax.named_scope("ftq_lonely_fold"):
        q, s = encode_int8(head, step, salt=_SALT_LONELY - 1, block=codec.block)
        qg = lax.ppermute(q, axis_name, [(m + i, i) for i in range(l)])
        sg = lax.ppermute(s, axis_name, [(m + i, i) for i in range(l)])
        got = decode_int8(qg, sg, t, block=codec.block)
        v = jnp.where(idx < l, head + got, head)
    for i in range(tree.num_stages):
        with jax.named_scope(f"ftq_lonely_rs{i}"):
            v = _compressed_grouped_rs(v, axis_name, tree, i, codec, step)
    for i in reversed(range(tree.num_stages)):
        with jax.named_scope(f"ftq_lonely_ag{i}"):
            v = _compressed_grouped_ag(v, axis_name, tree, i, codec, step)
    with jax.named_scope("ftq_lonely_restore"):
        q, s = encode_int8(v, step, salt=_SALT_LONELY - 2, block=codec.block)
        q2 = lax.ppermute(q, axis_name, [(i, m + i) for i in range(l)])
        s2 = lax.ppermute(s, axis_name, [(i, m + i) for i in range(l)])
        back = decode_int8(q2, s2, t, block=codec.block)
        # every rank adopts decode(encode(result)): the encode is
        # deterministic and all tree ranks hold identical ``v``, so the
        # lonely ranks' shipped copy is bit-identical to what the tree
        # ranks compute locally — without this, lonely ranks would hold a
        # re-quantized result the tree ranks don't (replica drift)
        own = decode_int8(q, s, t, block=codec.block)
        out = jnp.where(idx >= m, back, own)
    res = head - decode_int8(
        *encode_int8(head, step, salt=0, block=codec.block), t, block=codec.block
    )
    return out, res
