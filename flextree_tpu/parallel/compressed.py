"""Compression-aware FlexTree collectives: codecs applied per hop on the wire.

``allreduce`` (``parallel/allreduce.py``) chooses the *shape* of the
collective; this module additionally chooses the *bytes*: each hop of the
tree/ring reduce-scatter + allgather carries the payload in a wire codec
(``ops/quantize.py``) instead of the gradient dtype.  The shape of the
implementation mirrors the uncompressed schedules exactly:

- **tree phase 1** (per stage): the local buffer is split into the stage's
  ``w`` tiles, each tile block-scale **encoded**, the encoded tiles (plus
  their f32 scales, ~0.4% of the payload) exchanged by a *grouped*
  ``lax.all_to_all`` over the stage groups — the same group/gap math as
  ``psum_scatter(axis_index_groups=...)``, and the same tile ownership
  (group position ``p`` ends up owning reduced tile ``p``) — then decoded
  and folded in f32.  Partial sums are re-encoded at each subsequent
  stage: compression is per hop, exactly like the wire formats EQuARX
  fuses into XLA's collectives (PAPERS.md).
- **tree phase 2**: the final reduced tile is encoded ONCE and forwarded
  *still encoded* through the stage allgathers (pure data movement — no
  decode/re-encode per hop), decoded once at the end.  One lossy event
  for the whole phase, and the gathers move 1/4 the bytes.
- **ring**: the classic 2(N-1)-step walk with the sent block encoded per
  hop and folded in f32; phase 2 forwards encoded blocks.
- **lonely**: the buddy fold/restore ``ppermute``s carry encoded payload,
  and the prefix-tree stages run a compressed ppermute-ring (grouped
  collectives cannot cover a partial axis — same constraint as
  ``_grouped_reduce_scatter_generic``).

The identity codec routes to the uncompressed ``allreduce`` — bitwise
identical by construction; ``bf16`` rides the existing schedules with a
bf16 payload (the collectives carry and accumulate bf16 on the wire — the
HLO linter holds them to it).  Sum-only: wire compression of a gradient
sync has no business reducing anything else.

Error feedback: ``return_residual=True`` additionally returns
``x - decode(encode(x))`` computed from the *actual* first-hop encode (the
same blocks, salt and stochastic-rounding step the wire used), so the
train state's EF residual telescopes exactly for tree schedules — see
``docs/QUANTIZED_COLLECTIVES.md`` for the contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.quantize import Codec, decode_int8, encode_int8, get_codec
from ..ops.reduce import get_op
from ..schedule.stages import LonelyTopology, Topology
from .allreduce import (
    _NATIVE_PSUM,
    _groups_or_none,
    _lonely_allgather,
    _lonely_reduce_scatter,
    _next_in_group,
    _ring_allgather,
    _ring_reduce_scatter,
    _split_main_tail,
    _tree_allgather,
    _tree_reduce_scatter,
    allreduce,
)

__all__ = [
    "compressed_allreduce",
    "compressed_reduce_scatter",
    "compressed_all_gather",
    "local_residual",
]

# salt namespaces so no two encode sites share a stochastic-rounding
# stream: phase-1 stage i uses salt i (stage 0 == the canonical salt 0 of
# Codec.roundtrip), the others get distinct high bits
_SALT_AG = 0x41470000
_SALT_RING = 0x52490000
_SALT_LONELY = 0x4C4F0000


def _padded(tile: int, block: int) -> int:
    return tile + (-tile) % block


def compressed_allreduce(
    x: jax.Array,
    axis_name,
    topo=None,
    codec="f32",
    chunks: int = 1,
    step=0,
    return_residual: bool = False,
):
    """Sum-allreduce of ``x`` over ``axis_name`` with ``codec`` on the wire.

    Drop-in for ``allreduce(x, axis_name, topo, op='sum', chunks=...)``;
    ``step`` keys the deterministic stochastic rounding (pass the train
    step counter — traced scalars are fine).  ``return_residual=True``
    returns ``(result, residual)`` where ``residual = x - C(x)`` is the
    local input-quantization loss for error feedback (zeros for lossless
    codecs; sub-N tails are reduced in exact f32, so their residual is 0).
    """
    codec = get_codec(codec)
    n = lax.axis_size(axis_name)
    if not codec.lossy or n <= 1:
        out = allreduce(x, axis_name, topo=topo, op="sum", chunks=chunks)
        if return_residual:
            return out, jnp.zeros_like(x)
        return out
    if codec.name == "bf16":
        wire = x.astype(jnp.bfloat16)
        out = allreduce(wire, axis_name, topo=topo, op="sum", chunks=chunks)
        out = out.astype(x.dtype)
        if return_residual:
            return out, x - wire.astype(x.dtype)
        return out

    # ---- int8 block-scaled, per-hop ----
    topo = Topology.resolve(n, topo)
    shape = x.shape
    v = x.reshape(-1).astype(jnp.float32)
    parts: list[jax.Array] = []
    res_parts: list[jax.Array] = []
    if isinstance(topo, LonelyTopology):
        head, tail = _split_main_tail(v, topo.tree.num_nodes)
        if head is not None:
            out, res = _lonely_int8(head, axis_name, topo, codec, step)
            parts.append(out)
            res_parts.append(res)
    else:
        head, tail = _split_main_tail(v, n)
        if head is not None:
            if topo.is_ring:
                out, res = _ring_int8(head, axis_name, n, codec, step)
                parts.append(out)
                res_parts.append(res)
            else:
                out, res = _tree_int8(head, axis_name, topo, codec, chunks, step)
                parts.append(out)
                res_parts.append(res)
    if tail is not None:
        # <N-element remainder: one tiny dense f32 collective, exact —
        # compression has nothing to amortize on sub-N payloads
        parts.append(_NATIVE_PSUM(tail, axis_name))
        res_parts.append(jnp.zeros_like(tail))
    out = (parts[0] if len(parts) == 1 else jnp.concatenate(parts)).reshape(shape)
    out = out.astype(x.dtype)
    if return_residual:
        res = (
            res_parts[0] if len(res_parts) == 1 else jnp.concatenate(res_parts)
        ).reshape(shape)
        return out, res.astype(x.dtype)
    return out


def local_residual(x: jax.Array, codec, step=0) -> jax.Array:
    """Canonical local residual ``x - C(x)`` for error feedback when the
    wire residual is not available (the ``codec.roundtrip`` map over the
    flat buffer, salt 0 — exactly the stage-0 encode of a block-aligned
    tree).  Zeros for lossless codecs."""
    codec = get_codec(codec)
    if not codec.lossy:
        return jnp.zeros_like(x)
    return x - codec.roundtrip(x, step)


# ------------------------------------------------- split phases (PR 7)
#
# ``compressed_allreduce`` composes a per-hop-compressed reduce-scatter
# with an encoded-forwarding allgather; these entry points expose the two
# halves as first-class collectives with the SAME shard layout as the
# uncompressed split (``parallel.allreduce.reduce_scatter``: owned head
# block per ``schedule.blocks.owned_block``, <N tail reduced dense in
# exact f32 and replicated).  Salts match the fused paths, so for
# block-aligned buffers ``compressed_all_gather(compressed_reduce_scatter
# (x)) == compressed_allreduce(x)`` bitwise per codec.


def compressed_reduce_scatter(
    x: jax.Array,
    axis_name,
    topo=None,
    codec="int8",
    step=0,
    return_residual: bool = False,
):
    """Phase 1 alone with ``codec`` on the wire: this rank's reduced shard
    (owned head block + exact-f32 replicated tail).  Sum-only.

    ``return_residual=True`` also returns the local input-quantization
    residual ``x - C(x)`` for error feedback: the wire-exact first-hop
    encode for tree shapes, the canonical local map for ring/lonely (the
    same rule as ``compressed_allreduce``); the tail is exact, so its
    residual is 0.
    """
    codec = get_codec(codec)
    n = lax.axis_size(axis_name)
    if not codec.lossy or n <= 1:
        from .allreduce import reduce_scatter

        out = reduce_scatter(x, axis_name, topo=topo, op="sum")
        if return_residual:
            return out, jnp.zeros_like(x)
        return out
    topo = Topology.resolve(n, topo)
    owners = topo.tree.num_nodes if isinstance(topo, LonelyTopology) else n
    shape = x.shape
    v = x.reshape(-1).astype(jnp.float32)
    head, tail = _split_main_tail(v, owners)

    parts: list[jax.Array] = []
    res = jnp.zeros_like(v)
    if codec.name == "bf16":
        if head is not None:
            wire = head.astype(jnp.bfloat16)
            rop = get_op("sum")
            if isinstance(topo, LonelyTopology):
                tile = _lonely_reduce_scatter(wire, axis_name, topo, rop)
            elif topo.is_ring:
                tile = _ring_reduce_scatter(wire, axis_name, n, rop)
            else:
                tile = _tree_reduce_scatter(wire, axis_name, topo, rop)
            parts.append(tile.astype(jnp.float32))
            res = res.at[: head.shape[0]].set(head - wire.astype(jnp.float32))
    elif head is not None:
        if isinstance(topo, LonelyTopology):
            tile = _lonely_rs_int8(head, axis_name, topo, codec, step)
            own = decode_int8(
                *encode_int8(head, step, salt=0, block=codec.block),
                head.shape[0], block=codec.block,
            )
            parts.append(tile)
            res = res.at[: head.shape[0]].set(head - own)
        elif topo.is_ring:
            tile = _ring_rs_int8(head, axis_name, n, codec, step)
            own = decode_int8(
                *encode_int8(head, step, salt=0, block=codec.block),
                head.shape[0], block=codec.block,
            )
            parts.append(tile)
            res = res.at[: head.shape[0]].set(head - own)
        else:
            tile, own0 = _tree_rs_int8_all_stages(head, axis_name, topo, codec, step)
            parts.append(tile)
            res = res.at[: head.shape[0]].set(head - own0)
    if tail is not None:
        parts.append(_NATIVE_PSUM(tail, axis_name))
    if not parts:
        out = jnp.zeros((0,), x.dtype)
    else:
        out = (parts[0] if len(parts) == 1 else jnp.concatenate(parts)).astype(
            x.dtype
        )
    if return_residual:
        return out, res.reshape(shape).astype(x.dtype)
    return out


def compressed_all_gather(
    x: jax.Array, axis_name, topo=None, out_shape=None, codec="int8", step=0
) -> jax.Array:
    """Phase 2 alone with ``codec`` on the wire: the owned head block is
    encoded ONCE and forwarded still-encoded through the stage gathers
    (one lossy event for the whole phase); every rank decodes identical
    bytes, so replicas cannot drift — including the owner, which adopts
    ``decode(encode(tile))`` rather than its exact local tile.  The tail
    part of the shard is appended locally, exact."""
    codec = get_codec(codec)
    n = lax.axis_size(axis_name)
    if not codec.lossy or n <= 1:
        from .allreduce import all_gather

        return all_gather(x, axis_name, topo=topo, out_shape=out_shape)
    topo = Topology.resolve(n, topo)
    owners = topo.tree.num_nodes if isinstance(topo, LonelyTopology) else n
    v = x.reshape(-1).astype(jnp.float32)
    shard_len = v.shape[0]
    if out_shape is not None:
        count = 1
        for d in out_shape:
            count *= d
        tile = count // owners
        if tile + count % owners != shard_len:
            raise ValueError(
                f"shard of {shard_len} elements does not match out_shape "
                f"{out_shape} over {owners} owners"
            )
    else:
        tile = shard_len
    head_tile, tail = v[:tile], v[tile:]
    parts: list[jax.Array] = []
    if tile:
        if codec.name == "bf16":
            wire = head_tile.astype(jnp.bfloat16)
            if isinstance(topo, LonelyTopology):
                full = _lonely_allgather(wire, axis_name, topo)
            elif topo.is_ring:
                full = _ring_allgather(wire, axis_name, n)
            else:
                full = _tree_allgather(wire, axis_name, topo)
            parts.append(full.astype(jnp.float32))
        elif isinstance(topo, LonelyTopology):
            parts.append(_lonely_ag_int8(head_tile, axis_name, topo, codec, step))
        elif topo.is_ring:
            parts.append(_ring_ag_int8(head_tile, axis_name, n, codec, step))
        else:
            parts.append(
                _ag_int8(head_tile, axis_name, topo, codec, step, _SALT_AG)
            )
    if tail.shape[0]:
        parts.append(tail)
    out = (parts[0] if len(parts) == 1 else jnp.concatenate(parts)).astype(x.dtype)
    if out_shape is not None:
        out = out.reshape(-1)[:count].reshape(out_shape)
    return out


# --------------------------------------------------------------- tree


def _stage_rs_int8(v, axis_name, topo: Topology, stage: int, codec: Codec, step):
    """One compressed phase-1 stage: encode the w tiles, grouped
    all_to_all of (int8 payload, f32 scales), decode + fold in f32.
    Returns (reduced tile, this rank's decoded own-encode) — the latter is
    the wire-exact roundtrip used for the stage-0 residual."""
    w = topo.widths[stage]
    tile = v.shape[0] // w
    groups = _groups_or_none(topo, stage)
    q, s = encode_int8(v.reshape(w, tile), step, salt=stage, block=codec.block)
    with jax.named_scope(f"ftq_rs_stage{stage}_w{w}"):
        qx = lax.all_to_all(
            q, axis_name, split_axis=0, concat_axis=0, axis_index_groups=groups
        )
        sx = lax.all_to_all(
            s, axis_name, split_axis=0, concat_axis=0, axis_index_groups=groups
        )
    dec = decode_int8(qx, sx, tile, block=codec.block)
    own = decode_int8(q, s, tile, block=codec.block).reshape(-1)
    return dec.sum(axis=0), own


def _ag_int8(tile_v, axis_name, topo: Topology, codec: Codec, step, salt):
    """Phase 2: encode the reduced tile once, forward it *encoded* through
    the stage allgathers, decode every segment at the end."""
    t = tile_v.shape[0]
    tp = _padded(t, codec.block)
    q, s = encode_int8(tile_v, step, salt=salt, block=codec.block)
    for i in reversed(range(topo.num_stages)):
        groups = _groups_or_none(topo, i)
        with jax.named_scope(f"ftq_ag_stage{i}_w{topo.widths[i]}"):
            q = lax.all_gather(q, axis_name, axis_index_groups=groups, axis=0, tiled=True)
            s = lax.all_gather(s, axis_name, axis_index_groups=groups, axis=0, tiled=True)
    segs = q.shape[0] // tp
    dec = decode_int8(
        q.reshape(segs, tp), s.reshape(segs, -1), t, block=codec.block
    )
    return dec.reshape(-1)


def _tree_rs_int8_all_stages(piece, axis_name, topo: Topology, codec: Codec, step):
    """All phase-1 stages of the compressed tree: returns (reduced tile,
    stage-0 own-encode roundtrip of the whole input) — the latter is the
    wire-exact residual reference for error feedback."""
    own0 = None
    v = piece
    for i in range(topo.num_stages):
        v, own = _stage_rs_int8(v, axis_name, topo, i, codec, step)
        if i == 0:
            own0 = own
    return v, own0


def _tree_int8(head, axis_name, topo: Topology, codec: Codec, chunks: int, step):
    """Compressed k-ary tree on the divisible head, optionally
    chunk-pipelined with the same phase-2/phase-1 interleaving as
    ``tree_allreduce``."""
    from .allreduce import _chunk_sizes

    n = topo.num_nodes

    def rs_all(piece):
        return _tree_rs_int8_all_stages(piece, axis_name, topo, codec, step)

    sizes = _chunk_sizes(head.size, n, chunks)
    if len(sizes) == 1:
        tile, own0 = rs_all(head)
        out = _ag_int8(tile, axis_name, topo, codec, step, _SALT_AG)
        return out, head - own0
    pieces, off = [], 0
    for sz in sizes:
        pieces.append(head[off : off + sz])
        off += sz
    outs, residuals, scattered = [], [], None
    for c, piece in enumerate(pieces):
        with jax.named_scope(f"ftq_chunk{c}_rs"):
            cur, own0 = rs_all(piece)
        residuals.append(piece - own0)
        if scattered is not None:
            with jax.named_scope(f"ftq_chunk{c - 1}_ag"):
                outs.append(
                    _ag_int8(scattered, axis_name, topo, codec, step, _SALT_AG + c - 1)
                )
        scattered = cur
    with jax.named_scope(f"ftq_chunk{len(pieces) - 1}_ag"):
        outs.append(
            _ag_int8(
                scattered, axis_name, topo, codec, step, _SALT_AG + len(pieces) - 1
            )
        )
    return jnp.concatenate(outs), jnp.concatenate(residuals)


# --------------------------------------------------------------- ring


def _ring_rs_int8(head, axis_name, n: int, codec: Codec, step):
    """Compressed ring phase 1 alone: per-hop encode of the sent block,
    f32 fold at the receiver; returns the fully-reduced owned block
    ``(idx + 1) % n`` in f32 (never end-quantized — phase 2 owns that
    lossy event)."""
    split = head.shape[0] // n
    idx = lax.axis_index(axis_name)
    right = [(j, (j + 1) % n) for j in range(n)]
    v = head

    for hop in range(n - 1):
        send_b = (idx - hop) % n
        recv_b = (idx - hop - 1) % n
        chunk = lax.dynamic_slice_in_dim(v, send_b * split, split, axis=0)
        q, s = encode_int8(chunk, step, salt=_SALT_RING + hop, block=codec.block)
        with jax.named_scope(f"ftq_ring_rs{hop}"):
            q = lax.ppermute(q, axis_name, right)
            s = lax.ppermute(s, axis_name, right)
        got = decode_int8(q, s, split, block=codec.block)
        cur = lax.dynamic_slice_in_dim(v, recv_b * split, split, axis=0)
        v = lax.dynamic_update_slice_in_dim(v, cur + got, recv_b * split, axis=0)

    own_b = (idx + 1) % n
    return lax.dynamic_slice_in_dim(v, own_b * split, split, axis=0)


def _ring_ag_int8(own, axis_name, n: int, codec: Codec, step):
    """Compressed ring phase 2 alone: encode the owned block once, forward
    it still encoded around the ring, decode every assembled block."""
    split = own.shape[0]
    sp = _padded(split, codec.block)
    nb = sp // codec.block
    idx = lax.axis_index(axis_name)
    right = [(j, (j + 1) % n) for j in range(n)]
    own_b = (idx + 1) % n
    q, s = encode_int8(own, step, salt=_SALT_RING - 1, block=codec.block)
    qbuf = jnp.zeros((n * sp,), jnp.int8)
    sbuf = jnp.zeros((n * nb,), jnp.float32)
    qbuf = lax.dynamic_update_slice_in_dim(qbuf, q, own_b * sp, axis=0)
    sbuf = lax.dynamic_update_slice_in_dim(sbuf, s, own_b * nb, axis=0)
    for hop in range(n - 1):
        send_b = (idx + 1 - hop) % n
        recv_b = (idx - hop) % n
        cq = lax.dynamic_slice_in_dim(qbuf, send_b * sp, sp, axis=0)
        cs = lax.dynamic_slice_in_dim(sbuf, send_b * nb, nb, axis=0)
        with jax.named_scope(f"ftq_ring_ag{hop}"):
            cq = lax.ppermute(cq, axis_name, right)
            cs = lax.ppermute(cs, axis_name, right)
        qbuf = lax.dynamic_update_slice_in_dim(qbuf, cq, recv_b * sp, axis=0)
        sbuf = lax.dynamic_update_slice_in_dim(sbuf, cs, recv_b * nb, axis=0)
    dec = decode_int8(
        qbuf.reshape(n, sp), sbuf.reshape(n, nb), split, block=codec.block
    )
    return dec.reshape(-1)


def _ring_int8(head, axis_name, n: int, codec: Codec, step):
    """Compressed ring: the split phases composed (``_ring_rs_int8`` +
    ``_ring_ag_int8``).  The residual is the canonical local map (ring
    blocks are first encoded at differing fold depths, so no single wire
    encode covers the whole local buffer — see
    docs/QUANTIZED_COLLECTIVES.md)."""
    own = _ring_rs_int8(head, axis_name, n, codec, step)
    out = _ring_ag_int8(own, axis_name, n, codec, step)
    res = head - decode_int8(*encode_int8(head, step, salt=0, block=codec.block),
                             head.shape[0], block=codec.block)
    return out, res


# --------------------------------------------------------------- lonely


def _compressed_grouped_rs(v, axis_name, topo: Topology, stage: int, codec: Codec, step):
    """Width-w grouped reduce-scatter as a compressed ppermute ring —
    the lossy twin of ``_grouped_reduce_scatter_generic`` (grouped XLA
    collectives cannot cover a partial axis, so lonely prefix trees ride
    permutations; ranks outside ``topo.num_nodes`` receive zeros and are
    overwritten by the caller)."""
    w, gap = topo.widths[stage], topo.gaps[stage]
    tile = v.shape[0] // w
    idx = lax.axis_index(axis_name)
    pos = (idx // gap) % w
    perm = [(r, _next_in_group(r, w, gap)) for r in range(topo.num_nodes)]

    cur_send = (pos - 1) % w
    acc = v
    for hop in range(w - 1):
        chunk = lax.dynamic_slice_in_dim(acc, cur_send * tile, tile, axis=0)
        q, s = encode_int8(
            chunk, step, salt=_SALT_LONELY + 16 * stage + hop, block=codec.block
        )
        q = lax.ppermute(q, axis_name, perm)
        s = lax.ppermute(s, axis_name, perm)
        got = decode_int8(q, s, tile, block=codec.block)
        recv_b = (cur_send - 1) % w
        cur = lax.dynamic_slice_in_dim(acc, recv_b * tile, tile, axis=0)
        acc = lax.dynamic_update_slice_in_dim(acc, cur + got, recv_b * tile, axis=0)
        cur_send = recv_b
    return lax.dynamic_slice_in_dim(acc, pos * tile, tile, axis=0)


def _compressed_grouped_ag(v, axis_name, topo: Topology, stage: int, codec: Codec, step):
    """Width-w grouped allgather forwarding encoded blocks around the
    group ring (phase-2 twin of ``_compressed_grouped_rs``)."""
    w, gap = topo.widths[stage], topo.gaps[stage]
    t = v.shape[0]
    tp = _padded(t, codec.block)
    nb = tp // codec.block
    idx = lax.axis_index(axis_name)
    pos = (idx // gap) % w
    perm = [(r, _next_in_group(r, w, gap)) for r in range(topo.num_nodes)]

    q, s = encode_int8(v, step, salt=_SALT_LONELY + 4096 + stage, block=codec.block)
    qbuf = jnp.zeros((w * tp,), jnp.int8)
    sbuf = jnp.zeros((w * nb,), jnp.float32)
    qbuf = lax.dynamic_update_slice_in_dim(qbuf, q, pos * tp, axis=0)
    sbuf = lax.dynamic_update_slice_in_dim(sbuf, s, pos * nb, axis=0)
    for hop in range(w - 1):
        send_b = (pos - hop) % w
        recv_b = (pos - hop - 1) % w
        cq = lax.dynamic_slice_in_dim(qbuf, send_b * tp, tp, axis=0)
        cs = lax.dynamic_slice_in_dim(sbuf, send_b * nb, nb, axis=0)
        cq = lax.ppermute(cq, axis_name, perm)
        cs = lax.ppermute(cs, axis_name, perm)
        qbuf = lax.dynamic_update_slice_in_dim(qbuf, cq, recv_b * tp, axis=0)
        sbuf = lax.dynamic_update_slice_in_dim(sbuf, cs, recv_b * nb, axis=0)
    dec = decode_int8(qbuf.reshape(w, tp), sbuf.reshape(w, nb), t, block=codec.block)
    return dec.reshape(-1)


def _lonely_rs_int8(head, axis_name, topo: LonelyTopology, codec: Codec, step):
    """Compressed lonely phase 1 alone: encoded buddy fold, compressed
    prefix-tree RS stages, then one encoded ppermute shipping each buddy's
    reduced tile to its lonely rank.  Tree ranks keep their exact f32
    tile; lonely ranks hold ``decode(encode(tile))`` — the mirror copy is
    within one quantization step of the buddy's (exactly mirrored for the
    identity/bf16-representable case), and the allgather ignores it."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    idx = lax.axis_index(axis_name)
    t = head.shape[0]
    with jax.named_scope("ftq_lonely_fold"):
        q, s = encode_int8(head, step, salt=_SALT_LONELY - 1, block=codec.block)
        qg = lax.ppermute(q, axis_name, [(m + i, i) for i in range(l)])
        sg = lax.ppermute(s, axis_name, [(m + i, i) for i in range(l)])
        got = decode_int8(qg, sg, t, block=codec.block)
        v = jnp.where(idx < l, head + got, head)
    for i in range(tree.num_stages):
        with jax.named_scope(f"ftq_lonely_rs{i}"):
            v = _compressed_grouped_rs(v, axis_name, tree, i, codec, step)
    with jax.named_scope("ftq_lonely_ship_shard"):
        q, s = encode_int8(v, step, salt=_SALT_LONELY - 3, block=codec.block)
        q2 = lax.ppermute(q, axis_name, [(i, m + i) for i in range(l)])
        s2 = lax.ppermute(s, axis_name, [(i, m + i) for i in range(l)])
        shipped = decode_int8(q2, s2, v.shape[0], block=codec.block)
        return jnp.where(idx >= m, shipped, v)


def _lonely_ag_int8(tile, axis_name, topo: LonelyTopology, codec: Codec, step):
    """Compressed lonely phase 2 alone: compressed prefix-tree AG stages,
    then the encoded restore with every rank adopting
    ``decode(encode(result))`` — the same replica-consistency rule as
    ``_lonely_int8``'s restore."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    idx = lax.axis_index(axis_name)
    v = tile
    for i in reversed(range(tree.num_stages)):
        with jax.named_scope(f"ftq_lonely_ag{i}"):
            v = _compressed_grouped_ag(v, axis_name, tree, i, codec, step)
    with jax.named_scope("ftq_lonely_restore"):
        q, s = encode_int8(v, step, salt=_SALT_LONELY - 2, block=codec.block)
        q2 = lax.ppermute(q, axis_name, [(i, m + i) for i in range(l)])
        s2 = lax.ppermute(s, axis_name, [(i, m + i) for i in range(l)])
        back = decode_int8(q2, s2, v.shape[0], block=codec.block)
        own = decode_int8(q, s, v.shape[0], block=codec.block)
        return jnp.where(idx >= m, back, own)


def _lonely_int8(head, axis_name, topo: LonelyTopology, codec: Codec, step):
    """Compressed ``m+l`` shape: encoded buddy fold, compressed prefix-tree
    stages, encoded restore (structure mirrors ``lonely_allreduce``)."""
    tree, m, l = topo.tree, topo.tree.num_nodes, topo.lonely
    idx = lax.axis_index(axis_name)
    t = head.shape[0]

    with jax.named_scope("ftq_lonely_fold"):
        q, s = encode_int8(head, step, salt=_SALT_LONELY - 1, block=codec.block)
        qg = lax.ppermute(q, axis_name, [(m + i, i) for i in range(l)])
        sg = lax.ppermute(s, axis_name, [(m + i, i) for i in range(l)])
        got = decode_int8(qg, sg, t, block=codec.block)
        v = jnp.where(idx < l, head + got, head)
    for i in range(tree.num_stages):
        with jax.named_scope(f"ftq_lonely_rs{i}"):
            v = _compressed_grouped_rs(v, axis_name, tree, i, codec, step)
    for i in reversed(range(tree.num_stages)):
        with jax.named_scope(f"ftq_lonely_ag{i}"):
            v = _compressed_grouped_ag(v, axis_name, tree, i, codec, step)
    with jax.named_scope("ftq_lonely_restore"):
        q, s = encode_int8(v, step, salt=_SALT_LONELY - 2, block=codec.block)
        q2 = lax.ppermute(q, axis_name, [(i, m + i) for i in range(l)])
        s2 = lax.ppermute(s, axis_name, [(i, m + i) for i in range(l)])
        back = decode_int8(q2, s2, t, block=codec.block)
        # every rank adopts decode(encode(result)): the encode is
        # deterministic and all tree ranks hold identical ``v``, so the
        # lonely ranks' shipped copy is bit-identical to what the tree
        # ranks compute locally — without this, lonely ranks would hold a
        # re-quantized result the tree ranks don't (replica drift)
        own = decode_int8(q, s, t, block=codec.block)
        out = jnp.where(idx >= m, back, own)
    res = head - decode_int8(
        *encode_int8(head, step, salt=0, block=codec.block), t, block=codec.block
    )
    return out, res
