"""Training step for the MoE model: dp x ep x sp x tp in one shard_map.

Composition rules (extending ``flextree_tpu.parallel.train``):

- ``ep`` is a *data* axis outside the MoE layers (the batch shards over
  dp x ep jointly) and the *expert* axis inside them (tokens all-to-all to
  their experts' owners) — the standard "expert parallelism reuses data
  parallelism's devices" layout.
- Expert weights shard over ep (leading expert axis) and tp (hidden dim),
  so they sync only over the axes they're replicated on (dp, sp) — the
  same replication-axes rule, driven by the MoE param specs.
- The loss adds the router load-balance term: ``ce_mean +
  router_aux_weight * aux_mean``, with the aux averaged over all devices.
"""

from __future__ import annotations

import jax

from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.moe import MoEConfig, init_moe_params, moe_forward, moe_param_specs
from ..models.transformer import cross_entropy_loss
from .pipeline import factor_devices_4d, make_mesh_4d
from .train import (
    TrainConfig,
    adamw_apply,
    maybe_clip_grads,
    metric_specs,
    make_state_specs,
    make_train_state,
    maybe_autotune_grad_topo,
    resolve_axis_topos,
    sync_with_feedback,
    validate_tp,
    zero_layout_for,
)

__all__ = [
    "init_moe_train_state",
    "moe_state_specs",
    "make_moe_train_step",
    "make_mesh_moe",
    "factor_devices_moe",
]


def init_moe_train_state(
    key, cfg: MoEConfig, train_cfg=None, mesh=None,
    axis_names: tuple[str, str, str, str] = ("dp", "ep", "sp", "tp"),
) -> dict:
    params = init_moe_params(key, cfg)
    layout = None
    if train_cfg is not None and train_cfg.shard_optimizer:
        if mesh is None:
            raise ValueError(
                "shard_optimizer=True: init_moe_train_state needs mesh="
            )
        layout = zero_layout_for(
            mesh, params,
            moe_param_specs(cfg, axis_names[3], axis_names[1]), axis_names,
        )
    return make_train_state(params, train_cfg, layout=layout)


def moe_state_specs(
    cfg: MoEConfig, tp_axis: str | None = "tp", ep_axis: str | None = "ep",
    train_cfg=None, mesh=None,
    axis_names: tuple[str, str, str, str] = ("dp", "ep", "sp", "tp"),
) -> dict:
    pspecs = moe_param_specs(cfg, tp_axis, ep_axis)
    layout = None
    if train_cfg is not None and train_cfg.shard_optimizer:
        if mesh is None:
            raise ValueError("shard_optimizer=True: moe_state_specs needs mesh=")
        shapes = jax.eval_shape(
            lambda k: init_moe_params(k, cfg), jax.random.PRNGKey(0)
        )
        layout = zero_layout_for(mesh, shapes, pspecs, axis_names)
    return make_state_specs(pspecs, train_cfg, layout=layout)


def factor_devices_moe(n: int) -> tuple[int, int, int, int]:
    """(dp, ep, sp, tp) with ep covered first (8 -> (1, 2, 2, 2)) — the
    same specialty-axis-first policy as the pipeline's 4-axis split."""
    return factor_devices_4d(n)


def make_mesh_moe(
    n_devices: int | None = None,
    shape: tuple[int, int, int, int] | None = None,
    axis_names: tuple[str, str, str, str] = ("dp", "ep", "sp", "tp"),
) -> Mesh:
    return make_mesh_4d(n_devices, shape, axis_names)


def make_moe_train_step(
    mesh: Mesh,
    model_cfg: MoEConfig,
    train_cfg: TrainConfig = TrainConfig(),
    axis_names: tuple[str, str, str, str] = ("dp", "ep", "sp", "tp"),
    serialize_overlap: bool = False,
):
    """Jitted ``(state, tokens, targets) -> (state, metrics)``.

    ``tokens``/``targets``: (B, T) int32, batch sharded over (dp, ep),
    sequence over sp.  ``metrics``: global mean ``loss`` (cross entropy),
    ``aux`` (router balance), and ``total`` (what is optimized).

    ``train_cfg.overlap`` routes the backward through the readiness-
    ordered segmented engine (``parallel.overlap``) — per-layer grads
    fire their sync buckets as they are produced; ``serialize_overlap``
    builds its barrier twin (see ``train.make_train_step``).
    """
    dp, ep, sp, tp = axis_names
    for a in axis_names:
        if a not in mesh.shape:
            raise ValueError(f"mesh is missing axis {a!r}; has {mesh.axis_names}")
    ep_size, tp_size = mesh.shape[ep], mesh.shape[tp]
    if model_cfg.n_experts % ep_size:
        raise ValueError(
            f"n_experts={model_cfg.n_experts} must be divisible by ep={ep_size}"
        )
    if model_cfg.top_k > model_cfg.n_experts:
        raise ValueError("top_k cannot exceed n_experts")
    validate_tp(model_cfg, tp_size)
    train_cfg = maybe_autotune_grad_topo(
        mesh, model_cfg, train_cfg, axis_names, init_fn=init_moe_params
    )

    sspecs = moe_state_specs(
        model_cfg, tp, ep, train_cfg, mesh=mesh, axis_names=axis_names
    )
    data_spec = P((dp, ep), sp)
    mesh_axes = axis_names
    n_devices = 1
    for a in mesh_axes:
        n_devices *= mesh.shape[a]
    zero_layout = None
    if train_cfg.shard_optimizer:
        shapes = jax.eval_shape(
            lambda k: init_moe_params(k, model_cfg), jax.random.PRNGKey(0)
        )
        zero_layout = zero_layout_for(mesh, shapes, sspecs["params"], axis_names)

    def device_step(state, tokens, targets):
        # tp-fold redundancy only: dp/ep/sp partition the data
        n_total_tokens = (
            tokens.size
            * lax.axis_size(dp)
            * lax.axis_size(ep)
            * lax.axis_size(sp)
            * lax.axis_size(tp)
        )

        topos = resolve_axis_topos(mesh, mesh_axes, train_cfg.grad_topo)
        if train_cfg.overlap:
            from .overlap import moe_overlap_step_grads

            ce, aux, grads, new_ef = moe_overlap_step_grads(
                state, tokens, targets, model_cfg, train_cfg,
                sspecs["params"], mesh_axes, topos, n_total_tokens,
                n_devices, tp_axis=tp, sp_axis=sp, ep_axis=ep,
                serialize=serialize_overlap, zero_layout=zero_layout,
            )
        else:

            def local_loss(params):
                logits, aux = moe_forward(
                    params, tokens, model_cfg,
                    tp_axis=tp, sp_axis=sp, ep_axis=ep,
                )
                loss_sum, _ = cross_entropy_loss(logits, targets)
                ce = loss_sum / n_total_tokens
                # aux is a per-device mean; average it over every device
                # (tp copies are redundant but identical, so the global
                # mean is exact under the same 1/n_devices weighting)
                aux_term = model_cfg.router_aux_weight * aux / n_devices
                return ce + aux_term, (ce, aux)

            (_, (ce, aux)), grads = jax.value_and_grad(
                local_loss, has_aux=True
            )(state["params"])
            if not train_cfg.shard_optimizer:
                grads, new_ef = sync_with_feedback(
                    state, grads, sspecs["params"], mesh_axes, topos, train_cfg
                )
            else:
                new_ef = None  # the zero path carries EF itself

        global_ce = ce
        global_aux = aux / n_devices
        for ax in mesh_axes:
            global_ce = lax.psum(global_ce, ax)
            global_aux = lax.psum(global_aux, ax)

        metrics = {
            "loss": global_ce,
            "aux": global_aux,
            "total": global_ce + model_cfg.router_aux_weight * global_aux,
        }
        if train_cfg.shard_optimizer:
            from .zero import (
                maybe_clip_shards,
                zero_apply_and_gather,
                zero_sync_and_update,
            )

            if train_cfg.overlap:
                shard_tree = maybe_clip_shards(
                    grads, sspecs["params"], train_cfg, zero_layout, metrics
                )
                new_state = zero_apply_and_gather(
                    state, shard_tree, sspecs["params"], mesh_axes, topos,
                    train_cfg, zero_layout,
                )
                if new_ef is not None:
                    new_state["ef"] = new_ef
            else:
                new_state = zero_sync_and_update(
                    state, grads, sspecs["params"], mesh_axes, topos,
                    train_cfg, zero_layout, metrics,
                )
            return new_state, metrics
        grads = maybe_clip_grads(grads, sspecs["params"], train_cfg, metrics)
        new_state = adamw_apply(state, grads, train_cfg)
        if new_ef is not None:
            new_state["ef"] = new_ef
        return new_state, metrics

    mspec = metric_specs(train_cfg, {"loss": P(), "aux": P(), "total": P()})
    sharded = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(sspecs, data_spec, data_spec),
        out_specs=(sspecs, mspec),
        check_vma=False,
    )
    return jax.jit(sharded)
