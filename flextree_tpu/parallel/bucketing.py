"""Gradient bucketing/fusion for the FlexTree gradient sync.

The reference's whole value proposition is amortizing per-message latency
across the fabric (``cost_model/CostModel.h``), yet a transformer gradient
tree hands the sync dozens of tiny bias/layernorm leaves — and every leaf
synced alone pays the full per-stage launch+latency term (measured ~3.6 ms
per extra dispatch on the bench host, WINS.md).  The standard fix is
message fusion: pack leaves into a few flat buckets and run ONE scheduled
collective per bucket — k small leaves pay ``k * (launch + latency)``
per-leaf, one fused bucket pays it once.  The α-β decomposition behind the
bucket-size choice is the time-cost model of arXiv:2409.04202; the size
itself comes from the calibrated planner (``planner.choose_bucket_bytes``),
not a magic constant.

Grouping: leaves fuse only when they agree on **(replication-axis-set,
dtype)** — the axis set because each bucket runs exactly one allreduce
sequence (a leaf synced over ``(dp, sp)`` cannot share a buffer with one
synced over ``(dp, sp, tp)``), the dtype because the flat buffer has one.
:func:`replication_key` is the shared helper both this module and
``train.global_grad_norm``'s axis-set grouping use.

**Bitwise identity** with the per-leaf sync is a hard design constraint
(the per-leaf path stays as the A/B oracle): it holds because, per mesh
axis, every element keeps the exact cross-rank reduction association it
had per-leaf:

- *tree/flat stages* (``psum_scatter``/``all_gather``) reduce elementwise
  across a rank group — the association is position-independent, so
  packing leaves into one buffer cannot change any element's value;
- *tails*: each leaf's <N-element remainder is fused into ONE dense
  ``psum`` per bucket (vs one per leaf) — ``psum`` is elementwise, so
  fusing tails is also value-preserving, and which elements are tail
  elements is decided per leaf exactly as ``_split_main_tail`` does;
- *ring*: the ring's accumulation order for an element depends on its
  block index ``b = pos // (size // N)``, so naive concatenation WOULD
  change values.  Ring buckets therefore pack **block-interleaved** —
  fused block ``b`` is the concatenation of every leaf's block ``b`` —
  which preserves each element's block index and hence its association.

Lonely (``m+l``) topologies interleave a positional buddy fold with the
ppermute-ring stage machinery and are not position-independent in any
packing; buckets fall back to per-leaf sync there (lonely shapes exist for
awkward world sizes, not for throughput — WINS.md).  The bucketed path is
sum-only, which is all a gradient sync needs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..schedule.stages import LonelyTopology, Topology
from ..utils.profiling import comm_span
from .allreduce import _NATIVE_PSUM, allreduce, ring_allreduce, tree_allreduce

__all__ = [
    "spec_axes",
    "replication_key",
    "Bucket",
    "plan_buckets",
    "bucketed_sync_grads",
    "DEFAULT_MAX_BUCKET_BYTES",
    "CPU_MAX_BUCKET_BYTES",
]

#: Memory cap on a fused flat buffer when the planner-derived size is used —
#: a bucket materializes one packed copy of its leaves, so an unbounded
#: bucket would double peak gradient memory for the largest group.
DEFAULT_MAX_BUCKET_BYTES = 64 << 20

#: Planner-derived cap on CPU backends.  The alpha-beta chooser only prices
#: dispatch + bytes, and on the 1-core bench host it lands on one giant
#: bucket — which measured ~25% SLOWER end-to-end than per-leaf sync inside
#: the train step, while 64-128 KiB buckets beat per-leaf by ~15%
#: (BENCH_BUCKETING.json): in-step, the fused pack -> collective -> unpack
#: -> AdamW chain must stay cache-hot, a locality term the dispatch model
#: cannot see.  Real accelerators stream collectives from HBM, so the big
#: DEFAULT_MAX_BUCKET_BYTES stays their cap.
CPU_MAX_BUCKET_BYTES = 128 << 10


def _default_max_bucket_bytes() -> int:
    """Backend-resolved cap for the planner-derived bucket size (the same
    per-backend-constants pattern as ``planner.calibrate.default_params``)."""
    try:
        backend = jax.default_backend()
    except Exception:  # no backend initialized (e.g. pure planning tests)
        backend = "cpu"
    return CPU_MAX_BUCKET_BYTES if backend == "cpu" else DEFAULT_MAX_BUCKET_BYTES


def spec_axes(spec) -> tuple[str, ...]:
    """Mesh axes a ``PartitionSpec`` *names* (sorted) — the axes the leaf is
    sharded over.  ``None`` (fully replicated) names no axes."""
    names: set[str] = set()
    for entry in tuple(spec) if spec is not None else ():
        if entry is None:
            continue
        names.update(entry if isinstance(entry, (tuple, list)) else (entry,))
    return tuple(sorted(names))


def replication_key(spec, mesh_axes) -> tuple[str, ...]:
    """Mesh axes a parameter with PartitionSpec ``spec`` is *replicated* on,
    in ``mesh_axes`` order — the axes its gradient must be allreduced over,
    and the grouping key for bucketing.  Complement of :func:`spec_axes`
    within ``mesh_axes``."""
    used = set(spec_axes(spec))
    return tuple(a for a in mesh_axes if a not in used)


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One fused sync unit: ``indices`` into the flattened gradient leaves
    (flat-tree order), all sharing ``axes`` (replication axes to reduce
    over, mesh order) and ``dtype``."""

    axes: tuple[str, ...]
    dtype: str
    indices: tuple[int, ...]
    nbytes: int


def plan_buckets(
    leaves: Sequence[Any],
    specs: Sequence[Any],
    mesh_axes,
    topos: Mapping[str, Any] | None = None,
    axis_sizes: Mapping[str, int] | None = None,
    bucket_bytes: int | None = None,
    params=None,
    max_bucket_bytes: int | None = None,
    codec=None,
    sharded: bool = False,
) -> tuple[Bucket, ...]:
    """Partition flattened gradient leaves into fused sync buckets.

    ``leaves`` only need ``.size``/``.dtype`` (abstract values work, so HLO
    tests can plan without materializing).  Leaves group by
    ``(replication_key, dtype)`` preserving flat order; within a group,
    consecutive leaves pack greedily until the bucket reaches
    ``bucket_bytes``.  ``bucket_bytes=None`` derives the size per group from
    the calibrated cost model (``planner.choose_bucket_bytes`` on the
    group's own topologies and total bytes, capped at ``max_bucket_bytes``
    — backend-resolved when None: in-step cache locality caps CPU hosts at
    ``CPU_MAX_BUCKET_BYTES``, see the constants above); an explicit value
    is used as-is.  Groups with an empty axis set (leaves sharded over
    every mesh axis) are skipped — they need no sync.
    """
    if max_bucket_bytes is None:
        max_bucket_bytes = _default_max_bucket_bytes()
    groups: dict[tuple[tuple[str, ...], str], list[int]] = {}
    for i, (g, spec) in enumerate(zip(leaves, specs)):
        axes = replication_key(spec, mesh_axes)
        if axes and axis_sizes is not None:
            axes = tuple(a for a in axes if axis_sizes.get(a, 1) > 1)
        if not axes:
            continue
        groups.setdefault((axes, jnp.dtype(g.dtype).name), []).append(i)

    buckets: list[Bucket] = []
    for (axes, dtype), idxs in groups.items():
        itemsize = jnp.dtype(dtype).itemsize
        sizes = [leaves[i].size * itemsize for i in idxs]
        cap = bucket_bytes
        if cap is None:
            cap = _derived_bucket_bytes(
                sum(sizes), len(idxs), axes, topos or {}, axis_sizes or {},
                params, max_bucket_bytes, codec, sharded=sharded,
            )
        cap = max(int(cap), 1)
        cur: list[int] = []
        cur_bytes = 0
        for i, nb in zip(idxs, sizes):
            if cur and cur_bytes + nb > cap:
                buckets.append(Bucket(axes, dtype, tuple(cur), cur_bytes))
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nb
        if cur:
            buckets.append(Bucket(axes, dtype, tuple(cur), cur_bytes))
    return tuple(buckets)


def _derived_bucket_bytes(
    total_bytes, n_leaves, axes, topos, axis_sizes, params, max_bucket_bytes,
    codec=None, sharded: bool = False,
):
    """Planner-derived bucket size for one (axes, dtype) group: the sync
    runs one allreduce per axis per bucket, so the launch term the chooser
    amortizes is the sum of the per-axis fixed costs.  ``codec`` makes the
    chooser's byte terms wire-accurate for compressed syncs (fewer wire
    bytes per bucket -> the argmin shifts toward fewer, larger buckets).
    ``sharded`` prices the ZeRO split schedule instead (grad
    reduce-scatter + param all-gather on the first axis, shard-sized
    allreduce on the rest — ``planner.choose_bucket_bytes``)."""
    from ..planner.choose import choose_bucket_bytes

    cost_topos = []
    for ax in axes:
        n = int(axis_sizes.get(ax, 0)) or None
        topo = topos.get(ax)
        if topo is None:  # the "psum" sentinel: one fused native collective
            if n is None:
                continue
            topo = Topology.flat(n)
        cost_topos.append(Topology.resolve(n or topo.num_nodes, topo))
    if not cost_topos:
        return max_bucket_bytes
    derived = choose_bucket_bytes(
        total_bytes, cost_topos, n_leaves=n_leaves, params=params, codec=codec,
        sharded=sharded,
    )
    return min(derived, max_bucket_bytes)


def _unpack(fused, segments):
    """Split a fused flat buffer back into per-leaf pieces of ``segments``
    element counts."""
    out, off = [], 0
    for s in segments:
        out.append(lax.slice_in_dim(fused, off, off + s, axis=0))
        off += s
    return out


def _fused_native_psum(leaves, axis_name):
    """Fuse the ``"psum"``-sentinel axis: one native all-reduce per bucket.
    ``psum`` is elementwise across ranks, so fusion is value-preserving."""
    flats = [g.reshape(-1) for g in leaves]
    fused = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
    red = _NATIVE_PSUM(fused, axis_name)
    return [
        p.reshape(g.shape) for p, g in zip(_unpack(red, [f.size for f in flats]), leaves)
    ]


def _fused_axis_allreduce(leaves, axis_name, topo, chunks: int = 1):
    """One FlexTree allreduce over ``axis_name`` for a whole bucket.

    Packs the leaves' divisible heads into one scheduled collective and
    their <N-element remainders into ONE dense tail collective (vs one per
    leaf via ``_split_main_tail``), preserving each element's per-leaf
    reduction association — see the module docstring for why each packing
    is bitwise-safe.
    """
    n = lax.axis_size(axis_name)
    if n <= 1:
        return list(leaves)
    topo = Topology.resolve(n, topo)
    if isinstance(topo, LonelyTopology):
        # positional buddy fold: not packing-invariant — per-leaf fallback
        return [allreduce(g, axis_name, topo=topo, op="sum") for g in leaves]
    if len(leaves) == 1:
        return [allreduce(leaves[0], axis_name, topo=topo, op="sum", chunks=chunks)]

    flats = [g.reshape(-1) for g in leaves]
    mains = [(v.size // n) * n for v in flats]
    head_ids = [i for i, m in enumerate(mains) if m]
    tail_ids = [i for i, (v, m) in enumerate(zip(flats, mains)) if v.size > m]
    heads_out: dict[int, jax.Array] = {}
    tails_out: dict[int, jax.Array] = {}

    if head_ids:
        if topo.is_ring:
            # block-interleaved packing: fused block b = [leaf block b ...],
            # so each element keeps its ring block index (= association)
            cols = [flats[i][: mains[i]].reshape(n, -1) for i in head_ids]
            widths = [c.shape[1] for c in cols]
            fused = jnp.concatenate(cols, axis=1).reshape(-1)
            red = ring_allreduce(fused, axis_name, op="sum").reshape(n, -1)
            off = 0
            for i, w in zip(head_ids, widths):
                heads_out[i] = red[:, off : off + w].reshape(-1)
                off += w
        else:
            segs = [flats[i][: mains[i]] for i in head_ids]
            fused = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
            red = tree_allreduce(fused, axis_name, topo=topo, op="sum", chunks=chunks)
            for i, piece in zip(head_ids, _unpack(red, [s.size for s in segs])):
                heads_out[i] = piece
    if tail_ids:
        segs = [flats[i][mains[i] :] for i in tail_ids]
        fused = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
        red = _NATIVE_PSUM(fused, axis_name)
        for i, piece in zip(tail_ids, _unpack(red, [s.size for s in segs])):
            tails_out[i] = piece

    out = []
    for i, g in enumerate(leaves):
        h, t = heads_out.get(i), tails_out.get(i)
        if h is None and t is None:
            out.append(g)  # zero-size leaf
        elif t is None:
            out.append(h.reshape(g.shape))
        elif h is None:
            out.append(t.reshape(g.shape))
        else:
            out.append(jnp.concatenate([h, t]).reshape(g.shape))
    return out


def _unpack_to(leaves, fused):
    """Reshape a fused flat f32 buffer back into the leaves' shapes/dtypes."""
    return [
        p.reshape(g.shape).astype(g.dtype)
        for p, g in zip(_unpack(fused, [g.size for g in leaves]), leaves)
    ]


def _fused_compressed_bucket(leaves, axes, topos, codec, chunks, step, bi, nbytes):
    """Lossy-codec bucket sync: pack the leaves into one flat buffer, run
    one ``compressed_allreduce`` per axis, unpack.  No bitwise contract
    (that belongs to the identity codec), so no block-interleaving or
    split-tail choreography is needed — the compressed collective handles
    its own sub-N tail in exact f32.  Returns (synced leaves, per-leaf
    input-quantization residuals): wire-exact when the FIRST axis is
    compressed (only that axis sees this rank's local data — a residual
    taken after an exact psum axis would be re-injected once per rank of
    that axis next step), else the canonical ``x - C(x)``.  Same rule as
    the per-leaf path in ``train.sync_grads``."""
    from .compressed import compressed_allreduce, local_residual

    from ..obs import bucket_provenance

    flats = [g.reshape(-1).astype(jnp.float32) for g in leaves]
    fused = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
    res = None
    for k, ax in enumerate(axes):
        name = f"ftq_bucket{bi}_{ax}_{len(leaves)}leaves_{nbytes}B"
        prov = bucket_provenance(
            (ax,), topos, nbytes, n_leaves=len(leaves), codec=codec,
            chunks=chunks,
        )
        with comm_span(name, provenance=prov):
            if topos[ax] is None:
                fused = _NATIVE_PSUM(fused, ax)  # sentinel stays exact f32
            elif res is None and k == 0:
                fused, res = compressed_allreduce(
                    fused, ax, topo=topos[ax], codec=codec, chunks=chunks,
                    step=step, return_residual=True,
                )
            else:
                fused = compressed_allreduce(
                    fused, ax, topo=topos[ax], codec=codec, chunks=chunks,
                    step=step,
                )
    if res is None:
        # first axis was exact (psum sentinel) or no axis at all: canonical
        # residual of the packed input
        src = flats[0] if len(flats) == 1 else jnp.concatenate(flats)
        res = local_residual(src, codec, step)
    return _unpack_to(leaves, fused), _unpack_to(leaves, res)


def bucketed_sync_grads(
    grads,
    pspecs,
    mesh_axes,
    topos: Mapping[str, Any],
    bucket_bytes: int | None = None,
    chunks: int = 1,
    params=None,
    codec="f32",
    step=0,
    return_residual: bool = False,
):
    """Bucketed/fused FlexTree gradient sync — the fused twin of
    ``train.sync_grads`` (collective-context function; call inside
    ``shard_map``).

    Semantics are identical (sum each leaf over its replication axes, per
    axis in ``mesh_axes`` order) and the result is bitwise-identical to the
    per-leaf sync; the collective count drops from leaves x stages to
    buckets x stages (+ one fused tail per bucket per axis).
    ``bucket_bytes=None`` derives the size from the calibrated planner;
    ``chunks > 1`` runs each bucket's tree collectives chunk-pipelined.
    Per-bucket ``comm_span`` scopes (``ft_bucket*``) mark each bucket's
    collectives in profiler traces so comm time is attributable per bucket.

    A lossy ``codec`` routes each bucket through ``compressed_allreduce``
    (wire-compressed per hop; the bitwise contract applies to the identity
    codec only); ``return_residual=True`` then also returns the per-leaf
    error-feedback residuals.
    """
    from ..ops.quantize import get_codec

    codec = get_codec(codec)
    flat_g, treedef = jax.tree.flatten(grads)
    flat_s = treedef.flatten_up_to(pspecs)
    axis_sizes = {ax: lax.axis_size(ax) for ax in mesh_axes}
    buckets = plan_buckets(
        flat_g, flat_s, mesh_axes, topos=topos, axis_sizes=axis_sizes,
        bucket_bytes=bucket_bytes, params=params,
        codec=codec if codec.lossy else None,
    )
    out = list(flat_g)
    residuals = [jnp.zeros_like(g) for g in flat_g] if return_residual else None
    for bi, b in enumerate(buckets):
        leaves = [out[i] for i in b.indices]
        if codec.lossy:
            leaves, res = _fused_compressed_bucket(
                leaves, b.axes, topos, codec, chunks, step, bi, b.nbytes
            )
            if return_residual:
                for i, r in zip(b.indices, res):
                    residuals[i] = r
        else:
            from ..obs import bucket_provenance

            for ax in b.axes:
                name = f"ft_bucket{bi}_{ax}_{len(b.indices)}leaves_{b.nbytes}B"
                prov = bucket_provenance(
                    (ax,), topos, b.nbytes, n_leaves=len(b.indices),
                    dtype=b.dtype, chunks=chunks,
                )
                with comm_span(name, provenance=prov):
                    if topos[ax] is None:
                        leaves = _fused_native_psum(leaves, ax)
                    else:
                        leaves = _fused_axis_allreduce(
                            leaves, ax, topos[ax], chunks
                        )
        for i, g in zip(b.indices, leaves):
            out[i] = g
    out_tree = treedef.unflatten(out)
    if return_residual:
        return out_tree, treedef.unflatten(residuals)
    return out_tree
