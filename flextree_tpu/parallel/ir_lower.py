"""IR lowering: turn a verified ``schedule.ir.IRProgram`` into the jitted
collective.

``schedule.ir.compile_ir`` is the front door — it model-checks the program
and only then calls :func:`lower_ir` here.  Lowering adds the second
refusal: the program's stage list must equal its family's CANONICAL
emission (``_canonical_twin``), so the object the checker certified is
provably the object that runs — an IR/executable divergence is a compile
error, not a silent re-derivation (and the ``analysis.ir_equivalence``
pass independently re-checks the lowered StableHLO against the stage
list).

Lowering strategies per stage kind (the same calls
``parallel/allreduce.py`` makes today):

- **grouped** stages lower to one XLA grouped collective:
  ``lax.psum_scatter(axis_index_groups=stage.groups, tiled=True)`` for a
  sum reduce-scatter, ``lax.all_gather`` for the gather, and the
  ppermute-ring helpers for non-sum ops or prefix trees (lonely);
- **pair** stages lower to one ``lax.ppermute`` per send-slot: each rank
  gathers its declared block set, permutes, and folds (``sum``) or
  stores (``copy``) the received blocks — this is the generic executor
  the swing and generalized families run through (no per-family JAX
  code at all: the block-map IS the program);
- **ring-step** stages lower ROLLED: the 2(N-1) declarative steps
  compile to two ``fori_loop`` s of one ``ppermute`` each, exactly the
  legacy ring program (O(1) program size in N).

Chunk-pipelined trees: the IR's chunk tags declare the interleaving
(chunk ``c``'s allgather between chunk ``c+1``'s reduce-scatter and its
own); the executor replays that order with chunk sizes derived from the
live buffer (block-maps are size-independent — the program was checked
at a representative count, and every check is count-invariant).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax
import jax

from ..ops.reduce import get_op
from ..schedule import ir as sir
from ..schedule.ir import IRProgram, IRViolationError
from ..schedule.stages import LonelyTopology, Topology
from .allreduce import (
    _chunk_sizes,
    _grouped_allgather_generic,
    _grouped_reduce_scatter_generic,
    _groups_or_none,
    _jnp_fn,
    _small_dense_allreduce,
    _split_main_tail,
    ring_allreduce,
)

__all__ = ["lower_ir"]


def _canonical_twin(prog: IRProgram) -> IRProgram:
    """Re-emit the program from its own family parameters."""
    if prog.family == "tree":
        return sir.tree_ir(prog.topo, count=prog.count, chunks=prog.chunks)
    if prog.family == "ring":
        return sir.ring_ir(prog.num_nodes, count=prog.count)
    if prog.family == "lonely":
        return sir.lonely_ir(prog.topo, count=prog.count)
    if prog.family == "swing":
        return sir.swing_ir(prog.num_nodes, count=prog.count)
    if prog.family == "generalized":
        return sir.generalized_ir(prog.widths, prog.ports, count=prog.count)
    raise IRViolationError(f"unknown IR family {prog.family!r}")


def _require_canonical(prog: IRProgram) -> None:
    """Refuse a program whose stages diverged from the canonical emission:
    the lowering below realizes exactly the canonical message pattern, so
    running a divergent (even if individually verified) stage list would
    silently execute something other than what was declared."""
    twin = _canonical_twin(prog)
    if prog.stages != twin.stages or prog.scheduled != twin.scheduled:
        raise IRViolationError(
            f"IR/executable divergence: {prog} does not match the canonical "
            f"{prog.family} emission — refusing to lower a stage list the "
            f"executor would not faithfully realize"
        )


# ----------------------------------------------------------- pair stages


def _pair_slots(st: "sir.IRStage"):
    """Split a pair stage's transfers into send-slots: slot ``j`` holds
    every rank's ``j``-th transfer (a generalized round with ``ports=p``
    has ``p`` slots; swing/fold/restore have one).  Every slot is one
    ``ppermute`` with a uniform payload shape."""
    per_src: dict[int, list] = {}
    for x in st.xfers:
        per_src.setdefault(x.src, []).append(x)
    n_slots = max(len(v) for v in per_src.values())
    return [
        [v[j] for v in per_src.values() if len(v) > j] for j in range(n_slots)
    ]


def _pair_block_exchange(blocks_view, axis_name, st, num_nodes, fold_fn):
    """Execute one pair stage on the ``(m, tile)`` block view: per slot,
    gather each rank's declared blocks, ``ppermute``, fold or store at
    the receiver's declared indices.  Ranks outside the permutation
    receive zeros and (for ``copy``) may clobber scratch blocks — they
    are, by construction, ranks whose data is restored afterwards."""
    idx = lax.axis_index(axis_name)
    for slot in _pair_slots(st):
        k = len(slot[0].blocks)
        send_idx = np.zeros((num_nodes, k), dtype=np.int32)
        recv_idx = np.zeros((num_nodes, k), dtype=np.int32)
        perm = []
        for x in slot:
            send_idx[x.src] = x.blocks
            recv_idx[x.dst] = x.blocks
            perm.append((x.src, x.dst))
        my_send = jnp.take(jnp.asarray(send_idx), idx, axis=0)
        payload = jnp.take(blocks_view, my_send, axis=0)
        got = lax.ppermute(payload, axis_name, perm)
        my_recv = jnp.take(jnp.asarray(recv_idx), idx, axis=0)
        if st.combine == sir.SUM:
            cur = jnp.take(blocks_view, my_recv, axis=0)
            blocks_view = blocks_view.at[my_recv].set(fold_fn(cur, got))
        else:
            blocks_view = blocks_view.at[my_recv].set(got)
    return blocks_view


def _pair_family_exec(x, axis_name, prog: IRProgram, rop):
    """The generic executor for pair-stage families (swing, generalized):
    head/tail split over the ``scheduled`` block owners, whole-buffer
    fold/restore hops for the non-power-of-two extras, block-map pair
    exchanges for everything else."""
    if rop.name != "sum":
        raise NotImplementedError(
            f"IR family {prog.family!r} lowers op='sum' only (got {rop.name!r})"
        )
    fn = _jnp_fn(rop)
    m = prog.scheduled
    idx = lax.axis_index(axis_name)
    shape = x.shape
    v = x.reshape(-1)
    head, tail = _split_main_tail(v, m)
    parts = []
    if head is not None:
        tile = head.shape[0] // m
        for st in prog.stages:
            if st.phase == "fold":
                with jax.named_scope(f"ft_{prog.family}_fold"):
                    perm = [(x_.src, x_.dst) for x_ in st.xfers]
                    extras = len(perm)
                    got = lax.ppermute(head, axis_name, perm)
                    head = jnp.where(idx < extras, fn(head, got), head)
            elif st.phase == "restore":
                with jax.named_scope(f"ft_{prog.family}_restore"):
                    perm = [(x_.src, x_.dst) for x_ in st.xfers]
                    got = lax.ppermute(head, axis_name, perm)
                    head = jnp.where(idx >= m, got, head)
            else:
                scope = f"ft_{prog.family}_{st.phase}_stage{st.index}"
                with jax.named_scope(scope):
                    view = head.reshape(m, tile)
                    view = _pair_block_exchange(
                        view, axis_name, st, prog.num_nodes, fn
                    )
                    head = view.reshape(-1)
        parts.append(head)
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return out.reshape(shape)


# ---------------------------------------------------------- tree / lonely


def _tree_rs_from_stages(v, axis_name, stages, topo: Topology, rop):
    """Phase 1 driven by the IR's grouped rs stage rows — the same
    ``psum_scatter``/``ppermute-ring`` calls ``_tree_reduce_scatter``
    makes, with the groups read off the stage records."""
    for st in stages:
        w = topo.widths[st.index]
        with jax.named_scope(f"ft_rs_stage{st.index}_w{w}"):
            if rop.name == "sum":
                v = lax.psum_scatter(
                    v,
                    axis_name,
                    scatter_dimension=0,
                    axis_index_groups=_groups_or_none(topo, st.index),
                    tiled=True,
                )
            else:
                v = _grouped_reduce_scatter_generic(
                    v, axis_name, topo, st.index, rop
                )
    return v


def _tree_ag_from_stages(v, axis_name, stages, topo: Topology):
    for st in stages:
        w = topo.widths[st.index]
        with jax.named_scope(f"ft_ag_stage{st.index}_w{w}"):
            v = lax.all_gather(
                v,
                axis_name,
                axis_index_groups=_groups_or_none(topo, st.index),
                axis=0,
                tiled=True,
            )
    return v


def _tree_exec(x, axis_name, prog: IRProgram, rop):
    """The tree program: chunk-interleaved grouped stages, head/tail
    split — trace-for-trace what ``tree_allreduce`` emits (the golden
    suite holds the compiled HLO equal)."""
    topo: Topology = prog.topo
    n = topo.num_nodes
    rs_stages = [s for s in prog.stages if s.phase == "rs" and s.chunk == 0]
    ag_stages = [s for s in prog.stages if s.phase == "ag" and s.chunk == prog.chunks - 1]
    shape = x.shape
    head, tail = _split_main_tail(x, n)
    parts = []
    if head is not None:
        sizes = _chunk_sizes(head.size, n, prog.chunks)
        if len(sizes) == 1:
            h = _tree_rs_from_stages(head, axis_name, rs_stages, topo, rop)
            parts.append(_tree_ag_from_stages(h, axis_name, ag_stages, topo))
        else:
            pieces, off = [], 0
            for s in sizes:
                pieces.append(head[off : off + s])
                off += s
            outs, scattered = [], None
            for c, piece in enumerate(pieces):
                with jax.named_scope(f"ft_chunk{c}_rs"):
                    cur = _tree_rs_from_stages(
                        piece, axis_name, rs_stages, topo, rop
                    )
                if scattered is not None:
                    with jax.named_scope(f"ft_chunk{c - 1}_ag"):
                        outs.append(
                            _tree_ag_from_stages(
                                scattered, axis_name, ag_stages, topo
                            )
                        )
                scattered = cur
            with jax.named_scope(f"ft_chunk{len(pieces) - 1}_ag"):
                outs.append(
                    _tree_ag_from_stages(scattered, axis_name, ag_stages, topo)
                )
            parts.append(jnp.concatenate(outs))
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    v = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return v.reshape(shape)


def _lonely_exec(x, axis_name, prog: IRProgram, rop):
    """The lonely program driven off its IR stages: fold hop, prefix-tree
    grouped stages (always the ppermute-ring helpers — XLA's grouped
    collectives cannot cover a rank subset), restore hop — trace-for-
    trace ``lonely_allreduce``."""
    topo: LonelyTopology = prog.topo
    tree, m = topo.tree, topo.tree.num_nodes
    fn = _jnp_fn(rop)
    idx = lax.axis_index(axis_name)
    shape = x.shape
    v = x.reshape(-1)
    head, tail = _split_main_tail(v, m)
    parts = []
    if head is not None:
        for st in prog.stages:
            if st.phase == "fold":
                with jax.named_scope("ft_lonely_fold"):
                    perm = [(x_.src, x_.dst) for x_ in st.xfers]
                    got = lax.ppermute(head, axis_name, perm)
                    head = jnp.where(idx < len(perm), fn(head, got), head)
            elif st.phase == "rs":
                w = tree.widths[st.index]
                with jax.named_scope(f"ft_lonely_rs_stage{st.index}_w{w}"):
                    head = _grouped_reduce_scatter_generic(
                        head, axis_name, tree, st.index, rop
                    )
            elif st.phase == "ag":
                w = tree.widths[st.index]
                with jax.named_scope(f"ft_lonely_ag_stage{st.index}_w{w}"):
                    head = _grouped_allgather_generic(
                        head, axis_name, tree, st.index
                    )
            else:  # restore
                with jax.named_scope("ft_lonely_restore"):
                    perm = [(x_.src, x_.dst) for x_ in st.xfers]
                    got2 = lax.ppermute(head, axis_name, perm)
                    head = jnp.where(idx >= m, got2, head)
        parts.append(head)
    if tail is not None:
        parts.append(_small_dense_allreduce(tail, axis_name, rop))
    out = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return out.reshape(shape)


# ----------------------------------------------------------------- entry


def lower_ir(prog: IRProgram, op: str = "sum"):
    """Lower a (verified) IR program; returns ``f(x, axis_name) -> x``.

    Call only through ``schedule.ir.compile_ir`` — this function assumes
    the model checks already ran; it re-checks only the canonical-twin
    structural equality (the IR/executable-divergence guard)."""
    _require_canonical(prog)
    rop = get_op(op)

    if prog.family == "tree":
        return lambda x, axis_name: _tree_exec(x, axis_name, prog, rop)
    if prog.family == "lonely":
        return lambda x, axis_name: _lonely_exec(x, axis_name, prog, rop)
    if prog.family == "ring":
        # the 2(N-1) ring-step stages compile ROLLED: two fori_loops of
        # one ppermute each (the canonical-twin check above pinned the
        # declarative walk to the reference block schedule)
        return lambda x, axis_name: ring_allreduce(x, axis_name, op=rop)
    return lambda x, axis_name: _pair_family_exec(x, axis_name, prog, rop)
