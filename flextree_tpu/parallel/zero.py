"""ZeRO-1 sharded-optimizer training: FlexTree split collectives on the
gradient-sync seam.

The replicated train path keeps a full copy of the optimizer moments on
every data-parallel rank and syncs gradients with a full allreduce — but
FlexTree's phase 1 already *is* a grouped reduce-scatter and phase 2 an
allgather (``parallel/allreduce.py``).  This module splits the step at
that seam (the ROADMAP's "Sharded training workload" item):

1. **reduce-scatter** each gradient bucket over the leaf's FIRST
   replication axis (wire-compressed per hop when a codec is set — the
   regime EQuARX targets, where the quantized payload also shrinks with
   world size);
2. secondary replication axes allreduce only the 1/N **shard**;
3. the AdamW update runs on the owned shard only, against sharded
   moments (``mu``/``nu`` memory drops by the shard-axis size);
4. **all-gather** the updated parameter shards (per bucket, so XLA can
   overlap one bucket's gather with another's optimizer math).

Wire accounting per synced byte ``B`` on the shard axis: the replicated
path moves ``2B(N-1)/N`` (reduce-scatter + allgather of *gradients*); the
sharded path moves ``B(N-1)/N`` of gradients down and ``B(N-1)/N`` of
*parameters* up — identical for f32, but the codec now applies to BOTH
phases (grads down, params up), so the quantized sharded step moves
``~2·r·B(N-1)/N`` bytes (``r`` = wire ratio, ~0.25 for int8) against the
replicated fused f32 baseline's ``2B(N-1)/N`` — the measured floor
``BENCH_SHARDED.json`` enforces.  Parameter quantization is safe because
the authoritative **master copy is sharded f32** (``master_*`` state
entries, lossy codecs only): every rank's working params are
``decode(encode(master))`` of identical bytes, so replicas cannot drift
and the quantization error never accumulates (unlike gradients, which
carry an EF residual for exactly that reason).

Shard layout (the contract ``docs/SHARDED.md`` documents): per LOCAL
leaf (the per-device shard a model-parallel axis may already have
carved), the divisible head splits into ``N`` blocks and the rank at
shard-axis position ``r`` owns block ``schedule.blocks.owned_block(topo,
r)``; the ``< N``-element tail is reduced by one dense collective and
updated REPLICATED on every rank (tails are bias/norm scraps — sharding
them would cost a broadcast to save bytes).  Buckets pack leaf heads
**block-interleaved** (fused block ``b`` = every leaf's block ``b``) so
one fused collective per bucket still yields per-leaf shards — and so
the ring walk keeps each element's per-leaf block association, which is
what makes the sharded step **bitwise equal** to the replicated step for
the identity codec across flat/tree/ring shard topologies
(property-tested in ``tests/test_sharded.py``).  Lonely shard topologies
fall back to the flat tree for the sharded collectives (lonely ranks own
no block; lonely shapes exist for awkward world sizes, not for ZeRO).

Checkpoints of sharded runs are CONSOLIDATED (``make_consolidate_fn`` —
each survivor all-gathers every leaf back to the replicated layout on
device, through the same ``all_gather`` collective the step uses), so a
checkpoint is world-size-independent and the elastic runtime's
shrink-to-survivors re-shards it into any survivor world
(``make_reshard_fn``) — the ``fit`` loop's ``state_pack``/
``state_unpack`` hooks wire this through.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..schedule.blocks import shard_layout
from ..schedule.stages import LonelyTopology, Topology
from ..utils.profiling import comm_span
from .allreduce import _NATIVE_PSUM, all_gather, allreduce, reduce_scatter
from .bucketing import plan_buckets, replication_key, spec_axes

__all__ = [
    "ZeroShard",
    "ZeroLeafPlan",
    "ZeroLayout",
    "build_zero_layout",
    "init_zero_entries",
    "zero_state_specs",
    "zero_reduce_scatter_grads",
    "zero_apply_and_gather",
    "zero_sync_and_update",
    "sharded_grad_norm",
    "maybe_clip_shards",
    "make_consolidate_fn",
    "make_reshard_fn",
    "zero_shard_bytes",
]


class ZeroShard:
    """One leaf's sharded gradient: the owned head block plus the
    replicated tail.  Deliberately NOT a registered pytree — tree
    utilities must treat it as a leaf so the overlap engine can carry
    shard trees through its per-segment machinery unchanged."""

    __slots__ = ("tile", "tail")

    def __init__(self, tile, tail):
        self.tile = tile
        self.tail = tail


@dataclasses.dataclass(frozen=True)
class ZeroLeafPlan:
    """Static sharding plan for one gradient/parameter leaf.  All sizes
    are LOCAL (per-device): a model-parallel axis in the leaf's own
    PartitionSpec has already carved the leaf before the optimizer
    sharding sees it."""

    index: int
    axes: tuple[str, ...]  # replication axes (size > 1), mesh order
    model_axes: tuple[str, ...]  # axes in the leaf's own spec, mesh order
    shard_ax: str | None  # axes[0], or None for unsynced leaves
    n: int  # shard-axis size (1 when unsharded)
    size: int  # local element count
    head: int  # (size // n) * n
    tile: int  # head // n — owned elements
    tail: int  # size - head — replicated elements

    @property
    def sharded(self) -> bool:
        return self.shard_ax is not None


@dataclasses.dataclass(frozen=True)
class ZeroLayout:
    """The whole tree's sharding plan (host-level, static).

    Built once at step-build time from parameter shapes, specs and axis
    sizes — deliberately independent of the wire topology, so the state
    SHAPES survive an autotune re-pick; only the block→rank permutation
    (``perm_for``) reads the live topology.
    """

    mesh_axes: tuple[str, ...]
    axis_sizes: Mapping[str, int]
    leaves: tuple[ZeroLeafPlan, ...]

    @property
    def n_sharded(self) -> int:
        return sum(1 for l in self.leaves if l.sharded)

    def perm_for(self, topos: Mapping[str, Any], ax: str) -> tuple[int, ...]:
        """Block owned per shard-axis position on ``ax`` under the
        resolved ``topos``."""
        n = int(self.axis_sizes[ax])
        return shard_layout(_shard_topo(topos.get(ax), n))


def _shard_topo(topo, n: int):
    """The topology the sharded collectives actually run on an axis: the
    configured shape, except ``None`` (the "psum" sentinel) and lonely
    shapes resolve to the flat tree (one grouped XLA collective per
    phase; lonely ranks own no block, so the seam is not shardable)."""
    if topo is None:
        return Topology.flat(n)
    topo = Topology.resolve(n, topo)
    if isinstance(topo, LonelyTopology):
        return Topology.flat(n)
    return topo


def _local_size(shape, spec, axis_sizes: Mapping[str, int]) -> int:
    """Per-device element count of a leaf whose GLOBAL shape is ``shape``
    under PartitionSpec ``spec``."""
    total = 1
    for d in shape:
        total *= int(d)
    denom = 1
    for a in spec_axes(spec):
        denom *= int(axis_sizes.get(a, 1))
    return total // denom


def build_zero_layout(
    params_shapes,
    pspecs,
    mesh_axes,
    axis_sizes: Mapping[str, int],
    local: bool = False,
) -> ZeroLayout:
    """Sharding plan for a parameter tree: each leaf shards over the
    FIRST mesh axis (mesh order) it is replicated on; leaves replicated
    nowhere (covered by model-parallel axes) stay unsharded.

    ``local=False`` (host side) treats ``params_shapes`` as GLOBAL shapes
    and divides by the leaf's own spec axes; ``local=True`` (inside
    ``shard_map``, where tracers already carry per-device shapes) uses
    the sizes as given.
    """
    flat_p, treedef = jax.tree.flatten(params_shapes)
    flat_s = treedef.flatten_up_to(pspecs)
    leaves = []
    for i, (p, spec) in enumerate(zip(flat_p, flat_s)):
        axes = tuple(
            a
            for a in replication_key(spec, mesh_axes)
            if int(axis_sizes.get(a, 1)) > 1
        )
        model_axes = tuple(a for a in mesh_axes if a in set(spec_axes(spec)))
        size = (
            int(p.size) if local else _local_size(p.shape, spec, axis_sizes)
        )
        if axes:
            shard_ax = axes[0]
            n = int(axis_sizes[shard_ax])
        else:
            shard_ax, n = None, 1
        tile = size // n
        leaves.append(
            ZeroLeafPlan(
                i, axes, model_axes, shard_ax, n, size,
                tile * n, tile, size - tile * n,
            )
        )
    return ZeroLayout(tuple(mesh_axes), dict(axis_sizes), tuple(leaves))


# ------------------------------------------------------------ state layout


def _global_len(plan: ZeroLeafPlan, per_device: int, axis_sizes, with_shard_ax):
    mult = 1
    if with_shard_ax and plan.shard_ax is not None:
        mult *= int(axis_sizes[plan.shard_ax])
    for a in plan.model_axes:
        mult *= int(axis_sizes.get(a, 1))
    return per_device * mult


def init_zero_entries(params, layout: ZeroLayout, lossy: bool) -> dict:
    """Sharded-optimizer state entries around a HOST-GLOBAL params tree.

    Moment layout per leaf: ``*_shard`` holds the owned head block (a
    per-device ``(tile,)`` buffer, sharded over ``(shard_ax, *model
    axes)``), ``*_tail`` the replicated <N tail (sharded over the model
    axes only), ``*_rep`` the full leaf for unsynced leaves.  A lossy
    wire codec adds the sharded f32 ``master_*`` parameter copy; it
    initializes to ZEROS and the first step bootstraps it from the (still
    exact) working params — which block a rank owns depends on the wire
    topology, something the step knows and host init deliberately
    doesn't.  Empty slots are zero-size arrays so every entry shares the
    params treedef.
    """
    flat_p, treedef = jax.tree.flatten(params)
    sizes = layout.axis_sizes

    def build(part):
        out = []
        for plan, p in zip(layout.leaves, flat_p):
            if part == "rep":
                out.append(
                    jnp.zeros_like(p)
                    if not plan.sharded
                    else jnp.zeros((0,), jnp.float32)
                )
            elif part == "shard":
                n = (
                    _global_len(plan, plan.tile, sizes, with_shard_ax=True)
                    if plan.sharded
                    else 0
                )
                out.append(jnp.zeros((n,), jnp.float32))
            else:  # tail
                n = (
                    _global_len(plan, plan.tail, sizes, with_shard_ax=False)
                    if plan.sharded
                    else 0
                )
                out.append(jnp.zeros((n,), jnp.float32))
        return treedef.unflatten(out)

    entries = {
        "mu_shard": build("shard"),
        "mu_tail": build("tail"),
        "mu_rep": build("rep"),
        "nu_shard": build("shard"),
        "nu_tail": build("tail"),
        "nu_rep": build("rep"),
    }
    if lossy:
        entries["master_shard"] = build("shard")
        entries["master_tail"] = build("tail")
    return entries


def zero_state_specs(pspecs, layout: ZeroLayout, lossy: bool) -> dict:
    """PartitionSpecs for :func:`init_zero_entries`' trees: owned blocks
    are 1-D buffers sharded over the compound ``(shard_ax, *model
    axes)``; tails over the model axes alone; ``*_rep`` keeps the leaf's
    own spec."""
    flat_s, treedef = jax.tree.flatten(
        pspecs, is_leaf=lambda x: isinstance(x, P) or x is None
    )

    def build(part):
        out = []
        for plan, spec in zip(layout.leaves, flat_s):
            if part == "rep":
                out.append(spec if not plan.sharded else P())
            elif not plan.sharded:
                out.append(P())
            elif part == "shard":
                out.append(P((plan.shard_ax,) + plan.model_axes))
            else:
                out.append(P(plan.model_axes) if plan.model_axes else P())
        return treedef.unflatten(out)

    specs = {
        "mu_shard": build("shard"),
        "mu_tail": build("tail"),
        "mu_rep": build("rep"),
        "nu_shard": build("shard"),
        "nu_tail": build("tail"),
        "nu_rep": build("rep"),
    }
    if lossy:
        specs["master_shard"] = build("shard")
        specs["master_tail"] = build("tail")
    return specs


# -------------------------------------------------------- collective layer


def _interleave_pack(heads: Sequence[jax.Array], n: int) -> jax.Array:
    """Block-interleaved bucket packing: fused block ``b`` is the
    concatenation of every leaf's block ``b``, so one fused collective
    yields per-leaf shards AND each element keeps its per-leaf block
    index (the ring association rule — same packing as the replicated
    fused ring path, which is what keeps the sharded sync bitwise)."""
    cols = [h.reshape(n, -1) for h in heads]
    fused = cols[0] if len(cols) == 1 else jnp.concatenate(cols, axis=1)
    return fused.reshape(-1)


def _uninterleave(flat: jax.Array, n: int, widths: Sequence[int]) -> list[jax.Array]:
    """Inverse of :func:`_interleave_pack` for a full (n-block) buffer."""
    rows = flat.reshape(n, -1)
    out, off = [], 0
    for w in widths:
        out.append(lax.slice_in_dim(rows, off, off + w, axis=1).reshape(-1))
        off += w
    return out


def _split_tile(tile: jax.Array, widths: Sequence[int]) -> list[jax.Array]:
    out, off = [], 0
    for w in widths:
        out.append(lax.slice_in_dim(tile, off, off + w, axis=0))
        off += w
    return out


def _rs_wire(fused, ax, topo, codec, step):
    """Phase-1 wire for one packed bucket: returns (owned block, local
    input-quantization residual or None).  Delegates to the split
    collectives — ONE wire implementation, so a codec/salt/residual fix
    there cannot silently diverge from the sharded step (the packed
    bucket is always block-divisible, so the tail path never engages)."""
    if not codec.lossy:
        return reduce_scatter(fused, ax, topo=topo), None
    from .compressed import compressed_reduce_scatter

    return compressed_reduce_scatter(
        fused, ax, topo=topo, codec=codec, step=step, return_residual=True
    )


def _ag_wire(tile, ax, topo, codec, step):
    """Phase-2 wire for one packed bucket of updated param blocks —
    delegates like :func:`_rs_wire`."""
    if not codec.lossy:
        return all_gather(tile, ax, topo=topo)
    from .compressed import compressed_all_gather

    return compressed_all_gather(tile, ax, topo=topo, codec=codec, step=step)


def zero_reduce_scatter_grads(
    grads,
    pspecs,
    mesh_axes,
    topos: Mapping[str, Any],
    *,
    layout: ZeroLayout | None = None,
    bucket_bytes: int | None = None,
    codec="f32",
    step=0,
    return_residual: bool = False,
):
    """Sharded gradient sync, phase 1: one fused reduce-scatter per bucket
    over the shard axis (wire-compressed under a lossy ``codec``), one
    dense collective per bucket for the <N tails, and an allreduce of the
    *shard* over each secondary replication axis — exactly the replicated
    fused sync's per-element reductions, minus the gradient allgather.

    Returns a tree of :class:`ZeroShard` per synced leaf (unsynced leaves
    pass through as plain arrays); with ``return_residual=True`` also the
    per-leaf error-feedback residual tree (the wire's actual first-hop
    encode for the shard axis).  Collective-context function.
    """
    from ..ops.quantize import get_codec

    codec = get_codec(codec)
    flat_g, treedef = jax.tree.flatten(grads)
    flat_s = treedef.flatten_up_to(pspecs)
    axis_sizes = {ax: lax.axis_size(ax) for ax in mesh_axes}
    if layout is None:
        layout = build_zero_layout(
            flat_g, flat_s, mesh_axes, axis_sizes, local=True
        )
    buckets = plan_buckets(
        flat_g, flat_s, mesh_axes, topos=topos, axis_sizes=axis_sizes,
        bucket_bytes=bucket_bytes, codec=codec if codec.lossy else None,
        sharded=True,
    )
    out: list[Any] = list(flat_g)
    residuals = [jnp.zeros_like(g) for g in flat_g] if return_residual else None

    for bi, b in enumerate(buckets):
        plans = [layout.leaves[i] for i in b.indices]
        shard_ax = b.axes[0]
        n = int(axis_sizes[shard_ax])
        topo = _shard_topo(topos.get(shard_ax), n)
        leaves = [flat_g[i].reshape(-1).astype(jnp.float32) for i in b.indices]
        heads = [g[: p.head] for g, p in zip(leaves, plans) if p.tile]
        head_plans = [p for p in plans if p.tile]
        tails = [g[p.head :] for g, p in zip(leaves, plans) if p.tail]
        tail_plans = [p for p in plans if p.tail]
        name = f"ftz_rs_bucket{bi}_{shard_ax}_{len(b.indices)}leaves_{b.nbytes}B"

        tile = None
        with comm_span(name):
            if heads:
                fused = _interleave_pack(heads, n)
                tile, res = _rs_wire(fused, shard_ax, topo, codec, step)
                if return_residual and res is not None:
                    widths = [p.tile for p in head_plans]
                    for p, r in zip(head_plans, _uninterleave(res, n, widths)):
                        flat_res = jnp.zeros((p.size,), jnp.float32)
                        flat_res = flat_res.at[: p.head].set(r)
                        residuals[p.index] = flat_res.reshape(
                            flat_g[p.index].shape
                        ).astype(flat_g[p.index].dtype)
            red_tail = None
            if tails:
                fused_t = tails[0] if len(tails) == 1 else jnp.concatenate(tails)
                red_tail = _NATIVE_PSUM(fused_t, shard_ax)
            # secondary replication axes: sync only the shard (1/N bytes)
            for ax in b.axes[1:]:
                if topos.get(ax) is None:
                    if tile is not None:
                        tile = _NATIVE_PSUM(tile, ax)
                    if red_tail is not None:
                        red_tail = _NATIVE_PSUM(red_tail, ax)
                    continue
                t2 = Topology.resolve(int(axis_sizes[ax]), topos[ax])
                if tile is not None:
                    if codec.lossy:
                        from .compressed import compressed_allreduce

                        tile = compressed_allreduce(
                            tile, ax, topo=t2, codec=codec, step=step
                        )
                    else:
                        tile = allreduce(tile, ax, topo=t2, op="sum")
                if red_tail is not None:
                    red_tail = _NATIVE_PSUM(red_tail, ax)

        tile_parts = (
            _split_tile(tile, [p.tile for p in head_plans])
            if tile is not None
            else []
        )
        tile_by_idx = {p.index: t for p, t in zip(head_plans, tile_parts)}
        tail_parts = (
            _split_tile(red_tail, [p.tail for p in tail_plans]) if tails else []
        )
        tail_by_idx = {p.index: t for p, t in zip(tail_plans, tail_parts)}
        for i in b.indices:
            out[i] = ZeroShard(
                tile_by_idx.get(i, jnp.zeros((0,), jnp.float32)),
                tail_by_idx.get(i, jnp.zeros((0,), jnp.float32)),
            )
    if return_residual:
        return treedef.unflatten(out), treedef.unflatten(residuals)
    return treedef.unflatten(out)


# ----------------------------------------------------------- update + AG


def _adamw_elem(p, g, mu, nu, t, train_cfg):
    """The exact :func:`train.adamw_apply` element math, factored so the
    sharded update cannot drift from the replicated one (bitwise for f32:
    same inputs, same expression tree)."""
    c1 = 1.0 - train_cfg.b1 ** t
    c2 = 1.0 - train_cfg.b2 ** t
    mu = train_cfg.b1 * mu + (1.0 - train_cfg.b1) * g
    nu = train_cfg.b2 * nu + (1.0 - train_cfg.b2) * (g * g)
    delta = (mu / c1) / (jnp.sqrt(nu / c2) + train_cfg.eps)
    if train_cfg.weight_decay:
        delta = delta + train_cfg.weight_decay * p
    return delta, mu, nu


def sharded_grad_norm(shard_tree, pspecs, layout: ZeroLayout):
    """True global L2 norm of a sharded gradient tree: owned head blocks
    partition each leaf's head over the shard axis (psum restores the
    total exactly once); tails are replicated over the shard axis, so
    their square-sum joins WITHOUT that psum; leaf-spec (model-parallel)
    axes psum once per axis-set group, exactly as
    ``train.global_grad_norm``."""
    flat_g, treedef = jax.tree.flatten(
        shard_tree, is_leaf=lambda x: isinstance(x, ZeroShard)
    )
    flat_s = treedef.flatten_up_to(pspecs)
    by_key: dict[tuple, Any] = {}

    def add(key, val):
        by_key[key] = by_key.get(key, jnp.float32(0.0)) + val

    for plan, g, spec in zip(layout.leaves, flat_g, flat_s):
        leaf_axes = spec_axes(spec)
        if isinstance(g, ZeroShard):
            if plan.tile:
                add(
                    (plan.shard_ax,) + leaf_axes,
                    jnp.sum(jnp.square(g.tile.astype(jnp.float32))),
                )
            if plan.tail:
                add(leaf_axes, jnp.sum(jnp.square(g.tail.astype(jnp.float32))))
        else:
            add(leaf_axes, jnp.sum(jnp.square(g.astype(jnp.float32))))
    total = jnp.float32(0.0)
    for axes, sq in by_key.items():
        for ax in axes:
            sq = lax.psum(sq, ax)
        total = total + sq
    return jnp.sqrt(total)


def maybe_clip_shards(
    shard_tree, pspecs, train_cfg, layout: ZeroLayout, metrics: dict | None
):
    """Sharded twin of ``train.maybe_clip_grads``: the true global norm
    from owned shards, recorded and applied.  Values match the replicated
    path to float tolerance (different summation order), so the bitwise
    sharded==replicated contract holds only with clipping off —
    documented in docs/SHARDED.md."""
    if not train_cfg.grad_clip_norm:
        return shard_tree
    if train_cfg.grad_clip_norm < 0:
        raise ValueError(
            f"grad_clip_norm must be positive, got {train_cfg.grad_clip_norm}"
        )
    norm = sharded_grad_norm(shard_tree, pspecs, layout)
    if metrics is not None:
        metrics["grad_norm"] = norm
    scale = jnp.minimum(1.0, train_cfg.grad_clip_norm / jnp.maximum(norm, 1e-12))

    def scl(g):
        if isinstance(g, ZeroShard):
            return ZeroShard(g.tile * scale, g.tail * scale)
        return g * scale.astype(g.dtype)

    return jax.tree.map(scl, shard_tree, is_leaf=lambda x: isinstance(x, ZeroShard))


def zero_apply_and_gather(
    state,
    shard_tree,
    pspecs,
    mesh_axes,
    topos: Mapping[str, Any],
    train_cfg,
    layout: ZeroLayout,
):
    """Phase 2 of the sharded step: AdamW on the owned shards, then one
    fused parameter all-gather per bucket (wire-compressed under the
    step's codec; every rank decodes identical bytes, and lossy codecs
    update the sharded f32 master copy so the error never accumulates —
    the master bootstraps from the working params at step 0, when they
    are still exact).  Returns the new state dict (params fully
    materialized).  Collective-context function.
    """
    from ..ops.quantize import get_codec
    from .train import schedule_lr

    codec = get_codec(train_cfg.codec)
    step = state["step"] + 1
    t = step.astype(jnp.float32)
    lr = schedule_lr(train_cfg, step)
    lossy = codec.lossy
    bootstrap = state["step"] == 0  # master_* holds zeros before step 1

    params = state["params"]
    flat_p, treedef = jax.tree.flatten(params)
    flat_g = treedef.flatten_up_to(shard_tree)
    flat_s = treedef.flatten_up_to(pspecs)
    axis_sizes = {ax: lax.axis_size(ax) for ax in mesh_axes}

    def flt(key):
        return treedef.flatten_up_to(state[key])

    mu_sh, mu_tl, mu_rp = flt("mu_shard"), flt("mu_tail"), flt("mu_rep")
    nu_sh, nu_tl, nu_rp = flt("nu_shard"), flt("nu_tail"), flt("nu_rep")
    ma_sh = flt("master_shard") if lossy else [None] * len(flat_p)
    ma_tl = flt("master_tail") if lossy else [None] * len(flat_p)

    new_p = list(flat_p)
    new = {
        k: [None] * len(flat_p)
        for k in (
            "mu_shard", "mu_tail", "mu_rep", "nu_shard", "nu_tail", "nu_rep"
        )
    }
    if lossy:
        new["master_shard"] = [None] * len(flat_p)
        new["master_tail"] = [None] * len(flat_p)

    # per-bucket parameter all-gather: group synced leaves exactly like
    # the gradient reduce-scatter did, so gathers stay fused
    buckets = plan_buckets(
        flat_p, flat_s, mesh_axes, topos=topos, axis_sizes=axis_sizes,
        bucket_bytes=train_cfg.bucket_bytes,
        codec=codec if codec.lossy else None, sharded=True,
    )
    bucketed = {i for b in buckets for i in b.indices}

    # --- unsynced (model-parallel-only) leaves: plain replicated AdamW
    for i, plan in enumerate(layout.leaves):
        if i in bucketed:
            continue
        g = flat_g[i]
        delta, mu, nu = _adamw_elem(
            flat_p[i], g.astype(flat_p[i].dtype), mu_rp[i], nu_rp[i], t, train_cfg
        )
        new_p[i] = flat_p[i] - lr * delta
        new["mu_rep"][i], new["nu_rep"][i] = mu, nu
        new["mu_shard"][i], new["nu_shard"][i] = mu_sh[i], nu_sh[i]
        new["mu_tail"][i], new["nu_tail"][i] = mu_tl[i], nu_tl[i]
        if lossy:
            new["master_shard"][i] = ma_sh[i]
            new["master_tail"][i] = ma_tl[i]

    for bi, b in enumerate(buckets):
        shard_ax = b.axes[0]
        n = int(axis_sizes[shard_ax])
        topo = _shard_topo(topos.get(shard_ax), n)
        perm = jnp.asarray(layout.perm_for(topos, shard_ax), jnp.int32)
        own_b = perm[lax.axis_index(shard_ax)]

        upd_tiles: list[jax.Array] = []
        head_plans: list[ZeroLeafPlan] = []
        for i in b.indices:
            plan = layout.leaves[i]
            g = flat_g[i]
            p_flat = flat_p[i].reshape(-1).astype(jnp.float32)
            if plan.tile:
                own_block = lax.dynamic_slice_in_dim(
                    p_flat[: plan.head], own_b * plan.tile, plan.tile, axis=0
                )
                p_tile = (
                    jnp.where(bootstrap, own_block, ma_sh[i]) if lossy else own_block
                )
                d, mu, nu = _adamw_elem(
                    p_tile, g.tile, mu_sh[i], nu_sh[i], t, train_cfg
                )
                new_tile = p_tile - lr * d
                new["mu_shard"][i], new["nu_shard"][i] = mu, nu
                if lossy:
                    new["master_shard"][i] = new_tile
                upd_tiles.append(new_tile)
                head_plans.append(plan)
            else:
                new["mu_shard"][i], new["nu_shard"][i] = mu_sh[i], nu_sh[i]
                if lossy:
                    new["master_shard"][i] = ma_sh[i]
            if plan.tail:
                p_tail = p_flat[plan.head :]
                if lossy:
                    p_tail = jnp.where(bootstrap, p_tail, ma_tl[i])
                d, mu, nu = _adamw_elem(
                    p_tail, g.tail, mu_tl[i], nu_tl[i], t, train_cfg
                )
                new_tail = p_tail - lr * d
                new["mu_tail"][i], new["nu_tail"][i] = mu, nu
                if lossy:
                    new["master_tail"][i] = new_tail
            else:
                new_tail = jnp.zeros((0,), jnp.float32)
                new["mu_tail"][i], new["nu_tail"][i] = mu_tl[i], nu_tl[i]
                if lossy:
                    new["master_tail"][i] = ma_tl[i]
            new["mu_rep"][i], new["nu_rep"][i] = mu_rp[i], nu_rp[i]
            new_p[i] = ("pending", new_tail)  # filled after the gather

        name = f"ftz_ag_bucket{bi}_{shard_ax}_{len(b.indices)}leaves_{b.nbytes}B"
        full_by_idx: dict[int, jax.Array] = {}
        if upd_tiles:
            packed = (
                upd_tiles[0] if len(upd_tiles) == 1 else jnp.concatenate(upd_tiles)
            )
            with comm_span(name):
                full = _ag_wire(packed, shard_ax, topo, codec, step)
            widths = [p.tile for p in head_plans]
            for p, h in zip(head_plans, _uninterleave(full, n, widths)):
                full_by_idx[p.index] = h
        for i in b.indices:
            plan = layout.leaves[i]
            _, new_tail = new_p[i]
            parts = []
            if plan.tile:
                parts.append(full_by_idx[i])
            if plan.tail:
                # lossy codecs roundtrip the tail through the codec too:
                # the tail never hits the wire, but replicas must hold
                # the SAME deterministic view of the master — the exact
                # f32 tail is that view (every rank computed it
                # identically), so it needs no quantization
                parts.append(new_tail)
            flat_new = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
            new_p[i] = flat_new.reshape(flat_p[i].shape).astype(flat_p[i].dtype)

    out = {"params": treedef.unflatten(new_p), "step": step}
    for k, vals in new.items():
        out[k] = treedef.unflatten(vals)
    return out


def zero_sync_and_update(
    state, grads, pspecs, mesh_axes, topos, train_cfg, layout: ZeroLayout,
    metrics: dict | None = None,
):
    """The whole sharded optimizer step: EF merge, per-bucket quantized
    reduce-scatter, (optional) global-norm clipping from shards, sharded
    AdamW, per-bucket parameter all-gather.  Returns the new state.
    The step-family twin of ``sync_with_feedback`` + ``maybe_clip_grads``
    + ``adamw_apply`` — bitwise-equal results for the identity codec.
    """
    from .train import _sync_codec

    codec = _sync_codec(train_cfg)
    new_ef = None
    if codec.lossy:
        v = jax.tree.map(lambda g, e: g + e.astype(g.dtype), grads, state["ef"])
        shard_tree, new_ef = zero_reduce_scatter_grads(
            v, pspecs, mesh_axes, topos, layout=layout,
            bucket_bytes=train_cfg.bucket_bytes, codec=codec,
            step=state["step"], return_residual=True,
        )
    else:
        shard_tree = zero_reduce_scatter_grads(
            grads, pspecs, mesh_axes, topos, layout=layout,
            bucket_bytes=train_cfg.bucket_bytes,
        )
    shard_tree = maybe_clip_shards(shard_tree, pspecs, train_cfg, layout, metrics)
    new_state = zero_apply_and_gather(
        state, shard_tree, pspecs, mesh_axes, topos, train_cfg, layout
    )
    if new_ef is not None:
        new_state["ef"] = new_ef
    return new_state


# -------------------------------------------------- host-side re-sharding


def make_consolidate_fn(mesh, pspecs, layout: ZeroLayout, grad_topo, lossy: bool):
    """Jitted ``sharded state -> replicated checkpoint state`` converter.

    Every survivor all-gathers each leaf's moment (and master) shards
    back into the replicated layout — on device, through the same
    ``all_gather`` collective the step runs, so the consolidated
    checkpoint is world-size-independent (``{"params", "mu", "nu",
    "step"[, "ef"]}``, restorable by the replicated path too).  With a
    lossy codec the consolidated ``params`` are the f32 MASTER values
    (the authoritative copy) — except at step 0, before the first update
    populated the master, when the working params (still exact) stand in.
    """
    from .train import resolve_axis_topos

    mesh_axes = layout.mesh_axes
    topos = resolve_axis_topos(mesh, mesh_axes, grad_topo)
    in_specs = {"params": pspecs, "step": P()}
    in_specs.update(zero_state_specs(pspecs, layout, lossy))
    out_specs = {"params": pspecs, "mu": pspecs, "nu": pspecs, "step": P()}

    def device_fn(state):
        flat_p, treedef = jax.tree.flatten(state["params"])

        def gather(shard_key, tail_key, rep_key):
            sh = treedef.flatten_up_to(state[shard_key])
            tl = treedef.flatten_up_to(state[tail_key])
            rp = treedef.flatten_up_to(state[rep_key])
            out = []
            for plan, base in zip(layout.leaves, flat_p):
                if not plan.sharded:
                    out.append(rp[plan.index].astype(base.dtype))
                    continue
                topo = _shard_topo(topos.get(plan.shard_ax), plan.n)
                shard = jnp.concatenate([sh[plan.index], tl[plan.index]])
                full = all_gather(
                    shard, plan.shard_ax, topo=topo, out_shape=base.shape
                )
                out.append(full.astype(base.dtype))
            return treedef.unflatten(out)

        out = {
            "mu": gather("mu_shard", "mu_tail", "mu_rep"),
            "nu": gather("nu_shard", "nu_tail", "nu_rep"),
            "step": state["step"],
        }
        if lossy:
            # unsynced leaves have no master — their working params are
            # authoritative, so "params" is the rep source; at step 0 the
            # master is still the zeros placeholder and the (still exact)
            # working params stand in
            gathered = gather("master_shard", "master_tail", "params")
            out["params"] = jax.tree.map(
                lambda m, p: jnp.where(state["step"] == 0, p, m), gathered,
                state["params"],
            )
            out["ef"] = state["ef"]
        else:
            out["params"] = state["params"]
        return out

    if lossy:
        in_specs["ef"] = pspecs
        out_specs["ef"] = pspecs

    return jax.jit(
        jax.shard_map(
            device_fn, mesh=mesh, in_specs=(in_specs,), out_specs=out_specs,
            check_vma=False,
        )
    )


def make_reshard_fn(mesh, pspecs, layout: ZeroLayout, grad_topo, lossy: bool):
    """Jitted ``replicated checkpoint state -> sharded state`` converter
    for ``layout``'s (possibly different) world — the live
    shrink-to-survivors re-shard: every survivor re-partitions the full
    CRC-verified checkpoint into its newly owned blocks."""
    from .train import resolve_axis_topos

    mesh_axes = layout.mesh_axes
    topos = resolve_axis_topos(mesh, mesh_axes, grad_topo)
    in_specs = {"params": pspecs, "mu": pspecs, "nu": pspecs, "step": P()}
    out_specs = {"params": pspecs, "step": P()}
    out_specs.update(zero_state_specs(pspecs, layout, lossy))
    if lossy:
        in_specs["ef"] = pspecs
        out_specs["ef"] = pspecs

    def device_fn(state):
        flat_p, treedef = jax.tree.flatten(state["params"])

        def split(tree):
            flat = treedef.flatten_up_to(tree)
            shards, tails, reps = [], [], []
            for plan, v in zip(layout.leaves, flat):
                if not plan.sharded:
                    shards.append(jnp.zeros((0,), jnp.float32))
                    tails.append(jnp.zeros((0,), jnp.float32))
                    reps.append(v)
                    continue
                perm = jnp.asarray(
                    layout.perm_for(topos, plan.shard_ax), jnp.int32
                )
                own_b = perm[lax.axis_index(plan.shard_ax)]
                fv = v.reshape(-1).astype(jnp.float32)
                shards.append(
                    lax.dynamic_slice_in_dim(
                        fv[: plan.head], own_b * plan.tile, plan.tile, axis=0
                    )
                    if plan.tile
                    else jnp.zeros((0,), jnp.float32)
                )
                tails.append(fv[plan.head :])
                reps.append(jnp.zeros((0,), jnp.float32))
            return (
                treedef.unflatten(shards),
                treedef.unflatten(tails),
                treedef.unflatten(reps),
            )

        mu_s, mu_t, mu_r = split(state["mu"])
        nu_s, nu_t, nu_r = split(state["nu"])
        out = {
            "params": state["params"],
            "step": state["step"],
            "mu_shard": mu_s, "mu_tail": mu_t, "mu_rep": mu_r,
            "nu_shard": nu_s, "nu_tail": nu_t, "nu_rep": nu_r,
        }
        if lossy:
            ma_s, ma_t, _ = split(state["params"])
            out["master_shard"], out["master_tail"] = ma_s, ma_t
            out["ef"] = state["ef"]
        return out

    return jax.jit(
        jax.shard_map(
            device_fn, mesh=mesh, in_specs=(in_specs,), out_specs=out_specs,
            check_vma=False,
        )
    )


def zero_shard_bytes(layout: ZeroLayout, lossy: bool = False) -> dict:
    """Analytic per-rank optimizer-state bytes under ``layout`` vs the
    replicated layout — the accounting BENCH_SHARDED.json verifies
    against live buffer sizes.  Counts mu+nu (+the sharded master when
    lossy); the working params are excluded on both sides (both keep a
    full copy).  Sizes are per-device (layout sizes are local)."""
    sharded = replicated = 0
    for l in layout.leaves:
        leaf_rep = 2 * 4 * l.size  # mu + nu, f32
        replicated += leaf_rep
        if l.sharded:
            per_rank = 2 * 4 * (l.tile + l.tail)
            if lossy:
                per_rank += 4 * (l.tile + l.tail)
            sharded += per_rank
        else:
            sharded += leaf_rep
    return {
        "replicated_bytes": replicated,
        "sharded_bytes_per_rank": sharded,
        "ratio": (sharded / replicated) if replicated else 1.0,
    }
