"""Pipeline parallelism: stage-sharded layers, microbatched GPipe schedule.

The fourth parallelism axis of the framework (after dp/sp/tp): the
transformer's layer stack is split into ``pp`` contiguous stages, each
device on the ``pp`` mesh axis holds ``n_layers/pp`` layers (the per-layer
parameter pytree is *stacked* on a leading layer axis and sharded over
``pp``), and activations flow stage-to-stage with ``lax.ppermute`` — the
same ICI neighbor-exchange primitive as the ring allreduce
(``flextree_tpu.parallel.allreduce.ring_allreduce``; the reference's ring
block walk, ``allreduce_over_mpi/mpi_mod.hpp:1119-1147``, repurposed to
carry activations instead of gradient blocks).

Schedule: GPipe.  The local batch splits into ``M`` microbatches; the loop
runs ``M + pp - 1`` ticks.  Each tick every stage processes one microbatch
(or a bubble), then the activation rotates one hop right.  Stage 0 injects
embeddings; the last stage computes loss.  Bubbles compute garbage that is
never read — their cotangent is zero, so gradients are exact (the moral
analog of the reference's empty trailing blocks that are skipped rather
than special-cased, ``mpi_mod.hpp:679-696``).  The loop is a ``lax.scan``,
so the compiled program is O(1) in ``M``.

SPMD note: every stage runs the *same* program every tick (uniform compute,
one collective permute) — no data-dependent control flow crosses a
collective, which is what keeps the schedule compilable under ``jit`` with
static shapes.  The final-norm + vocab matmul and the loss are computed on
every stage and masked, rather than branched, for the same reason.

Gradient sync composes with the other axes exactly as in
``flextree_tpu.parallel.train``: stacked layer parameters are *sharded*
over ``pp`` (no sync on that axis), embeddings/final-norm are replicated
over ``pp`` and synced with the FlexTree allreduce alongside dp/sp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.transformer import (
    TransformerConfig,
    cross_entropy_loss,
    final_logits,
    global_positions,
    init_params,
    layer_forward,
    param_specs,
)
from .train import (
    TrainConfig,
    adamw_apply,
    maybe_clip_grads,
    metric_specs,
    make_mesh_nd,
    make_state_specs,
    make_train_state,
    maybe_autotune_grad_topo,
    resolve_axis_topos,
    spread_factors,
    sync_with_feedback,
    validate_tp,
    zero_layout_for,
)

__all__ = [
    "stack_layer_params",
    "unstack_layer_params",
    "pipeline_param_specs",
    "pipeline_state_specs",
    "init_pipeline_train_state",
    "make_pipeline_train_step",
    "make_mesh_4d",
    "factor_devices_4d",
]


# ------------------------------------------------------------ param layout


def stack_layer_params(params: dict) -> dict:
    """List-of-layer-dicts -> one dict of (L, ...) stacked leaves.

    The stacked leading axis is the pipeline shard axis; ``lax.scan`` over
    it applies the stage's local layers in order.
    """
    layers = params["layers"]
    stacked = {
        k: jnp.stack([layer[k] for layer in layers]) for k in layers[0]
    }
    return {"embed": params["embed"], "ln_f": params["ln_f"], "layers": stacked}


def unstack_layer_params(params: dict) -> dict:
    """Inverse of :func:`stack_layer_params` (host-side, for checkpoints)."""
    stacked = params["layers"]
    n_layers = next(iter(stacked.values())).shape[0]
    layers = [
        {k: v[i] for k, v in stacked.items()} for i in range(n_layers)
    ]
    return {"embed": params["embed"], "ln_f": params["ln_f"], "layers": layers}


def pipeline_param_specs(
    cfg: TransformerConfig, pp_axis: str | None = "pp", tp_axis: str | None = "tp"
) -> dict:
    """PartitionSpecs for the stacked layout: leading layer axis over
    ``pp_axis``, per-layer dims tp-sharded as in ``param_specs``."""
    per_layer = param_specs(cfg, tp_axis)["layers"][0]
    stacked = {k: P(pp_axis, *spec) for k, spec in per_layer.items()}
    return {"embed": P(None, None), "ln_f": P(None), "layers": stacked}


def init_pipeline_train_state(
    key, cfg: TransformerConfig, train_cfg=None, mesh=None,
    axis_names: tuple[str, str, str, str] = ("dp", "pp", "sp", "tp"),
) -> dict:
    params = stack_layer_params(init_params(key, cfg))
    layout = None
    if train_cfg is not None and train_cfg.shard_optimizer:
        if mesh is None:
            raise ValueError(
                "shard_optimizer=True: init_pipeline_train_state needs mesh="
            )
        layout = zero_layout_for(
            mesh, params,
            pipeline_param_specs(cfg, axis_names[1], axis_names[3]),
            axis_names,
        )
    return make_train_state(params, train_cfg, layout=layout)


def pipeline_state_specs(
    cfg: TransformerConfig, pp_axis: str | None = "pp", tp_axis: str | None = "tp",
    train_cfg=None, mesh=None,
    axis_names: tuple[str, str, str, str] = ("dp", "pp", "sp", "tp"),
) -> dict:
    pspecs = pipeline_param_specs(cfg, pp_axis, tp_axis)
    layout = None
    if train_cfg is not None and train_cfg.shard_optimizer:
        if mesh is None:
            raise ValueError(
                "shard_optimizer=True: pipeline_state_specs needs mesh="
            )
        shapes = jax.eval_shape(
            lambda k: stack_layer_params(init_params(k, cfg)),
            jax.random.PRNGKey(0),
        )
        layout = zero_layout_for(mesh, shapes, pspecs, axis_names)
    return make_state_specs(pspecs, train_cfg, layout=layout)


# ------------------------------------------------------------- mesh helper


def factor_devices_4d(n: int) -> tuple[int, int, int, int]:
    """Split ``n`` devices into (dp, pp, sp, tp), pp/sp/tp-first.

    Largest prime factors land on pp, then sp, then tp, then dp — the
    axes that exercise distinct machinery get covered before plain data
    parallelism (8 -> (1, 2, 2, 2), 16 -> (2, 2, 2, 2)).
    """
    return spread_factors(n, 4, order=[1, 2, 3, 0])


def make_mesh_4d(
    n_devices: int | None = None,
    shape: tuple[int, int, int, int] | None = None,
    axis_names: tuple[str, str, str, str] = ("dp", "pp", "sp", "tp"),
) -> Mesh:
    if shape is None:
        shape = factor_devices_4d(
            len(jax.devices()) if n_devices is None else n_devices
        )
    return make_mesh_nd(n_devices, shape, axis_names)


# ---------------------------------------------------------------- schedule


def _pipeline_loss_sum(
    params,
    toks,
    tgts,
    cfg: TransformerConfig,
    *,
    pp_axis: str,
    tp_axis: str | None,
    sp_axis: str | None,
):
    """Sum of token losses over all local microbatches, on the last stage.

    ``toks``/``tgts``: (M, mb, T_local) int32.  Returns a scalar that is
    the full loss sum on the last pipeline stage and 0 elsewhere (so a
    plain ``psum`` over the mesh gives the global sum exactly once).
    """
    n = lax.axis_size(pp_axis)
    idx = lax.axis_index(pp_axis)
    m_count, mb, t_local = toks.shape
    positions = global_positions(t_local, sp_axis)
    right = [(j, (j + 1) % n) for j in range(n)]

    def stage_apply(x):
        def body(h, layer):
            return (
                layer_forward(
                    layer, h, positions, cfg, tp_axis=tp_axis, sp_axis=sp_axis
                ),
                None,
            )

        x, _ = lax.scan(body, x, params["layers"])
        return x

    def final_loss(y, tgt_mb):
        logits = final_logits(params["embed"], params["ln_f"], y)
        loss_sum, _ = cross_entropy_loss(logits, tgt_mb)
        return loss_sum

    def tick(carry, t):
        state, loss_acc = carry
        tok_mb = lax.dynamic_index_in_dim(
            toks, jnp.clip(t, 0, m_count - 1), keepdims=False
        )
        inj = params["embed"][tok_mb].astype(cfg.dtype)
        x = jnp.where(idx == 0, inj, state)
        y = stage_apply(x)
        mb_i = t - (n - 1)
        tgt_mb = lax.dynamic_index_in_dim(
            tgts, jnp.clip(mb_i, 0, m_count - 1), keepdims=False
        )
        l = final_loss(y, tgt_mb)
        valid = (idx == n - 1) & (mb_i >= 0)
        loss_acc = loss_acc + jnp.where(valid, l, 0.0)
        state = lax.ppermute(y, pp_axis, right)
        return (state, loss_acc), None

    state0 = jnp.zeros((mb, t_local, cfg.d_model), cfg.dtype)
    # inherit q-style varying axes from the embed of the first microbatch so
    # the scan carry has a consistent vma type under tp/sp sharding
    state0 = state0 + 0 * params["embed"][toks[0]].astype(cfg.dtype)
    (state, loss_sum), _ = lax.scan(
        tick, (state0, jnp.zeros((), jnp.float32)), jnp.arange(m_count + n - 1)
    )
    return loss_sum


def make_pipeline_train_step(
    mesh: Mesh,
    model_cfg: TransformerConfig,
    train_cfg: TrainConfig = TrainConfig(),
    n_microbatches: int = 2,
    axis_names: tuple[str, str, str, str] = ("dp", "pp", "sp", "tp"),
    serialize_overlap: bool = False,
):
    """Jitted 4-axis train step ``(state, tokens, targets) -> (state,
    metrics)`` with GPipe pipeline parallelism over ``axis_names[1]``.

    ``state`` uses the stacked layout (``init_pipeline_train_state``);
    ``tokens``/``targets`` are (B, T) int32, batch over dp, sequence over
    sp; the per-device batch must be divisible by ``n_microbatches``.

    ``train_cfg.overlap``: the backward of the GPipe tick loop is a
    ``lax.scan`` transpose — one fused op emitting every gradient at
    once, a dataflow barrier readiness ordering cannot reach inside (that
    would take MPMD per-stage programs).  The overlap path therefore
    schedules the sync collectives into the post-backward bubble: fired
    per readiness bucket (head / layer stack / embed), each
    data-dependent only on its own leaves, overlappable with the loss
    psum, metrics and optimizer tail (``overlap.overlap_sync_with_
    feedback``; docs/OVERLAP.md states the honest limit).
    ``serialize_overlap`` builds its barrier twin.
    """
    dp, pp, sp, tp = axis_names
    for a in axis_names:
        if a not in mesh.shape:
            raise ValueError(f"mesh is missing axis {a!r}; has {mesh.axis_names}")
    pp_size = mesh.shape[pp]
    if model_cfg.n_layers % pp_size:
        raise ValueError(
            f"n_layers={model_cfg.n_layers} must be divisible by pp={pp_size}"
        )
    validate_tp(model_cfg, mesh.shape[tp])
    train_cfg = maybe_autotune_grad_topo(
        mesh, model_cfg, train_cfg, axis_names,
        init_fn=lambda k, cfg: stack_layer_params(init_params(k, cfg)),
    )

    sspecs = pipeline_state_specs(
        model_cfg, pp, tp, train_cfg, mesh=mesh, axis_names=axis_names
    )
    data_spec = P(dp, sp)
    mesh_axes = axis_names
    zero_layout = None
    if train_cfg.shard_optimizer:
        shapes = jax.eval_shape(
            lambda k: stack_layer_params(init_params(k, model_cfg)),
            jax.random.PRNGKey(0),
        )
        zero_layout = zero_layout_for(mesh, shapes, sspecs["params"], axis_names)

    def device_step(state, tokens, targets):
        b_local, t_local = tokens.shape
        if b_local % n_microbatches:
            raise ValueError(
                f"local batch {b_local} not divisible by "
                f"n_microbatches={n_microbatches}"
            )
        mb = b_local // n_microbatches
        toks = tokens.reshape(n_microbatches, mb, t_local)
        tgts = targets.reshape(n_microbatches, mb, t_local)
        # loss exists once per (dp, sp, tp) replica set (on the last pp
        # stage), so normalize by the global token count including the
        # tp-fold redundancy — same rule as train.make_train_step
        n_total_tokens = (
            tokens.size
            * lax.axis_size(dp)
            * lax.axis_size(sp)
            * lax.axis_size(tp)
        )

        def local_loss(params):
            loss_sum = _pipeline_loss_sum(
                params, toks, tgts, model_cfg,
                pp_axis=pp, tp_axis=tp, sp_axis=sp,
            )
            return loss_sum / n_total_tokens

        loss, grads = jax.value_and_grad(local_loss)(state["params"])

        topos = resolve_axis_topos(mesh, mesh_axes, train_cfg.grad_topo)
        if train_cfg.shard_optimizer:
            # ZeRO path: the scan transpose already emits every gradient
            # at once (the GPipe dataflow barrier — docs/OVERLAP.md), and
            # the sharded sync fires per bucket with each bucket
            # data-dependent only on its own leaves, so the post-backward
            # bubble scheduling the overlap path buys is structural here;
            # the overlap/serialize flags are no-ops for the sharded
            # pipeline step.
            from .zero import zero_sync_and_update

            global_loss = loss
            for ax in mesh_axes:
                global_loss = lax.psum(global_loss, ax)
            metrics = {"loss": global_loss}
            new_state = zero_sync_and_update(
                state, grads, sspecs["params"], mesh_axes, topos, train_cfg,
                zero_layout, metrics,
            )
            return new_state, metrics

        if train_cfg.overlap:
            from .overlap import overlap_sync_with_feedback

            grads, new_ef = overlap_sync_with_feedback(
                state, grads, sspecs["params"], mesh_axes, topos, train_cfg,
                serialize=serialize_overlap,
            )
        else:
            grads, new_ef = sync_with_feedback(
                state, grads, sspecs["params"], mesh_axes, topos, train_cfg
            )
        global_loss = loss
        for ax in mesh_axes:
            global_loss = lax.psum(global_loss, ax)

        metrics = {"loss": global_loss}
        grads = maybe_clip_grads(grads, sspecs["params"], train_cfg, metrics)
        new_state = adamw_apply(state, grads, train_cfg)
        if new_ef is not None:
            new_state["ef"] = new_ef
        return new_state, metrics

    mspec = metric_specs(train_cfg, {"loss": P()})
    sharded = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(sspecs, data_spec, data_spec),
        out_specs=(sspecs, mspec),
        check_vma=False,
    )
    return jax.jit(sharded)
