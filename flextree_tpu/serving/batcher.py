"""Continuous batcher: request queue, admission by token budget, slots.

The batcher is a pure host-side state machine — no JAX in this module —
so every transition is unit-testable without a device.  State:

- a FIFO **queue** of submitted :class:`Request`\\ s (head-of-line order
  is preserved; a request that does not fit blocks the ones behind it —
  no starvation of big requests by a stream of small ones);
- ``slots`` decode **slots**, each empty or holding a :class:`SeqState`.
  The slot count is the compiled decode batch size S: the jitted paged
  decode always runs S rows, empty slots ride along as masked no-ops
  (their pool writes land in the null block).

**Admission math** (``try_admit``): a request needs ``ceil((prompt_len +
max_new_tokens) / block_size)`` cache blocks.  The batcher reserves ALL
of them at admission — conservative (a request that stops early returns
blocks it never wrote), but it makes mid-decode exhaustion structurally
impossible: an admitted request always runs to retirement, so the engine
never needs preemption/swap-out machinery.  A per-step **prefill token
budget** caps how much prefill work joins one step, bounding the decode
stall that admission imposes on already-running sequences
(join-at-step: new requests prefill into free slots while running
sequences keep decoding on the next step).

**Retirement** (``retire_ready``): a sequence is done when it has emitted
``max_new_tokens`` tokens or a token in its ``stop_tokens``.  Retirement
frees the slot and returns every reserved block to the allocator
immediately — freed blocks admit queued requests on the very next step.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from .kv_cache import BlockAllocator, CacheExhausted, PagedCacheConfig, NULL_BLOCK

__all__ = ["Request", "SeqState", "BatcherConfig", "ContinuousBatcher"]


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.  ``prompt`` is a 1-D int32 array;
    ``stop_tokens`` retire the sequence early; sampling knobs mirror
    ``models.generate`` (greedy by default, ``seed`` threads a
    deterministic key when ``temperature > 0``)."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    stop_tokens: tuple = ()
    temperature: float = 0.0
    top_k: int | None = None
    seed: int | None = None
    arrival_s: float = 0.0

    @property
    def prompt_len(self) -> int:
        return int(np.asarray(self.prompt).shape[0])


@dataclasses.dataclass
class SeqState:
    """A resident sequence: its reservation, progress, and timestamps."""

    request: Request
    block_ids: list
    length: int  # cache positions filled (prompt + written decode tokens)
    pending_token: int  # last emitted token, not yet written to the cache
    generated: list  # emitted tokens, stop token included
    done: bool = False
    admitted_s: float = 0.0
    first_token_s: float = 0.0
    done_s: float = 0.0

    @property
    def rid(self) -> int:
        return self.request.rid


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """``slots``: the compiled decode batch size S.
    ``max_prefill_tokens_per_step``: join-at-step budget — total prompt
    tokens admitted per engine step (at least one request is always
    admitted when a slot and blocks are free, so a long prompt cannot
    deadlock itself)."""

    slots: int = 4
    max_prefill_tokens_per_step: int = 256


class ContinuousBatcher:
    def __init__(self, pcfg: PagedCacheConfig, bcfg: BatcherConfig):
        self.pcfg = pcfg
        self.bcfg = bcfg
        self.allocator = BlockAllocator(pcfg.num_blocks)
        self.slots: list = [None] * bcfg.slots
        self.queue: deque = deque()
        self.rejected: list = []  # (rid, reason) for oversized requests

    # ---- intake ------------------------------------------------------------

    def submit(self, request: Request) -> bool:
        """Queue a request; oversized ones (they could NEVER be admitted)
        are rejected now, loudly, instead of clogging the queue head."""
        total = request.prompt_len + request.max_new_tokens
        if request.prompt_len < 1:
            self.rejected.append((request.rid, "empty prompt"))
            return False
        if total > self.pcfg.max_len:
            self.rejected.append(
                (request.rid,
                 f"prompt+max_new {total} exceeds max_len {self.pcfg.max_len}")
            )
            return False
        if request.temperature > 0 and request.seed is None:
            # reject BEFORE admission: discovered mid-prefill this would
            # wedge the slot (blocks reserved, no sampler key)
            self.rejected.append(
                (request.rid, "temperature > 0 requires seed=")
            )
            return False
        self.queue.append(request)
        return True

    # ---- admission ---------------------------------------------------------

    def blocks_needed(self, request: Request) -> int:
        return self.pcfg.blocks_for(
            request.prompt_len + request.max_new_tokens
        )

    def try_admit(self, now_s: float = 0.0) -> list:
        """Admit queued requests into free slots under the block and
        prefill-token budgets.  Returns ``[(slot_idx, SeqState), ...]``
        for the engine to prefill; the states are already resident (the
        reservation happened here — all-or-nothing per request)."""
        admitted = []
        budget = self.bcfg.max_prefill_tokens_per_step
        while self.queue:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req = self.queue[0]
            if admitted and req.prompt_len > budget:
                break  # join-at-step budget spent; next step picks it up
            try:
                blocks = self.allocator.alloc(self.blocks_needed(req))
            except CacheExhausted:
                break  # FIFO head-of-line: wait for retirements
            self.queue.popleft()
            budget -= req.prompt_len
            state = SeqState(
                request=req,
                block_ids=blocks,
                length=req.prompt_len,
                pending_token=-1,
                generated=[],
                admitted_s=now_s,
            )
            slot = free_slots[0]
            self.slots[slot] = state
            admitted.append((slot, state))
        return admitted

    # ---- the decode-step view ---------------------------------------------

    def active_slots(self) -> list:
        """Slots holding a live, not-yet-done sequence that has a pending
        token to write (i.e. participates in the next decode step)."""
        return [
            i for i, s in enumerate(self.slots)
            if s is not None and not s.done
        ]

    def batch_arrays(self):
        """(tables (S, P), lengths (S,), tokens (S,), active (S,)) int32 /
        bool numpy views of the current slots — inactive rows are
        all-NULL_BLOCK tables at length 0 with token 0 (masked no-ops)."""
        S, P = self.bcfg.slots, self.pcfg.blocks_per_seq
        tables = np.full((S, P), NULL_BLOCK, np.int32)
        lengths = np.zeros((S,), np.int32)
        tokens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for i, s in enumerate(self.slots):
            if s is None or s.done:
                continue
            tables[i, : len(s.block_ids)] = s.block_ids
            lengths[i] = s.length
            tokens[i] = s.pending_token
            active[i] = True
        return tables, lengths, tokens, active

    def record_first_token(self, slot: int, token: int, now_s: float) -> None:
        s = self.slots[slot]
        s.pending_token = int(token)
        s.generated.append(int(token))
        s.first_token_s = now_s
        self._maybe_finish(s, now_s)

    def record_decode_token(self, slot: int, token: int, now_s: float) -> None:
        """The decode step wrote ``pending_token``'s K/V at ``length`` and
        produced ``token`` for the next position."""
        s = self.slots[slot]
        s.length += 1
        s.pending_token = int(token)
        s.generated.append(int(token))
        self._maybe_finish(s, now_s)

    def _maybe_finish(self, s: SeqState, now_s: float) -> None:
        hit_stop = s.generated[-1] in s.request.stop_tokens
        if hit_stop or len(s.generated) >= s.request.max_new_tokens:
            s.done = True
            s.done_s = now_s

    # ---- retirement --------------------------------------------------------

    def retire_ready(self) -> list:
        """Free every done slot's blocks; returns ``[(slot_idx, SeqState)]``
        for the finished sequences."""
        finished = []
        for i, s in enumerate(self.slots):
            if s is not None and s.done:
                self.allocator.free(s.block_ids)
                self.slots[i] = None
                finished.append((i, s))
        return finished

    # ---- introspection -----------------------------------------------------

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def idle(self) -> bool:
        return not self.queue and all(s is None for s in self.slots)

    def inflight_requests(self) -> list:
        """Every submitted-but-unfinished request — queued or resident.
        The replica pool drains this to re-route off a dead replica."""
        out = [s.request for s in self.slots if s is not None]
        out.extend(self.queue)
        return out
