"""Continuous batcher: request queue, admission by token budget, slots.

The batcher is a pure host-side state machine — no JAX in this module —
so every transition is unit-testable without a device.  State:

- a FIFO **queue** of submitted :class:`Request`\\ s (head-of-line order
  is preserved; a request that does not fit blocks the ones behind it —
  no starvation of big requests by a stream of small ones);
- ``slots`` decode **slots**, each empty or holding a :class:`SeqState`.
  The slot count is the compiled decode batch size S: the jitted paged
  decode always runs S rows, empty slots ride along as masked no-ops
  (their pool writes land in the null block).

**Admission math** (``try_admit``) comes in two modes
(``BatcherConfig.admission``):

- ``"reserve"`` (the conservative default): a request reserves ALL
  ``ceil((prompt_len + max_new_tokens) / block_size)`` blocks at
  admission — wasteful (a request that stops early returns blocks it
  never wrote), but mid-decode exhaustion is structurally impossible and
  an admitted request always runs to retirement.
- ``"ondemand"`` (the vLLM-style allocator): admission reserves only the
  PROMPT's blocks; decode blocks are allocated one at a time as each
  sequence's length crosses a block boundary (``grow_for_decode``).  The
  same pool now keeps more sequences resident — and pool exhaustion
  mid-decode becomes structurally possible, which is what the
  **preemption** machinery below exists for.

**Preemption** (on-demand mode only): when ``grow_for_decode`` cannot
allocate, the engine picks the NEWEST resident sequence
(``pick_victim`` — newest-first minimizes wasted work and cannot starve
the oldest), swaps its written K/V out to host memory (or drops it for
prefill-replay recompute), and ``preempt`` frees its blocks and parks it
on the ``preempted`` queue.  Preempted sequences resume with strict
priority over fresh admissions (``try_resume`` runs first and
``try_admit`` refuses to admit past a non-empty preempted queue — fresh
short requests must never starve a half-done long one), and a resumed
sequence continues bit-identically from its saved state.  Preempted
sequences stay in ``inflight_requests()`` so the replica pool's drain
re-queues them through the same exactly-once machinery as resident ones.

A per-step **prefill token budget** caps how much prefill work joins one
step, bounding the decode stall that admission imposes on already-running
sequences (join-at-step: new requests prefill into free slots while
running sequences keep decoding on the next step).

**Retirement** (``retire_ready``): a sequence is done when it has emitted
``max_new_tokens`` tokens or a token in its ``stop_tokens``.  Retirement
frees the slot and returns every held block to the allocator
immediately — freed blocks resume preempted sequences or admit queued
requests on the very next step.

**Prefix cache** (``BatcherConfig.prefix_cache``, off by default): a
:class:`~flextree_tpu.serving.prefix_index.PrefixIndex` shares full
prompt blocks across requests.  Admission matches the longest cached
block-aligned prefix, RETAINS those blocks instead of allocating, and
the engine prefills only the suffix; retirement inserts the sequence's
full prompt blocks into the index and RELEASES everything it held;
pool pressure evicts idle index entries before blocking admission.
Still a pure host-side state machine: the index stores token ids and
block ids, never tensors.
"""

from __future__ import annotations

import dataclasses
from collections import deque

import numpy as np

from .kv_cache import BlockAllocator, CacheExhausted, PagedCacheConfig, NULL_BLOCK
from .prefix_index import PrefixIndex

__all__ = [
    "Request",
    "SeqState",
    "PreemptedSeq",
    "BatcherConfig",
    "ContinuousBatcher",
]


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request.  ``prompt`` is a 1-D int32 array;
    ``stop_tokens`` retire the sequence early; sampling knobs mirror
    ``models.generate`` (greedy by default, ``seed`` threads a
    deterministic key when ``temperature > 0``)."""

    rid: int
    prompt: np.ndarray
    max_new_tokens: int
    stop_tokens: tuple = ()
    temperature: float = 0.0
    top_k: int | None = None
    seed: int | None = None
    arrival_s: float = 0.0

    @property
    def prompt_len(self) -> int:
        return int(np.asarray(self.prompt).shape[0])


@dataclasses.dataclass
class SeqState:
    """A resident sequence: its reservation, progress, and timestamps."""

    request: Request
    block_ids: list
    length: int  # cache positions filled (prompt + written decode tokens)
    pending_token: int  # last emitted token, not yet written to the cache
    generated: list  # emitted tokens, stop token included
    done: bool = False
    admitted_s: float = 0.0
    first_token_s: float = 0.0
    done_s: float = 0.0
    admit_seq: int = 0  # monotonic admission stamp: victim = largest
    preempts: int = 0  # times this sequence was preempted
    # prefix-cache admission state: how many leading cache positions came
    # from the index (the engine prefills only the rest), how many leading
    # block_ids are SHARED (retained, never written by this sequence), and
    # the shared source of a copy-on-write fork — the engine gathers the
    # mid-block prefix from it, then releases it
    cached_tokens: int = 0
    shared_blocks: int = 0
    cow_src: int | None = None
    # one ``_now`` stamp per emitted token (first token included): the
    # inter-token latency distribution is ``diff(token_times)`` — the
    # decode-SLO quantity the disagg bench holds p99 floors against,
    # which the mean ``per_token_s`` on CompletedRequest cannot carry
    token_times: list = dataclasses.field(default_factory=list)

    @property
    def rid(self) -> int:
        return self.request.rid


@dataclasses.dataclass
class PreemptedSeq:
    """A sequence evicted mid-decode: its full progress plus the swapped
    K/V (host-side per-layer arrays for ``length`` positions), or ``kv =
    None`` when the engine chose prefill-replay recompute."""

    state: SeqState
    kv: object = None


@dataclasses.dataclass(frozen=True)
class BatcherConfig:
    """``slots``: the compiled decode batch size S.
    ``max_prefill_tokens_per_step``: join-at-step budget — total prompt
    tokens admitted per engine step (at least one request is always
    admitted when a slot and blocks are free, so a long prompt cannot
    deadlock itself).
    ``admission``: ``"reserve"`` (whole budget up front, no preemption
    possible — the conservative default) or ``"ondemand"`` (prompt
    blocks only; decode grows per block boundary, exhaustion preempts).
    ``preempt``: what the engine saves when it evicts — ``"swap"`` (K/V
    bytes to host memory; resume is a scatter, bit-identical by
    construction) or ``"recompute"`` (drop the K/V, replay
    prompt+generated through prefill on resume — cheaper for short
    contexts, pays forward FLOPs and a per-length compile).
    ``prefix_cache``: enable the cross-request prefix index — admission
    shares cached full-block prefixes and prefills only the suffix;
    retirement releases blocks into the index instead of freeing them
    (off by default: a warm index intentionally keeps retired prompt
    blocks out of the free list, which changes the pool-accounting
    invariants callers may assert)."""

    slots: int = 4
    max_prefill_tokens_per_step: int = 256
    admission: str = "reserve"
    preempt: str = "swap"
    prefix_cache: bool = False

    def __post_init__(self):
        if self.admission not in ("reserve", "ondemand"):
            raise ValueError(f"unknown admission mode {self.admission!r}")
        if self.preempt not in ("swap", "recompute"):
            raise ValueError(f"unknown preempt mode {self.preempt!r}")


class ContinuousBatcher:
    def __init__(self, pcfg: PagedCacheConfig, bcfg: BatcherConfig):
        self.pcfg = pcfg
        self.bcfg = bcfg
        self.allocator = BlockAllocator(pcfg.num_blocks)
        self.prefix_index = (
            PrefixIndex(pcfg.block_size, self.allocator)
            if bcfg.prefix_cache else None
        )
        self.slots: list = [None] * bcfg.slots
        self.queue: deque = deque()
        self.preempted: deque = deque()  # PreemptedSeq, resume-first FIFO
        self.rejected: list = []  # (rid, reason) for oversized requests
        self.admit_blocked: tuple | None = None  # (rid, want, free) last round
        self._admit_seq = 0  # monotonic stamp for newest-first victimhood

    @property
    def ondemand(self) -> bool:
        return self.bcfg.admission == "ondemand"

    # ---- intake ------------------------------------------------------------

    def submit(self, request: Request) -> bool:
        """Queue a request; oversized ones (they could NEVER be admitted)
        are rejected now, loudly, instead of clogging the queue head."""
        total = request.prompt_len + request.max_new_tokens
        if request.prompt_len < 1:
            self.rejected.append((request.rid, "empty prompt"))
            return False
        if total > self.pcfg.max_len:
            self.rejected.append(
                (request.rid,
                 f"prompt+max_new {total} exceeds max_len {self.pcfg.max_len}")
            )
            return False
        if self.pcfg.blocks_for(total) > self.pcfg.num_blocks - 1:
            # the pool can NEVER hold it: under reservation it would wedge
            # the queue head forever, under on-demand it would livelock the
            # preemption loop (nothing else to evict frees enough)
            self.rejected.append(
                (request.rid,
                 f"needs {self.pcfg.blocks_for(total)} blocks, pool holds "
                 f"{self.pcfg.num_blocks - 1}")
            )
            return False
        if request.temperature > 0 and request.seed is None:
            # reject BEFORE admission: discovered mid-prefill this would
            # wedge the slot (blocks reserved, no sampler key)
            self.rejected.append(
                (request.rid, "temperature > 0 requires seed=")
            )
            return False
        self.queue.append(request)
        return True

    # ---- admission ---------------------------------------------------------

    def blocks_needed(self, request: Request) -> int:
        """Blocks the request needs AT ADMISSION: the whole prompt+output
        budget under reservation, the prompt only under on-demand."""
        if self.ondemand:
            return self.pcfg.blocks_for(request.prompt_len)
        return self.pcfg.blocks_for(
            request.prompt_len + request.max_new_tokens
        )

    def _next_admit_seq(self) -> int:
        self._admit_seq += 1
        return self._admit_seq

    def _alloc_with_evict(self, n: int) -> list:
        """Allocate ``n`` blocks, evicting idle prefix-index entries
        under pool pressure first (LRU, index-only holders) — live
        sequences always outrank cold cache."""
        try:
            return self.allocator.alloc(n)
        except CacheExhausted:
            if self.prefix_index is None:
                raise
            self.prefix_index.evict(n - self.allocator.num_free)
            return self.allocator.alloc(n)

    def _match_prefix(self, req: Request):
        """Look up the longest cached block-aligned prefix for ``req``.

        Returns ``(shared, cow_src, cached_tokens)``: the leading block
        ids to share outright (retained here), the shared block to
        copy-on-write fork when the cached chain reaches past them (its
        tail positions must be re-derived into a private copy — never
        written in place in the shared original), and how many leading
        cache positions the engine's prefill may skip.

        A hit always leaves at least TWO suffix tokens: the last prompt
        token must run through the model for its logits regardless, and
        a one-token suffix would put the attention matmuls in the
        ``Tq=1`` shape class, which XLA lowers with a different
        accumulation order than the multi-row prefill — breaking the
        bitwise identity the whole cache rests on.  So shared blocks are
        capped at ``(prompt_len - 2) // block_size`` and a full-chain
        hit re-derives the final two positions (the second-to-last one
        landing mid-block in the COW fork)."""
        if self.prefix_index is None:
            return [], None, 0
        matched = self.prefix_index.match(np.asarray(req.prompt))
        if not matched:
            return [], None, 0
        bs = self.pcfg.block_size
        n_shared = min(len(matched), (req.prompt_len - 2) // bs)
        shared = matched[:n_shared]
        cow_src = matched[n_shared] if len(matched) > n_shared else None
        cached = (
            req.prompt_len - 2 if cow_src is not None else n_shared * bs
        )
        if cached <= 0:
            return [], None, 0
        self.allocator.retain(shared)
        if cow_src is not None:
            # hold the fork source until the engine has gathered its
            # bytes — an eviction between admission and prefill would
            # otherwise hand the suffix prefill a recycled block
            self.allocator.retain([cow_src])
        return shared, cow_src, cached

    def try_admit(self, now_s: float = 0.0) -> list:
        """Admit queued requests into free slots under the block and
        prefill-token budgets.  Returns ``[(slot_idx, SeqState), ...]``
        for the engine to prefill; the states are already resident (the
        allocation happened here — all-or-nothing per request).  Sets
        ``admit_blocked`` when the queue head is blocked on BLOCKS (not
        slots) — the engine's ``serve_admit_blocked`` signal.

        With the prefix cache on, the queue head's longest cached
        block-aligned prefix is shared (retained) instead of allocated,
        only the SUFFIX blocks are taken from the free list, and the
        prefill-token budget is charged for the suffix alone — a cache
        hit is cheap to admit in exactly the proportion it is cheap to
        prefill."""
        if self.preempted:
            # resume-first, strictly: fresh admissions must not take the
            # blocks a half-done preempted sequence is waiting for (and
            # admit_blocked keeps whatever try_resume just recorded —
            # clearing it here would wipe the resume-blocked signal)
            return []
        self.admit_blocked = None
        admitted = []
        budget = self.bcfg.max_prefill_tokens_per_step
        while self.queue:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            req = self.queue[0]
            shared, cow_src, cached = self._match_prefix(req)
            suffix_tokens = req.prompt_len - cached
            retained = shared + ([cow_src] if cow_src is not None else [])
            if admitted and suffix_tokens > budget:
                # join-at-step budget spent; next step picks it up
                self.allocator.release(retained)
                break
            try:
                blocks = self._alloc_with_evict(
                    self.blocks_needed(req) - len(shared)
                )
            except CacheExhausted as e:
                # FIFO head-of-line: wait for retirements
                self.allocator.release(retained)
                self.admit_blocked = (req.rid, e.want, e.free)
                break
            self.queue.popleft()
            budget -= suffix_tokens
            state = SeqState(
                request=req,
                block_ids=shared + blocks,
                length=req.prompt_len,
                pending_token=-1,
                generated=[],
                admitted_s=now_s,
                admit_seq=self._next_admit_seq(),
                cached_tokens=cached,
                shared_blocks=len(shared),
                cow_src=cow_src,
            )
            slot = free_slots[0]
            self.slots[slot] = state
            admitted.append((slot, state))
        return admitted

    def admit_migrated(self, request: Request, first_token: int,
                       now_s: float = 0.0):
        """Admit a sequence whose PREFILL ran on another replica: its KV
        blocks arrive over the wire, its first token is already emitted.

        Mirrors ``try_admit``'s discipline — resume-first (a migrated
        arrival must not take the blocks a half-done preempted sequence
        is waiting for), all-or-nothing allocation, ``admit_blocked`` set
        on block pressure — but skips the queue: migration is an
        admit-or-refuse handshake, so a sequence that cannot land NOW is
        refused back to the prefill side rather than parked.  Returns
        ``(slot_idx, SeqState)`` with the state resident (length =
        prompt_len, first token recorded) and the block ids ready for
        the engine's import scatter, or ``None`` to refuse.  The caller
        still owes the decode-token recording from the next step on."""
        if self.preempted:
            return None
        free_slots = [i for i, s in enumerate(self.slots) if s is None]
        if not free_slots:
            return None
        try:
            blocks = self._alloc_with_evict(self.blocks_needed(request))
        except CacheExhausted as e:
            self.admit_blocked = (request.rid, e.want, e.free)
            return None
        state = SeqState(
            request=request,
            block_ids=blocks,
            length=request.prompt_len,
            pending_token=int(first_token),
            generated=[int(first_token)],
            admitted_s=now_s,
            first_token_s=now_s,
            admit_seq=self._next_admit_seq(),
        )
        state.token_times.append(now_s)
        self._maybe_finish(state, now_s)
        slot = free_slots[0]
        self.slots[slot] = state
        return slot, state

    # ---- on-demand growth / preemption / resume ----------------------------

    def blocks_for_resume(self, state: SeqState) -> int:
        """Blocks a resumed sequence needs right now: its ``length``
        written positions plus the current block its next decode write
        lands in (``length // bs + 1`` covers both, mid-block or not)."""
        return state.length // self.pcfg.block_size + 1

    def try_resume(self, now_s: float = 0.0) -> list:
        """Re-admit preempted sequences (FIFO, strict priority) into free
        slots.  Returns ``[(slot_idx, SeqState, kv), ...]`` for the engine
        to scatter (``kv`` is the swapped host K/V, or None for
        prefill-replay recompute).  All-or-nothing per sequence.  The
        resumed state KEEPS its original admission stamp: re-stamping
        would make it the newest resident and therefore the very next
        victim — a full swap-in immediately paid back out as a swap-out
        with zero tokens decoded."""
        self.admit_blocked = None
        resumed = []
        while self.preempted:
            free_slots = [i for i, s in enumerate(self.slots) if s is None]
            if not free_slots:
                break
            pre = self.preempted[0]
            try:
                blocks = self._alloc_with_evict(
                    self.blocks_for_resume(pre.state)
                )
            except CacheExhausted as e:
                self.admit_blocked = (pre.state.rid, e.want, e.free)
                break
            self.preempted.popleft()
            pre.state.block_ids = blocks
            slot = free_slots[0]
            self.slots[slot] = pre.state
            resumed.append((slot, pre.state, pre.kv))
        return resumed

    def grow_for_decode(self) -> list:
        """On-demand only: allocate the block each active sequence's next
        decode write needs, OLDEST first (so exhaustion lands on the
        newest, which is also the preemption victim).  Returns the slots
        that grew; raises :class:`CacheExhausted` when a needed block
        cannot be allocated — the engine's preemption trigger."""
        if not self.ondemand:
            return []
        grown = []
        order = sorted(
            self.active_slots(), key=lambda i: self.slots[i].admit_seq
        )
        for i in order:
            s = self.slots[i]
            need = s.length // self.pcfg.block_size + 1
            while len(s.block_ids) < need:
                s.block_ids.extend(self._alloc_with_evict(1))
                if i not in grown:
                    grown.append(i)
        return grown

    def pick_victim(self) -> int | None:
        """The preemption victim: the most recently ADMITTED resident
        (largest admission stamp; a resumed sequence keeps its original
        stamp, so it is never the immediate next victim of the swap-in
        it just paid for).  None when fewer than two sequences are
        resident — preempting the only one could never unblock anything."""
        active = self.active_slots()
        if len(active) < 2:
            return None
        return max(active, key=lambda i: self.slots[i].admit_seq)

    def preempt(self, slot: int, kv=None) -> SeqState:
        """Evict ``slot``: release every held block (shared prefix blocks
        just drop this holder — the index and any co-sharing sequence
        keep them alive), park the sequence (and the engine-saved ``kv``,
        if swapping) on the resume queue.  A resumed sequence gets
        all-private blocks, so its sharing bookkeeping resets here."""
        s = self.slots[slot]
        if s is None:
            raise ValueError(f"slot {slot} holds no sequence")
        self.allocator.release(s.block_ids)
        if s.cow_src is not None:  # unconsumed fork source (engine never
            self.allocator.release([s.cow_src])  # prefilled) — drop it
            s.cow_src = None
        s.block_ids = []
        s.shared_blocks = 0
        s.preempts += 1
        self.slots[slot] = None
        self.preempted.append(PreemptedSeq(state=s, kv=kv))
        return s

    # ---- the decode-step view ---------------------------------------------

    def active_slots(self) -> list:
        """Slots holding a live, not-yet-done sequence that has a pending
        token to write (i.e. participates in the next decode step)."""
        return [
            i for i, s in enumerate(self.slots)
            if s is not None and not s.done
        ]

    def batch_arrays(self):
        """(tables (S, P), lengths (S,), tokens (S,), active (S,)) int32 /
        bool numpy views of the current slots — inactive rows are
        all-NULL_BLOCK tables at length 0 with token 0 (masked no-ops)."""
        S, P = self.bcfg.slots, self.pcfg.blocks_per_seq
        tables = np.full((S, P), NULL_BLOCK, np.int32)
        lengths = np.zeros((S,), np.int32)
        tokens = np.zeros((S,), np.int32)
        active = np.zeros((S,), bool)
        for i, s in enumerate(self.slots):
            if s is None or s.done:
                continue
            tables[i, : len(s.block_ids)] = s.block_ids
            lengths[i] = s.length
            tokens[i] = s.pending_token
            active[i] = True
        return tables, lengths, tokens, active

    def record_first_token(self, slot: int, token: int, now_s: float) -> None:
        s = self.slots[slot]
        s.pending_token = int(token)
        s.generated.append(int(token))
        s.first_token_s = now_s
        s.token_times.append(now_s)
        self._maybe_finish(s, now_s)

    def record_decode_token(self, slot: int, token: int, now_s: float) -> None:
        """The decode step wrote ``pending_token``'s K/V at ``length`` and
        produced ``token`` for the next position."""
        s = self.slots[slot]
        s.length += 1
        s.pending_token = int(token)
        s.generated.append(int(token))
        s.token_times.append(now_s)
        self._maybe_finish(s, now_s)

    def _maybe_finish(self, s: SeqState, now_s: float) -> None:
        hit_stop = s.generated[-1] in s.request.stop_tokens
        if hit_stop or len(s.generated) >= s.request.max_new_tokens:
            s.done = True
            s.done_s = now_s

    # ---- retirement --------------------------------------------------------

    def retire_ready(self) -> list:
        """Release every done slot's blocks; returns ``[(slot_idx,
        SeqState)]`` for the finished sequences.

        With the prefix cache on, the sequence's FULL prompt blocks
        (``prompt_len // block_size`` of them — never the tail block
        decode wrote into) are first inserted into the index, which
        retains the ones it adopts; the release that follows then only
        returns the un-adopted remainder to the free list.  A sequence
        that was itself a cache hit walks the same trie path it was
        admitted from, so its shared blocks are found already indexed
        and adopted zero times."""
        finished = []
        for i, s in enumerate(self.slots):
            if s is not None and s.done:
                if self.prefix_index is not None:
                    full = s.request.prompt_len // self.pcfg.block_size
                    self.prefix_index.insert(
                        np.asarray(s.request.prompt), s.block_ids[:full]
                    )
                if s.cow_src is not None:  # defensive: engine clears this
                    self.allocator.release([s.cow_src])
                    s.cow_src = None
                self.allocator.release(s.block_ids)
                self.slots[i] = None
                finished.append((i, s))
        return finished

    # ---- introspection -----------------------------------------------------

    @property
    def num_active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def idle(self) -> bool:
        return (
            not self.queue
            and not self.preempted
            and all(s is None for s in self.slots)
        )

    def inflight_requests(self) -> list:
        """Every submitted-but-unfinished request — queued, resident, or
        preempted.  The replica pool drains this to re-route off a dead
        replica; a preempted sequence missing here would be the silently
        lost request the exactly-once contract forbids."""
        out = [s.request for s in self.slots if s is not None]
        out.extend(p.state.request for p in self.preempted)
        out.extend(self.queue)
        return out
