"""Paged/blocked KV cache: ragged sequences share one static-shaped pool.

The contiguous cache in ``models.generate`` allocates ``max_len`` slots
per sequence up front; a serving batch of ragged lengths wastes most of
that and, worse, couples every sequence's lifetime to the batch's.  The
paged layout breaks the coupling the way vLLM's PagedAttention does:

- the pool is per layer ``(num_blocks, block_size, H, Dh)`` — one static
  shape for the whole server lifetime, so the decode step stays ONE
  compiled program regardless of which sequences are resident;
- each sequence owns a **block table** (a row of block ids): block
  ``p`` of the table holds cache positions ``p*block_size ..``; tables
  are plain int32 inputs to the jitted step, so the host can remap them
  between steps without recompiling;
- a host-side :class:`BlockAllocator` (LIFO free list) hands blocks out
  at admission and takes them back at retirement — freeing is O(blocks),
  immediate, and per sequence.

Block id 0 is the **null block**: never allocated, it pads every table
row past the sequence's reserved blocks.  Gathered null-block content is
always beyond the causal bound, where ``cached_attention``'s mask drives
the softmax weight to exactly 0.0 in f32 — so whatever the null block
holds contributes exactly nothing, and the paged decode stays **bitwise
identical** to the contiguous-cache decode (the property
``tools/bench_serving.py`` machine-checks).

The decode step has two attention paths behind a ``fused=`` switch:

- **gather** (``fused=False``) — gather the table's blocks into a
  per-row contiguous (S, P*block_size, H, Dh) view, run exactly the
  ``models.generate`` math (shared helpers, not copies — the bitwise
  contract depends on one definition), and scatter the newly produced
  K/V back into each row's current block.  This is the correctness
  ORACLE: it is the path proven bitwise against ``generate``.
- **fused** (``fused=True``) — ``ops.paged_attention`` walks the block
  table with an online-softmax accumulator, reading K/V straight from
  the pools and never materializing the (S, P*bs, H, Dh) view (the ~5 MB
  of per-round copies the gather path pays at the bench config), and
  stops at the batch's causal frontier instead of the full table width.
  Identical masking, different floating-point summation order: gated
  against the gather oracle within ``ops.paged_attention.
  FUSED_DECODE_ATOL`` (tests + every ``tools/bench_paged.py`` rep), not
  bitwise.

Either way all phases live in one jitted function with the pool buffers
donated, so steady-state decode is two compiled programs total (prefill
+ paged decode), same as the contiguous path.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from ..models.generate import _qkv
from ..ops.paged_attention import paged_attention, paged_attention_gather
from ..models.transformer import (
    TransformerConfig,
    apply_rope,
    final_logits,
    mlp_block,
    rms_norm,
)

__all__ = [
    "NULL_BLOCK",
    "CacheExhausted",
    "PagedCacheConfig",
    "BlockAllocator",
    "init_pools",
    "write_prefill",
    "write_prefill_at",
    "write_swapped",
    "paged_decode_step",
    "make_paged_decode_fn",
    "gather_seq",
    "export_blocks",
    "write_imported",
]

#: Block id 0 is reserved: it pads table rows and is never allocated.
NULL_BLOCK = 0


class CacheExhausted(RuntimeError):
    """The allocator cannot satisfy a reservation — the admission layer's
    signal to keep the request queued.  ``code`` is the stable taxonomy
    tag, same pattern as ``FT_INIT_TIMEOUT`` / ``FT_STEP_TIMEOUT``."""

    code = "FT_CACHE_EXHAUSTED"

    def __init__(self, want: int, free: int):
        self.want, self.free = want, free
        super().__init__(
            f"{self.code}: need {want} cache blocks, {free} free"
        )


@dataclasses.dataclass(frozen=True)
class PagedCacheConfig:
    """Shape of the paged pool.  ``num_blocks`` counts the null block, so
    ``num_blocks - 1`` are allocatable; ``blocks_per_seq`` is the block
    table width P — the longest admissible sequence is ``max_len =
    block_size * blocks_per_seq`` tokens (prompt + generated)."""

    num_blocks: int
    block_size: int = 16
    blocks_per_seq: int = 8

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is reserved)")
        if self.block_size < 1 or self.blocks_per_seq < 1:
            raise ValueError("block_size and blocks_per_seq must be >= 1")

    @property
    def max_len(self) -> int:
        return self.block_size * self.blocks_per_seq

    def blocks_for(self, tokens: int) -> int:
        """Blocks needed to hold ``tokens`` cache positions."""
        return -(-tokens // self.block_size)


class BlockAllocator:
    """Host-side LIFO free list over block ids ``1..num_blocks-1``, with
    per-block reference counts for cross-request prefix sharing.

    LIFO keeps the working set of pool pages hot; double frees and
    foreign ids are loud errors (a silently double-freed block would be
    handed to two sequences and corrupt both).

    Refcount semantics: ``alloc`` hands blocks out at refcount 1;
    ``retain`` adds a holder (a second sequence sharing a cached prefix
    block, or the prefix index adopting a retired prompt block);
    ``release`` drops one holder and the free list regains the block only
    when the count reaches 0.  ``free`` keeps its historical meaning —
    "this block is exclusively mine and I am done" — and is LOUD when the
    block is shared (freeing a shared block out from under its other
    holders is exactly the corruption refcounts exist to prevent).
    ``fork_block`` is the copy-on-write primitive: given a SHARED block,
    it allocates a private twin for the caller to copy into; the caller
    then releases its reference on the shared original."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is reserved)")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, 0, -1))  # pop() yields 1 first
        self._allocated: set[int] = set()
        self._refcount: dict[int, int] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    def refcount(self, block: int) -> int:
        """Current holder count (0 for free / never-allocated ids)."""
        return self._refcount.get(block, 0)

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` blocks or raise :class:`CacheExhausted` (taking
        nothing — admission is all-or-nothing per request).  Each block
        comes out at refcount 1."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} blocks")
        if n > len(self._free):
            raise CacheExhausted(n, len(self._free))
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        for b in out:
            self._refcount[b] = 1
        return out

    def retain(self, blocks) -> None:
        """Add one holder to each block (all must be allocated)."""
        blocks = list(blocks)
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(
                    f"cannot retain block {b}: not allocated"
                )
        for b in blocks:
            self._refcount[b] += 1

    def release(self, blocks) -> None:
        """Drop one holder from each block; a block returns to the free
        list only when its refcount reaches 0.  Duplicate ids and
        non-allocated blocks are loud — releasing the same block twice in
        one call would silently drop a holder someone else still is."""
        blocks = list(blocks)
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"duplicate block ids in release(): {blocks}")
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(
                    f"block {b} is not allocated (double release or "
                    f"foreign id)"
                )
        for b in blocks:
            self._refcount[b] -= 1
            if self._refcount[b] == 0:
                del self._refcount[b]
                self._allocated.remove(b)
                self._free.append(b)

    def free(self, blocks) -> None:
        """Return exclusively-held blocks to the free list.  Loud on
        duplicates, foreign ids, AND shared blocks — a holder that thinks
        it owns a shared block outright has a refcount bug upstream."""
        blocks = list(blocks)
        if len(set(blocks)) != len(blocks):
            raise ValueError(f"duplicate block ids in free(): {blocks}")
        for b in blocks:
            if b not in self._allocated:
                raise ValueError(
                    f"block {b} is not allocated (double free or foreign id)"
                )
            if self._refcount[b] != 1:
                raise ValueError(
                    f"block {b} is shared (refcount "
                    f"{self._refcount[b]}); use release(), not free()"
                )
        for b in blocks:
            del self._refcount[b]
            self._allocated.remove(b)
            self._free.append(b)

    def fork_block(self, src: int) -> int:
        """Copy-on-write fork: allocate a private twin for SHARED block
        ``src``.  The caller copies the pool contents (or re-derives them
        bitwise, as the suffix prefill does) into the returned block and
        then releases its own reference on ``src``.  Forking a private
        block is a loud error — a refcount-1 block needs no COW, and a
        caller asking for one has lost track of who shares what."""
        if src not in self._allocated:
            raise ValueError(f"cannot fork block {src}: not allocated")
        if self._refcount[src] < 2:
            raise ValueError(
                f"cannot fork block {src}: refcount "
                f"{self._refcount[src]} (not shared — write in place)"
            )
        return self.alloc(1)[0]


def init_pools(cfg: TransformerConfig, pcfg: PagedCacheConfig) -> dict:
    """Per-layer (num_blocks, block_size, H, Dh) K/V pools, zeros in the
    compute dtype — mirrors ``init_kv_cache``'s structure with the batch
    and length axes folded into (block, offset)."""
    shape = (pcfg.num_blocks, pcfg.block_size, cfg.n_heads, cfg.head_dim)
    return {
        "k": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
        "v": [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)],
    }


def write_prefill(pools: dict, cache: dict, block_ids) -> dict:
    """Scatter a single-sequence contiguous prefill cache into the pool.

    ``cache`` is ``prefill``'s output for a batch of ONE (its per-layer
    K/V is (1, max_len, H, Dh) with zeros past the prompt); the first
    ``len(block_ids) * block_size`` positions land in ``block_ids`` in
    order.  Positions past the prompt scatter zeros — the same zeros the
    contiguous cache holds there, which the decode writes then fill in.
    """
    idx = jnp.asarray(block_ids, jnp.int32)
    n = len(block_ids)
    out_k, out_v = [], []
    for pk, pv, kc, vc in zip(pools["k"], pools["v"], cache["k"], cache["v"]):
        bs = pk.shape[1]
        if kc.shape[1] < n * bs:
            raise ValueError(
                f"prefill cache holds {kc.shape[1]} positions, "
                f"{n} blocks need {n * bs}"
            )
        out_k.append(pk.at[idx].set(kc[0, : n * bs].reshape(n, bs, *pk.shape[2:])))
        out_v.append(pv.at[idx].set(vc[0, : n * bs].reshape(n, bs, *pv.shape[2:])))
    return {"k": out_k, "v": out_v}


def write_prefill_at(pools: dict, cache: dict, block_ids,
                     start_block: int) -> dict:
    """Scatter a prefill cache's positions FROM ``start_block * bs``
    onward into ``block_ids`` — the suffix half of a prefix-cache hit.

    ``cache`` is ``prefill_suffix``'s output for a batch of ONE: its
    positions below ``start_block * bs`` belong to CACHED blocks this
    call must never rewrite (they may be shared with other sequences), so
    only the slice ``[start_block*bs, (start_block + len(block_ids))*bs)``
    is scattered.  ``start_block`` must be static (it selects a slice at
    trace time); the engine jits this with ``static_argnums``.
    """
    idx = jnp.asarray(block_ids, jnp.int32)
    n = int(idx.shape[0])
    if start_block < 0:
        raise ValueError(f"start_block must be >= 0, got {start_block}")
    out_k, out_v = [], []
    for pk, pv, kc, vc in zip(pools["k"], pools["v"], cache["k"], cache["v"]):
        bs = pk.shape[1]
        s0 = start_block * bs
        if kc.shape[1] < s0 + n * bs:
            raise ValueError(
                f"prefill cache holds {kc.shape[1]} positions, blocks "
                f"{start_block}..{start_block + n} need {s0 + n * bs}"
            )
        out_k.append(
            pk.at[idx].set(kc[0, s0 : s0 + n * bs].reshape(n, bs, *pk.shape[2:]))
        )
        out_v.append(
            pv.at[idx].set(vc[0, s0 : s0 + n * bs].reshape(n, bs, *pv.shape[2:]))
        )
    return {"k": out_k, "v": out_v}


def write_swapped(pools: dict, kv: dict, block_ids) -> dict:
    """Scatter a swapped-out sequence's saved K/V back into newly
    assigned blocks — the resume half of preemption.

    ``kv`` is per-layer ``{"k": [(n*bs, H, Dh)], "v": [...]}`` with
    exactly ``len(block_ids) * block_size`` positions (the engine pads
    the saved ``length`` positions with zeros host-side).  The pad
    positions sit at or past the sequence's causal bound, so — the same
    argument as ``write_prefill``'s over-scatter — they are invisible
    until the decode writes overwrite them.  The restored bytes are the
    exact bytes ``gather_seq`` saved, which is what makes swap-in resume
    bit-identical.
    """
    idx = jnp.asarray(block_ids, jnp.int32)
    n = idx.shape[0]
    out_k, out_v = [], []
    for pk, pv, k, v in zip(pools["k"], pools["v"], kv["k"], kv["v"]):
        bs = pk.shape[1]
        if k.shape[0] != n * bs:
            raise ValueError(
                f"swapped K/V holds {k.shape[0]} positions, "
                f"{n} blocks need {n * bs}"
            )
        out_k.append(pk.at[idx].set(k.reshape(n, bs, *pk.shape[2:])))
        out_v.append(pv.at[idx].set(v.reshape(n, bs, *pv.shape[2:])))
    return {"k": out_k, "v": out_v}


def paged_decode_step(params, pools, tables, lengths, tokens,
                      cfg: TransformerConfig, fused: bool = False,
                      impl: str = "jnp"):
    """One decode step for S slots over the paged pool.

    ``tables`` (S, P) int32 block tables, ``lengths`` (S,) int32 cache
    positions already filled per slot, ``tokens`` (S,) int32 the token to
    decode at each slot's position.  Returns ``(logits, pools)`` — (S,
    vocab) f32 next-position logits and the pool with each slot's new K/V
    scattered at ``(tables[s, lengths[s]//bs], lengths[s] % bs)``.

    Inactive slots are driven with table rows of all-NULL_BLOCK and
    length 0: their writes land in the null block and their logits are
    garbage the host discards; active rows never reference the null block
    below their causal bound, so pollution there is invisible (masked
    weights are exactly 0.0 — see the module docstring).

    The per-layer math calls the SAME helpers as the contiguous decode
    (``_qkv`` / ``apply_rope`` / ``mlp_block`` / ``final_logits``).
    ``fused=False`` attends through ``ops.paged_attention_gather`` — the
    gathered view has the same (S, P*bs) key length the contiguous cache
    would, which plus exact-zero masking is the whole bitwise-identity
    argument.  ``fused=True`` attends through ``ops.paged_attention``
    (``impl=`` "jnp" block-streaming or "pallas"): same masking, online-
    softmax summation order, within ``FUSED_DECODE_ATOL`` of the oracle.
    """
    s = tokens.shape[0]
    positions = lengths[:, None].astype(jnp.int32)  # (S, 1) per-sequence
    bs = pools["k"][0].shape[1]
    row = jnp.arange(s)
    blk = tables[row, lengths // bs]  # (S,) current block per slot
    off = lengths % bs
    attend = paged_attention if fused else paged_attention_gather
    kwargs = {"impl": impl} if fused else {}
    x = params["embed"][tokens[:, None]].astype(cfg.dtype)
    new_k, new_v = [], []
    for layer, pk, pv in zip(params["layers"], pools["k"], pools["v"]):
        h = rms_norm(x, layer["ln1"])
        q, k, v = _qkv(layer, h, cfg)
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)
        attn = attend(
            q[:, 0], k[:, 0], v[:, 0], pk, pv, tables, lengths, **kwargs
        )[:, None]
        o = attn.reshape(s, 1, -1) @ layer["wo"].astype(cfg.dtype)
        x = x + o
        x = mlp_block(layer, x, cfg)
        # scatter the appended K/V back into each row's current block
        new_k.append(pk.at[blk, off].set(k[:, 0]))
        new_v.append(pv.at[blk, off].set(v[:, 0]))
    logits = final_logits(params["embed"], params["ln_f"], x)
    return logits[:, 0], {"k": new_k, "v": new_v}


def make_paged_decode_fn(cfg: TransformerConfig, donate: bool = True,
                         fused: bool = False, impl: str = "jnp"):
    """Jit ``paged_decode_step`` with the pool buffers donated (the old
    pool is dead the moment the new one exists — donation keeps steady-
    state decode allocation-free).  ``fused=``/``impl=`` select the
    attention path (see :func:`paged_decode_step`)."""
    return jax.jit(
        partial(paged_decode_step, cfg=cfg, fused=fused, impl=impl),
        donate_argnums=(1,) if donate else (),
    )


def export_blocks(pools: dict, block_ids) -> dict:
    """Pull a sequence's blocks out of the pool at BLOCK granularity —
    per-layer ``(n, bs, H, Dh)`` — for migration to another replica.

    This is deliberately NOT :func:`gather_seq`: no ``(n*bs, H, Dh)``
    contiguous row is ever materialized.  The wire payload ships blocks
    exactly as the pool stores them, and the importing side scatters the
    same block-shaped arrays straight back with :func:`write_imported` —
    so the f32 path moves the pool bytes verbatim (the bitwise-identity
    argument) and neither side pays a reshape/copy beyond the device→host
    transfer itself.
    """
    idx = jnp.asarray(block_ids, jnp.int32)
    return {
        "k": [pk[idx] for pk in pools["k"]],
        "v": [pv[idx] for pv in pools["v"]],
    }


def write_imported(pools: dict, kv: dict, block_ids) -> dict:
    """Scatter migrated block-shaped K/V into newly assigned blocks — the
    receiving half of :func:`export_blocks`.

    ``kv`` is per-layer ``{"k": [(n, bs, H, Dh)], "v": [...]}`` with
    exactly ``len(block_ids)`` blocks.  Positions in the final block past
    the migrated sequence's length sit at or beyond its causal bound, so
    — the same over-scatter argument as :func:`write_swapped` — whatever
    the tail holds is invisible until decode writes overwrite it.  On the
    f32 codec the scattered bytes are the exact bytes
    :func:`export_blocks` read, which is what keeps a migrated decode
    bitwise against the colocated engine.
    """
    idx = jnp.asarray(block_ids, jnp.int32)
    n = idx.shape[0]
    out_k, out_v = [], []
    for pk, pv, k, v in zip(pools["k"], pools["v"], kv["k"], kv["v"]):
        if k.shape[0] != n or k.shape[1:] != pk.shape[1:]:
            raise ValueError(
                f"imported K/V shaped {tuple(k.shape)}, "
                f"{n} blocks of {tuple(pk.shape[1:])} expected"
            )
        out_k.append(pk.at[idx].set(k))
        out_v.append(pv.at[idx].set(v))
    return {"k": out_k, "v": out_v}


def gather_seq(pools: dict, block_ids, length: int | None = None) -> dict:
    """Test/debug helper: one sequence's contiguous K/V view — per-layer
    (n_blocks*bs, H, Dh), truncated to ``length`` if given."""
    idx = jnp.asarray(block_ids, jnp.int32)
    out = {"k": [], "v": []}
    for pk, pv in zip(pools["k"], pools["v"]):
        k = pk[idx].reshape(-1, *pk.shape[2:])
        v = pv[idx].reshape(-1, *pv.shape[2:])
        if length is not None:
            k, v = k[:length], v[:length]
        out["k"].append(k)
        out["v"].append(v)
    return out
