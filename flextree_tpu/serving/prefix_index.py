"""Radix index over prompt token ids: cross-request prefix-cache lookup.

Millions of requests share system prompts and few-shot preambles; the
paged pool (``kv_cache``) makes the K/V of a shared prefix reusable
because a block's bytes are a pure function of the token prefix that
produced them (RoPE positions are absolute, the causal mask zeroes
everything else — the same argument that makes paged decode bitwise
against ``generate``).  This module is the lookup structure:

- a **radix trie at block granularity**: each edge is the tuple of
  exactly ``block_size`` token ids a FULL block was computed from, each
  node owns that block id.  Only full blocks are cacheable — a partial
  tail block receives decode writes and is always private to its
  sequence, so it never enters the index.
- **refcounted adoption**: ``insert`` (called at retirement with the
  sequence's full PROMPT blocks — never the decode-polluted tail)
  retains each block it adopts; ``match`` returns the longest chain of
  cached blocks for a prompt, and the batcher retains the ones it
  shares.  A block leaves the pool's free list exactly while someone —
  index or sequence — holds it.
- **LRU eviction under pool pressure**: when admission cannot allocate,
  the batcher asks the index to give blocks back.  Only entries with no
  live sequence holder (allocator refcount 1 — the index's own
  reference) are evictable, leaves first (evicting an interior node
  would orphan reachable children), least-recently-matched first.
- **deterministic keying**: keys are token-id tuples, the LRU clock is a
  logical counter, and ties break on node creation order — two replicas
  fed the same request sequence build bit-identical tries, which is what
  makes prefix-affinity routing at the front door worth anything.

Invariant violations (double-indexed block, wrong key width, foreign
block) raise :class:`PrefixIndexError` loudly — a silently corrupted
index would hand one sequence another prompt's K/V.
"""

from __future__ import annotations

from .kv_cache import BlockAllocator

__all__ = ["PrefixIndexError", "PrefixIndex"]


class PrefixIndexError(RuntimeError):
    """An index invariant broke — stable-code'd like the other loud
    serving failures."""

    code = "FT_PREFIX_INDEX"

    def __init__(self, msg: str):
        super().__init__(f"{self.code}: {msg}")


class _Node:
    __slots__ = ("key", "block", "children", "last_used", "seq")

    def __init__(self, key: tuple, block: int, seq: int):
        self.key = key
        self.block = block
        self.children: dict = {}
        self.last_used = seq
        self.seq = seq  # creation order: the deterministic LRU tie-break


class PrefixIndex:
    """Block-granularity radix trie over prompt token ids.

    The allocator is taken at construction so retain/release stay next
    to the structural mutation they justify — an index entry without its
    allocator reference (or vice versa) is exactly the leak/corruption
    pair the churn test hunts."""

    def __init__(self, block_size: int, allocator: BlockAllocator):
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.block_size = int(block_size)
        self.allocator = allocator
        self._children: dict = {}  # root level: key tuple -> _Node
        self._blocks: set[int] = set()  # every indexed block, for loudness
        self._clock = 0
        # accounting the engine exports (counters, not gauges: the index
        # is single-threaded under the engine loop)
        self.lookups = 0
        self.hit_blocks = 0
        self.inserted = 0
        self.evictions = 0
        self.on_evict = None  # optional hook(block_id) for events/metrics

    # ---- internals ---------------------------------------------------------

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    @staticmethod
    def _key(tokens, j: int, bs: int) -> tuple:
        return tuple(int(t) for t in tokens[j * bs : (j + 1) * bs])

    @property
    def size(self) -> int:
        return len(self._blocks)

    # ---- lookup ------------------------------------------------------------

    def match(self, tokens) -> list:
        """Longest chain of cached FULL blocks prefixing ``tokens``.

        Returns the block ids in prefix order (possibly empty).  At most
        ``len(tokens) // block_size`` blocks match — the partial tail is
        never cached; the ADMISSION layer further decides how many of the
        matched blocks it can share outright and whether the last one
        needs a copy-on-write fork (a full-prompt hit still must run the
        final token through the model for its logits).  Touches the LRU
        clock along the matched path."""
        bs = self.block_size
        limit = len(tokens) // bs
        self.lookups += 1
        out: list = []
        children = self._children
        now = self._tick()
        for j in range(limit):
            node = children.get(self._key(tokens, j, bs))
            if node is None:
                break
            node.last_used = now
            out.append(node.block)
            children = node.children
        self.hit_blocks += len(out)
        return out

    # ---- insertion (at retirement) -----------------------------------------

    def insert(self, tokens, block_ids) -> int:
        """Adopt a retired sequence's full PROMPT blocks into the trie.

        ``block_ids`` must cover ``len(block_ids) * block_size`` leading
        tokens of ``tokens`` with FULL blocks — the caller passes
        ``block_ids[: prompt_len // block_size]``, never the tail block
        decode wrote into.  A chain node that already exists keeps its
        existing block (first writer wins; both hold bitwise-identical
        bytes, so preferring the resident one avoids a pointless retain/
        release churn).  Newly adopted blocks are retained — the index
        becomes a holder.  Returns how many blocks were adopted."""
        bs = self.block_size
        n = len(block_ids)
        if n * bs > len(tokens):
            raise PrefixIndexError(
                f"insert of {n} blocks needs {n * bs} tokens, "
                f"got {len(tokens)}"
            )
        children = self._children
        now = self._tick()
        adopted = 0
        for j in range(n):
            key = self._key(tokens, j, bs)
            node = children.get(key)
            if node is None:
                b = int(block_ids[j])
                if b in self._blocks:
                    raise PrefixIndexError(
                        f"block {b} is already indexed under another "
                        f"prefix — one block, one owner chain"
                    )
                self.allocator.retain([b])
                node = children[key] = _Node(key, b, now)
                self._blocks.add(b)
                adopted += 1
            node.last_used = now
            children = node.children
        self.inserted += adopted
        return adopted

    # ---- eviction (under pool pressure) ------------------------------------

    def _evictable_leaves(self):
        """Yield ``(parent_children, key, node)`` for every leaf whose
        block has no live holder beyond the index itself."""
        stack = [self._children]
        while stack:
            children = stack.pop()
            for key, node in children.items():
                if node.children:
                    stack.append(node.children)
                elif self.allocator.refcount(node.block) == 1:
                    yield children, key, node

    def evict(self, want: int) -> int:
        """Release up to ``want`` blocks by evicting LRU leaves whose
        only holder is the index.  Entries shared with live sequences
        are not evictable (releasing them would free nothing — the
        sequence still holds them) and interior nodes fall as their
        children do.  Returns how many blocks were released."""
        freed = 0
        while freed < max(int(want), 0):
            best = None
            for children, key, node in self._evictable_leaves():
                rank = (node.last_used, node.seq)
                if best is None or rank < best[0]:
                    best = (rank, children, key, node)
            if best is None:
                break
            _, children, key, node = best
            del children[key]
            self._blocks.discard(node.block)
            self.allocator.release([node.block])
            self.evictions += 1
            freed += 1
            if self.on_evict is not None:
                self.on_evict(node.block)
        return freed

    def clear(self) -> int:
        """Release every index-held block (the drain path: after this,
        all refcounts the index contributed are gone and a leak check
        can demand the free list be whole again).  Returns the count."""
        n = 0
        stack = [self._children]
        while stack:
            children = stack.pop()
            for node in children.values():
                self.allocator.release([node.block])
                n += 1
                stack.append(node.children)
        self._children = {}
        self._blocks = set()
        return n

    # ---- invariants --------------------------------------------------------

    def check(self) -> None:
        """Loud structural audit: every node's key is exactly one block
        wide, its block is allocated with the index among its holders,
        and no block is indexed twice."""
        seen: set = set()
        stack = [self._children]
        while stack:
            children = stack.pop()
            for key, node in children.items():
                if len(key) != self.block_size:
                    raise PrefixIndexError(
                        f"node key width {len(key)} != block_size "
                        f"{self.block_size}"
                    )
                if key != node.key:
                    raise PrefixIndexError(
                        f"node filed under {key} carries key {node.key}"
                    )
                if self.allocator.refcount(node.block) < 1:
                    raise PrefixIndexError(
                        f"indexed block {node.block} has no holders "
                        f"(refcount 0) — the index's reference leaked"
                    )
                if node.block in seen:
                    raise PrefixIndexError(
                        f"block {node.block} indexed twice"
                    )
                seen.add(node.block)
                stack.append(node.children)
        if seen != self._blocks:
            raise PrefixIndexError(
                f"block set drifted: walk found {sorted(seen)}, "
                f"tracker holds {sorted(self._blocks)}"
            )

    def key_paths(self) -> list:
        """Every root-to-node key path, sorted — the deterministic-keying
        witness: two replicas fed the same requests produce identical
        paths (block ids may differ; the KEYS are the contract)."""
        return [path for path, _ in self.node_paths()]

    def node_paths(self) -> list:
        """Every ``(root-to-node key path, block id)`` pair, sorted by
        path — the drain-handoff export walk: the path IS the token
        prefix the node's block was computed from, so a successor can
        recompute the block from the path alone (the block id is local
        to THIS replica's pool and never travels)."""
        out = []
        stack = [((), self._children)]
        while stack:
            prefix, children = stack.pop()
            for key, node in children.items():
                path = prefix + (key,)
                out.append((path, node.block))
                stack.append((path, node.children))
        return sorted(out)
