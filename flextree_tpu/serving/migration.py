"""KV migration payloads: block-shaped K/V packed for the replica wire.

Prefill/decode disaggregation ships a sequence's KV blocks from the
prefill replica that computed them to the decode replica that will own
the sequence.  This module is the wire format — the pure function pair
``pack_kv`` / ``unpack_kv`` between the engine's block-granular export
(``kv_cache.export_blocks``, per-layer ``(n, bs, H, Dh)``) and bytes:

- **codec per hop, reusing ``ops/quantize.py``** (EQuARX's move applied
  to the migration hop instead of the allreduce hop): ``f32`` ships the
  pool bytes verbatim — ``np.float32`` tobytes/frombuffer is bitwise, so
  an f32 migration is provably byte-identical to a local prefill and the
  greedy decode stays bitwise against the colocated engine.  ``int8``
  ships block-scaled 8-bit at ~4x less wire, with per-element error
  bounded by ``Codec.error_bound(amax, 1, widths=(1,))`` = ``amax/127``
  for the single migration hop (one encode, one decode, no accumulation)
  — ``tools/bench_disagg.py`` machine-checks both the bound and greedy
  token identity against the oracle.
- **refuse, don't guess**: the decode side verifies the whole-payload
  CRC, every per-tensor CRC, the declared geometry against its OWN model
  config, and the byte counts before a single element lands in its pool.
  Any mismatch raises :class:`MigrationError` (``FT_MIGRATION_REFUSED``)
  and the payload is dropped — admitting a corrupt or mis-shaped KV
  would silently poison one sequence's attention, the exact failure
  class the CRC-trailered RPC framing exists to make loud.

Tensor order on the wire is fixed (layer-major, K before V) so two
replicas never need to negotiate layout; the meta dict travels in the
RPC JSON body, the blob rides base64-chunked frames (``rpc.chunk_blob``).
"""

from __future__ import annotations

import zlib

import numpy as np

from ..ops.quantize import decode_int8, encode_int8, get_codec

__all__ = [
    "MigrationError",
    "pack_kv",
    "unpack_kv",
    "migration_error_bound",
]


class MigrationError(RuntimeError):
    """A migration payload failed verification (or packing hit an
    unsupported codec) — the decode side refuses the handoff and the
    prefill side falls back to releasing its export.  Stable-code'd like
    the other loud serving failures."""

    code = "FT_MIGRATION_REFUSED"

    def __init__(self, msg: str):
        super().__init__(f"{self.code}: {msg}")


def _crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def _tensors(kv: dict):
    """Fixed wire order: layer-major, K before V."""
    for layer, (k, v) in enumerate(zip(kv["k"], kv["v"])):
        yield layer, "k", k
        yield layer, "v", v


def pack_kv(kv: dict, *, codec: str = "f32") -> tuple[dict, bytes]:
    """Pack block-shaped K/V into ``(meta, blob)`` for the wire.

    ``kv`` is ``export_blocks`` output: per-layer ``(n, bs, H, Dh)``.
    ``meta`` declares the geometry, codec, and per-tensor byte spans +
    CRCs; ``blob`` is the concatenated tensor payload in fixed order.
    The f32 codec emits each tensor's float32 bytes verbatim (bitwise);
    int8 emits ``encode_int8``'s (q, scales) pair per tensor, flattened,
    with the tensor's amax recorded so the receiver can state the
    documented error bound without re-deriving it.
    """
    c = get_codec(codec)
    if c.name not in ("f32", "int8"):
        raise MigrationError(
            f"codec {c.name!r} is not a migration codec (f32 | int8)"
        )
    first = np.asarray(kv["k"][0])
    n, bs, heads, dh = first.shape
    tensors, parts = [], []
    for layer, part, arr in _tensors(kv):
        a = np.ascontiguousarray(np.asarray(arr, dtype=np.float32))
        if a.shape != (n, bs, heads, dh):
            raise MigrationError(
                f"layer {layer} {part} shaped {a.shape}, expected "
                f"{(n, bs, heads, dh)}"
            )
        if c.name == "f32":
            payload = a.tobytes()
            entry = {"layer": layer, "part": part, "nbytes": len(payload)}
        else:
            flat = a.reshape(-1)
            q, scales = encode_int8(flat, 0, salt=0, block=c.block)
            qb = np.asarray(q, np.int8).tobytes()
            sb = np.ascontiguousarray(np.asarray(scales, np.float32)).tobytes()
            payload = qb + sb
            entry = {
                "layer": layer,
                "part": part,
                "nbytes": len(payload),
                "nbytes_q": len(qb),
                "length": int(flat.shape[0]),
                "amax": float(np.max(np.abs(flat))) if flat.size else 0.0,
            }
        entry["crc32"] = _crc(payload)
        tensors.append(entry)
        parts.append(payload)
    blob = b"".join(parts)
    meta = {
        "codec": c.name,
        "codec_block": c.block,
        "n_blocks": int(n),
        "block_size": int(bs),
        "n_heads": int(heads),
        "head_dim": int(dh),
        "n_layers": len(kv["k"]),
        "nbytes": len(blob),
        "crc32": _crc(blob),
        "tensors": tensors,
    }
    return meta, blob


def unpack_kv(meta: dict, blob: bytes) -> dict:
    """Verify and decode a migration payload back to block-shaped K/V.

    Refuses loudly (:class:`MigrationError`) on: whole-blob CRC or byte
    count drift, per-tensor CRC drift, tensor count vs declared layers,
    byte spans that do not reconstruct the declared geometry, unknown
    codec.  On success returns ``{"k": [np (n, bs, H, Dh) f32], "v":
    [...]}`` ready for ``kv_cache.write_imported``.
    """
    try:
        codec = get_codec(meta["codec"])
        n = int(meta["n_blocks"])
        bs = int(meta["block_size"])
        heads = int(meta["n_heads"])
        dh = int(meta["head_dim"])
        layers = int(meta["n_layers"])
        tensors = list(meta["tensors"])
    except (KeyError, TypeError, ValueError) as e:
        raise MigrationError(f"malformed migration meta: {e}") from None
    if len(blob) != int(meta.get("nbytes", -1)):
        raise MigrationError(
            f"payload is {len(blob)} bytes, meta declares {meta.get('nbytes')}"
        )
    if _crc(blob) != int(meta.get("crc32", -1)):
        raise MigrationError("payload CRC mismatch — corrupt migration blob")
    if len(tensors) != 2 * layers:
        raise MigrationError(
            f"{len(tensors)} tensors declared for {layers} layers "
            f"(expected {2 * layers})"
        )
    shape = (n, bs, heads, dh)
    count = int(np.prod(shape))
    out = {"k": [None] * layers, "v": [None] * layers}
    off = 0
    for i, entry in enumerate(tensors):
        try:
            layer, part = int(entry["layer"]), str(entry["part"])
            nbytes, crc = int(entry["nbytes"]), int(entry["crc32"])
        except (KeyError, TypeError, ValueError) as e:
            raise MigrationError(f"malformed tensor entry {i}: {e}") from None
        if not (0 <= layer < layers and part in ("k", "v")):
            raise MigrationError(f"tensor entry {i} addresses {part}@{layer}")
        if out[part][layer] is not None:
            raise MigrationError(f"duplicate tensor {part}@{layer}")
        payload = blob[off : off + nbytes]
        if len(payload) != nbytes:
            raise MigrationError(
                f"tensor {part}@{layer} truncated: {len(payload)}/{nbytes} bytes"
            )
        off += nbytes
        if _crc(payload) != crc:
            raise MigrationError(f"tensor {part}@{layer} CRC mismatch")
        if codec.name == "f32":
            if nbytes != count * 4:
                raise MigrationError(
                    f"tensor {part}@{layer} is {nbytes} bytes, shape "
                    f"{shape} needs {count * 4}"
                )
            arr = np.frombuffer(payload, np.float32).reshape(shape)
        else:
            try:
                nbytes_q = int(entry["nbytes_q"])
                length = int(entry["length"])
            except (KeyError, TypeError, ValueError) as e:
                raise MigrationError(
                    f"malformed int8 tensor entry {i}: {e}"
                ) from None
            blk = codec.block
            padded = -(-length // blk) * blk
            if length != count or nbytes_q != padded:
                raise MigrationError(
                    f"tensor {part}@{layer} int8 geometry drift: length "
                    f"{length} (want {count}), q bytes {nbytes_q} (want {padded})"
                )
            if nbytes != nbytes_q + (padded // blk) * 4:
                raise MigrationError(
                    f"tensor {part}@{layer} is {nbytes} bytes, int8 + "
                    f"scales need {nbytes_q + (padded // blk) * 4}"
                )
            q = np.frombuffer(payload[:nbytes_q], np.int8)
            scales = np.frombuffer(payload[nbytes_q:], np.float32)
            arr = np.asarray(
                decode_int8(q, scales, length, block=blk), np.float32
            ).reshape(shape)
        out[part][layer] = arr
    if off != len(blob):
        raise MigrationError(
            f"{len(blob) - off} trailing bytes after the declared tensors"
        )
    return out


def migration_error_bound(meta: dict) -> float:
    """The documented per-element absolute error bound of one unpacked
    payload: 0 for f32, ``max(amax)/127`` across tensors for int8 — one
    migration hop is one encode + one decode with no accumulation, i.e.
    ``Codec.error_bound(amax, n=1, widths=(1,))``.  The disagg bench
    machine-checks decoded values against this."""
    codec = get_codec(meta["codec"])
    if not codec.lossy:
        return 0.0
    amax = max(
        (float(t.get("amax", 0.0)) for t in meta.get("tensors", ())),
        default=0.0,
    )
    return codec.error_bound(amax, 1, widths=(1,))
