"""Serving front-end: continuous-batching generation over a paged KV cache.

The traffic-facing layer of the framework — requests in, tokens out,
benchmarked in throughput and latency percentiles instead of step time:

- :mod:`.kv_cache` — the paged/blocked KV cache: fixed-size blocks, a
  host-side free-list allocator, per-sequence block tables; ragged
  sequences share one static-shaped pool and the decode step stays one
  compiled program (gather pages → batched ragged decode → scatter
  appended K/V), bitwise-identical to the contiguous-cache ``generate``.
- :mod:`.batcher` — the continuous batcher: FIFO request queue, admission
  by token budget (all cache blocks reserved up front, so admitted
  requests never hit mid-decode exhaustion), join-at-step prefill, and
  per-sequence retirement that frees blocks immediately.
- :mod:`.engine` — one serving replica: paged pool + batcher + the two
  jitted programs, with per-request greedy/temperature/top-k sampling and
  TTFT / per-token timestamps on an injectable clock.
- :mod:`.pool` — the elastic replica pool: ``runtime.Supervisor``
  heartbeat/lease membership over replicas, a ``StepWatchdog`` deadline
  around each scheduling round, and drain/re-route off dead replicas so
  the pool degrades instead of failing.

Measured artifact: ``tools/bench_serving.py`` → ``BENCH_SERVING.json``
(open-loop Poisson load; machine-checked floors).  Design notes and the
honest limits: ``docs/SERVING.md``.
"""

from .batcher import (
    BatcherConfig,
    ContinuousBatcher,
    PreemptedSeq,
    Request,
    SeqState,
)
from .engine import CompletedRequest, ServingEngine
from .kv_cache import (
    NULL_BLOCK,
    BlockAllocator,
    CacheExhausted,
    PagedCacheConfig,
    gather_seq,
    init_pools,
    make_paged_decode_fn,
    paged_decode_step,
    write_prefill,
    write_swapped,
)
from .pool import PoolConfig, ReplicaFailed, ReplicaPool

__all__ = [
    "NULL_BLOCK",
    "BlockAllocator",
    "CacheExhausted",
    "PagedCacheConfig",
    "init_pools",
    "write_prefill",
    "write_swapped",
    "paged_decode_step",
    "make_paged_decode_fn",
    "gather_seq",
    "Request",
    "SeqState",
    "PreemptedSeq",
    "BatcherConfig",
    "ContinuousBatcher",
    "ServingEngine",
    "CompletedRequest",
    "PoolConfig",
    "ReplicaFailed",
    "ReplicaPool",
]
