"""Serving front-end: continuous-batching generation over a paged KV cache.

The traffic-facing layer of the framework — requests in, tokens out,
benchmarked in throughput and latency percentiles instead of step time:

- :mod:`.kv_cache` — the paged/blocked KV cache: fixed-size blocks, a
  host-side free-list allocator, per-sequence block tables; ragged
  sequences share one static-shaped pool and the decode step stays one
  compiled program (gather pages → batched ragged decode → scatter
  appended K/V), bitwise-identical to the contiguous-cache ``generate``.
- :mod:`.batcher` — the continuous batcher: FIFO request queue, admission
  by token budget (all cache blocks reserved up front, so admitted
  requests never hit mid-decode exhaustion), join-at-step prefill, and
  per-sequence retirement that frees blocks immediately.
- :mod:`.engine` — one serving replica: paged pool + batcher + the two
  jitted programs, with per-request greedy/temperature/top-k sampling and
  TTFT / per-token timestamps on an injectable clock.
- :mod:`.prefix_index` — the cross-request prefix cache: a radix trie
  over prompt token ids at block granularity, refcounted copy-on-write
  sharing of full prompt blocks, LRU eviction under pool pressure, and
  suffix-only prefill on a hit — bitwise-identical to a cold engine
  (``tools/bench_prefix.py`` → ``BENCH_PREFIX.json``).  The front door
  routes by prefix affinity so shared prompts land where their blocks
  already are.
- :mod:`.pool` — the elastic replica pool: ``runtime.Supervisor``
  heartbeat/lease membership over replicas, a ``StepWatchdog`` deadline
  around each scheduling round, and drain/re-route off dead replicas so
  the pool degrades instead of failing.
- :mod:`.rpc` / :mod:`.replica_main` / :mod:`.frontdoor` — the
  real-process tier: a CRC-trailered frame protocol over TCP, a replica
  server process per engine (heartbeat-registered, SIGTERM-drainable),
  and a front-door router with deadlines, bounded retries, windowed-p99
  hedging, circuit breakers, and load shedding — exactly-once results
  via replica-side idempotency, proven under kill chaos by
  ``tools/rpc_chaos.py`` → ``RPC_CHAOS.json``.
- :mod:`.migration` / :mod:`.costs` — prefill/decode disaggregation:
  replicas run as ``--role prefill`` (prompt forward only, KV shipped
  out) or ``--role decode`` (admit migrated blocks mid-stream), the KV
  payload rides the frame protocol as int8/f32 block-scaled tensors
  with per-tensor CRCs, and the cost planner's migration-vs-recompute
  crossover decides per request whether the hop pays — proven by
  ``tools/bench_disagg.py`` → ``BENCH_DISAGG.json``.

Measured artifact: ``tools/bench_serving.py`` → ``BENCH_SERVING.json``
(open-loop Poisson load; machine-checked floors).  Design notes and the
honest limits: ``docs/SERVING.md``.
"""

from .batcher import (
    BatcherConfig,
    ContinuousBatcher,
    PreemptedSeq,
    Request,
    SeqState,
)
from .engine import CompletedRequest, ServingEngine
from .frontdoor import (
    FrontDoor,
    FrontDoorConfig,
    FrontDoorResult,
    ReplicaClient,
)
from .kv_cache import (
    NULL_BLOCK,
    BlockAllocator,
    CacheExhausted,
    PagedCacheConfig,
    gather_seq,
    init_pools,
    make_paged_decode_fn,
    paged_decode_step,
    write_prefill,
    write_prefill_at,
    write_swapped,
)
from .migration import (
    MigrationError,
    migration_error_bound,
    pack_kv,
    unpack_kv,
)
from .pool import PoolConfig, ReplicaFailed, ReplicaPool
from .prefix_index import PrefixIndex, PrefixIndexError
from .replica_main import ReplicaConfig, ReplicaServer
from .rpc import (
    RpcConnection,
    RpcConnRefused,
    RpcError,
    RpcShed,
    RpcTimeout,
    RpcTornFrame,
)

__all__ = [
    "NULL_BLOCK",
    "BlockAllocator",
    "CacheExhausted",
    "PagedCacheConfig",
    "init_pools",
    "write_prefill",
    "write_prefill_at",
    "write_swapped",
    "paged_decode_step",
    "make_paged_decode_fn",
    "gather_seq",
    "Request",
    "SeqState",
    "PreemptedSeq",
    "BatcherConfig",
    "ContinuousBatcher",
    "PrefixIndex",
    "PrefixIndexError",
    "ServingEngine",
    "CompletedRequest",
    "PoolConfig",
    "ReplicaFailed",
    "ReplicaPool",
    "RpcError",
    "RpcTimeout",
    "RpcConnRefused",
    "RpcTornFrame",
    "RpcShed",
    "RpcConnection",
    "ReplicaConfig",
    "ReplicaServer",
    "FrontDoor",
    "FrontDoorConfig",
    "FrontDoorResult",
    "ReplicaClient",
    "MigrationError",
    "pack_kv",
    "unpack_kv",
    "migration_error_bound",
]
