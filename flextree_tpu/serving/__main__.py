"""Serving entrypoint: ``python -m flextree_tpu.serving``.

Drives one :class:`ServingEngine` over a synthetic open-batch workload
from the command line — the serving twin of ``python -m
flextree_tpu.trainer``, and the place both decode paths and both
admission modes stay drivable::

    # the defaults: fused decode, reservation admission
    python -m flextree_tpu.serving --requests 16

    # the gather oracle path (bitwise vs generate)
    python -m flextree_tpu.serving --no-fused-decode

    # vLLM-style on-demand allocation with swap-out preemption
    python -m flextree_tpu.serving --admission ondemand --preempt swap \\
        --blocks 33 --requests 24

Prints a JSON report: completions, throughput, TTFT percentiles, and the
cache-pressure accounting (free/active blocks, occupancy histogram,
preempt/resume counters) from the engine's metrics registry.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flextree_tpu.serving")
    ap.add_argument("--requests", type=int, default=12)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--blocks", type=int, default=65,
                    help="pool size INCLUDING the reserved null block")
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--blocks-per-seq", type=int, default=10)
    ap.add_argument("--max-new", type=int, default=24)
    ap.add_argument("--prompt-len", type=int, default=12,
                    help="prompts are uniform over [4, prompt-len]")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=128)
    ap.add_argument("--n-heads", type=int, default=8)
    ap.add_argument("--n-layers", type=int, default=2)
    ap.add_argument("--d-ff", type=int, default=512)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument(
        "--fused-decode", action=argparse.BooleanOptionalAction, default=True,
        help="fused paged-attention decode (ops/paged_attention.py): "
        "stream K/V blocks through an online softmax instead of "
        "materializing the gathered row — within a pinned tolerance of "
        "the gather oracle (the default; see BENCH_PAGED.json). "
        "--no-fused-decode keeps the gather path, which is bitwise vs "
        "generate",
    )
    ap.add_argument(
        "--decode-impl", choices=["jnp", "pallas"], default="jnp",
        help="fused-path implementation: the batched block-streaming jnp "
        "twin (default; fastest on CPU) or the Pallas kernel "
        "(interpreted off-TPU)",
    )
    ap.add_argument(
        "--admission", choices=["reserve", "ondemand"], default="reserve",
        help="block admission policy (docs/SERVING.md): reserve = whole "
        "prompt+output budget up front (no preemption possible — the "
        "conservative default), ondemand = prompt blocks only, decode "
        "grows per block boundary and pool exhaustion preempts the "
        "newest sequence",
    )
    ap.add_argument(
        "--preempt", choices=["swap", "recompute"], default="swap",
        help="what an evicted sequence keeps: swap = K/V bytes to host "
        "memory (bit-identical resume), recompute = drop and replay "
        "prefill on resume (cheaper for short contexts)",
    )
    ap.add_argument("--report", type=str, default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--cpu", action="store_true",
                    help="pin the CPU backend (generation is single-device)")
    args = ap.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from ..models.transformer import TransformerConfig, init_params
    from . import BatcherConfig, PagedCacheConfig, Request, ServingEngine

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
    )
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    pcfg = PagedCacheConfig(
        num_blocks=args.blocks, block_size=args.block_size,
        blocks_per_seq=args.blocks_per_seq,
    )
    eng = ServingEngine(
        params, cfg, pcfg,
        BatcherConfig(slots=args.slots, admission=args.admission,
                      preempt=args.preempt),
        fused=args.fused_decode,
        decode_impl=args.decode_impl,
    )
    rng = np.random.default_rng(args.seed)
    reqs = [
        Request(
            rid=i,
            prompt=rng.integers(
                0, args.vocab, (int(rng.integers(4, args.prompt_len + 1)),)
            ).astype(np.int32),
            max_new_tokens=args.max_new,
        )
        for i in range(args.requests)
    ]
    eng.warmup(
        sorted({r.prompt_len for r in reqs}),
        {pcfg.blocks_for(r.prompt_len + r.max_new_tokens) for r in reqs},
    )
    import time

    t0 = time.monotonic()
    submitted = sum(1 for r in reqs if eng.submit(r))
    eng.run_until_idle()
    makespan = time.monotonic() - t0
    tokens = sum(d.n_tokens for d in eng.completed.values())
    report = {
        "config": {
            "fused_decode": args.fused_decode,
            "decode_impl": args.decode_impl,
            "admission": args.admission,
            "preempt": args.preempt,
            "slots": args.slots,
            "blocks": args.blocks,
        },
        "submitted": submitted,
        "rejected": list(eng.batcher.rejected),
        "completed": len(eng.completed),
        "tokens": tokens,
        "makespan_s": round(makespan, 3),
        "throughput_tok_s": round(tokens / makespan, 2) if makespan else 0.0,
        **eng.report(),
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.report:
        with open(args.report, "w") as f:
            f.write(text + "\n")
    return 0 if len(eng.completed) == submitted else 1


if __name__ == "__main__":
    raise SystemExit(main())
