"""The fault-tolerant front door: route, retry, hedge, shed, account.

The client side of the real-process serving stack (:mod:`.replica_main`
is the server side, :mod:`.rpc` the wire).  One :class:`FrontDoor` owns
the request lifecycle from intake to exactly-once result:

- **discovery** — replicas are found through the shared control dir:
  ``rpc_{rank:05d}.json`` endpoint files (CRC-trailered) say where to
  connect, the Supervisor heartbeats say who is HEALTHY / STRAGGLER /
  DEAD (:class:`~flextree_tpu.runtime.supervisor.MembershipView`) — the
  same membership the training stack replans from;
- **routing** — healthy replicas first, least-outstanding among them
  (the pool's ``_route`` rule, now over processes), circuit-breaker
  strike-out per replica (``breaker_strikes`` consecutive transport
  failures open it for ``breaker_cooldown_s``);
- **deadlines** — every request has one total budget from its arrival
  stamp; the wire carries the *remaining* budget (monotonic clocks have
  no cross-process epoch), and a replica refuses an already-expired
  request instead of executing it;
- **retries** — bounded exponential backoff on the typed transport
  failures (``FT_RPC_TIMEOUT`` / ``FT_RPC_CONN_REFUSED`` /
  ``FT_RPC_TORN_FRAME``) and on replica-side sheds; a ``drain`` refusal
  re-routes immediately (the replica is leaving, not failing);
- **hedging** — when an attempt is still outstanding after the windowed
  p99 of recent attempt latencies (times ``hedge_factor``), a duplicate
  attempt goes to a *different* replica and the first result wins.  Safe
  by construction: the replica-side idempotency store computes each rid
  once, so the loser is a wasted RPC, never a forked sequence;
- **shedding** — over ``shed_outstanding`` requests in flight, intake
  refuses loudly (``serve.shed`` + a ``serve_shed`` flight event) rather
  than queueing into a latency cliff;
- **exactly-once results** — ``completed`` is first-writer-wins under a
  lock; a hedge race's second result increments
  ``serve.duplicate_results`` and is dropped.

TTFT is stamped ONCE at intake (:meth:`FrontDoor.submit`): however many
retries, hedges, and re-routes a request suffers, its reported TTFT is
``(winning attempt's send - arrival) + the replica's queue-to-first-
token time`` — queue and retry time included, the PR 9 stamping rule
extended across the wire.  Per-replica windowed TTFT histograms (and
the retry/hedge/shed/drain counters) export through
``obs metrics DIR --prom`` via :meth:`write_metrics`.

Clocks (``_now``) and backoff sleeps (``_sleep``) are module-level
injectables, same pattern as ``engine._now`` / ``supervisor._wall``.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
import zlib

import numpy as np

from ..obs import MetricsRegistry, record_event
from ..runtime.ctrlfile import read_control_json
from ..runtime.supervisor import DEAD, HEALTHY, MembershipView
from ..utils.logging import get_logger
from .rpc import (
    RpcConnection,
    RpcConnRefused,
    RpcError,
    RpcShed,
    RpcTimeout,
)

__all__ = ["FrontDoorConfig", "FrontDoorResult", "ReplicaClient", "FrontDoor"]

log = get_logger("flextree.serving")

# injection points for tests (patch these, not time.*)
_now = time.monotonic
_sleep = time.sleep


@dataclasses.dataclass(frozen=True)
class FrontDoorConfig:
    """Knobs, grouped by mechanism (defaults sized for localhost chaos;
    a real DCN wants every timeout an order of magnitude up)."""

    # deadlines
    request_timeout_s: float = 30.0  # total budget per request
    attempt_timeout_s: float = 4.0  # one RPC's budget (capped by request)
    connect_timeout_s: float = 1.0
    # retries
    max_attempts: int = 8  # total launches per rid, hedges included
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 1.0
    # hedging
    hedge_factor: float = 2.0  # delay = factor x windowed-p99 attempt
    hedge_min_samples: int = 8  # no p99, no hedging (cold start)
    hedge_floor_s: float = 0.05  # never hedge tighter than this
    max_hedges: int = 1  # per attempt round; 0 disables (the twin)
    # breaker
    breaker_strikes: int = 3
    breaker_cooldown_s: float = 2.0
    # shedding: over ``shed_outstanding`` in flight, intake refuses —
    # but PREDICTED PREFIX HITS (first block hashed in the affinity
    # table) ride a further ``shed_hit_headroom`` of slack.  A hit costs
    # a fraction of a miss's prefill, so when something must be shed,
    # shedding the miss first buys more admitted tokens per unit of
    # capacity; 0 restores hit-blind shedding.
    shed_outstanding: int = 64
    shed_hit_headroom: int = 16
    # prefix affinity: requests whose first ``affinity_span`` prompt
    # tokens hash alike PREFER the replica that last completed one (its
    # prefix index is warm there) — a preference only, never overriding
    # health, breaker, or drain avoidance; 0 disables.  Matches the
    # replica default block size so the span is exactly one cacheable
    # block.
    affinity_span: int = 8
    # disaggregation: prompts of at least ``migrate_min_prompt_len``
    # tokens route to a dedicated prefill replica, which ships the
    # finished KV (coded per ``migrate_codec``) to a decode replica and
    # hands the request off there; ``None`` disables migration and every
    # request runs colocated.  Set the threshold from
    # ``costs.migration_crossover_tokens`` so the per-request
    # migrate-vs-recompute decision is one integer compare against the
    # planner's crossover — short prompts never pay the hop.
    migrate_min_prompt_len: int | None = None
    migrate_codec: str = "f32"
    # workers + membership thresholds (match SupervisorConfig defaults)
    dispatchers: int = 4
    straggler_s: float = 1.0
    lease_s: float = 3.0
    slo_window_s: float = 10.0


@dataclasses.dataclass(frozen=True)
class FrontDoorResult:
    """One exactly-once result as the client sees it."""

    rid: int
    tokens: np.ndarray
    ttft_s: float  # arrival -> first token, queue + retries included
    rank: int  # the replica whose attempt won
    attempts: int  # launches it took (1 = clean first try)
    hedged: bool
    migrated: bool = False  # prefill ran on a prefill replica, KV shipped
    # replica-measured gaps between consecutive emitted tokens (len =
    # n_tokens - 1): the decode inter-token latency, free of front-door
    # queueing — what the disaggregation bench prices its p99 floor on
    intervals_s: tuple = ()


class ReplicaClient:
    """Front-door state for one replica process: endpoint, connection,
    outstanding count, breaker, and its own windowed-TTFT registry."""

    def __init__(self, rank: int, cfg: FrontDoorConfig):
        self.rank = rank
        self.cfg = cfg
        self.host: str | None = None  # guarded-by: _lock
        self.port: int | None = None  # guarded-by: _lock
        self.pid: int | None = None  # guarded-by: _lock
        self.role = "both"  # guarded-by: _lock (from the endpoint file)
        self.prefill_depth = 0  # guarded-by: _lock (replica-reported)
        self.conn: RpcConnection | None = None  # guarded-by: _lock
        self.outstanding = 0  # guarded-by: _lock
        self.strikes = 0  # guarded-by: _lock
        self.open_until = 0.0  # guarded-by: _lock (breaker horizon, _now)
        self.registry = MetricsRegistry()
        self.registry.windowed_histogram(
            "serve.ttft_ms", interval_s=cfg.slo_window_s / 10.0, intervals=10
        )
        self._lock = threading.Lock()

    def update_endpoint(
        self, host: str, port: int, pid: int, role: str = "both"
    ) -> None:
        # called from whichever dispatcher thread refreshes first, racing
        # connection() on other dispatchers — same lock, or a half-updated
        # endpoint can be dialed
        with self._lock:
            if (host, port, pid, role) == (
                self.host, self.port, self.pid, self.role
            ):
                return
            # a replaced process (same rank, new pid/port): drop the old
            # connection, the next attempt dials the new endpoint
            old, self.conn = self.conn, None
            self.host, self.port, self.pid = host, port, pid
            self.role = role
        if old is not None:
            old.close()

    def connection(self) -> RpcConnection:
        """The rank's live connection, dialing if needed.  The dial
        happens OUTSIDE the lock — a slow/unreachable endpoint must cost
        only the dialing thread, not every thread touching this client's
        breaker or outstanding count for connect_timeout_s."""
        with self._lock:
            if self.conn is not None and self.conn.dead is None:
                return self.conn
            host, port = self.host, self.port
        if host is None or port is None:
            raise RpcConnRefused(f"rank {self.rank}: no endpoint")
        conn = RpcConnection.connect(
            host, port, timeout_s=self.cfg.connect_timeout_s
        )
        with self._lock:
            if self.conn is not None and self.conn.dead is None:
                # lost a dial race: keep the winner, close ours
                loser = conn
            elif (host, port) != (self.host, self.port):
                # endpoint replaced mid-dial: the process we reached is
                # the stale one — fail this attempt, next one redials
                loser = conn
                conn = None
            else:
                self.conn = conn
                loser = None
            winner = self.conn
        if loser is not None:
            loser.close()
        if conn is None:
            raise RpcConnRefused(
                f"rank {self.rank}: endpoint replaced mid-dial"
            )
        return winner

    # breaker ----------------------------------------------------------------

    def breaker_open(self, now: float) -> bool:
        return now < self.open_until

    def strike(self, now: float, registry: MetricsRegistry) -> None:
        # dispatcher threads strike concurrently; unlocked, two strikes
        # can lose an increment and a breaker that should open stays shut
        with self._lock:
            self.strikes += 1
            opened = self.strikes >= self.cfg.breaker_strikes
            if opened:
                self.open_until = now + self.cfg.breaker_cooldown_s
                self.strikes = 0
        if opened:
            registry.counter("serve.breaker_opens").inc()
            record_event(
                "breaker_open", peer=self.rank,
                cooldown_s=self.cfg.breaker_cooldown_s,
            )

    def clear_strikes(self) -> None:
        with self._lock:
            self.strikes = 0

    def close(self) -> None:
        with self._lock:
            conn, self.conn = self.conn, None
        if conn is not None:
            conn.close()


class FrontDoor:
    """Route requests to replica processes; deliver exactly-once results.

    Usage::

        fd = FrontDoor(ctrl_dir, FrontDoorConfig()).start()
        for r in requests:
            fd.submit(r.rid, r.prompt, r.max_new_tokens)
        fd.wait_idle(timeout_s=60)
        fd.completed[rid].tokens  # np.int32, bitwise vs generate
        fd.close()
    """

    def __init__(self, dir: str, cfg: FrontDoorConfig | None = None):
        self.dir = dir
        self.cfg = cfg or FrontDoorConfig()
        self.metrics = MetricsRegistry()
        self.metrics.windowed_histogram(
            "serve.ttft_ms",
            interval_s=self.cfg.slo_window_s / 10.0, intervals=10,
        )
        # attempt latency drives the hedge trigger: a WINDOWED p99 so a
        # quiet hour ago can't mask a straggler now
        self.metrics.windowed_histogram(
            "serve.attempt_ms",
            interval_s=self.cfg.slo_window_s / 10.0, intervals=10,
        )
        self.membership = MembershipView(
            dir, straggler_s=self.cfg.straggler_s, lease_s=self.cfg.lease_s
        )
        self.clients: dict[int, ReplicaClient] = {}  # guarded-by: _lock
        self.completed: dict[int, FrontDoorResult] = {}  # guarded-by: _lock
        self.failed: dict[int, str] = {}  # guarded-by: _lock (FT_RPC_* code)
        self.shed_rids: list[int] = []  # guarded-by: _lock
        self._arrival: dict[int, float] = {}  # guarded-by: _lock
        self._attempt_seq: dict[int, int] = {}  # guarded-by: _lock
        # prefix affinity: first-block hash -> rank that last completed a
        # request carrying it (that replica's prefix index is warm)
        self._affinity: dict[int, int] = {}  # guarded-by: _lock
        self._rid_phash: dict[int, int] = {}  # guarded-by: _lock
        self._inflight: set[int] = set()  # guarded-by: _lock
        # destined role per inflight rid: shed accounting is per role so
        # a flood of long prompts filling the prefill tier can't shed
        # decode-bound traffic (and vice versa)
        self._inflight_role: dict[int, str] = {}  # guarded-by: _lock
        # arrival->first-token of a completed handoff, stamped when the
        # prefill replica reports the migration done; the collect
        # attempt's own ttft would otherwise overwrite the real one
        self._migration_ttft: dict[int, float] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._work: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # ---- lifecycle ---------------------------------------------------------

    def start(self) -> "FrontDoor":
        for i in range(self.cfg.dispatchers):
            t = threading.Thread(
                target=self._dispatch_loop, daemon=True,
                name=f"ft-frontdoor-{i}",
            )
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        self._stop.set()
        for _ in self._threads:
            self._work.put(None)
        for t in self._threads:
            t.join(timeout=2.0)
        with self._lock:
            clients = list(self.clients.values())  # a join timeout above
            # can leave a dispatcher alive and refreshing; don't iterate
            # the live dict under it
        for client in clients:
            client.close()

    # ---- discovery ---------------------------------------------------------

    def refresh(self) -> None:
        """Re-read the endpoint files; a torn or missing file simply
        leaves that rank unroutable until its writer finishes."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for name in sorted(names):
            if not (name.startswith("rpc_") and name.endswith(".json")):
                continue
            ep = read_control_json(os.path.join(self.dir, name))
            if ep is None:
                continue
            try:
                rank = int(ep["rank"])
                host, port, pid = ep["host"], int(ep["port"]), int(ep["pid"])
                role = str(ep.get("role", "both"))
            except (KeyError, ValueError, TypeError):
                continue
            # the insert races other dispatchers' refresh() calls AND
            # _routable's iteration — both under the same lock; the
            # endpoint update itself locks per client, outside ours
            with self._lock:
                client = self.clients.get(rank)
                if client is None:
                    client = self.clients[rank] = ReplicaClient(
                        rank, self.cfg
                    )
            client.update_endpoint(host, port, pid, role)

    def _routable(
        self, exclude=(), prefer=None, role="decode"
    ) -> "ReplicaClient | None":
        """Healthy first, then stragglers; least-outstanding within the
        tier; DEAD and breaker-open replicas never.  ``prefer`` names a
        rank to pick over the load balance IF it survives every health /
        breaker / exclusion filter into the healthy tier — affinity is a
        tiebreak inside the safe set, never a way back into it.

        ``role`` selects the routing tier: ``"decode"`` (plain and
        collect generates — decode and colocated replicas,
        least-outstanding) or ``"prefill"`` (migrate-flagged prefills —
        dedicated prefill replicas only, weighted by their reported
        intake queue depth plus our outstanding count, so a replica
        digesting a deep prefill backlog stops attracting more)."""
        self.refresh()
        states = {r: s.state for r, s in self.membership.poll().items()}
        now = _now()
        with self._lock:
            clients = list(self.clients.items())  # snapshot vs refresh()
        tiers: dict[str, list[ReplicaClient]] = {"healthy": [], "other": []}
        for rank, client in clients:
            if rank in exclude or client.breaker_open(now):
                continue
            if role == "prefill":
                if client.role != "prefill":
                    continue
            elif client.role == "prefill":
                # dedicated prefill replicas shed plain generates with a
                # "role" refusal — never route one there
                continue
            state = states.get(rank)
            if state == DEAD:
                continue
            key = "healthy" if state in (None, HEALTHY) else "other"
            tiers[key].append(client)
        if prefer is not None:
            for client in tiers["healthy"]:
                if client.rank == prefer:
                    self.metrics.counter("serve.affinity_routed").inc()
                    return client
            self.metrics.counter("serve.affinity_miss").inc()
        if role == "prefill":
            load = lambda c: (c.prefill_depth + c.outstanding, c.rank)
        else:
            load = lambda c: (c.outstanding, c.rank)
        for tier in (tiers["healthy"], tiers["other"]):
            if tier:
                return min(tier, key=load)
        return None

    # ---- intake ------------------------------------------------------------

    def submit(self, rid: int, prompt, max_new_tokens: int) -> bool:
        """Queue one request.  The arrival stamp is written exactly once
        here — a retried / hedged / re-routed request keeps it, so TTFT
        includes every queue and recovery second.  Returns False on an
        intake shed (accounted, never silently dropped)."""
        p = np.asarray(prompt, np.int32)
        span = self.cfg.affinity_span
        phash = None
        if span > 0 and len(p) > span:
            # hash exactly the first cacheable block span; prompts no
            # longer than it can't share a FULL cached block, so routing
            # them by affinity would buy nothing.  Computed BEFORE the
            # shed decision: whether this is a predicted hit decides how
            # much headroom it gets
            phash = zlib.crc32(p[:span].tobytes())
        # the destined role decides whose capacity this request consumes:
        # a long prompt heads for the prefill tier, so admitting or
        # shedding it is a PREFILL capacity decision — counting it
        # against decode capacity would let a heavy-prefill tail shed
        # decode-bound traffic it never competes with (and vice versa)
        role = "prefill" if (
            self.cfg.migrate_min_prompt_len is not None
            and len(p) >= self.cfg.migrate_min_prompt_len
        ) else "decode"
        with self._lock:
            inflight = sum(
                1 for r in self._inflight
                if self._inflight_role.get(r, "decode") == role
            )
            headroom = self.cfg.shed_hit_headroom
            hit = phash is not None and phash in self._affinity
            limit = self.cfg.shed_outstanding + (headroom if hit else 0)
            if inflight >= limit:
                self.metrics.counter("serve.shed").inc()
                self.metrics.counter(f"serve.shed_{role}").inc()
                if not hit and inflight < (
                    self.cfg.shed_outstanding + headroom
                ):
                    # a predicted hit at this load would have been
                    # admitted: this shed is the miss-first policy acting
                    self.metrics.counter("serve.shed_miss_first").inc()
                self.shed_rids.append(rid)
                record_event(
                    "serve_shed", rid=rid, where="frontdoor",
                    inflight=inflight, reason="FT_RPC_SHED",
                    predicted_hit=hit, role=role,
                )
                return False
            self._arrival.setdefault(rid, _now())
            self._inflight.add(rid)
            self._inflight_role[rid] = role
            if phash is not None:
                self._rid_phash[rid] = phash
        self._work.put((rid, p, int(max_new_tokens)))
        return True

    @property
    def idle(self) -> bool:
        with self._lock:
            return not self._inflight

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.idle:
                return True
            time.sleep(0.01)
        return self.idle

    # ---- the dispatch machinery --------------------------------------------

    def _dispatch_loop(self) -> None:
        while not self._stop.is_set():
            item = self._work.get()
            if item is None:
                return
            rid, prompt, max_new = item
            try:
                self._execute(rid, prompt, max_new)
            finally:
                with self._lock:
                    self._inflight.discard(rid)
                    self._inflight_role.pop(rid, None)

    def _next_attempt(self, rid: int) -> int:
        with self._lock:
            n = self._attempt_seq.get(rid, 0)
            self._attempt_seq[rid] = n + 1
            return n

    def _attempts_used(self, rid: int) -> int:
        with self._lock:
            return self._attempt_seq.get(rid, 0)

    def _hedge_delay_s(self) -> float | None:
        """``hedge_factor`` x the windowed p99 of attempt latency, once
        enough samples exist; None disables hedging this round."""
        if self.cfg.max_hedges <= 0:
            return None
        hist = self.metrics.windowed_histogram("serve.attempt_ms")
        if hist.window_count() < self.cfg.hedge_min_samples:
            return None
        p99_s = hist.window_percentile(0.99) / 1e3
        return max(self.cfg.hedge_floor_s, self.cfg.hedge_factor * p99_s)

    def _launch_attempt(
        self, client: ReplicaClient, payload: dict, timeout_s: float,
        resq: queue.Queue,
    ) -> None:
        """Fire one RPC on its own thread; the outcome (ok / typed error)
        lands on ``resq``.  Outstanding accounting is per replica and
        released whatever happens — under the client's lock, because
        concurrent attempt threads' unlocked `+=`/`-=` lose updates and
        a client that looks forever-busy (or forever-idle) skews the
        least-outstanding routing for the rest of the run."""
        with client._lock:
            client.outstanding += 1

        def _run():
            send_mono = _now()
            try:
                conn = client.connection()
                reply = conn.call(payload, timeout_s=timeout_s)
            except RpcError as e:
                resq.put(("err", e, client, send_mono))
            else:
                resq.put(("ok", reply, client, send_mono))
            finally:
                with client._lock:
                    client.outstanding -= 1

        threading.Thread(
            target=_run, daemon=True, name="ft-frontdoor-attempt"
        ).start()

    def _execute(self, rid: int, prompt: np.ndarray, max_new: int) -> None:
        cfg = self.cfg
        arrival = self._arrival[rid]
        deadline = arrival + cfg.request_timeout_s
        backoff = cfg.backoff_base_s
        avoid: set = set()  # ranks that drain-refused this rid
        # the planner decision, folded to one compare: prompts past the
        # calibrated crossover ship their KV, shorter ones never pay the
        # hop.  Flips off for the rest of THIS rid on any handoff (the
        # sequence now lives on the decode side — collect, don't re-ship)
        # or migrate failure (fall back to the colocated path).
        migrate = (
            cfg.migrate_min_prompt_len is not None
            and len(prompt) >= cfg.migrate_min_prompt_len
        )
        prefer_pin = None  # decode rank a completed handoff pinned us to
        while True:
            now = _now()
            if now >= deadline:
                self._fail(rid, RpcTimeout.code)
                return
            if self._attempts_used(rid) >= cfg.max_attempts:
                self._fail(rid, "FT_RPC_RETRIES")
                return
            with self._lock:
                phash = self._rid_phash.get(rid)
                prefer = self._affinity.get(phash) if phash is not None \
                    else None
            if prefer_pin is not None:
                prefer = prefer_pin
            client = None
            extra = None
            if migrate:
                pre = self._routable(exclude=avoid, role="prefill")
                tgt = self._routable(prefer=prefer, role="decode")
                if pre is not None and tgt is not None:
                    with tgt._lock:
                        host, port = tgt.host, tgt.port
                    if host is not None:
                        client = pre
                        extra = {
                            "migrate_to": {
                                "host": host, "port": int(port),
                                "rank": tgt.rank,
                            },
                            "codec": cfg.migrate_codec,
                        }
                if client is None:
                    # no dedicated prefill tier (or no decode target)
                    # routable right now: the colocated path still
                    # works — don't strand the request on a preference
                    migrate = False
            if client is None:
                client = self._routable(exclude=avoid, prefer=prefer)
            if client is None and avoid:
                # everyone left has drain-refused us: better a draining
                # replica (it may still be up) than nobody
                avoid.clear()
                client = self._routable()
            if client is None:
                # nobody routable right now (all dead / breaker-open):
                # back off inside the budget and look again
                _sleep(min(backoff, max(0.0, deadline - _now())))
                backoff = min(backoff * 2.0, cfg.backoff_cap_s)
                continue
            verdict = self._attempt_round(
                rid, prompt, max_new, client, deadline, extra=extra
            )
            kind = verdict[0]
            if kind == "done":
                return
            if kind == "handoff":
                # the prefill replica already emitted the first token and
                # the decode replica holds the sequence: the remaining
                # work is a collect generate there, which attaches to the
                # in-flight sequence through the replica's dedup path
                migrate = False
                prefer_pin = verdict[1]
                avoid.discard(verdict[1])
                self.metrics.counter("serve.migrations").inc()
                continue
            if kind == "migrate_failed":
                # the prefill replica aborted the handoff (ship failed or
                # the decode side refused) and released its export: fall
                # back to a plain colocated generate for this rid
                migrate = False
                self.metrics.counter("serve.migration_fallback").inc()
                record_event("serve_migration_fallback", rid=rid,
                             code=verdict[1])
                continue
            if kind == "drain":
                # the replica is leaving, not failing: re-route at once,
                # and not back to the drainer
                avoid.add(verdict[1])
                self.metrics.counter("serve.drains").inc()
                record_event("serve_drain_reroute", rid=rid,
                             peer=verdict[1])
                continue
            # transport failure or replica shed: count a retry, back off
            self.metrics.counter("serve.retries").inc()
            record_event(
                "serve_retry", rid=rid, code=verdict[1],
                attempts=self._attempts_used(rid),
            )
            _sleep(min(backoff, max(0.0, deadline - _now())))
            backoff = min(backoff * 2.0, cfg.backoff_cap_s)

    def _attempt_round(
        self, rid, prompt, max_new, client: ReplicaClient, deadline: float,
        extra: dict | None = None,
    ):
        """One primary attempt plus up to ``max_hedges`` hedges; first
        usable outcome wins.  Returns ``("done",)``, ``("drain", rank)``,
        ``("retry", code)``, or — for a migrate-flagged attempt
        (``extra`` carries ``migrate_to`` + ``codec``) —
        ``("handoff", decode_rank)`` / ``("migrate_failed", code)``.
        Migrate attempts never hedge: a twin would ship a second KV copy
        for the dedup path to discard."""
        cfg = self.cfg
        resq: queue.Queue = queue.Queue()
        hedged = False
        outstanding = 0
        tried = []

        def _fire(target: ReplicaClient):
            nonlocal outstanding
            attempt = self._next_attempt(rid)
            remaining = deadline - _now()
            payload = {
                "kind": "generate",
                "rid": rid,
                "attempt": attempt,
                "prompt": [int(t) for t in prompt],
                "max_new_tokens": max_new,
                "deadline_in_s": round(remaining, 6),
            }
            if extra:
                payload.update(extra)
            timeout = min(cfg.attempt_timeout_s, max(remaining, 1e-3))
            self._launch_attempt(target, payload, timeout, resq)
            tried.append(target.rank)
            outstanding += 1

        _fire(client)
        hedge_delay = None if extra else self._hedge_delay_s()
        hedges = 0
        last_code = RpcTimeout.code
        while outstanding:
            remaining = deadline - _now()
            if remaining <= 0:
                return ("retry", RpcTimeout.code)
            wait = remaining
            if hedge_delay is not None and hedges < cfg.max_hedges:
                wait = min(wait, hedge_delay)
            try:
                kind, payload, rep, send_mono = resq.get(timeout=wait)
            except queue.Empty:
                if hedge_delay is not None and hedges < cfg.max_hedges:
                    twin = self._routable(exclude=tried)
                    if twin is not None and (
                        self._attempts_used(rid) < cfg.max_attempts
                    ):
                        hedges += 1
                        hedged = True
                        self.metrics.counter("serve.hedges").inc()
                        record_event(
                            "serve_hedge", rid=rid, primary=client.rank,
                            hedge=twin.rank,
                            delay_ms=round(hedge_delay * 1e3, 3),
                        )
                        _fire(twin)
                        continue
                    # nobody to hedge to: wait out the primary
                    hedge_delay = None
                continue
            outstanding -= 1
            if kind == "err":
                err: RpcError = payload
                last_code = err.code
                rep.strike(_now(), self.metrics)
                continue  # a hedge twin may still deliver
            self.metrics.histogram("serve.attempt_ms").observe(
                (_now() - send_mono) * 1e3
            )
            reply = payload
            if reply.get("prefill_depth") is not None:
                # piggybacked intake depth: the signal the prefill tier's
                # queue-depth-weighted routing balances on
                with rep._lock:
                    rep.prefill_depth = int(reply["prefill_depth"])
            if reply.get("drain"):
                return ("drain", rep.rank)
            if reply.get("handoff"):
                # migration done: first token is out, the sequence lives
                # on the decode replica.  Stamp the REAL ttft now — the
                # collect attempt's ttft_s would measure the attach, not
                # the prefill
                ttft_s = (send_mono - self._arrival[rid]) + float(
                    reply["ttft_s"]
                )
                with self._lock:
                    self._migration_ttft.setdefault(rid, ttft_s)
                rep.clear_strikes()
                record_event(
                    "serve_migration_handoff", rid=rid,
                    prefill=rep.rank, decode=int(reply["decode_rank"]),
                    ttft_ms=round(ttft_s * 1e3, 3),
                )
                return ("handoff", int(reply["decode_rank"]))
            if not reply.get("ok"):
                code = reply.get("code", "FT_RPC_ERROR")
                if reply.get("migrate_failed"):
                    return ("migrate_failed", code)
                last_code = code
                if code == RpcShed.code:
                    record_event("serve_shed_upstream", rid=rid,
                                 peer=rep.rank)
                continue
            rep.clear_strikes()
            self._deliver(rid, reply, rep, send_mono, hedged)
            return ("done",)
        return ("retry", last_code)

    # ---- elasticity (scale events from the lease driver) -------------------

    def reassign_affinity(self, old_rank: int, new_rank: int) -> int:
        """Point every prefix-affinity entry at ``old_rank`` to
        ``new_rank`` — the routing half of a prefix-warm drain handoff:
        the successor pre-warmed its index from the drainer's export, so
        the requests that used to hit the drainer should hit it.  Returns
        how many entries moved."""
        with self._lock:
            moved = [
                ph for ph, r in self._affinity.items() if r == old_rank
            ]
            for ph in moved:
                self._affinity[ph] = new_rank
        if moved:
            self.metrics.counter("serve.affinity_handoff").inc(len(moved))
            record_event(
                "serve_affinity_handoff", old=int(old_rank),
                new=int(new_rank), entries=len(moved),
            )
        return len(moved)

    def forget_replica(self, rank: int) -> None:
        """Drop a cleanly-departed replica: close its connection, remove
        its client, and clear any affinity entries still naming it (a
        stale preference is harmless — ``_routable`` falls back — but a
        clean exit should not leave one).  A crashed replica needs no
        call: membership marks it DEAD and routing skips it."""
        with self._lock:
            client = self.clients.pop(rank, None)
            stale = [
                ph for ph, r in self._affinity.items() if r == rank
            ]
            for ph in stale:
                del self._affinity[ph]
        if client is not None:
            client.close()
        record_event("serve_forget_replica", rank=int(rank),
                     stale_affinity=len(stale))

    # ---- results -----------------------------------------------------------

    def _deliver(
        self, rid: int, reply: dict, client: ReplicaClient,
        send_mono: float, hedged: bool,
    ) -> None:
        """First writer wins; a hedge race's loser is counted, dropped."""
        arrival = self._arrival[rid]
        ttft_s = (send_mono - arrival) + float(reply["ttft_s"])
        with self._lock:
            mig_ttft = self._migration_ttft.pop(rid, None)
        if mig_ttft is not None:
            # the first token came out of the prefill replica during the
            # handoff round; this reply's ttft_s timed the decode-side
            # attach, which is not what the client experienced
            ttft_s = mig_ttft
        result = FrontDoorResult(
            rid=rid,
            tokens=np.asarray(reply["tokens"], np.int32),
            ttft_s=ttft_s,
            rank=int(reply["rank"]),
            attempts=self._attempts_used(rid),
            hedged=hedged,
            migrated=mig_ttft is not None,
            intervals_s=tuple(
                float(d) for d in reply.get("intervals_s", ())
            ),
        )
        with self._lock:
            if rid in self.completed:
                self.metrics.counter("serve.duplicate_results").inc()
                record_event("serve_duplicate_result", rid=rid,
                             peer=client.rank)
                return
            self.completed[rid] = result
            phash = self._rid_phash.pop(rid, None)
            if phash is not None:
                # the winner's prefix index now holds this first block —
                # send the next request sharing it back there
                self._affinity[phash] = result.rank
        self.metrics.counter("serve.completed").inc()
        self.metrics.histogram("serve.ttft_ms").observe(ttft_s * 1e3)
        client.registry.histogram("serve.ttft_ms").observe(ttft_s * 1e3)
        record_event(
            "serve_result", rid=rid, peer=result.rank,
            attempts=result.attempts, hedged=hedged,
            ttft_ms=round(ttft_s * 1e3, 3), n_tokens=len(result.tokens),
        )

    def _fail(self, rid: int, code: str) -> None:
        with self._lock:
            if rid in self.completed:
                return
            self.failed[rid] = code
            self._rid_phash.pop(rid, None)
            self._migration_ttft.pop(rid, None)
        self.metrics.counter("serve.failed").inc()
        record_event("serve_failed", rid=rid, code=code)

    # ---- export ------------------------------------------------------------

    def snapshots(self) -> dict:
        """Label -> registry snapshot: the front door's aggregate plus
        one per replica (front-door-observed TTFT — queue and retries
        included, the SLO the client actually experiences)."""
        out = {"frontdoor": self.metrics.snapshot()}
        with self._lock:
            clients = sorted(self.clients.items())
        for rank, client in clients:
            out[f"fd_{rank:05d}"] = client.registry.snapshot()
        return out

    def prometheus(self) -> str:
        from ..obs import prometheus_exposition

        return prometheus_exposition(self.snapshots())

    def write_metrics(self, dir: str | None = None) -> list:
        """Drop ``metrics_frontdoor.json`` + ``metrics_fd_{rank}.json``
        into the control dir so ``obs metrics DIR --prom`` exports the
        per-replica windowed TTFT-p99 gauges and the retry / hedge /
        shed / drain counters next to the replica processes' own
        snapshots."""
        import json

        dir = dir or self.dir
        paths = []
        for label, snap in self.snapshots().items():
            path = os.path.join(dir, f"metrics_{label}.json")
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
            paths.append(path)
        return paths
