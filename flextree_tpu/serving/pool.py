"""Elastic replica pool: serving replicas under runtime supervision.

The training runtime already knows how to keep a job alive through
mid-run failures (``flextree_tpu.runtime``: heartbeat/lease membership,
step watchdogs, shrink-to-survivors).  Serving reuses exactly those
pieces over a pool of :class:`~flextree_tpu.serving.engine.ServingEngine`
replicas:

- every replica runs a :class:`~flextree_tpu.runtime.Supervisor`
  heartbeat (rank = replica index, step = scheduling rounds, EWMA = round
  duration) into a shared directory; a
  :class:`~flextree_tpu.runtime.MembershipView` classifies replicas
  healthy / straggler / dead from lease age — the SAME thresholds and
  ``_wall`` clock injection the chaos harness proved against real
  SIGKILL/SIGSTOP;
- each replica's scheduling round runs under a
  :class:`~flextree_tpu.runtime.StepWatchdog` deadline, so a hung decode
  (wedged backend, stuck compile) becomes a typed ``StepTimeout`` instead
  of stalling the whole pool;
- a **dead replica drains**: every request it had in flight (queued or
  resident) goes back to the pool queue and is re-routed to a survivor —
  the pool *degrades* (fewer replicas, longer queues) instead of failing.
  Generated-but-undelivered tokens die with the replica; the re-routed
  request recomputes from its prompt on the survivor (at-least-once
  execution, exactly-once results — the pool records a completion only
  once per request id, and greedy decoding makes the recompute
  bit-identical).

Death is declared conservatively but drains decisively: a watchdog
timeout marks the replica *suspect*, and a suspect engine is never
stepped again (the abandoned watchdog worker may still be executing
inside it — re-entering would race two threads through one engine).  The
drain fires when the lease expires or after ``max_suspect_strikes``
grace rounds, whichever comes first; a transient stall therefore costs
the replica (capacity lost, requests recomputed) but never corrupts it —
the same timeout-vs-death escalation ``fit`` uses, tilted toward safety.

Replicas here are in-process objects (the pool is single-host, like the
chaos harness's launcher); the heartbeat protocol is already
cross-process, so promoting replicas to real processes was transport
work, not a redesign — that tier now exists: :mod:`.replica_main` runs
one engine per real OS process behind the :mod:`.rpc` frame protocol,
and :mod:`.frontdoor` re-implements this pool's route/drain/exactly-once
rules over TCP with deadlines, retries, hedging, and circuit breakers
(proven under kill chaos by ``tools/rpc_chaos.py`` → ``RPC_CHAOS.json``).
This in-process pool remains the zero-serialization single-host fast
path and the reference semantics the RPC tier is held to.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque

from . import engine as _engine_mod

from ..obs import MetricsRegistry, dump_current, record_event
from ..runtime import (
    DEAD,
    MembershipView,
    StepTimeout,
    StepWatchdog,
    Supervisor,
    SupervisorConfig,
)
from ..utils.logging import get_logger
from .batcher import Request
from .engine import ServingEngine

__all__ = ["ReplicaFailed", "PoolConfig", "ReplicaPool"]

log = get_logger("flextree.serving")


class ReplicaFailed(RuntimeError):
    """A replica's engine raised mid-round — the crash signature (vs the
    hang signature, which is ``StepTimeout``)."""

    code = "FT_REPLICA_FAILED"


@dataclasses.dataclass(frozen=True)
class PoolConfig:
    """``heartbeat_dir`` is the shared beat directory; lease/straggler
    budgets mirror :class:`~flextree_tpu.runtime.SupervisorConfig`.
    ``step_timeout_s=None`` disables the watchdog (steps run inline).
    ``parallel_rounds`` steps the live replicas on concurrent threads
    instead of sequentially: each engine is still entered by exactly one
    thread per round (the single-thread-per-engine contract holds), but
    rounds overlap — XLA releases the GIL during execution, so on a
    multi-core host N replicas buy real pooled throughput, not just N
    queues.  Routing, harvest, and the reap stay on the caller's thread
    either way."""

    heartbeat_dir: str
    step_timeout_s: float | None = None
    interval_s: float = 0.05
    straggler_s: float = 1.0
    lease_s: float = 3.0
    max_suspect_strikes: int = 3
    parallel_rounds: bool = False


class _Replica:
    def __init__(self, rank: int, engine: ServingEngine, cfg: PoolConfig):
        self.rank = rank
        self.engine = engine
        self.supervisor = Supervisor(
            SupervisorConfig(
                rank=rank,
                dir=cfg.heartbeat_dir,
                interval_s=cfg.interval_s,
                straggler_s=cfg.straggler_s,
                lease_s=cfg.lease_s,
            )
        ).start()
        self.watchdog = StepWatchdog()
        self.alive = True
        self.released = False  # arbiter-controlled graceful removal
        self.strikes = 0
        self.rounds = 0
        self.assigned: dict = {}  # rid -> Request (the re-route copy)
        self.fail_mode: str | None = None  # test/chaos hook

    def step_once(self, timeout_s: float | None) -> None:
        self.watchdog.run(self._round, timeout_s=timeout_s, step=self.rounds)

    def _round(self):
        if self.fail_mode == "hang":
            # the in-process stand-in for a wedged decode: block until the
            # watchdog abandons this worker thread
            time.sleep(3600.0)
        if self.fail_mode == "raise":
            raise ReplicaFailed(
                f"{ReplicaFailed.code}: replica {self.rank} killed"
            )
        t0 = time.monotonic()
        self.engine.step()
        self.rounds += 1
        self.supervisor.record_step(self.rounds, time.monotonic() - t0)

    def shutdown(self) -> None:
        self.supervisor.stop()
        self.watchdog.close()


class ReplicaPool:
    """Route requests over supervised replicas; degrade on death.

    ``engines`` are pre-built replicas (their pool/slot configs may
    differ); the pool owns routing, supervision, drain, and the
    once-per-rid completion record.  Membership is elastic under arbiter
    control: :meth:`add_replica` joins a warmed engine mid-flight (burst
    spin-up) and :meth:`release_replica` gracefully drains one back out
    (chips returned to training) — docs/ARBITER.md.
    """

    def __init__(self, engines, cfg: PoolConfig):
        if not engines:
            raise ValueError("a replica pool needs at least one engine")
        self.cfg = cfg
        self.replicas = [
            _Replica(r, eng, cfg) for r, eng in enumerate(engines)
        ]
        self.membership = MembershipView(
            cfg.heartbeat_dir,
            straggler_s=cfg.straggler_s,
            lease_s=cfg.lease_s,
            configured=len(self.replicas),
        )
        self.queue: deque = deque()
        self.completed: dict = {}
        self.rejected: list = []  # (rid, reason) refused by a replica
        self.kills: list = []
        # pool-level accounting lives in a registry; report() is a view
        # over its snapshot, and the legacy attributes below are
        # properties reading the same counters (one bookkeeping path)
        self.metrics = MetricsRegistry()

    @property
    def submitted(self) -> int:
        return int(self.metrics.counter("pool.submitted").value)

    @property
    def reroutes(self) -> int:
        return int(self.metrics.counter("pool.reroutes").value)

    # ---- intake ------------------------------------------------------------

    def submit(self, request: Request) -> None:
        # stamp arrival at POOL intake (same injectable clock the engines
        # use): a re-routed request keeps its original arrival, so TTFT
        # includes the time it sat on the dead replica
        if request.arrival_s == 0.0:
            request = dataclasses.replace(
                request, arrival_s=_engine_mod._now()
            )
        self.queue.append(request)
        self.metrics.counter("pool.submitted").inc()
        self.metrics.gauge("pool.queue_depth").set(len(self.queue))

    @property
    def alive_replicas(self) -> list:
        return [r for r in self.replicas if r.alive]

    @property
    def degraded(self) -> bool:
        # a RELEASED replica is capacity the arbiter took back on purpose,
        # not a degradation — only deaths count
        return any(not r.alive and not r.released for r in self.replicas)

    @property
    def idle(self) -> bool:
        return not self.queue and all(
            r.engine.idle for r in self.alive_replicas
        )

    # ---- elastic membership (arbiter control) ------------------------------

    def add_replica(self, engine: ServingEngine) -> int:
        """Join a (warmed) engine to the pool as a new replica — the
        arbiter's burst spin-up.  The replica starts heartbeating
        immediately and is routable from the next ``step()``; warm the
        engine BEFORE adding it, or the first routed requests eat its
        compiles."""
        rank = len(self.replicas)
        self.replicas.append(_Replica(rank, engine, self.cfg))
        self.metrics.counter("pool.replica_adds").inc()
        self.metrics.gauge("pool.alive").set(len(self.alive_replicas))
        record_event("replica_add", replica=rank)
        log.info("replica %d joined the pool (%d alive)",
                 rank, len(self.alive_replicas))
        return rank

    def release_replica(self, rank: int) -> list:
        """Gracefully remove a replica — the arbiter's drain-on-return.

        Unlike :meth:`_drain` (the DEATH path) this is planned: the same
        harvest + exactly-once re-route of in-flight requests, but no
        forensic dump and no degradation mark — released capacity is the
        arbiter taking chips back, not a failure.  The engine is never
        stepped again.  Returns the re-routed request ids."""
        r = self.replicas[rank]
        if not r.alive:
            return []
        lost = self._remove(r, released=True)
        self.metrics.counter("pool.releases").inc()
        record_event(
            "replica_release", replica=rank,
            rerouted=[q.rid for q in lost],
            survivors=len(self.alive_replicas),
        )
        log.info(
            "replica %d released: %d in-flight requests re-routed to %d "
            "survivors", rank, len(lost), len(self.alive_replicas),
        )
        return [q.rid for q in lost]

    def _remove(self, r: _Replica, *, released: bool) -> list:
        """The shared removal body for BOTH exits (death drain / planned
        release): stop the heartbeat, harvest completions that raced in
        (dict reads are GIL-atomic; the engine itself is never
        re-entered), and re-queue the rest for exactly-once re-routing
        (greedy recompute is bit-identical).  Returns the lost requests."""
        r.alive = False
        r.released = released
        r.supervisor.stop()
        self._harvest(r)
        lost = [
            req for rid, req in r.assigned.items()
            if rid not in self.completed
        ]
        for req in lost:
            self.queue.append(req)
        self.metrics.counter("pool.reroutes").inc(len(lost))
        self.metrics.gauge("pool.alive").set(len(self.alive_replicas))
        return lost

    # ---- chaos hook --------------------------------------------------------

    def kill(self, rank: int, mode: str = "hang") -> None:
        """Simulate replica death: its heartbeat stops (a real process
        death's signature) and its rounds hang, raise, or — ``mode=
        "silent"`` — keep stepping until the lease verdict (the zombie
        whose heartbeat died first)."""
        if mode not in ("hang", "raise", "silent"):
            raise ValueError(f"unknown kill mode {mode!r}")
        r = self.replicas[rank]
        r.supervisor.stop()
        if mode != "silent":
            r.fail_mode = mode
        self.kills.append({"rank": rank, "mode": mode})
        record_event("kill", replica=rank, mode=mode)

    # ---- the pool round ----------------------------------------------------

    def _route(self) -> None:
        """Hand queued requests to the least-loaded alive replica —
        fewest outstanding requests, free cache blocks as the tiebreak
        (free blocks ALONE lag reality: a routed request reserves nothing
        until its replica's next admission pass); keep a copy for drain."""
        while self.queue:
            live = [r for r in self.alive_replicas if r.strikes == 0]
            if not live:
                return
            req = self.queue.popleft()
            if req.rid in self.completed:
                continue  # re-routed twin already finished elsewhere
            best = min(
                live,
                key=lambda r: (
                    len(r.assigned),
                    -r.engine.batcher.allocator.num_free,
                ),
            )
            if not best.engine.submit(req):
                # refused (oversized for that replica's pool, bad sampling
                # config): record at POOL level — a silently vanished
                # request is the one outcome a serving layer may never have
                reason = (
                    best.engine.batcher.rejected[-1][1]
                    if best.engine.batcher.rejected else "rejected"
                )
                self.rejected.append((req.rid, reason))
                self.metrics.counter("pool.rejected").inc()
                log.warning("request %d rejected by replica %d: %s",
                            req.rid, best.rank, reason)
                continue
            best.assigned[req.rid] = req
        self.metrics.gauge("pool.queue_depth").set(len(self.queue))

    def step(self) -> None:
        """One pool round: route, step every live replica under its
        watchdog (sequentially, or concurrently with
        ``parallel_rounds``), harvest completions, reap the dead."""
        self._route()
        stepping = []
        for r in self.alive_replicas:
            if r.strikes > 0:
                # suspect: the abandoned watchdog worker may still be
                # inside engine.step — never re-enter the engine; each
                # skipped round is a strike toward the grace limit
                r.strikes += 1
                continue
            stepping.append(r)
        if self.cfg.parallel_rounds and len(stepping) > 1:
            outcomes = {}

            def _run(rep):
                try:
                    rep.step_once(self.cfg.step_timeout_s)
                except Exception as e:  # settled on the caller's thread
                    outcomes[rep.rank] = e

            threads = [
                threading.Thread(
                    target=_run, args=(r,), name=f"ft-pool-round-{r.rank}"
                )
                for r in stepping
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            # an exception the suspect machinery doesn't model must
            # PROPAGATE exactly as it would from the sequential loop —
            # swallowing it would harvest a broken replica as healthy
            unexpected = [
                e for e in outcomes.values()
                if not isinstance(e, (StepTimeout, ReplicaFailed))
            ]
            if unexpected:
                raise unexpected[0]
            for r in stepping:
                self._settle(r, outcomes.get(r.rank))
        else:
            for r in stepping:
                try:
                    r.step_once(self.cfg.step_timeout_s)
                except (StepTimeout, ReplicaFailed) as e:
                    self._settle(r, e)
                else:
                    self._settle(r, None)
        self._reap()

    def _settle(self, r: _Replica, exc) -> None:
        """Classify one replica's round outcome (caller's thread — the
        completed/strike bookkeeping is never touched concurrently)."""
        if exc is None:
            self._harvest(r)
        elif isinstance(exc, StepTimeout):
            r.strikes = 1
            record_event("replica_suspect", replica=r.rank, why="timeout")
            log.warning("replica %d round timed out; suspect", r.rank)
        else:
            r.strikes = self.cfg.max_suspect_strikes
            record_event("replica_suspect", replica=r.rank, why="raise")
            log.warning("replica %d raised; awaiting verdict", r.rank)

    def _harvest(self, r: _Replica) -> None:
        for rid, done in list(r.engine.completed.items()):
            if rid not in self.completed:
                self.completed[rid] = done
            r.engine.completed.pop(rid)
            r.assigned.pop(rid, None)

    def _reap(self) -> None:
        """Drain the dead: lease expiry is authoritative for EVERY
        replica (a silently-dead heartbeat means the process is gone even
        if the in-process stand-in still steps); strike-out only for
        suspects."""
        status = self.membership.poll()
        for r in self.replicas:
            if not r.alive:
                continue
            peer = status.get(r.rank)
            lease_dead = peer is not None and peer.state == DEAD
            struck_out = r.strikes >= self.cfg.max_suspect_strikes
            if lease_dead or struck_out:
                self._drain(r, "lease" if lease_dead else "strikes")

    def _drain(self, r: _Replica, why: str) -> None:
        lost = self._remove(r, released=False)
        self.metrics.counter("pool.drains").inc()
        record_event(
            "drain", replica=r.rank, why=why, rerouted=[q.rid for q in lost],
            survivors=len(self.alive_replicas),
        )
        # engine strike-out / lease death is a failure path: guarantee the
        # forensic dump (ring context incl. the suspect/kill events)
        dump_current(f"replica_{why}", replica=r.rank, rerouted=len(lost))
        log.warning(
            "replica %d dead (%s): re-routing %d in-flight requests to "
            "%d survivors",
            r.rank, why, len(lost), len(self.alive_replicas),
        )

    def run_until_idle(self, max_rounds: int = 100_000) -> dict:
        for _ in range(max_rounds):
            if self.idle:
                break
            if not self.alive_replicas and self.queue:
                raise RuntimeError(
                    "no replicas left alive with requests still queued"
                )
            self.step()
        else:
            raise RuntimeError(f"pool not idle after {max_rounds} rounds")
        return self.report()

    def report(self) -> dict:
        """The pool's accounting — a view over its metrics registry (the
        legacy keys read the same counters) plus each replica engine's
        own registry snapshot."""
        self.metrics.gauge("pool.alive").set(len(self.alive_replicas))
        return {
            "replicas": len(self.replicas),
            "alive": len(self.alive_replicas),
            "released": sum(1 for r in self.replicas if r.released),
            "degraded": self.degraded,
            "submitted": self.submitted,
            "completed": len(self.completed),
            "rejected": list(self.rejected),
            "reroutes": self.reroutes,
            "kills": list(self.kills),
            "metrics": self.metrics.snapshot(),
            "replica_metrics": {
                r.rank: r.engine.report() for r in self.replicas
            },
        }

    def shutdown(self) -> None:
        for r in self.replicas:
            r.shutdown()

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
