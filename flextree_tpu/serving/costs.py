"""Paged-decode cost estimates: the serving planner's predicted side.

The training stack prices every collective before it runs and PR 12
closed the loop on the residuals; serving had measured histograms
(round/TTFT) but no predictions to hold them against.  This module
supplies the predicted half so the engine can emit
``serve_round_measured`` spans — measured decode round (and prefill)
time beside a cost estimate priced from the SAME calibratable constants
the rest of the planner uses (``TpuCostParams.bwd_GFLOPs`` as the
achievable compute throughput, ``reduce_bw_GBps`` as the HBM-bound
byte-stream rate).

The estimate is deliberately first-order: dense projection FLOPs per
decoded token plus the attention walk's K/V byte traffic over the batch
causal frontier (the paged pools are read once per round up to the
frontier — exactly the quantity the fused kernel's win shrinks with,
BENCH_PAGED.json).  It does not model dispatch overlap or sampling-host
time; that is what the residual loop is FOR — drift between this
estimate and the measured rounds is the serving-side feedback signal,
per-phase attributable like the training residuals (compute-bound vs
byte-bound terms are separate fields of the prediction).
"""

from __future__ import annotations

__all__ = [
    "decode_round_flops",
    "decode_round_bytes",
    "predict_decode_round_us",
    "predict_prefill_us",
    "kv_migration_elems",
    "predict_migration_us",
    "plan_migration",
    "migration_crossover_tokens",
]


def _dense_flops_per_token(cfg) -> float:
    """Dense (projection + MLP + LM head) multiply-accumulate FLOPs to
    decode one token: 2·weights touched."""
    d, ff = cfg.d_model, cfg.d_ff
    per_layer = 4 * d * d + 2 * d * ff  # qkvo + in/out MLP
    return 2.0 * (cfg.n_layers * per_layer + d * cfg.vocab_size)


def decode_round_flops(cfg, n_active: int, max_len: int) -> float:
    """FLOPs for one decode round over ``n_active`` slots attending up to
    ``max_len`` positions (the batched walk runs to the batch frontier)."""
    attn = 4.0 * max_len * cfg.d_model * cfg.n_layers  # QK^T + AV per token
    return n_active * (_dense_flops_per_token(cfg) + attn)


def decode_round_bytes(cfg, pcfg, n_active: int, frontier_blocks: int) -> float:
    """K/V pool bytes streamed in one decode round: every active slot
    reads the pools up to the batch frontier (blocks × block_size
    positions × K and V × heads × head_dim × itemsize × layers)."""
    try:
        import numpy as np

        itemsize = np.dtype(cfg.dtype).itemsize
    except TypeError:
        itemsize = 4
    per_pos = 2 * cfg.n_heads * cfg.head_dim * itemsize * cfg.n_layers
    return float(n_active * frontier_blocks * pcfg.block_size * per_pos)


def predict_decode_round_us(
    cfg, pcfg, n_active: int, max_len: int, params=None
) -> dict:
    """Predicted decode-round time, split into the two attributable
    phases: ``compute_us`` (dense+attention FLOPs over the calibrated
    achievable throughput) and ``bytes_us`` (K/V streaming at the
    HBM-bound byte rate).  Returns ``{"predicted_us", "compute_us",
    "bytes_us"}`` — the per-term decomposition the serving residual
    stream attributes drift against."""
    from ..parallel.overlap import resolve_bwd_GFLOPs
    from ..planner.calibrate import default_params

    if params is None:
        params = default_params()
    if n_active <= 0:
        return {"predicted_us": 0.0, "compute_us": 0.0, "bytes_us": 0.0}
    frontier_blocks = min(
        (max(int(max_len), 1) + pcfg.block_size - 1) // pcfg.block_size,
        pcfg.blocks_per_seq,
    )
    gflops = max(resolve_bwd_GFLOPs(params), 1e-6)
    compute_us = decode_round_flops(cfg, n_active, max_len) / (gflops * 1e3)
    bytes_us = decode_round_bytes(cfg, pcfg, n_active, frontier_blocks) / (
        max(params.reduce_bw_GBps, 1e-6) * 1e3
    )
    return {
        "predicted_us": compute_us + bytes_us,
        "compute_us": compute_us,
        "bytes_us": bytes_us,
    }


def predict_prefill_us(cfg, prompt_len: int, params=None,
                       cached_tokens: int = 0) -> float:
    """Predicted prefill compute time for one prompt (the TTFT floor a
    non-queued request could hit): dense FLOPs for every prompt token
    plus the causal attention triangle.

    ``cached_tokens`` is the prefix-cache hit length: those tokens pay
    neither dense FLOPs nor their attention rows, but the suffix still
    attends over the FULL prefix — so the attention term is the triangle
    minus the cached sub-triangle (``t² − c²``), not ``(t − c)²``.
    Pricing a hit as a full prefill would poison the serving residual
    stream the feedback loop pools."""
    from ..parallel.overlap import resolve_bwd_GFLOPs
    from ..planner.calibrate import default_params

    if params is None:
        params = default_params()
    t = max(int(prompt_len), 1)
    c = min(max(int(cached_tokens), 0), t - 1)
    dense = _dense_flops_per_token(cfg) * (t - c)
    attn = 2.0 * (t * t - c * c) * cfg.d_model * cfg.n_layers
    gflops = max(resolve_bwd_GFLOPs(params), 1e-6)
    return (dense + attn) / (gflops * 1e3)


def kv_migration_elems(cfg, pcfg, prompt_len: int) -> int:
    """f32 elements per K-or-V tensor of one migrated sequence: the block
    footprint of the prompt (``blocks_for``, whole blocks — migration
    ships the tail block too) × block positions × heads × head_dim.  One
    sequence ships ``2 * n_layers`` such tensors."""
    n_blocks = pcfg.blocks_for(max(int(prompt_len), 1))
    return n_blocks * pcfg.block_size * cfg.n_heads * cfg.head_dim


def predict_migration_us(cfg, pcfg, prompt_len: int, codec="f32",
                         params=None) -> dict:
    """Predicted time to ship one sequence's KV to a decode replica: the
    α–β wire term (DCN latency + codec wire bytes over DCN bandwidth)
    plus, for lossy codecs, the encode+decode pass over the f32 payload
    at the calibrated codec throughput.  Returns ``{"predicted_us",
    "wire_us", "codec_us", "bytes_on_wire"}`` — the same per-term
    decomposition style as :func:`predict_decode_round_us`, so migration
    residuals stay phase-attributable."""
    from ..ops.quantize import get_codec
    from ..planner.calibrate import default_params

    if params is None:
        params = default_params()
    c = get_codec(codec)
    elems = kv_migration_elems(cfg, pcfg, prompt_len)
    n_tensors = 2 * cfg.n_layers
    bytes_on_wire = n_tensors * c.wire_bytes(elems)
    wire_us = params.dcn.latency_us + bytes_on_wire / (
        max(params.dcn.bandwidth_GBps, 1e-6) * 1e3
    )
    codec_us = 0.0
    if c.hop_cost:
        codec_us = 2.0 * (n_tensors * elems * 4) / (
            max(params.codec_bw_GBps, 1e-6) * 1e3
        )
    return {
        "predicted_us": wire_us + codec_us,
        "wire_us": wire_us,
        "codec_us": codec_us,
        "bytes_on_wire": bytes_on_wire,
    }


def plan_migration(cfg, pcfg, prompt_len: int, codec="f32",
                   params=None) -> dict:
    """The migrate-vs-local decision for one request: ship the quantized
    KV (``predict_migration_us``) or recompute the prefill on the decode
    replica (``predict_prefill_us``)?  Prefill FLOPs grow quadratically
    in the prompt while the wire term grows linearly, so short prompts
    recompute (never pay the hop) and long prompts ship.  Returns
    ``{"migrate", "migrate_us", "recompute_us", "bytes_on_wire"}``."""
    mig = predict_migration_us(cfg, pcfg, prompt_len, codec, params)
    recompute_us = predict_prefill_us(cfg, prompt_len, params)
    return {
        "migrate": mig["predicted_us"] < recompute_us,
        "migrate_us": mig["predicted_us"],
        "recompute_us": recompute_us,
        "bytes_on_wire": mig["bytes_on_wire"],
    }


def migration_crossover_tokens(cfg, pcfg, codec="f32", params=None):
    """Smallest prompt length at which shipping the KV beats recomputing
    the prefill (``None`` if no prompt admissible under ``pcfg.max_len``
    ever crosses).  The front door uses this as its routing threshold so
    the per-request decision is one integer compare, and the SERVING doc
    quotes it as the crossover the calibration constants imply."""
    from ..planner.calibrate import default_params

    if params is None:
        params = default_params()
    for t in range(1, pcfg.max_len + 1):
        if plan_migration(cfg, pcfg, t, codec, params)["migrate"]:
            return t
    return None
