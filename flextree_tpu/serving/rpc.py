"""Length-prefixed, CRC-trailered RPC framing for the serving front door.

The serving stack's control plane is already cross-process (heartbeats,
leases, coordination all ride :mod:`~flextree_tpu.runtime.ctrlfile`'s
trailered files on a shared directory); this module gives the REQUEST
path the same discipline over a TCP byte stream.  A frame is::

    [4-byte big-endian payload length N][N payload bytes]

where the payload reuses the control-file format exactly — one compact
JSON line followed by a ``{"len": ..., "crc32": ...}`` trailer line
(:func:`~flextree_tpu.runtime.ctrlfile.control_trailer`) — so the same
property holds on the wire that holds on disk: truncation or corruption
at ANY byte offset parse-refuses, it never half-parses into a plausible
message.  A violated frame raises :class:`RpcTornFrame`; since a byte
stream past a framing violation cannot be re-synchronized, the owning
connection is dead from that point (the caller's retry machinery treats
it like a reset).

Error taxonomy (the RPC extension of the bring-up layer's ``FT_INIT_*``
codes, pinned in ``tests/test_rpc.py`` the way ``FT_INIT_TIMEOUT`` is
pinned in ``tests/test_launch.py``):

- ``FT_RPC_TIMEOUT`` (:class:`RpcTimeout`) — no response inside the
  deadline (attempt budget or propagated request deadline);
- ``FT_RPC_CONN_REFUSED`` (:class:`RpcConnRefused`) — connect refused,
  reset, or EOF: the replica process is gone or never there;
- ``FT_RPC_TORN_FRAME`` (:class:`RpcTornFrame`) — framing violation:
  short read, CRC/length mismatch, or an oversized-length header;
- ``FT_RPC_SHED`` (:class:`RpcShed`) — the request was refused under
  admission pressure (front door or replica), loudly and immediately.

:class:`RpcConnection` multiplexes one socket: every request frame
carries a ``corr`` correlation id, responses may arrive in ANY order
(continuous batching finishes requests out of submission order), and a
single reader thread routes each response to the waiter that owns its
``corr``.  All sends go through one write lock so concurrent callers
never interleave partial frames.

Everything here is host-side stdlib networking — no JAX — so the whole
protocol is unit-testable against an in-memory ``socket.socketpair()``.
"""

from __future__ import annotations

import base64
import binascii
import json
import socket
import struct
import threading

from ..runtime.ctrlfile import control_trailer

__all__ = [
    "RpcError",
    "RpcTimeout",
    "RpcConnRefused",
    "RpcTornFrame",
    "RpcShed",
    "MAX_FRAME_BYTES",
    "MAX_KV_CHUNK_BYTES",
    "encode_frame",
    "decode_frame_payload",
    "send_frame",
    "recv_frame",
    "chunk_blob",
    "join_chunks",
    "RpcConnection",
]

#: refuse any frame claiming more than this many payload bytes: a torn
#: or adversarial length header must fail fast, not allocate gigabytes
#: and stall the reader until the peer's OOM kills it
MAX_FRAME_BYTES = 8 * 1024 * 1024

#: raw bytes per KV-transfer chunk: base64 inflates 4/3 and the JSON
#: envelope adds a trailer, so 2 MiB raw rides well under the 8 MiB
#: frame cap while keeping a multi-block migration to a handful of
#: frames — a streamed KV transfer is many bounded frames, never one
#: frame sized to the payload
MAX_KV_CHUNK_BYTES = 2 * 1024 * 1024

_LEN = struct.Struct(">I")


class RpcError(RuntimeError):
    """Base of the RPC failure taxonomy (``code`` mirrors the bring-up
    layer's ``FT_INIT_*`` convention; every subclass's code is pinned in
    ``tests/test_rpc.py``)."""

    code = "FT_RPC_ERROR"

    def __str__(self) -> str:  # the code leads, grep-stable
        base = super().__str__()
        return f"{self.code}: {base}" if base else self.code


class RpcTimeout(RpcError):
    """No response inside the deadline (attempt or propagated)."""

    code = "FT_RPC_TIMEOUT"


class RpcConnRefused(RpcError):
    """Connect refused / reset / EOF — the peer process is gone."""

    code = "FT_RPC_CONN_REFUSED"


class RpcTornFrame(RpcError):
    """Framing violation: short read, CRC mismatch, oversized header.
    The owning connection cannot be trusted past this point."""

    code = "FT_RPC_TORN_FRAME"


class RpcShed(RpcError):
    """Refused under admission pressure — loud, immediate, retryable
    elsewhere (or surfaced to the caller as the availability trade)."""

    code = "FT_RPC_SHED"


# --------------------------------------------------------------------------
# framing
# --------------------------------------------------------------------------


def encode_frame(payload: dict) -> bytes:
    """``payload`` -> one wire frame (length prefix + body + trailer)."""
    body = (json.dumps(payload, sort_keys=True) + "\n").encode("utf-8")
    trailer = (
        json.dumps(control_trailer(body), sort_keys=True) + "\n"
    ).encode("utf-8")
    raw = body + trailer
    if len(raw) > MAX_FRAME_BYTES:
        raise RpcTornFrame(
            f"refusing to encode {len(raw)}-byte frame "
            f"(max {MAX_FRAME_BYTES})"
        )
    return _LEN.pack(len(raw)) + raw


def decode_frame_payload(raw: bytes) -> dict:
    """Verify and parse one frame's payload bytes (body + trailer).

    The SAME acceptance rule as ``runtime.ctrlfile``: the trailer is the
    last newline-terminated line and must agree byte-for-byte with the
    body it certifies; anything else is :class:`RpcTornFrame` — there is
    no legacy trailer-less fallback to hide a clean truncation in."""
    if not raw.endswith(b"\n"):
        raise RpcTornFrame("frame missing terminal newline (truncated)")
    stripped = raw.rstrip(b"\n")
    nl = stripped.rfind(b"\n")
    if nl < 0:
        raise RpcTornFrame("frame has no trailer line")
    body, trailer_line = raw[: nl + 1], stripped[nl + 1 :]
    try:
        trailer = json.loads(trailer_line)
    except ValueError as e:
        raise RpcTornFrame(f"unparseable trailer: {e}") from e
    if not isinstance(trailer, dict):
        raise RpcTornFrame("trailer is not an object")
    expect = control_trailer(body)
    if (
        trailer.get("len") != expect["len"]
        or trailer.get("crc32") != expect["crc32"]
    ):
        raise RpcTornFrame(
            f"trailer mismatch: wire {trailer.get('len')}/"
            f"{trailer.get('crc32')} vs computed {expect['len']}/"
            f"{expect['crc32']}"
        )
    try:
        payload = json.loads(body)
    except ValueError as e:
        raise RpcTornFrame(f"unparseable body under valid CRC: {e}") from e
    if not isinstance(payload, dict):
        raise RpcTornFrame("frame body is not an object")
    return payload


def send_frame(sock: socket.socket, payload: dict) -> None:
    """Encode and send one frame; connection-level failures map to
    :class:`RpcConnRefused`."""
    try:
        sock.sendall(encode_frame(payload))
    except socket.timeout as e:
        raise RpcTimeout(f"send stalled: {e}") from e
    except OSError as e:
        raise RpcConnRefused(f"send failed: {e}") from e


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        try:
            chunk = sock.recv(min(n - got, 1 << 16))
        except socket.timeout as e:
            raise RpcTimeout(f"recv stalled at {got}/{n} bytes: {e}") from e
        except OSError as e:
            raise RpcConnRefused(f"recv failed: {e}") from e
        if not chunk:
            if got == 0:
                raise RpcConnRefused("peer closed (EOF at frame boundary)")
            raise RpcTornFrame(f"peer closed mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(
    sock: socket.socket, *, max_frame: int = MAX_FRAME_BYTES
) -> dict:
    """Read one frame; raises the typed taxonomy, never returns garbage.

    ``RpcConnRefused`` at a frame BOUNDARY is a clean close; everything
    mid-frame is torn.  An oversized length header is refused before a
    single payload byte is read."""
    header = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length == 0 or length > max_frame:
        raise RpcTornFrame(
            f"refusing frame header claiming {length} bytes "
            f"(max {max_frame})"
        )
    return decode_frame_payload(_recv_exact(sock, length))


def chunk_blob(blob: bytes, *, chunk_bytes: int = MAX_KV_CHUNK_BYTES) -> list:
    """Split a binary payload into base64 strings, each from at most
    ``chunk_bytes`` raw bytes, for streaming over JSON frames.  Always at
    least one chunk (an empty payload is one empty chunk) so a transfer
    has a well-defined ``total`` and a final frame to hang the metadata
    on."""
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    if not blob:
        return [""]
    return [
        base64.b64encode(blob[i : i + chunk_bytes]).decode("ascii")
        for i in range(0, len(blob), chunk_bytes)
    ]


def join_chunks(chunks) -> bytes:
    """Reassemble :func:`chunk_blob` output; undecodable base64 is a
    framing-class violation (:class:`RpcTornFrame`) — the CRC check in
    ``migration.unpack_kv`` guards the CONTENT, this guards the
    transport encoding."""
    try:
        return b"".join(
            base64.b64decode(c.encode("ascii"), validate=True)
            for c in chunks
        )
    except (binascii.Error, UnicodeEncodeError, AttributeError) as e:
        raise RpcTornFrame(f"undecodable KV chunk: {e}") from e


# --------------------------------------------------------------------------
# the multiplexed connection
# --------------------------------------------------------------------------


class RpcConnection:
    """One socket, many in-flight calls, responses in any order.

    ``call()`` assigns a correlation id, sends under the write lock, and
    blocks on its own waiter slot; the reader thread routes each inbound
    frame to the waiter owning its ``corr``.  When the stream dies (EOF,
    reset, torn frame) EVERY outstanding waiter fails with the same
    typed error — the front door's retry loop treats the batch of
    failures like the connection reset it is.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        # corr -> {event, reply|error}
        self._waiters: dict[int, dict] = {}  # guarded-by: _lock
        self._next_corr = 0  # guarded-by: _lock
        self._dead: RpcError | None = None  # guarded-by: _lock
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="ft-rpc-reader"
        )
        self._reader.start()

    @classmethod
    def connect(
        cls, host: str, port: int, *, timeout_s: float = 1.0
    ) -> "RpcConnection":
        try:
            sock = socket.create_connection((host, port), timeout=timeout_s)
        except socket.timeout as e:
            raise RpcTimeout(f"connect to {host}:{port}: {e}") from e
        except OSError as e:
            raise RpcConnRefused(f"connect to {host}:{port}: {e}") from e
        sock.settimeout(None)  # per-call deadlines live on the waiters
        return cls(sock)

    @property
    def dead(self) -> RpcError | None:
        return self._dead

    def _read_loop(self) -> None:
        while True:
            try:
                payload = recv_frame(self._sock)
            except RpcError as e:
                self._fail_all(e)
                return
            corr = payload.get("corr")
            with self._lock:
                waiter = self._waiters.pop(corr, None)
            if waiter is not None:
                waiter["reply"] = payload
                waiter["event"].set()
            # an unmatched corr (waiter timed out and left) is dropped:
            # the replica-side idempotency store makes the orphaned
            # result safe to lose

    def _fail_all(self, err: RpcError) -> None:
        with self._lock:
            if self._dead is None:
                self._dead = err
            waiters, self._waiters = self._waiters, {}
        for waiter in waiters.values():
            waiter["error"] = err
            waiter["event"].set()

    def call(self, payload: dict, *, timeout_s: float) -> dict:
        """Send ``payload`` (a ``corr`` id is stamped in) and wait for
        the matching response; :class:`RpcTimeout` when the deadline
        lapses, the connection's fatal error when it died instead."""
        if self._dead is not None:
            raise self._dead
        event = threading.Event()
        waiter: dict = {"event": event, "reply": None, "error": None}
        with self._lock:
            corr = self._next_corr
            self._next_corr += 1
            self._waiters[corr] = waiter
        framed = dict(payload, corr=corr)
        try:
            # the blocking send under _wlock is the design: the write
            # lock IS the frame serializer (partial frames from two
            # callers must never interleave), it is held for exactly one
            # sendall, and no other lock ever nests inside it
            with self._wlock:
                send_frame(self._sock, framed)  # concurrency: ok — see above
        except RpcError as e:
            with self._lock:
                self._waiters.pop(corr, None)
            self._fail_all(e)
            raise
        if not event.wait(timeout_s):
            with self._lock:
                self._waiters.pop(corr, None)
            raise RpcTimeout(
                f"no response for corr={corr} within {timeout_s:.3f}s"
            )
        if waiter["error"] is not None:
            raise waiter["error"]
        return waiter["reply"]

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
