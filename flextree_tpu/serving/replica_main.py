"""One serving replica as a real process: TCP front, engine loop, drain.

``python -m flextree_tpu.serving.replica_main --rank R --dir CTRL ...``
boots a :class:`~flextree_tpu.serving.engine.ServingEngine` behind the
:mod:`.rpc` frame protocol and registers it in the shared control
directory the rest of the runtime already uses:

- an **endpoint file** ``rpc_{rank:05d}.json`` (host, port, pid) written
  with the CRC-trailer discipline, the front door's discovery source;
- the existing :class:`~flextree_tpu.runtime.supervisor.Supervisor`
  **heartbeat**, so :class:`MembershipView` classifies this process
  HEALTHY/STRAGGLER/DEAD exactly like a training rank — a SIGKILL'd
  replica leaves a lease expiry, a SIGSTOP'd one a stale-but-leased beat;
- the **flight recorder** (``flight_{rank:05d}.jsonl`` + a
  ``metrics_{rank:05d}.json`` snapshot on exit), so every dedup, shed,
  and drain is a forensic event and ``obs metrics DIR --prom`` exports
  the replica's counters per real process.

Threading: sockets are owned by daemon threads (one acceptor, one reader
per connection) that do nothing but parse frames and push work onto an
intake queue; the **engine loop is the only thread that touches the
engine** (the engine is not thread-safe, and single ownership keeps the
decode path identical to the in-process oracle).  The loop alternates
draining intake with ``engine.step()`` and answers each waiter on the
connection its request arrived on.

Exactly-once results: the engine's ``completed`` dict keyed by rid IS
the idempotency store.  A retried or hedged attempt for a finished rid
is answered from the store without re-execution; an attempt for an
in-flight rid attaches as an extra waiter on the same execution.  Either
way the tokens are computed once, so duplicated delivery can never fork
the sequence (and greedy decode stays bitwise vs ``generate``).

Graceful drain (SIGTERM): stop accepting, answer every queued and
in-flight request with a ``drain`` refusal (the front door re-queues to
survivors — PR 9's re-route rule, now across a wire), flush the flight
record, exit 0.

**Roles** (``--role {prefill,decode,both}``): a ``prefill`` replica only
accepts migrate-flagged generates — it runs the prompt's prefill, emits
the first token, and ships the KV blocks to the decode replica named in
the request (``kv_chunk`` stream + ``kv_admit`` handshake over the same
framed RPC, blocks held until the ack); a ``decode`` replica runs the
normal engine loop and additionally lands migrated sequences
(``engine.admit_migrated`` — verify, scatter, decode from there);
``both`` (the default) is the colocated engine unchanged.  The endpoint
file carries the role so the front door can tier its routing.

Chaos knobs (env, used by ``tools/rpc_chaos.py`` and
``tools/bench_disagg.py``; OFF by default):

- ``FT_RPC_TEAR_EVERY=k`` — corrupt a byte inside every k-th response
  frame's payload (length header intact, so the stream stays aligned
  and the client's CRC check is what catches it);
- ``FT_RPC_DECODE_SLEEP=s`` — stretch every decode round by ``s``
  seconds, widening the window for a mid-decode SIGKILL / SIGSTOP;
- ``FT_RPC_PREFILL_SLEEP=s`` — stretch every prefill by ``s`` seconds
  per computed prompt token
  (applied to ALL roles equally): scales the prefill:decode cost ratio
  toward production shapes so the colocated prefill stall the disagg
  bench measures is visible at tiny-model CPU scale.
"""

from __future__ import annotations

import argparse
import os
import queue
import signal
import socket
import sys
import threading
import time

from ..obs import record_event
from ..runtime.ctrlfile import write_control_json
from ..runtime.supervisor import Supervisor, SupervisorConfig
from ..utils.logging import get_logger
from .migration import MigrationError
from .rpc import (
    RpcConnection,
    RpcError,
    chunk_blob,
    encode_frame,
    join_chunks,
    recv_frame,
)

__all__ = ["ENDPOINT_FMT", "ROLES", "ReplicaConfig", "ReplicaServer", "main"]

log = get_logger("flextree.serving")

ENDPOINT_FMT = "rpc_{rank:05d}.json"

#: replica roles; ``serve.role`` gauge encodes them in this tuple's order
ROLES = ("both", "prefill", "decode")

#: chaos env knobs (documented in docs/FAILURE_MODEL.md §RPC failures)
FT_RPC_TEAR_EVERY_ENV = "FT_RPC_TEAR_EVERY"
FT_RPC_DECODE_SLEEP_ENV = "FT_RPC_DECODE_SLEEP"
FT_RPC_PREFILL_SLEEP_ENV = "FT_RPC_PREFILL_SLEEP"


class ReplicaConfig:
    """Plumbing for one replica process (model config rides separately)."""

    def __init__(
        self,
        rank: int,
        dir: str,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        max_pending: int = 64,
        idle_poll_s: float = 0.02,
        role: str = "both",
    ):
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; one of {ROLES}")
        self.rank = int(rank)
        self.dir = dir
        self.host = host
        self.port = int(port)
        self.max_pending = int(max_pending)
        self.idle_poll_s = float(idle_poll_s)
        self.role = role


class ReplicaServer:
    """The accept/parse/execute/respond machine around one engine.

    Usable in-process for tests (``start()`` / ``stop()``) and as the
    body of the real process entrypoint (:func:`main`).
    """

    def __init__(self, engine, cfg: ReplicaConfig):
        self.engine = engine
        self.cfg = cfg
        self._intake: queue.Queue = queue.Queue()
        # rid -> [(sock, corr, attempt, recv_mono), ...]: every attempt
        # waiting on that rid's single execution
        self._waiters: dict[int, list] = {}
        # rid -> recv stamp of the attempt that started the execution
        # (TTFT is measured from first receipt, not from a later retry)
        self._recv_stamp: dict[int, float] = {}
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._stop = threading.Event()
        self.draining = threading.Event()
        self.drained = threading.Event()
        # optional drain hook (e.g. the prefix handoff export), invoked
        # on the ENGINE thread after refusals, before ``drained`` is set
        # — whatever it writes is durably on disk before any drain ack
        self.on_drain = None
        self.port: int | None = None
        self._sent_frames = 0
        tear = os.environ.get(FT_RPC_TEAR_EVERY_ENV)
        self._tear_every = int(tear) if tear else 0
        sleep = os.environ.get(FT_RPC_DECODE_SLEEP_ENV)
        self._decode_sleep = float(sleep) if sleep else 0.0
        psleep = os.environ.get(FT_RPC_PREFILL_SLEEP_ENV)
        if psleep:
            # applied to EVERY role (colocated included): the knob scales
            # the prefill:decode ratio, it must not bias the comparison
            engine.chaos_prefill_sleep_s = float(psleep)
        # migration state — engine-thread only (like the engine itself):
        # rid -> buffered inbound KV chunks, and cached client
        # connections to decode replicas for outbound shipping
        self._kv_buf: dict[int, list] = {}
        self._mig_conns: dict[tuple, RpcConnection] = {}
        engine.metrics.gauge("serve.role").set(ROLES.index(cfg.role))

    # ---- lifecycle ---------------------------------------------------------

    def start(self, *, engine_thread: bool = True) -> "ReplicaServer":
        """Bind, publish the endpoint file, start the socket threads (and
        the engine loop as a thread unless the caller runs
        :meth:`run_engine_loop` itself — the process entrypoint keeps it
        on the main thread so SIGTERM lands between bytecodes there)."""
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((self.cfg.host, self.cfg.port))
        self._listener.listen(32)
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        os.makedirs(self.cfg.dir, exist_ok=True)
        path = os.path.join(
            self.cfg.dir, ENDPOINT_FMT.format(rank=self.cfg.rank)
        )
        write_control_json(
            self.cfg.dir, path,
            {
                "rank": self.cfg.rank,
                "pid": os.getpid(),
                "host": self.cfg.host,
                "port": self.port,
                "role": self.cfg.role,
                "wall": time.time(),
            },
        )
        t = threading.Thread(
            target=self._accept_loop, daemon=True, name="ft-rpc-accept"
        )
        t.start()
        self._threads.append(t)
        if engine_thread:
            t = threading.Thread(
                target=self.run_engine_loop, daemon=True,
                name="ft-rpc-engine",
            )
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self._close_conns()
        for c in self._mig_conns.values():
            c.close()
        self._mig_conns.clear()
        for t in self._threads:
            t.join(timeout=2.0)
        # a connection the acceptor admitted DURING the close sweep above
        # would otherwise survive with a client blocked on it until its
        # attempt timeout — sweep again now that the acceptor has joined
        self._close_conns()

    def _close_conns(self) -> None:
        for conn in list(self._conns):
            # shutdown first: close() alone does not wake a reader
            # thread blocked in recv on another thread's stack
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def initiate_drain(self) -> None:
        """Signal-handler entry: flip the flag, let the engine loop do
        the actual refusals on its own thread/iteration."""
        self.draining.set()

    # ---- socket side (daemon threads; never touch the engine) --------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            conn.settimeout(None)
            self._conns.append(conn)
            t = threading.Thread(
                target=self._read_loop, args=(conn,), daemon=True,
                name="ft-rpc-conn",
            )
            t.start()
            self._threads.append(t)

    def _read_loop(self, conn: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                payload = recv_frame(conn)
            except RpcError:
                # client went away or sent a torn frame: this connection
                # is unrecoverable (byte stream can't resync) — drop it;
                # the engine loop skips dead-socket waiters on respond
                try:
                    conn.close()
                except OSError:
                    pass
                return
            self._intake.put((conn, payload, time.monotonic()))

    # ---- engine side (ONE thread owns the engine) --------------------------

    def run_engine_loop(self) -> None:
        """Drain intake, step the engine, answer completions — until
        stopped or drained.  The only frame-sending thread, so responses
        on a shared connection never interleave."""
        while not self._stop.is_set():
            if self.draining.is_set():
                self._drain()
                return
            busy = not self.engine.idle
            self._pump_intake(block=not busy)
            if not self.engine.idle:
                if self._decode_sleep:
                    time.sleep(self._decode_sleep)
                self.engine.step()
            self._flush_completions()

    def _pump_intake(self, *, block: bool) -> None:
        timeout = self.cfg.idle_poll_s if block else 0.0
        while True:
            try:
                conn, payload, recv_mono = self._intake.get(timeout=timeout)
            except queue.Empty:
                return
            timeout = 0.0  # only the first get() blocks
            self._handle(conn, payload, recv_mono)

    def _prefill_depth(self) -> int:
        """Prefill backlog right now: migrate work still parked in intake
        (handling is synchronous on the engine thread, so intake IS the
        queue).  Exported as a gauge and piggybacked on every reply a
        prefill replica sends — the front door's dispatch weight."""
        depth = self._intake.qsize()
        self.engine.metrics.gauge("serve.prefill_queue_depth").set(depth)
        return depth

    def _handle(self, conn, payload: dict, recv_mono: float) -> None:
        corr = payload.get("corr")
        kind = payload.get("kind")
        if kind == "ping":
            self._respond(
                conn, corr,
                {"ok": True, "rank": self.cfg.rank, "role": self.cfg.role,
                 "prefill_depth": self._prefill_depth()},
            )
            return
        if kind in ("kv_chunk", "kv_admit"):
            self._handle_kv(conn, corr, kind, payload, recv_mono)
            return
        if kind != "generate":
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_ERROR",
                 "error": f"unknown kind {kind!r}"},
            )
            return
        rid = int(payload["rid"])
        attempt = int(payload.get("attempt", 0))
        if self.draining.is_set():
            self._respond(
                conn, corr, {"ok": False, "drain": True, "rid": rid}
            )
            return
        if payload.get("migrate_to") is not None:
            if self.cfg.role == "decode":
                # mis-routed: decode replicas never run the prefill half
                self._respond(
                    conn, corr,
                    {"ok": False, "code": "FT_RPC_SHED", "rid": rid,
                     "reason": "role"},
                )
                return
            self._handle_migrate(conn, corr, payload, recv_mono)
            return
        if self.cfg.role == "prefill":
            # a prefill replica holds no decode slots for the fleet: a
            # plain generate here would silently recreate the colocated
            # stall disaggregation exists to remove
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_SHED", "rid": rid,
                 "reason": "role"},
            )
            return
        # deadline propagation: the front door sends the REMAINING budget
        # (monotonic clocks have no cross-process epoch, so the wire
        # carries a duration, stamped against our clock at receipt)
        deadline = payload.get("deadline_in_s")
        if deadline is not None and float(deadline) <= 0.0:
            self.engine.metrics.counter("serve.deadline_refused").inc()
            record_event(
                "serve_deadline_refused", rid=rid, attempt=attempt,
            )
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_TIMEOUT", "rid": rid},
            )
            return
        # ---- the idempotency store: engine.completed keyed by rid ----
        done = self.engine.completed.get(rid)
        if done is not None:
            self.engine.metrics.counter("serve.dedup_hits").inc()
            record_event("serve_dedup", rid=rid, attempt=attempt,
                         stage="completed")
            self._respond(conn, corr, self._result_payload(rid, attempt))
            return
        if rid in self._waiters:
            # in-flight: attach this attempt to the single execution
            self.engine.metrics.counter("serve.dedup_hits").inc()
            record_event("serve_dedup", rid=rid, attempt=attempt,
                         stage="inflight")
            self._waiters[rid].append((conn, corr, attempt))
            return
        # ---- replica-side admission: bounded backlog -----------------
        backlog = len(self._waiters)
        if backlog >= self.cfg.max_pending:
            self.engine.metrics.counter("serve.shed").inc()
            record_event(
                "serve_shed", rid=rid, attempt=attempt, where="replica",
                backlog=backlog,
            )
            self._respond(
                conn, corr, {"ok": False, "code": "FT_RPC_SHED", "rid": rid}
            )
            return
        import numpy as np

        from .batcher import Request

        req = Request(
            rid=rid,
            prompt=np.asarray(payload["prompt"], np.int32),
            max_new_tokens=int(payload["max_new_tokens"]),
            arrival_s=recv_mono,  # replica-clock stamp; the front door
            # composes total TTFT from its own arrival stamp
        )
        if not self.engine.submit(req):
            self.engine.metrics.counter("serve.shed").inc()
            record_event(
                "serve_shed", rid=rid, attempt=attempt, where="replica",
                reason="rejected",
            )
            self._respond(
                conn, corr, {"ok": False, "code": "FT_RPC_SHED", "rid": rid}
            )
            return
        self._waiters[rid] = [(conn, corr, attempt)]
        self._recv_stamp[rid] = recv_mono

    # ---- migration: the prefill half (runs on the engine thread) -----------

    def _handle_migrate(self, conn, corr, payload: dict,
                        recv_mono: float) -> None:
        """Prefill + ship + reply: the whole export→ship→admit-or-refuse→
        release handshake, synchronous on the engine thread (a prefill
        replica's engine has no resident decodes to starve; the intake
        backlog is the queue depth the front door weighs)."""
        import numpy as np

        from .batcher import Request

        rid = int(payload["rid"])
        attempt = int(payload.get("attempt", 0))
        to = payload["migrate_to"]
        codec = str(payload.get("codec", "f32"))
        deadline = payload.get("deadline_in_s")
        if deadline is not None and float(deadline) <= 0.0:
            self.engine.metrics.counter("serve.deadline_refused").inc()
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_TIMEOUT", "rid": rid},
            )
            return
        req = Request(
            rid=rid,
            prompt=np.asarray(payload["prompt"], np.int32),
            max_new_tokens=int(payload["max_new_tokens"]),
            arrival_s=recv_mono,
        )
        t0 = time.monotonic()
        try:
            out = self.engine.prefill_for_migration(req, codec=codec)
        except MigrationError as e:
            self._respond(
                conn, corr,
                {"ok": False, "code": MigrationError.code, "rid": rid,
                 "error": str(e), "migrate_failed": True},
            )
            return
        if out is None:  # pool cannot hold the prompt right now
            self.engine.metrics.counter("serve.shed_prefill").inc()
            record_event("serve_shed", rid=rid, attempt=attempt,
                         where="replica", role="prefill",
                         reason="export_blocked")
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_SHED", "rid": rid,
                 "reason": "export_blocked",
                 "prefill_depth": self._prefill_depth()},
            )
            return
        remaining = None
        if deadline is not None:
            remaining = float(deadline) - (time.monotonic() - recv_mono)
        ship_timeout = max(min(10.0 if remaining is None else remaining,
                               10.0), 0.5)
        try:
            reply = self._ship_kv(to, rid, attempt, payload, out,
                                  timeout_s=ship_timeout)
        except (RpcError, OSError, KeyError, TypeError, ValueError) as e:
            # receiver unreachable, died mid-stream, or spoke garbage:
            # ABORT — release our export, let the front door retry
            self.engine.release_exported(rid, acked=False)
            self.engine.metrics.counter("serve.migration_ship_failed").inc()
            record_event("serve_migration_ship_failed", rid=rid,
                         error=str(e)[:120])
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_CONN_REFUSED", "rid": rid,
                 "migrate_failed": True, "error": str(e)[:120]},
            )
            return
        if not reply.get("ok") or not reply.get("admitted"):
            # clean refusal from the decode side (capacity or poisoned):
            # same abort discipline, different loudness
            self.engine.release_exported(rid, acked=False)
            self.engine.metrics.counter("serve.migration_ship_refused").inc()
            record_event("serve_migration_ship_refused", rid=rid,
                         code=reply.get("code"))
            self._respond(
                conn, corr,
                {"ok": False,
                 "code": str(reply.get("code", MigrationError.code)),
                 "rid": rid, "migrate_failed": True},
            )
            return
        # ACK: the decode side owns a verified copy — NOW the blocks go
        self.engine.release_exported(rid, acked=True)
        ship_ms = (time.monotonic() - t0) * 1e3
        self.engine.metrics.histogram("serve.migration_ms").observe(ship_ms)
        record_event(
            "serve_migration_send", rid=rid,
            to_rank=int(to.get("rank", -1)), codec=codec,
            bytes=len(out["blob"]), ms=round(ship_ms, 3),
        )
        self._respond(
            conn, corr,
            {"ok": True, "rid": rid, "attempt": attempt,
             "rank": self.cfg.rank, "handoff": True,
             "decode_rank": int(to.get("rank", -1)),
             "ttft_s": round(out["ttft_s"], 6),
             "prefill_depth": self._prefill_depth()},
        )

    def _ship_kv(self, to: dict, rid: int, attempt: int, payload: dict,
                 out: dict, *, timeout_s: float) -> dict:
        """Stream the packed KV to the decode replica: bounded
        ``kv_chunk`` frames, then the ``kv_admit`` frame carrying the
        meta, the first token, and the request — the receiver's
        admit-or-refuse comes back as this call's reply."""
        key = (str(to["host"]), int(to["port"]))
        conn = self._mig_conns.get(key)
        if conn is None or conn.dead is not None:
            conn = RpcConnection.connect(
                key[0], key[1], timeout_s=min(timeout_s, 2.0)
            )
            self._mig_conns[key] = conn
        chunks = chunk_blob(out["blob"])
        try:
            for i, c in enumerate(chunks[:-1]):
                ack = conn.call(
                    {"kind": "kv_chunk", "rid": rid, "seq": i, "chunk": c},
                    timeout_s=timeout_s,
                )
                if not ack.get("ok"):
                    return ack
            return conn.call(
                {
                    "kind": "kv_admit",
                    "rid": rid,
                    "attempt": attempt,
                    "seq": len(chunks) - 1,
                    "total": len(chunks),
                    "chunk": chunks[-1],
                    "meta": out["meta"],
                    "first_token": out["first_token"],
                    "prompt": [int(t) for t in payload["prompt"]],
                    "max_new_tokens": int(payload["max_new_tokens"]),
                },
                timeout_s=timeout_s,
            )
        except RpcError:
            self._mig_conns.pop(key, None)
            raise

    # ---- migration: the decode half (runs on the engine thread) ------------

    def _handle_kv(self, conn, corr, kind: str, payload: dict,
                   recv_mono: float) -> None:
        """Receive a KV transfer: buffer ``kv_chunk`` frames, then on
        ``kv_admit`` reassemble, verify, and land the sequence
        (admit-or-refuse — never a queue: the prefill side is holding
        blocks against our answer)."""
        import numpy as np

        from .batcher import Request

        rid = int(payload["rid"])
        if self.draining.is_set() or self.cfg.role == "prefill":
            self._kv_buf.pop(rid, None)
            self._respond(
                conn, corr,
                {"ok": False, "drain": self.draining.is_set(), "rid": rid,
                 "code": "FT_RPC_SHED", "reason": "role"
                 if self.cfg.role == "prefill" else "drain"},
            )
            return
        if kind == "kv_chunk":
            buf = self._kv_buf.setdefault(rid, [])
            # a runaway stream must not buffer unbounded bytes: cap at
            # what MAX_FRAME_BYTES-bounded chunks can legitimately need
            # for one pool's worth of blocks
            if len(buf) >= 64:
                self._kv_buf.pop(rid, None)
                self._respond(
                    conn, corr,
                    {"ok": False, "code": MigrationError.code, "rid": rid,
                     "error": "chunk stream exceeds buffer cap"},
                )
                return
            buf.append((int(payload["seq"]), str(payload["chunk"])))
            self._respond(conn, corr, {"ok": True, "rid": rid,
                                       "seq": int(payload["seq"])})
            return
        # ---- kv_admit: reassemble + verify + admit -------------------
        parts = self._kv_buf.pop(rid, [])
        parts.append((int(payload["seq"]), str(payload["chunk"])))
        total = int(payload.get("total", len(parts)))
        seqs = [s for s, _ in parts]
        if sorted(seqs) != list(range(total)):
            self._respond(
                conn, corr,
                {"ok": False, "code": MigrationError.code, "rid": rid,
                 "error": f"chunk sequence {sorted(seqs)} != 0..{total - 1}"},
            )
            return
        # idempotent re-send (the prefill side retried after a lost ack):
        # the sequence is already ours — ack again, never double-admit
        inflight = {r.rid for r in self.engine.batcher.inflight_requests()}
        if rid in self.engine.completed or rid in inflight:
            self.engine.metrics.counter("serve.dedup_hits").inc()
            record_event("serve_dedup", rid=rid, stage="migrated")
            self._respond(conn, corr,
                          {"ok": True, "admitted": True, "rid": rid,
                           "dup": True})
            return
        try:
            blob = join_chunks(c for _, c in sorted(parts))
            req = Request(
                rid=rid,
                prompt=np.asarray(payload["prompt"], np.int32),
                max_new_tokens=int(payload["max_new_tokens"]),
                arrival_s=recv_mono,
            )
            slot = self.engine.admit_migrated(
                req, int(payload["first_token"]), payload["meta"], blob
            )
        except (RpcError, MigrationError, KeyError, TypeError,
                ValueError) as e:
            self.engine.metrics.counter("serve.migration_poisoned").inc()
            record_event("serve_migration_refuse", rid=rid,
                         reason="poisoned", error=str(e)[:120])
            self._respond(
                conn, corr,
                {"ok": False, "code": MigrationError.code, "rid": rid,
                 "error": str(e)[:200]},
            )
            return
        if slot is None:  # capacity refusal (counted by the engine)
            self._respond(
                conn, corr,
                {"ok": False, "code": "FT_RPC_SHED", "rid": rid,
                 "reason": "capacity"},
            )
            return
        # a placeholder waiter entry makes the rid IN-FLIGHT to the
        # dedup path: the front door's collect-generate attaches here
        # instead of re-submitting a resident sequence
        self._waiters.setdefault(rid, [])
        self._recv_stamp[rid] = recv_mono
        self._respond(conn, corr,
                      {"ok": True, "admitted": True, "rid": rid})

    def _flush_completions(self) -> None:
        if not self._waiters:
            return
        finished = [
            rid for rid in self._waiters if rid in self.engine.completed
        ]
        for rid in finished:
            waiters = self._waiters.pop(rid)
            for conn, corr, attempt in waiters:
                self._respond(conn, corr, self._result_payload(rid, attempt))
            self._recv_stamp.pop(rid, None)

    def _result_payload(self, rid: int, attempt: int) -> dict:
        done = self.engine.completed[rid]
        return {
            "ok": True,
            "rid": rid,
            "attempt": attempt,
            "rank": self.cfg.rank,
            "tokens": [int(t) for t in done.tokens],
            # durations on THIS process's monotonic clock; the front
            # door adds its own queue/retry time on its clock
            "ttft_s": round(done.ttft_s, 6),
            "decode_s": round(done.done_s - done.first_token_s, 6),
            # per-decode-token gaps on this clock: the inter-token
            # latency samples the disagg bench's p99 floor reads
            "intervals_s": [round(d, 6) for d in done.intervals_s],
        }

    def _drain(self) -> None:
        """Refuse everything outstanding so the front door re-routes it,
        then stop.  In-flight executions are abandoned mid-decode — the
        survivors' recompute is bit-identical, so dropping partial work
        is correct (and cheaper than a token-handoff protocol)."""
        self._pump_intake(block=False)  # late arrivals get refusals too
        n = 0
        for rid, waiters in sorted(self._waiters.items()):
            for conn, corr, _attempt in waiters:
                self._respond(
                    conn, corr, {"ok": False, "drain": True, "rid": rid}
                )
                n += 1
        self._waiters.clear()
        self._recv_stamp.clear()
        self.engine.metrics.counter("serve.drain_refusals").inc(n)
        record_event("drain", rank=self.cfg.rank, refused=n,
                     reason="sigterm")
        if self.on_drain is not None:
            try:
                self.on_drain()
            except Exception as e:  # a failed export must not wedge drain
                log.warning("replica %d drain hook failed: %s",
                            self.cfg.rank, e)
                record_event("serve_drain_hook_failed", rank=self.cfg.rank,
                             error=str(e))
        log.info("replica %d drained: %d refusals", self.cfg.rank, n)
        self.drained.set()

    def _respond(self, conn, corr, payload: dict) -> None:
        raw = encode_frame(dict(payload, corr=corr))
        self._sent_frames += 1
        if (
            self._tear_every
            and self._sent_frames % self._tear_every == 0
            and len(raw) > 12
        ):
            # chaos: flip one byte mid-body.  The length header stays
            # correct so the client reads a full, aligned frame — the
            # CRC trailer is the ONLY thing standing between this and a
            # silently corrupted token stream
            torn = bytearray(raw)
            torn[8] ^= 0xFF
            raw = bytes(torn)
            record_event("rpc_tear_injected", frame=self._sent_frames)
        try:
            conn.sendall(raw)
        except OSError:
            # client hung up (timed out, hedged elsewhere, died): its
            # result stays in the idempotency store for the retry
            pass


# --------------------------------------------------------------------------
# the process entrypoint
# --------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="flextree_tpu.serving.replica_main")
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--dir", required=True,
                    help="shared control dir (endpoints + heartbeats + obs)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--max-pending", type=int, default=64)
    ap.add_argument("--role", choices=ROLES, default="both",
                    help="prefill: migrate-flagged generates only; "
                         "decode: engine loop + migrated admissions; "
                         "both: the colocated engine (default)")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--blocks", type=int, default=65)
    ap.add_argument("--block-size", type=int, default=8)
    ap.add_argument("--blocks-per-seq", type=int, default=10)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--n-heads", type=int, default=8)
    ap.add_argument("--n-layers", type=int, default=4)
    ap.add_argument("--d-ff", type=int, default=1024)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--warmup-prompt-lens", default="",
                    help="CSV of prompt lengths to compile before serving")
    ap.add_argument("--warmup-max-new", type=int, default=0,
                    help="warm the block-reservation write for prompt+this")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable the cross-request prefix cache")
    ap.add_argument("--warmup-suffix-lens", default="",
                    help="CSV of cached:suffix pairs (e.g. 32:4,32:12) to "
                         "compile the suffix prefill for before serving")
    ap.add_argument("--handoff-out", default="",
                    help="on drain, export the prefix index (token "
                         "prefixes + block content hashes) to this file")
    ap.add_argument("--handoff-in", default="",
                    help="at boot, pre-warm the prefix cache from a "
                         "predecessor's handoff export (checksum-refused "
                         "or missing file degrades to a cold start)")
    args = ap.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from ..models.transformer import TransformerConfig, init_params
    from ..obs import flight_recorder, install_signal_dump
    from . import BatcherConfig, PagedCacheConfig, ServingEngine

    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, d_ff=args.d_ff,
    )
    # deterministic params: every replica (and the oracle in the chaos
    # driver) derives the SAME weights from the seed — no checkpoint
    # shipping needed for a bitwise cross-process comparison
    params = init_params(jax.random.PRNGKey(args.seed), cfg)
    pcfg = PagedCacheConfig(
        num_blocks=args.blocks, block_size=args.block_size,
        blocks_per_seq=args.blocks_per_seq,
    )
    engine = ServingEngine(
        params, cfg, pcfg,
        BatcherConfig(slots=args.slots, prefix_cache=args.prefix_cache),
        fused=False,  # the gather path: proven bitwise vs generate
    )
    if args.warmup_prompt_lens or args.warmup_suffix_lens:
        lens = sorted(
            {int(t) for t in args.warmup_prompt_lens.split(",") if t}
        )
        blocks = (
            {pcfg.blocks_for(t + args.warmup_max_new) for t in lens}
            if args.warmup_max_new else ()
        )
        buckets = [
            tuple(int(x) for x in pair.split(":"))
            for pair in args.warmup_suffix_lens.split(",") if pair
        ]
        # a decode-capable replica may receive migrated KV for any of
        # these prompt lengths: warm the import scatter per block count
        imports = (
            {pcfg.blocks_for(t) for t in lens}
            if args.role != "prefill" else ()
        )
        engine.warmup(lens, blocks, suffix_buckets=buckets,
                      import_counts=imports)

    rcfg = ReplicaConfig(
        args.rank, args.dir, host=args.host, port=args.port,
        max_pending=args.max_pending, role=args.role,
    )
    server = ReplicaServer(engine, rcfg)
    if args.handoff_out:

        def _export_handoff() -> None:
            doc = engine.export_prefix_handoff()
            if doc is not None:
                write_control_json(args.dir, args.handoff_out, doc)

        server.on_drain = _export_handoff
    with flight_recorder(
        args.dir, args.rank, source="serve", registry=engine.metrics
    ) as rec:
        # inside the recorder, so a cold start is LOUD in the flight
        # record (the driver's floor), not just in the exit counters
        if args.handoff_in:
            from ..runtime.ctrlfile import read_control_json

            doc = read_control_json(args.handoff_in)
            if doc is None:
                # missing or checksum-refused: COLD START, never guessing
                # at corrupt bytes — the successor serves correctly, just
                # slower
                engine.metrics.counter("serve.handoff_cold_start").inc()
                record_event("serve_handoff_cold_start", rank=args.rank,
                             path=args.handoff_in)
                log.warning("replica %d: handoff %s absent/refused — "
                            "cold start", args.rank, args.handoff_in)
            else:
                stats = engine.prewarm_prefix_from_handoff(doc)
                record_event("serve_handoff_prewarm", rank=args.rank,
                             **stats)
                log.info("replica %d pre-warmed from %s: %s", args.rank,
                         args.handoff_in, stats)
        signal.signal(signal.SIGTERM, lambda s, f: server.initiate_drain())
        install_signal_dump(rec, (signal.SIGTERM,))
        with Supervisor(SupervisorConfig.from_env(args.rank, args.dir)) as sup:
            server.start(engine_thread=False)
            log.info(
                "replica %d serving on %s:%d (pid %d)",
                args.rank, rcfg.host, server.port, os.getpid(),
            )
            # the engine loop runs HERE, on the main thread, so SIGTERM's
            # drain flag is observed within one loop iteration
            try:
                server.run_engine_loop()
            finally:
                sup.record_step(engine.steps)
            server.stop()
    if server.drained.is_set():
        # a CLEAN drain retires the endpoint so discovery stops routing
        # here (a crash leaves it — the front door's strike/avoid logic
        # and the heartbeat DEAD classification cover that path)
        try:
            os.unlink(os.path.join(
                args.dir, ENDPOINT_FMT.format(rank=args.rank)
            ))
        except OSError:
            pass
    # a drain exit is a SUCCESS (rc 0): the front door re-routed our work
    return 0


if __name__ == "__main__":
    sys.exit(main())
